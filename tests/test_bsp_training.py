"""End-to-end BSP: Cifar10 model trains (loss drops, error < chance),
checkpoints resume, metrics flow through the recorder."""

import numpy as np
import pytest

from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.models.cifar10 import Cifar10_model
from theanompi_tpu.parallel import data_mesh
from theanompi_tpu.rules.bsp import run_bsp_session
from theanompi_tpu.utils import Recorder


def small_cfg(tmp_path, **kw):
    base = dict(batch_size=8, n_epochs=2, learning_rate=0.01,
                snapshot_dir=str(tmp_path), print_freq=0)
    base.update(kw)
    return ModelConfig(**base)


@pytest.mark.slow
def test_bsp_learns(mesh8, tmp_path):
    cfg = small_cfg(tmp_path, n_epochs=3)
    model = Cifar10_model(config=cfg, mesh=mesh8)
    res = run_bsp_session(model, checkpoint=False)
    assert res["epochs_run"] == 3
    errs = [r["val_error"] for r in res["records"]]
    # synthetic cifar is separable: error must drop well below chance
    assert errs[-1] < 0.75, f"val error did not improve: {errs}"
    assert res["records"][-1]["train_loss"] < res["records"][0]["train_loss"]


@pytest.mark.slow
def test_bsp_checkpoint_resume(mesh8, tmp_path):
    cfg = small_cfg(tmp_path, n_epochs=2)
    model = Cifar10_model(config=cfg, mesh=mesh8)
    res1 = run_bsp_session(model, checkpoint=True)
    assert res1["epochs_run"] == 2

    # resume: a fresh model picks up at epoch 2 and runs only epoch 2
    cfg2 = small_cfg(tmp_path, n_epochs=3)
    model2 = Cifar10_model(config=cfg2, mesh=mesh8)
    res2 = run_bsp_session(model2, resume=True, checkpoint=True)
    assert res2["epochs_run"] == 1
    # recorder reloads the full history on resume: epochs 0,1 from the
    # first session plus the newly-run epoch 2
    assert [r["epoch"] for r in res2["records"]] == [0, 1, 2]


def test_bsp_rule_api(mesh8, tmp_path):
    """The reference's rule.init(...).wait() shape (SURVEY.md §2.2)."""
    from theanompi_tpu import BSP

    cfg = small_cfg(tmp_path, n_epochs=1)
    rule = BSP()
    # a tiny dataset keeps the epoch short; the zoo-shortname path is
    # covered by test_launcher.test_tmlocal_bsp_end_to_end
    rule.init(devices=8, modelfile="tests._tiny_models",
              modelclass="TinyCifar128", config=cfg, checkpoint=False)
    res = rule.wait()
    assert res["epochs_run"] == 1
    assert "error" in res["val"]


def test_bsp_rule_propagates_errors():
    from theanompi_tpu import BSP

    rule = BSP()
    rule.init(devices=8, modelfile="theanompi_tpu.models.cifar10",
              modelclass="NoSuchClass")
    with pytest.raises(AttributeError):
        rule.wait()


@pytest.mark.slow
def test_sum_mode_with_scaled_lr_matches_avg(mesh8, tmp_path):
    """sync_type 'cdd' (sum) with lr/N ~ 'avg' with lr (exchanger parity)."""
    cfg_avg = small_cfg(tmp_path, n_epochs=1, seed=7)
    m_avg = Cifar10_model(config=cfg_avg, mesh=mesh8)
    r_avg = run_bsp_session(m_avg, sync_type="avg", checkpoint=False)

    cfg_sum = small_cfg(tmp_path, n_epochs=1, seed=7, learning_rate=0.01 / 8)
    m_sum = Cifar10_model(config=cfg_sum, mesh=mesh8)
    r_sum = run_bsp_session(m_sum, sync_type="cdd", checkpoint=False)

    # weight decay composes with lr differently across the two modes, so
    # allow loose tolerance — but curves must be close
    a = r_avg["records"][0]["train_loss"]
    b = r_sum["records"][0]["train_loss"]
    assert abs(a - b) / a < 0.15, (a, b)


def test_same_seed_identical_curve(mesh8, tmp_path):
    """Determinism guarantee: two sessions from the same seed produce
    bit-identical loss sequences (epoch shuffles are pure functions of
    (seed, epoch); augment draws come from the step rng; XLA reduction
    order is fixed for a fixed mesh)."""
    from tests._tiny_models import TinyCifar128

    def run(tag):
        cfg = small_cfg(tmp_path, n_epochs=1, seed=123,
                        snapshot_dir=str(tmp_path / tag))
        m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
        res = run_bsp_session(m, checkpoint=False)
        losses = [r["train_loss"] for r in res["records"]]
        return losses, res["val"]["loss"]

    l1, v1 = run("a")
    l2, v2 = run("b")
    assert l1 == l2          # bit-identical, not merely close
    assert v1 == v2


def test_resume_replays_exact_rng_draws(mesh8, tmp_path):
    """The step rng is a pure function of (seed, epoch): a model that
    jumps straight to epoch k draws the same keys as one that trained
    through epochs 0..k-1 — so resume is draw-exact for dropout and
    device-augmentation, not just statistically equivalent."""
    import jax

    from tests._tiny_models import TinyCifar128

    cfg = small_cfg(tmp_path, n_epochs=3, seed=11)
    a = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    b = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    a.begin_epoch(0)
    for _ in range(5):
        a._next_rng()      # consume draws during epoch 0
    a.cleanup_iter()
    a.begin_epoch(1)
    b.begin_epoch(1)  # fresh model jumping straight to epoch 1
    ka, kb = a._next_rng(), b._next_rng()
    assert jax.random.key_data(ka).tolist() == \
        jax.random.key_data(kb).tolist()
    a.cleanup()
    b.cleanup()
