"""Sharded parameter service (parallel/shards.py, ISSUE 8).

The acceptance bar is EXACTNESS: the center pytree partitioned across
K shard processes must be indistinguishable — byte-for-byte — from the
single-center run at every exchange (the elastic update and the whole
``build_optimizer`` zoo are per-leaf, and leaves are never split), and
a checkpoint taken through the cross-shard version fence must restore
a tree equal to SOME single global version, never a mix of shard A
after exchange E with shard B before it.  The fault matrix mirrors the
single-server restart tests per shard: killing one shard re-seeds only
that shard's leaf range on rejoin while its siblings run uninterrupted.
"""

from __future__ import annotations

import socket
import threading
import time

import jax
import numpy as np
import pytest

from theanompi_tpu.parallel.server import ASGDServer, EASGDServer
from theanompi_tpu.parallel.service import ServiceClient
from theanompi_tpu.parallel.shards import (
    ShardParamService,
    ShardedASGD,
    ShardedEASGD,
    partition_ranges,
    serve_shard,
    shard_addresses,
)
from theanompi_tpu.utils.helper_funcs import build_optimizer


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start_shard(port: int, index: int):
    ready, stop = threading.Event(), threading.Event()
    t = threading.Thread(target=serve_shard,
                         args=("127.0.0.1", port, index, ready, stop),
                         daemon=True)
    t.start()
    assert ready.wait(10)
    return t, stop


def _start_fleet(k: int):
    fleet = []
    for i in range(k):
        port = _free_port()
        t, stop = _start_shard(port, i)
        fleet.append({"addr": f"127.0.0.1:{port}", "port": port,
                      "thread": t, "stop": stop})
    return fleet


def _stop_fleet(fleet):
    for s in fleet:
        s["stop"].set()
        try:
            ServiceClient(s["addr"]).call("shutdown")
        except Exception:
            pass
        s["thread"].join(timeout=5)


@pytest.fixture()
def shard_env(monkeypatch):
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_KEY", "shards-test")
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_RETRIES", "6")
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_RETRY_DEADLINE_S", "20")


def _tree(seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)
    return {"a": rng.standard_normal((8, 4)).astype(np.float32),
            "b": rng.standard_normal((33,)).astype(np.float32),
            "c": {"d": rng.standard_normal((4, 4)).astype(np.float32),
                  "e": rng.standard_normal((9,)).astype(np.float32)},
            "f": rng.standard_normal((2, 2, 2)).astype(np.float32)}


def _assert_bytes_equal(a, b, msg=""):
    fa, ta = jax.tree.flatten(a)
    fb, tb = jax.tree.flatten(b)
    assert ta == tb, f"treedef mismatch {msg}"
    for x, y in zip(fa, fb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype and x.shape == y.shape, msg
        assert x.tobytes() == y.tobytes(), msg


# ---------------------------------------------------------------------------
# Leaf-range partitioning
# ---------------------------------------------------------------------------


class TestPartition:
    def test_contiguous_covering_deterministic(self):
        rng = np.random.default_rng(7)
        sizes = [int(s) for s in rng.integers(1, 10_000, size=37)]
        for k in (1, 2, 3, 5, 11, 37):
            r1 = partition_ranges(sizes, k)
            r2 = partition_ranges(list(sizes), k)
            assert r1 == r2  # pure function of (sizes, k)
            assert len(r1) == k
            assert r1[0][0] == 0 and r1[-1][1] == len(sizes)
            for (a, b), (c, d) in zip(r1, r1[1:]):
                assert b == c      # contiguous
            assert all(hi > lo for lo, hi in r1)  # never empty

    def test_byte_balance(self):
        # many same-sized leaves must split near-evenly
        sizes = [1000] * 64
        for k in (2, 4, 8):
            r = partition_ranges(sizes, k)
            loads = [sum(sizes[lo:hi]) for lo, hi in r]
            assert max(loads) <= 2 * min(loads)

    def test_zero_size_leaves_ok(self):
        r = partition_ranges([0, 10, 0, 10], 2)
        assert r[0][0] == 0 and r[-1][1] == 4

    def test_more_shards_than_leaves_refused(self):
        with pytest.raises(ValueError, match="at most one shard"):
            partition_ranges([1, 2], 3)
        with pytest.raises(ValueError, match="empty tree"):
            partition_ranges([], 1)

    def test_addr_parsing(self):
        assert shard_addresses(None) is None
        assert shard_addresses("h:1") == ["h:1"]
        assert shard_addresses("h:1, g:2,") == ["h:1", "g:2"]


# ---------------------------------------------------------------------------
# Equivalence pins: K shards byte-identical to the single center
# ---------------------------------------------------------------------------


class TestEquivalence:
    @pytest.mark.parametrize("k", [2, 4])
    def test_easgd_byte_identical_every_exchange(self, shard_env, rpc_loop, k):
        """Acceptance pin: a fixed-seed exchange sequence against K=2
        and K=4 shards reassembles byte-identically to the K=1
        single-center run at EVERY exchange, and the fenced center
        matches too."""
        tree = _tree(0)
        oracle = EASGDServer(tree, alpha=0.5)
        fleet = _start_fleet(k)
        try:
            srv = ShardedEASGD([s["addr"] for s in fleet], tree,
                               alpha=0.5, session_id=f"eq-{k}")
            for n in range(1, 6):
                w = jax.tree.map(
                    lambda x: x + np.float32(0.07 * n), tree)
                out = srv.exchange(w)
                exp = jax.tree.map(np.asarray,
                                   jax.device_get(oracle.exchange(w)))
                _assert_bytes_equal(out, exp, f"exchange {n} (K={k})")
            center, vclock = srv.fenced_center()
            _assert_bytes_equal(
                center,
                jax.tree.map(np.asarray,
                             jax.device_get(oracle.get_center())),
                f"center (K={k})")
            assert vclock == {srv._client_id: 5}
            assert srv.n_exchanges == 5
            srv.close()
        finally:
            _stop_fleet(fleet)

    def test_asgd_byte_identical_with_lr_schedule(self, shard_env):
        """Per-shard optimizers (SGD + momentum + weight decay, with a
        mid-run set_lr) reassemble byte-identically: every optax
        transform the builder emits is per-leaf, and leaves are never
        split."""
        tree = _tree(1)
        opt_cfg = dict(learning_rate=0.1, optimizer="sgd", momentum=0.9,
                       nesterov=False, weight_decay=1e-4)
        oracle = ASGDServer(tree, build_optimizer(**opt_cfg))
        fleet = _start_fleet(2)
        try:
            srv = ShardedASGD([s["addr"] for s in fleet], tree, opt_cfg,
                              session_id="asgd-eq")
            for n in range(1, 4):
                g = jax.tree.map(
                    lambda x: np.full_like(x, 0.01 * n,
                                           dtype=np.float32), tree)
                out = srv.push_pull(g)
                exp = jax.tree.map(np.asarray,
                                   jax.device_get(oracle.push_pull(g)))
                _assert_bytes_equal(out, exp, f"push {n}")
            srv.set_lr(0.02)
            oracle.set_lr(0.02)
            g = jax.tree.map(
                lambda x: np.ones_like(x, dtype=np.float32), tree)
            _assert_bytes_equal(
                srv.push_pull(g),
                jax.tree.map(np.asarray,
                             jax.device_get(oracle.push_pull(g))),
                "push after set_lr")
            assert srv.n_updates == 4
            srv.close()
        finally:
            _stop_fleet(fleet)

    def test_sharded_asgd_opt_state_contract(self, shard_env):
        """The documented optimizer-state trade: a restored opt_state
        is refused at init (no scatter), and get_opt_state is refused
        (no single-tree reassembly) — docs/RESILIENCE.md."""
        tree = _tree(2)
        fleet = _start_fleet(2)
        try:
            with pytest.raises(ValueError, match="opt_state"):
                ShardedASGD([s["addr"] for s in fleet], tree,
                            {"learning_rate": 0.1},
                            opt_state={"bogus": np.zeros(1)})
            srv = ShardedASGD([s["addr"] for s in fleet], tree,
                              {"learning_rate": 0.1}, session_id="oc")
            assert srv.supports_opt_state is False
            with pytest.raises(RuntimeError, match="opt_state"):
                srv.get_opt_state()
            srv.close()
        finally:
            _stop_fleet(fleet)


# ---------------------------------------------------------------------------
# Wire parity: restored trees byte-exact per shard, both protocols
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("protocol", ["v1", "v2"])
def test_restored_tree_byte_exact_per_shard(shard_env, monkeypatch,
                                            protocol):
    """test_service.py's restored-tree pin, per shard: the mixed-dtype
    tree survives the partition + per-shard wire + fence reassembly
    byte-exactly under BOTH protocols, and every shard connection
    negotiated the protocol asked for."""
    monkeypatch.setenv("THEANOMPI_TPU_WIRE_PROTOCOL", protocol)
    tree = {"f32": np.arange(12, dtype=np.float32).reshape(3, 4) * 0.37,
            "f64": np.linspace(0.0, 1.0, 7),
            "i32": np.arange(-5, 5, dtype=np.int32),
            "u8": np.arange(64, dtype=np.uint8).reshape(8, 8),
            "empty": np.zeros((0, 3), np.float32),
            "nested": [np.full((2, 2), 9.5, np.float16),
                       {"deep": np.array([True, False])}]}
    fleet = _start_fleet(2)
    try:
        srv = ShardedEASGD([s["addr"] for s in fleet], tree, alpha=0.5,
                           session_id=f"bytes-{protocol}")
        for c in srv._shard_clients:
            assert c.wire_protocol == protocol
        assert srv.wire_protocol == protocol
        _assert_bytes_equal(srv.get_center(), tree, protocol)
        srv.close()
    finally:
        _stop_fleet(fleet)


# ---------------------------------------------------------------------------
# The cross-shard version fence
# ---------------------------------------------------------------------------


class TestVersionFence:
    def test_atomic_cut_under_concurrent_exchanges(self, shard_env, rpc_loop):
        """THE atomicity pin: fenced reads taken while a worker
        exchanges concurrently always equal the oracle center at
        exactly the version the fence's vector clock names — never a
        mix of shard states from different exchanges."""
        tree = _tree(3)
        N = 30

        def w_at(n):
            return jax.tree.map(lambda x: x + np.float32(0.05 * n),
                                tree)

        oracle = EASGDServer(tree, alpha=0.5)
        centers = [jax.tree.map(np.asarray,
                                jax.device_get(oracle.get_center()))]
        for n in range(1, N + 1):
            oracle.exchange(w_at(n))
            centers.append(jax.tree.map(
                np.asarray, jax.device_get(oracle.get_center())))

        fleet = _start_fleet(2)
        try:
            srv = ShardedEASGD([s["addr"] for s in fleet], tree,
                               alpha=0.5, session_id="fence")
            errs: list[BaseException] = []

            def mutate():
                try:
                    for n in range(1, N + 1):
                        srv.exchange(w_at(n))
                        time.sleep(0.002)
                except BaseException as e:  # surfaced below
                    errs.append(e)

            mt = threading.Thread(target=mutate)
            mt.start()
            reads = 0
            try:
                while mt.is_alive():
                    cut, vclock = srv.fenced_center()
                    n = vclock.get(srv._client_id, 0)
                    _assert_bytes_equal(cut, centers[n],
                                        f"torn cut at version {n}")
                    reads += 1
            finally:
                mt.join(timeout=30)
            assert not errs, errs
            assert reads >= 1
            # quiescent read lands on the final version exactly
            cut, vclock = srv.fenced_center()
            assert vclock == {srv._client_id: N}
            _assert_bytes_equal(cut, centers[N], "final")
            srv.close()
        finally:
            _stop_fleet(fleet)

    def test_fence_over_mux_shared_sockets(self, shard_env,
                                           monkeypatch):
        """ISSUE 11: with THEANOMPI_TPU_SHARD_MUX=1 each shard's data
        client and fence client share ONE multiplexed socket.  The
        fence must still cut consistently under a concurrent exchange
        — safe because the selector loop routes shard_freeze/release
        to its control pool, so a freeze-parked mutation parks a
        worker, never the shared connection's read loop."""
        monkeypatch.setenv("THEANOMPI_TPU_RPC_LOOP", "selector")
        monkeypatch.setenv("THEANOMPI_TPU_SHARD_MUX", "1")
        tree = _tree(11)
        fleet = _start_fleet(2)
        try:
            srv = ShardedEASGD([s["addr"] for s in fleet], tree,
                               alpha=0.5, session_id="mux-fence")
            # the transports really multiplex (server granted mux)
            assert srv._transports and all(t.mux
                                           for t in srv._transports)
            oracle = EASGDServer(tree, alpha=0.5)
            w = jax.tree.map(lambda x: x + np.float32(0.25), tree)
            _assert_bytes_equal(
                srv.exchange(w),
                jax.tree.map(np.asarray,
                             jax.device_get(oracle.exchange(w))),
                "exchange over mux")
            done = threading.Event()

            def mutate():
                while not done.is_set():
                    srv.exchange(w)

            mt = threading.Thread(target=mutate)
            mt.start()
            try:
                for _ in range(5):
                    cut, vclock = srv.fenced_center()
                    assert vclock  # a consistent cut came back
            finally:
                done.set()
                mt.join(timeout=30)
            srv.close()
        finally:
            _stop_fleet(fleet)

    def test_concurrent_readers_fence_busy_retries(self, shard_env, rpc_loop):
        """Two readers fencing the same fleet (orchestrator +
        supervisor restart, say) both succeed — FenceBusy is retried,
        not surfaced."""
        tree = _tree(4)
        fleet = _start_fleet(2)
        try:
            srv = ShardedEASGD([s["addr"] for s in fleet], tree,
                               alpha=0.5, session_id="busy")
            results: list = []
            errs: list[BaseException] = []

            def read_loop():
                try:
                    for _ in range(5):
                        results.append(srv.fenced_center())
                except BaseException as e:
                    errs.append(e)

            readers = [threading.Thread(target=read_loop)
                       for _ in range(2)]
            for t in readers:
                t.start()
            for t in readers:
                t.join(timeout=30)
            assert not errs, errs
            assert len(results) == 10
            for cut, _ in results:
                _assert_bytes_equal(cut, tree, "unmutated center")
            srv.close()
        finally:
            _stop_fleet(fleet)

    def test_stale_fence_auto_expires(self, shard_env, monkeypatch):
        """A reader that froze a shard and died must not wedge
        training: past THEANOMPI_TPU_SHARD_FENCE_TIMEOUT_S the shard
        auto-releases and blocked exchanges proceed."""
        monkeypatch.setenv("THEANOMPI_TPU_SHARD_FENCE_TIMEOUT_S", "0.5")
        tree = _tree(5)
        fleet = _start_fleet(2)
        try:
            srv = ShardedEASGD([s["addr"] for s in fleet], tree,
                               alpha=0.5, session_id="stale")
            ghost = ServiceClient(fleet[0]["addr"])
            ghost.call("shard_freeze", "easgd", "stale", "ghost-token")
            # no release: the ghost reader is gone
            t0 = time.monotonic()
            out = srv.exchange(jax.tree.map(
                lambda x: x + np.float32(1.0), tree))
            assert time.monotonic() - t0 < 10
            assert all(np.isfinite(np.asarray(x)).all()
                       for x in jax.tree.leaves(out))
            # release with a stranger's token is a silent no-op
            ghost.call("shard_release", "easgd", "stale", "wrong-token")
            ghost.close()
            srv.close()
        finally:
            _stop_fleet(fleet)

    def test_stable_divergence_accepted(self, shard_env, rpc_loop):
        """Liveness under dead history (code-review finding): a client
        that died mid-scatter leaves its tag on SOME shards forever —
        exact clock equality is then permanently unreachable, but the
        fence must still produce cuts (3 stable frozen observations
        prove no straddler is pending) instead of failing every
        checkpoint until max_attempts."""
        tree = _tree(7)
        fleet = _start_fleet(2)
        try:
            srv = ShardedEASGD([s["addr"] for s in fleet], tree,
                               alpha=0.5, session_id="diverge")
            srv.exchange(jax.tree.map(
                lambda x: x + np.float32(0.5), tree))
            # a "dead" client's partial op: one tagged sub-exchange on
            # shard 0 only, never completed on shard 1
            lo, hi = srv._plan.ranges[0]
            flat = [np.asarray(x) for x in
                    jax.tree.leaves(jax.tree.map(
                        lambda x: x + np.float32(2.0), tree))]
            ghost = srv._shard_clients[0]
            ghost.call("shard_exchange", "diverge", flat[lo:hi],
                       "dead-client", 1)
            cut, vclock = srv.fenced_center()
            # the union-max clock names both writers
            assert vclock[srv._client_id] == 1
            assert vclock["dead-client"] == 1
            assert all(np.isfinite(np.asarray(x)).all()
                       for x in jax.tree.leaves(cut))
            # and live traffic afterwards still fences fine
            srv.exchange(jax.tree.map(
                lambda x: x + np.float32(1.0), tree))
            cut2, vclock2 = srv.fenced_center()
            assert vclock2[srv._client_id] == 2
            srv.close()
        finally:
            _stop_fleet(fleet)

    def test_freeze_unit_semantics(self):
        """In-process ShardParamService: admission blocks while
        frozen, the vector clock versions successful mutations only,
        and FenceBusy/ShardNotReady ride the typed-error channel."""
        from theanompi_tpu.parallel.service import (
            FenceBusy,
            ShardNotReady,
        )

        svc = ShardParamService(3)
        with pytest.raises(ShardNotReady):
            svc.handle("shard_freeze", "easgd", "s", "t0")
        svc.handle("easgd_init", {"w": np.zeros(4, np.float32)}, 0.5,
                   "s")
        info = svc.handle("shard_freeze", "easgd", "s", "t1")
        assert info == {"shard": 3, "vclock": {}, "applied": 0}
        with pytest.raises(FenceBusy):
            svc.handle("shard_freeze", "easgd", "s", "t2")
        admitted = threading.Event()

        def mutate():
            svc.handle("shard_exchange", "s",
                       {"w": np.ones(4, np.float32)}, "c", 1)
            admitted.set()

        t = threading.Thread(target=mutate, daemon=True)
        t.start()
        assert not admitted.wait(0.3)  # frozen: mutation parked
        svc.handle("shard_release", "easgd", "s", "t1")
        assert admitted.wait(5)
        t.join(5)
        info = svc.handle("shard_freeze", "easgd", "s", "t3")
        assert info["vclock"] == {"c": 1} and info["applied"] == 1
        svc.handle("shard_release", "easgd", "s", "t3")
        # a non-int seq is refused BEFORE the store op (an applied-but-
        # unversioned mutation would be invisible to the fence)
        with pytest.raises(ValueError, match="seq"):
            svc.handle("shard_exchange", "s",
                       {"w": np.ones(4, np.float32)}, "c", "bogus")
        # an at-least-once DUPLICATE (same client, same seq — a lost-
        # reply re-send) bumps the applied counter though the vclock is
        # unchanged: the counter is what lets post-read validation see
        # a duplicate that slipped through an expired fence
        svc.handle("shard_exchange", "s",
                   {"w": np.ones(4, np.float32)}, "c", 1)
        info = svc.handle("shard_freeze", "easgd", "s", "t4")
        assert info["vclock"] == {"c": 1} and info["applied"] == 2
        svc.handle("shard_release", "easgd", "s", "t4")

    def test_wait_ready_detects_wrong_shard(self, shard_env):
        """A stale process squatting on a shard's port (answering as a
        different shard index) must fail the fleet startup loudly —
        not be retried into a misleading 'never came up' timeout, and
        never be accepted (code-review finding: this was a bare
        assert, stripped under python -O)."""
        from theanompi_tpu.analysis.lockgraph import make_lock
        from theanompi_tpu.parallel.shards import ShardProcessGroup

        port = _free_port()
        t, stop = _start_shard(port, 5)  # wrong index on purpose
        try:
            g = ShardProcessGroup.__new__(ShardProcessGroup)
            g.host = "127.0.0.1"
            g._ports = [port]
            g._socks = [None]
            g._lock = make_lock("test-group-lock")
            g._stopping = threading.Event()
            g.max_restarts = 0

            class _FakeProc:
                returncode = None

                def poll(self):
                    return None

                def terminate(self):
                    pass

                def wait(self, timeout=None):
                    return 0

            g._procs = [_FakeProc()]
            g._restarts = {}
            with pytest.raises(RuntimeError,
                               match="answered as shard 5"):
                g._wait_ready(10.0)
        finally:
            stop.set()
            try:
                ServiceClient(f"127.0.0.1:{port}").call("shutdown")
            except Exception:
                pass
            t.join(timeout=5)


# ---------------------------------------------------------------------------
# Shard fault matrix
# ---------------------------------------------------------------------------


class TestShardFaultMatrix:
    def test_single_shard_kill_and_rejoin(self, shard_env):
        """Kill + restart ONE shard mid-run: the sibling shard's store
        is untouched (its exchange count runs uninterrupted), and the
        rejoin re-seeds ONLY the dead shard's leaf range — from the
        client's last good sub-result, the per-shard mirror of the
        single-server restart matrix."""
        tree = _tree(6)
        N = 5

        def w_at(n):
            return jax.tree.map(lambda x: x + np.float32(0.1 * n),
                                tree)

        fleet = _start_fleet(2)
        try:
            srv = ShardedEASGD([s["addr"] for s in fleet], tree,
                               alpha=0.5, session_id="kill")
            last = None
            for n in range(1, N + 1):
                last = srv.exchange(w_at(n))

            # hard restart of shard 1 only (same port, fresh store)
            s1 = fleet[1]
            s1["stop"].set()
            try:
                ServiceClient(s1["addr"]).call("shutdown")
            except Exception:
                pass
            s1["thread"].join(timeout=5)
            s1["thread"], s1["stop"] = _start_shard(s1["port"], 1)

            out = srv.exchange(w_at(N + 1))
            st0 = srv._shard_clients[0].call("stats")
            st1 = srv._shard_clients[1].call("stats")
            # sibling uninterrupted; dead shard rebuilt fresh
            assert st0["n_exchanges"] == N + 1
            assert st1["n_exchanges"] == 1

            # shard 0's range: center evolved normally.  shard 1's
            # range: center re-seeded from the client's LAST GOOD
            # sub-result, so new_w = w - a*(w - last)
            flat_out = [np.asarray(x) for x in jax.tree.leaves(out)]
            flat_w = [np.asarray(x) for x in jax.tree.leaves(w_at(N + 1))]
            flat_last = [np.asarray(x) for x in jax.tree.leaves(last)]
            lo, hi = srv._plan.ranges[1]
            for j in range(lo, hi):
                exp = (flat_w[j]
                       - np.float32(0.5) * (flat_w[j] - flat_last[j]))
                np.testing.assert_array_equal(
                    flat_out[j], exp.astype(np.float32),
                    err_msg=f"shard-1 leaf {j} rejoin math")
            # the fence works across the rebuilt shard too
            cut, vclock = srv.fenced_center()
            assert vclock == {srv._client_id: N + 1}
            assert all(np.isfinite(np.asarray(x)).all()
                       for x in jax.tree.leaves(cut))
            srv.close()
        finally:
            _stop_fleet(fleet)

    def test_gosgd_refuses_sharded_hub(self, shard_env, tmp_path):
        """The gossip hub stays unsharded: a comma-separated
        server_addr is a configuration error, surfaced immediately."""
        from theanompi_tpu import GOSGD
        from theanompi_tpu.models.base import ModelConfig

        rule = GOSGD()
        rule.init(devices=1, modelfile="tests._tiny_models",
                  modelclass="TinyCifar",
                  config=ModelConfig(batch_size=8, n_epochs=1,
                                     snapshot_dir=str(tmp_path),
                                     print_freq=0),
                  checkpoint=False,
                  server_addr="127.0.0.1:1,127.0.0.1:2")
        with pytest.raises(ValueError, match="unsharded"):
            rule.wait()


# ---------------------------------------------------------------------------
# Launcher flag validation (no processes spawned — all fail fast)
# ---------------------------------------------------------------------------


class TestLauncherShardFlag:
    @pytest.mark.parametrize("argv,match", [
        (["GOSGD", "-m", "cifar10", "--shards", "2"], "EASGD/ASGD"),
        (["BSP", "-m", "cifar10", "--shards", "2"], "EASGD/ASGD"),
        (["EASGD", "-m", "cifar10", "--shards", "2",
          "--server-addr", "h:1"], "not both"),
        (["EASGD", "-m", "cifar10", "--shards", "0"], ">= 1"),
    ])
    def test_invalid_combinations_exit(self, argv, match):
        from theanompi_tpu.launcher import tmlocal

        with pytest.raises(SystemExit, match=match):
            tmlocal(argv)


# ---------------------------------------------------------------------------
# Rules end-to-end over a sharded center
# ---------------------------------------------------------------------------


def _tiny_cfg(tmp_path, **kw):
    from theanompi_tpu.models.base import ModelConfig

    base = dict(batch_size=8, n_epochs=1, learning_rate=0.01,
                snapshot_dir=str(tmp_path), print_freq=0)
    base.update(kw)
    return ModelConfig(**base)


def test_easgd_rule_with_sharded_center(shard_env, tmp_path):
    """EASGD end-to-end against 2 real shard sockets: workers exchange
    leaf ranges concurrently, the orchestrator's per-epoch validation
    reads the center through the version fence while workers keep
    exchanging — the whole wiring under real concurrency."""
    from theanompi_tpu import EASGD

    fleet = _start_fleet(2)
    try:
        rule = EASGD()
        rule.init(devices=2, modelfile="tests._tiny_models",
                  modelclass="TinyCifar",
                  config=_tiny_cfg(tmp_path), tau=4, alpha=0.5,
                  checkpoint=False,
                  server_addr=",".join(s["addr"] for s in fleet))
        res = rule.wait()
        assert res["n_exchanges"] > 0
        assert np.isfinite(res["val"]["loss"])
        assert all(np.isfinite(np.asarray(v)).all()
                   for v in jax.tree.leaves(res["center"]))
    finally:
        _stop_fleet(fleet)


def test_asgd_rule_sharded_checkpoint_resume(shard_env, tmp_path):
    """ASGD over shards, with checkpointing: the per-epoch save goes
    through the fenced center read and the worker-side opt_state
    fallback (ShardedASGD.supports_opt_state), and a resumed session
    re-seeds the center exactly with fresh server momentum — the
    documented sharded-resume trade."""
    from theanompi_tpu import ASGD

    fleet = _start_fleet(2)
    try:
        addr = ",".join(s["addr"] for s in fleet)
        rule = ASGD()
        rule.init(devices=2, modelfile="tests._tiny_models",
                  modelclass="TinyCifar",
                  config=_tiny_cfg(tmp_path), checkpoint=True,
                  server_addr=addr)
        res1 = rule.wait()
        assert res1["n_updates"] > 0

        rule2 = ASGD()
        rule2.init(devices=2, modelfile="tests._tiny_models",
                   modelclass="TinyCifar",
                   config=_tiny_cfg(tmp_path, n_epochs=2),
                   checkpoint=True, resume=True, server_addr=addr)
        res2 = rule2.wait()
        assert np.isfinite(res2["val"]["loss"])
    finally:
        _stop_fleet(fleet)
