"""Native C++ fused augment vs the numpy oracle: identical randomness,
matching values, reflect-pad and flip semantics, and graceful fallback
(reference loader parity — SURVEY.md §2.9/§3.4)."""

import numpy as np
import pytest

from theanompi_tpu import native
from theanompi_tpu.data.utils import augment_normalize, center_normalize

needs_native = pytest.mark.skipif(not native.native_available(),
                                  reason="native build unavailable")


def batch(n=8, h=40, w=40, c=3, seed=0):
    return np.random.RandomState(seed).randint(
        0, 256, (n, h, w, c)).astype(np.uint8)


@needs_native
class TestNativeMatchesNumpy:
    def check(self, **kw):
        x = batch()
        # identical rng state for both paths -> identical crops/flips
        got = augment_normalize(x, 32, 32, np.random.default_rng(7), **kw)
        import theanompi_tpu.data.utils as U
        orig = U._use_native
        U._use_native = lambda images: False
        try:
            want = augment_normalize(x, 32, 32, np.random.default_rng(7),
                                     **kw)
        finally:
            U._use_native = orig
        assert got.dtype == want.dtype == np.float32
        # bitwise: the kernel mirrors numpy's exact f32 op order, so
        # training runs are independent of which impl decoded the batch
        np.testing.assert_array_equal(got, want)

    def test_plain_crop_flip(self):
        self.check()

    def test_with_normalization(self):
        self.check(mean=(0.45, 0.46, 0.47), std=(0.2, 0.21, 0.22))

    def test_reflect_pad(self):
        self.check(pad=4, mean=(0.5,) * 3, std=(0.5,) * 3)

    def test_no_flip(self):
        self.check(flip=False)

    def test_center_normalize(self):
        x = batch(n=5)
        got = center_normalize(x, 32, 32, mean=(0.4,) * 3, std=(0.3,) * 3)
        import theanompi_tpu.data.utils as U
        orig = U._use_native
        U._use_native = lambda images: False
        try:
            want = center_normalize(x, 32, 32, mean=(0.4,) * 3,
                                    std=(0.3,) * 3)
        finally:
            U._use_native = orig
        np.testing.assert_array_equal(got, want)


def test_fallback_on_float_input():
    # float input can't take the native path; must still work
    x = batch().astype(np.float32)
    out = augment_normalize(x, 32, 32, np.random.default_rng(0), divisor=1.0)
    assert out.shape == (8, 32, 32, 3) and out.dtype == np.float32


def test_env_kill_switch():
    # THEANOMPI_TPU_NATIVE=0 must disable the native path at load time;
    # run in a subprocess because availability is cached per process
    import subprocess
    import sys
    code = ("from theanompi_tpu import native; "
            "assert not native.native_available(); print('off')")
    env = dict(__import__('os').environ,
               THEANOMPI_TPU_NATIVE="0", JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0 and "off" in out.stdout, out.stderr


def test_bad_inputs_rejected():
    if not native.native_available():
        pytest.skip("native build unavailable")
    x = batch()
    n = len(x)
    ys = xs = np.zeros(n, np.int64)
    flips = np.zeros(n, np.uint8)
    with pytest.raises(ValueError, match="uint8"):
        native.crop_flip_normalize(x.astype(np.float32), ys, xs, flips,
                                   32, 32, np.zeros(3), np.ones(3))
    with pytest.raises(ValueError, match="mean/std"):
        native.crop_flip_normalize(x, ys, xs, flips, 32, 32,
                                   np.zeros(1), np.ones(3))


def test_center_normalize_rejects_undersized():
    with pytest.raises(ValueError, match="smaller than crop"):
        center_normalize(batch(h=16, w=16), 32, 32)


@needs_native
def test_dataset_batches_unchanged_by_native():
    """Cifar batches must be identical whichever impl runs (the rng
    draw order is part of the dataset's determinism contract)."""
    from theanompi_tpu.data.cifar10 import Cifar10_data
    import theanompi_tpu.data.utils as U

    d = Cifar10_data(synthetic_n=256)
    nat = [x for x, _ in d.train_batches(0, 64)]
    orig = U._use_native
    U._use_native = lambda images: False
    try:
        ref = [x for x, _ in d.train_batches(0, 64)]
    finally:
        U._use_native = orig
    for a, b in zip(nat, ref):
        np.testing.assert_array_equal(a, b)


@needs_native
def test_extreme_pad_reflect_matches_numpy():
    # pad >= h-1 requires REPEATED reflection (np.pad semantics); the
    # single-bounce version read out of bounds here
    x = batch(n=4, h=4, w=4)
    got = augment_normalize(x, 8, 8, np.random.default_rng(3), pad=4)
    import theanompi_tpu.data.utils as U
    orig = U._use_native
    U._use_native = lambda images: False
    try:
        want = augment_normalize(x, 8, 8, np.random.default_rng(3), pad=4)
    finally:
        U._use_native = orig
    np.testing.assert_array_equal(got, want)


@needs_native
def test_out_of_range_origins_rejected():
    x = batch(n=2)
    with pytest.raises(ValueError, match="out of range"):
        native.crop_flip_normalize(
            x, np.array([0, 50], np.int64), np.zeros(2, np.int64),
            np.zeros(2, np.uint8), 32, 32, np.zeros(3), np.ones(3))
