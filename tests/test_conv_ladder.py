"""tools/conv_ladder.py — the per-shape MFU decomposition of the
ResNet-50 step (VERDICT r2 #2).  The enumeration must reproduce the
canonical conv cost: 4.09 GMAC = 8.2 GF (2xMAC) forward at 224², and
its geometry must match theanompi_tpu/models/resnet50.py.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

from conv_ladder import conv_gflops, resnet50_convs  # noqa: E402


def test_enumeration_matches_canonical_flops():
    convs = resnet50_convs(batch=1)
    total = sum(count * conv_gflops(b, h, cin, cout, k, s)
                for (_, b, h, cin, cout, k, s, count) in convs)
    # canonical ResNet-50: 4.09 GMAC fwd conv cost = 8.18 GF in 2xMAC
    # (the fc layer's 2*2048*1000 = 0.004 GF is ignored)
    assert abs(total - 8.18) < 0.15, total
    # 16 bottleneck blocks: 4 first-blocks (4 convs each incl. proj)
    # + 12 repeats (3 distinct shapes, with multiplicity)
    n_convs = sum(c[-1] for c in convs)
    assert n_convs == 1 + 4 * 4 + 12 * 3, n_convs


def test_flops_scale_linearly_with_batch():
    one = sum(c[-1] * conv_gflops(*c[1:-1]) for c in resnet50_convs(1))
    four = sum(c[-1] * conv_gflops(*c[1:-1]) for c in resnet50_convs(4))
    assert abs(four - 4 * one) < 1e-6


def test_s2d_stem_swaps_only_the_stem():
    base = {c[0]: c for c in resnet50_convs(1, stem="conv7")}
    s2d = {c[0]: c for c in resnet50_convs(1, stem="s2d")}
    assert "stem_conv7" in base and "stem_s2d4x4" in s2d
    assert {k for k in base if not k.startswith("stem")} == \
           {k for k in s2d if not k.startswith("stem")}
    # the s2d re-parameterization preserves the stem's FLOPs up to the
    # 8/7-tap zero-padding (4*4*12 = 192 taps vs 7*7*3 = 147: x1.31)
    g7 = conv_gflops(*base["stem_conv7"][1:-1])
    g4 = conv_gflops(*s2d["stem_s2d4x4"][1:-1])
    assert 1.0 < g4 / g7 < 1.45, (g7, g4)
