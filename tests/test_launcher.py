"""Launcher CLI coverage (VERDICT r1 weak #7): arg parsing, zoo
shortname resolution, config overrides, and one end-to-end ``tmlocal``
session on the virtual CPU mesh."""

import dataclasses

import pytest

from theanompi_tpu.launcher import RULES, _build_parser, _resolve_model
from theanompi_tpu.models import MODEL_ZOO


def test_parser_rules_and_defaults():
    p = _build_parser(multihost=False)
    args = p.parse_args(["BSP"])
    assert args.rule == "BSP"
    assert args.modelfile == "theanompi_tpu.models.cifar10"
    assert args.devices is None and args.epochs is None
    assert args.sync_type == "avg"
    assert set(RULES) == {"BSP", "EASGD", "ASGD", "GOSGD", "SERVE"}


def test_parser_rejects_unknown_rule(capsys):
    p = _build_parser(multihost=False)
    with pytest.raises(SystemExit):
        p.parse_args(["PSGD"])


def test_parser_overrides():
    p = _build_parser(multihost=False)
    args = p.parse_args(["EASGD", "-D", "4", "--epochs", "3",
                         "--batch-size", "32", "--lr", "0.05",
                         "--tau", "7", "--alpha", "0.25",
                         "--sync-type", "cdd", "--platform", "cpu"])
    assert (args.devices, args.epochs, args.batch_size) == (4, 3, 32)
    assert (args.lr, args.tau, args.alpha) == (0.05, 7, 0.25)
    assert args.sync_type == "cdd" and args.platform == "cpu"


def test_parser_serve_mode():
    """SERVE (theanompi_tpu/serving, docs/SERVING.md) rides the same
    entry point; its knobs parse and the guards fire."""
    from theanompi_tpu.launcher import _run

    p = _build_parser(multihost=False)
    args = p.parse_args(["SERVE", "--export-dir", "/tmp/exp",
                         "--port", "45901", "--serve-replicas", "2",
                         "--max-batch", "16", "--max-delay-ms", "2.5",
                         "--serve-buckets", "1,4,16",
                         "--max-queue", "64", "--reload-poll-s", "0.5"])
    assert args.rule == "SERVE" and args.export_dir == "/tmp/exp"
    assert (args.port, args.serve_replicas, args.max_batch) == (45901, 2, 16)
    assert (args.max_delay_ms, args.serve_buckets) == (2.5, "1,4,16")
    assert (args.max_queue, args.reload_poll_s) == (64, 0.5)
    # --max-restarts default is None so each mode picks its own:
    # training fail-fast (0), SERVE supervised recovery (2, matching
    # serve_main) — the launcher must not silently disable serving's
    # documented restart-from-export
    assert args.max_restarts is None
    # SERVE without an export dir fails fast, before touching jax
    with pytest.raises(SystemExit, match="export-dir"):
        _run(p.parse_args(["SERVE"]), multihost=False)
    # and is single-host by construction
    mp = _build_parser(multihost=True)
    with pytest.raises(SystemExit, match="single-host"):
        _run(mp.parse_args(["SERVE", "--coordinator", "h0:1",
                            "--nhosts", "2", "--host-id", "0",
                            "--export-dir", "/tmp/exp"]),
             multihost=True)


def test_parser_serve_decode_mode(monkeypatch, tmp_path):
    """SERVE --decode (theanompi_tpu/decode): the knobs parse, reach
    serve_main as decode_opts, and --decode outside SERVE fails fast
    (silently ignoring it would fake a live decode plane)."""
    import theanompi_tpu.serving.server as srv
    from theanompi_tpu.launcher import _run

    p = _build_parser(multihost=False)
    args = p.parse_args(["SERVE", "--export-dir", "/tmp/exp",
                         "--decode", "--decode-page-size", "4",
                         "--decode-pages-per-seq", "2",
                         "--decode-max-seqs", "16",
                         "--decode-max-pending", "64",
                         "--decode-prefill-buckets", "8,32",
                         "--decode-prefill-batch", "4",
                         "--decode-prefill-delay-ms", "1.5"])
    assert args.decode and args.decode_page_size == 4
    seen = {}

    def fake_serve_main(export_dir, **kw):
        seen.update(kw, export_dir=export_dir)
        return 0

    monkeypatch.setattr(srv, "serve_main", fake_serve_main)
    _run(args, multihost=False)
    assert seen["decode"] is True
    assert seen["decode_opts"] == {
        "page_size": 4, "pages_per_seq": 2, "max_seqs": 16,
        "max_pending": 64, "prefill_buckets": (8, 32),
        "prefix_cache": True, "prefill_batch": 4,
        "prefill_delay_ms": 1.5}
    # default: decode off, opts None
    _run(p.parse_args(["SERVE", "--export-dir", "/tmp/exp"]),
         multihost=False)
    assert seen["decode"] is False and seen["decode_opts"] is None
    with pytest.raises(SystemExit):  # --decode is a SERVE option
        _run(p.parse_args(["BSP", "--decode"]), multihost=False)


def test_serve_defaults_to_supervised_recovery(monkeypatch, tmp_path):
    """tmlocal SERVE without --max-restarts must hand serve_main the
    serving default (2), not training's fail-fast 0 — otherwise one
    transient batch failure permanently loses the only replica."""
    import theanompi_tpu.serving.server as srv
    from theanompi_tpu.launcher import _run

    seen = {}

    def fake_serve_main(export_dir, **kw):
        seen.update(kw, export_dir=export_dir)
        return 0

    monkeypatch.setattr(srv, "serve_main", fake_serve_main)
    p = _build_parser(multihost=False)
    _run(p.parse_args(["SERVE", "--export-dir", str(tmp_path)]),
         multihost=False)
    assert seen["max_restarts"] == 2
    # an explicit value still wins
    _run(p.parse_args(["SERVE", "--export-dir", str(tmp_path),
                       "--max-restarts", "5"]), multihost=False)
    assert seen["max_restarts"] == 5


def test_parser_multihost_requires_coordination():
    p = _build_parser(multihost=True)
    with pytest.raises(SystemExit):  # --coordinator/--nhosts/--host-id
        p.parse_args(["BSP"])
    args = p.parse_args(["BSP", "--coordinator", "h0:1234",
                         "--nhosts", "2", "--host-id", "1"])
    assert args.coordinator == "h0:1234"
    assert (args.nhosts, args.host_id) == (2, 1)


def test_zoo_shortname_resolution():
    p = _build_parser(multihost=False)
    for shortname, (mod, cls) in MODEL_ZOO.items():
        args = p.parse_args(["BSP", "-m", shortname])
        assert _resolve_model(args) == (mod, cls)
    # explicit class overrides the zoo default
    args = p.parse_args(["BSP", "-m", "cifar10", "-c", "Other"])
    assert _resolve_model(args)[1] == "Other"


def test_custom_modelfile_requires_class():
    p = _build_parser(multihost=False)
    args = p.parse_args(["BSP", "-m", "my.custom.module"])
    with pytest.raises(SystemExit):
        _resolve_model(args)
    args = p.parse_args(["BSP", "-m", "my.custom.module", "-c", "MyModel"])
    assert _resolve_model(args) == ("my.custom.module", "MyModel")


def test_parallel_degree_flags():
    p = _build_parser(multihost=False)
    args = p.parse_args(["BSP", "--model-parallel", "4",
                         "--seq-parallel", "2"])
    assert (args.model_parallel, args.seq_parallel) == (4, 2)
    # async rules reject the BSP-only mesh flags
    from theanompi_tpu.launcher import tmlocal

    with pytest.raises(SystemExit, match="BSP options"):
        tmlocal(["EASGD", "-m", "tests._tiny_models", "-c", "TinyCifar",
                 "--model-parallel", "2"])


def test_local_aggregation_refusal_matrix():
    """--local-aggregation follows the --shards refusal matrix: GOSGD
    (whole-tree gossip, nothing to delta-sum) and BSP (in-step XLA
    collectives) refuse with a typed SystemExit instead of silently
    training at full wire cost."""
    from theanompi_tpu.launcher import tmlocal

    for rule in ("GOSGD", "BSP"):
        with pytest.raises(SystemExit,
                           match="local-aggregation applies to"):
            tmlocal([rule, "-m", "tests._tiny_models", "-c",
                     "TinyCifar", "--local-aggregation"])
    # EASGD/ASGD accept the flag (parse-level: it lands in kwargs)
    p = _build_parser(multihost=False)
    args = p.parse_args(["EASGD", "--local-aggregation"])
    assert args.local_aggregation is True
    assert p.parse_args(["ASGD"]).local_aggregation is False


@pytest.mark.slow
def test_tmlocal_tp_end_to_end(tmp_path, capsys):
    """tmlocal BSP --model-parallel: the TP model trains over a
    (data x model) mesh built by the rule from CLI flags alone."""
    from theanompi_tpu.launcher import tmlocal

    rc = tmlocal(["BSP", "-m", "transformer_lm_tp", "-D", "8",
                  "--model-parallel", "4", "--epochs", "1",
                  "--batch-size", "64", "--snapshot-dir", str(tmp_path)])
    assert rc == 0
    assert "final val:" in capsys.readouterr().out


def test_tmlocal_bsp_end_to_end(tmp_path, capsys):
    """The full CLI spine: tmlocal parses argv, applies config
    overrides, runs a 1-epoch BSP session on the CPU mesh and prints
    the final validation metrics."""
    from theanompi_tpu.launcher import tmlocal

    rc = tmlocal(["BSP", "-m", "tests._tiny_models", "-c", "TinyCifar",
                  "-D", "4", "--epochs", "1", "--batch-size", "16",
                  "--lr", "0.02", "--snapshot-dir", str(tmp_path)])
    assert rc == 0
    assert "final val:" in capsys.readouterr().out


def test_launcher_config_overrides_apply(tmp_path):
    """--batch-size/--lr/--snapshot-dir land in the model config (the
    reference's launcher forwarded per-model config the same way)."""
    from theanompi_tpu.rules import resolve_model_class

    cls = resolve_model_class("tests._tiny_models", "TinyCifar")
    cfg = dataclasses.replace(cls.default_config(), batch_size=32,
                              learning_rate=0.5,
                              snapshot_dir=str(tmp_path))
    assert cfg.batch_size == 32 and cfg.learning_rate == 0.5


def test_set_overrides_typed():
    from theanompi_tpu.launcher import _parse_config_sets

    out = _parse_config_sets([
        "optimizer=lars", "warmup_epochs=5", "lr_schedule=cosine",
        "momentum=0.95", "nesterov=true", "track_top5=0",
        "lr_decay_epochs=30,60,80", "data_dir=none",
    ])
    assert out == {"optimizer": "lars", "warmup_epochs": 5,
                   "lr_schedule": "cosine", "momentum": 0.95,
                   "nesterov": True, "track_top5": False,
                   "lr_decay_epochs": (30, 60, 80), "data_dir": None}


@pytest.mark.parametrize("bad,msg", [
    ("no_such_field=1", "unknown ModelConfig field"),
    ("warmup_epochs", "expects K=V"),
    ("nesterov=maybe", "expected a bool"),
    ("warmup_epochs=five", "expected a int"),
    ("batch_size=none", "expected a int"),   # none only for nullable
    ("nesterov=none", "expected a bool"),
])
def test_set_overrides_rejected(bad, msg):
    from theanompi_tpu.launcher import _parse_config_sets

    with pytest.raises(SystemExit, match=msg):
        _parse_config_sets([bad])
