"""Sequence-parallel transformer LM: trains end-to-end over a
(data x seq) mesh through the standard rule spine, and the (data x seq)
factorization is numerically equivalent to plain data parallelism."""

import jax
import numpy as np
import pytest

from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.models.transformer import TransformerLM
from theanompi_tpu.parallel.mesh import MeshSpec, make_training_mesh
from theanompi_tpu.utils.recorder import Recorder


def make_lm(mesh, seq_len=32, batch=4, seed=42):
    cfg = ModelConfig(batch_size=batch, n_epochs=1, learning_rate=0.5,
                      momentum=0.9, weight_decay=0.0, lr_schedule="constant",
                      print_freq=1000, seed=seed)
    return TransformerLM(config=cfg, mesh=mesh, vocab=32, seq_len=seq_len,
                         n_layers=2, d_model=32, n_heads=4)


@pytest.fixture(scope="module")
def dp_sp_mesh():
    return make_training_mesh(MeshSpec(data=2, seq=4), jax.devices()[:8])


class TestTransformerSP:
    @pytest.mark.slow  # convergence proof; the numeric contract is
    # test_dp_sp_equivalent_to_pure_dp below
    def test_learns_synthetic_grammar(self, dp_sp_mesh):
        m = make_lm(dp_sp_mesh)
        m.compile_iter_fns("avg")
        rec = Recorder(rank=1, size=8, print_freq=1000)
        m.begin_epoch(0)
        first = None
        for i in range(60):
            m.train_iter(i, rec)
            if i == 4:
                m._flush_metrics(rec)
                first = m.current_info["loss"]
        m._flush_metrics(rec)
        last = m.current_info["loss"]
        # ln(32) ≈ 3.47 at init; the 0.9-deterministic successor table
        # drives CE down fast once the table is learned
        assert first is not None and last < first - 0.5, (first, last)
        val = m.val_epoch(rec)
        assert val["error"] < 0.6
        m.cleanup()

    def test_dp_sp_equivalent_to_pure_dp(self):
        # same init, same global batch, no dropout: one train step over
        # (data=2, seq=4) must equal one over (data=8, seq=1)
        devs = jax.devices()[:8]
        mesh_sp = make_training_mesh(MeshSpec(data=2, seq=4), devs)
        mesh_dp = make_training_mesh(MeshSpec(data=8, seq=1), devs)

        results = []
        for mesh, batch in ((mesh_sp, 16), (mesh_dp, 4)):
            # per-shard batch sizes differ so the GLOBAL batch matches:
            # 16*2 == 4*8 == 32 sequences
            m = make_lm(mesh, batch=batch, seed=7)
            m.compile_iter_fns("avg")
            rec = Recorder(rank=1, size=8, print_freq=1000)
            m.begin_epoch(0)
            m.train_iter(0, rec)
            m._flush_metrics(rec)
            results.append(
                (jax.tree.map(np.asarray, m.state.params),
                 m.current_info["loss"]))
            m.cleanup()

        (p_sp, l_sp), (p_dp, l_dp) = results
        assert np.isclose(l_sp, l_dp, rtol=1e-4), (l_sp, l_dp)
        for a, b in zip(jax.tree.leaves(p_sp), jax.tree.leaves(p_dp)):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_zoo_entry_and_session(self, dp_sp_mesh, tmp_path):
        from theanompi_tpu.rules.bsp import run_bsp_session

        m = make_lm(dp_sp_mesh)
        m.config.snapshot_dir = str(tmp_path)
        out = run_bsp_session(m, max_epochs=1, checkpoint=True)
        assert out["epochs_run"] == 1
        assert np.isfinite(out["val"]["loss"])


def test_remat_identical_params_and_grads():
    """ModelConfig.remat: same param tree, same loss, same grads —
    only the backward's memory/recompute schedule changes."""
    from theanompi_tpu.models.transformer import TransformerLMNet

    kw = dict(vocab=16, n_layers=2, d_model=8, n_heads=2, d_ff=16,
              max_len=32)
    plain = TransformerLMNet(**kw, remat=False)
    remat = TransformerLMNet(**kw, remat=True)
    tokens = jax.random.randint(jax.random.key(0), (1, 8), 0, 16)
    vp = plain.init(jax.random.key(1), tokens, train=True)
    vr = remat.init(jax.random.key(1), tokens, train=True)
    assert jax.tree.structure(vp) == jax.tree.structure(vr)

    def loss(net, v):
        logits = net.apply(v, tokens, train=True)
        return (logits ** 2).mean()

    lp, gp = jax.jit(jax.value_and_grad(
        lambda v: loss(plain, v)))(vp)
    lr, gr = jax.jit(jax.value_and_grad(
        lambda v: loss(remat, v)))(vp)
    assert lp == pytest.approx(lr, rel=1e-6)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # integration compose; the remat contract itself is
# test_remat_identical_params_and_grads (fast)
def test_remat_trains_through_sp_spine(dp_sp_mesh):
    """remat composes with the (data x seq) ring-attention step."""
    cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.05,
                      print_freq=0, weight_decay=0.0, remat=True)
    m = TransformerLM(config=cfg, mesh=dp_sp_mesh, verbose=False,
                      n_layers=2, d_model=32, n_heads=4, seq_len=32)
    m.compile_iter_fns("avg")
    rec = Recorder(rank=0, size=8, print_freq=0)
    m.begin_epoch(0)
    for i in range(2):
        m.train_iter(i, rec)
    m._flush_metrics(rec)
    assert np.isfinite(rec.train_losses).all()
    m.cleanup()


def test_lm_declares_trained_flops(dp_sp_mesh):
    """The LM family reports achieved TFLOP/s like the CNN zoo: FLOPs
    per sequence = 6·n_active·L (2xMAC, fwd+bwd; embedding/positional
    tables excluded — gather + add, ~0 FLOPs) + the attention score/PV
    term 12·n_layers·L²·d, computed from the REAL param count so
    resized/TP models stay honest."""
    from jax import tree_util as jtu

    m = make_lm(dp_sp_mesh)
    flat = jtu.tree_flatten_with_path(m.state.params)[0]

    def is_table(path):
        keys = ({getattr(k, "key", None) for k in path}
                | {getattr(k, "name", None) for k in path})
        return bool(keys & {"embedding", "pos_emb"})

    active = sum(int(leaf.size) for p, leaf in flat if not is_table(p))
    total = sum(int(leaf.size) for _, leaf in flat)
    assert 0 < active < total  # the tables exist AND are excluded
    want = 6 * active * 32 + 12 * 2 * 32 * 32 * 32
    assert m.train_flops_per_sample == float(want)
    m.cleanup()


def test_lm_train_flops_discounts_experts():
    import jax.numpy as jnp

    from theanompi_tpu.models.transformer import _lm_train_flops

    params = {"dense": jnp.zeros((10,)), "experts": jnp.zeros((4, 5))}
    mask = {"dense": False, "experts": True}
    got = _lm_train_flops(params, n_layers=1, seq_len=2, d_model=3,
                          expert_mask=mask, n_experts=4)
    # top-1 routing: 20 expert weights count as 20/4 active per token
    want = 6 * (10 + 20 // 4) * 2 + 12 * 1 * 2 * 2 * 3
    assert got == float(want)


class TestSeqAxisRouting:
    """A size-1 seq axis must route attention through the fused local
    path, not a 1-hop ring that materializes the full (B,H,T,T) score
    matrix (the round-3 on-chip lm_b16_s2048 HBM OOM)."""

    def test_pure_dp_mesh_resolves_to_none(self):
        mesh = make_training_mesh(MeshSpec(data=8), jax.devices()[:8])
        m = make_lm(mesh)
        assert m._resolved_seq_axis() is None

    def test_sp_mesh_keeps_seq_axis(self, dp_sp_mesh):
        m = make_lm(dp_sp_mesh)
        assert m._resolved_seq_axis() == "seq"

    def test_pure_dp_never_calls_sequence_attention(self, monkeypatch):
        import theanompi_tpu.models.transformer as tr

        def boom(*a, **k):
            raise AssertionError("sequence_attention called on a "
                                 "size-1 seq axis")

        monkeypatch.setattr(tr, "sequence_attention", boom)
        mesh = make_training_mesh(MeshSpec(data=8), jax.devices()[:8])
        m = make_lm(mesh)
        m.compile_iter_fns("avg")
        rec = Recorder(rank=0, size=8, print_freq=1000)
        try:
            m.begin_epoch(0)
            m.train_iter(0, rec)   # would raise through trace if routed
        finally:
            m.cleanup()
