"""Optimizer zoo + LR schedule extensions (reference parity was
SGD+momentum only — SURVEY.md §2.8 layers lib 'SGD/momentum update
builders'; the zoo adds the families large-batch TPU recipes use)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.utils.helper_funcs import (
    OPTIMIZERS,
    build_optimizer,
    get_learning_rate,
    set_learning_rate,
)


@pytest.mark.parametrize("name", OPTIMIZERS)
def test_build_optimizer_updates_and_lr_mutable(name):
    """Every family: update() runs, moves params, and the lr is
    mutable in-place (the adjust_hyperp / remote-service contract)."""
    params = {"w": jnp.ones((4, 3)), "b": jnp.zeros(3)}
    tx = build_optimizer(0.1, optimizer=name, momentum=0.9,
                         weight_decay=1e-4)
    state = tx.init(params)
    grads = jax.tree.map(jnp.ones_like, params)
    updates, state = tx.update(grads, state, params)
    new_params = jax.tree.map(lambda p, u: p + u, params, updates)
    assert not np.allclose(np.asarray(new_params["w"]),
                           np.asarray(params["w"]))
    assert get_learning_rate(state) == pytest.approx(0.1)
    state = set_learning_rate(state, 0.01)
    assert get_learning_rate(state) == pytest.approx(0.01)


def test_unknown_optimizer_rejected():
    with pytest.raises(ValueError, match="unknown optimizer"):
        build_optimizer(0.1, optimizer="sgdm")


def test_adamw_decay_is_decoupled():
    """adamw applies decay directly to params (update == -lr*wd*p on
    zero grads), while adam's decay rides through the adaptive
    normalization — the magnitudes must differ accordingly."""
    params = {"w": jnp.full((4,), 2.0)}
    zeros = {"w": jnp.zeros((4,))}
    tx_w = build_optimizer(0.1, optimizer="adamw", weight_decay=0.01)
    up_w, _ = tx_w.update(zeros, tx_w.init(params), params)
    np.testing.assert_allclose(np.asarray(up_w["w"]),
                               -0.1 * 0.01 * 2.0, rtol=1e-6)
    # adam normalizes the decayed-grad signal, so its first update is
    # ~= -lr regardless of wd magnitude — NOT -lr*wd*p
    tx_a = build_optimizer(0.1, optimizer="adam", weight_decay=0.01)
    up_a, _ = tx_a.update(zeros, tx_a.init(params), params)
    assert abs(float(up_a["w"][0])) > 10 * abs(float(up_w["w"][0]))


class TestSchedules:
    def make(self, mesh8, **kw):
        from tests._tiny_models import TinyCifar

        cfg = ModelConfig(batch_size=2, print_freq=0, **kw)
        return TinyCifar(config=cfg, mesh=mesh8, verbose=False)

    def test_warmup_then_cosine(self, mesh8):
        m = self.make(mesh8, n_epochs=25, learning_rate=0.4,
                      lr_schedule="cosine", warmup_epochs=5)
        # linear ramp: (epoch+1)/warmup
        assert m.adjust_hyperp(0) == pytest.approx(0.4 / 5)
        assert m.adjust_hyperp(4) == pytest.approx(0.4)
        # cosine over the remaining 20 epochs
        assert m.adjust_hyperp(5) == pytest.approx(0.4)
        assert m.adjust_hyperp(15) == pytest.approx(0.2)
        assert m.adjust_hyperp(25) == pytest.approx(0.0, abs=1e-12)

    def test_warmup_applies_to_step_schedule_too(self, mesh8):
        m = self.make(mesh8, n_epochs=10, learning_rate=0.1,
                      lr_schedule="step", lr_decay_epochs=(6,),
                      lr_decay_factor=0.1, warmup_epochs=2)
        assert m.adjust_hyperp(0) == pytest.approx(0.05)
        assert m.adjust_hyperp(1) == pytest.approx(0.1)
        assert m.adjust_hyperp(2) == pytest.approx(0.1)
        assert m.adjust_hyperp(7) == pytest.approx(0.01)

    def test_model_trains_with_adamw(self, mesh8):
        """The zoo plugs into the BSP spine end to end."""
        from theanompi_tpu.utils.recorder import Recorder

        m = self.make(mesh8, n_epochs=1, learning_rate=1e-3,
                      optimizer="adamw", weight_decay=0.01)
        m.compile_iter_fns("avg")
        rec = Recorder(rank=0, size=8, print_freq=0)
        m.begin_epoch(0)
        for i in range(3):
            m.train_iter(i, rec)
        m._flush_metrics(rec)
        assert np.isfinite(rec.train_losses).all()
        # the remote-service wire format round-trips this optimizer
        rebuilt = build_optimizer(**m.optimizer_hyperparams())
        rebuilt.init(m_params := jax.tree.map(np.asarray,
                                              m.state.params))
        del m_params
        m.cleanup()


def test_label_smoothing_math():
    """eps-smoothed CE == (1-eps)*CE + eps*uniform-CE, exactly."""
    import jax

    from theanompi_tpu.models.layers import softmax_cross_entropy

    logits = jax.random.normal(jax.random.key(0), (8, 10))
    labels = jnp.arange(8) % 10
    eps = 0.1
    plain = softmax_cross_entropy(logits, labels)
    smooth = softmax_cross_entropy(logits, labels, eps)
    logp = jax.nn.log_softmax(logits)
    uniform_ce = -float(jnp.mean(logp))
    assert float(smooth) == pytest.approx(
        (1 - eps) * float(plain) + eps * uniform_ce, rel=1e-6)
    # smoothing=0 is exactly the plain loss (no perf/precision cost)
    assert float(softmax_cross_entropy(logits, labels, 0.0)) == float(plain)
