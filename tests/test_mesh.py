import numpy as np
import pytest

from theanompi_tpu.parallel import (
    AXIS_DATA,
    MeshSpec,
    data_axis_size,
    local_batch,
    make_training_mesh,
    shard_batch,
)


def test_data_mesh_shape(mesh8):
    assert data_axis_size(mesh8) == 8
    assert mesh8.shape[AXIS_DATA] == 8


def test_mesh_spec_degrees():
    d = MeshSpec(data=-1, model=2).degrees(8)
    assert d[AXIS_DATA] == 4 and d["model"] == 2
    with pytest.raises(ValueError):
        MeshSpec(data=3, model=3).degrees(8)


def test_mixed_axes_mesh(devices8):
    mesh = make_training_mesh(MeshSpec(data=4, model=2), devices8)
    assert mesh.shape[AXIS_DATA] == 4
    assert mesh.shape["model"] == 2


def test_local_batch(mesh8):
    assert local_batch(256, mesh8) == 32
    with pytest.raises(ValueError):
        local_batch(100, mesh8)


def test_shard_batch_places_on_mesh(mesh8):
    x = np.zeros((16, 3), np.float32)
    sx = shard_batch(x, mesh8)
    assert sx.sharding.spec == shard_batch(np.zeros((16,)), mesh8).sharding.spec
    # each device holds 2 rows
    shard_shapes = {s.data.shape for s in sx.addressable_shards}
    assert shard_shapes == {(2, 3)}
