"""Multi-host ASYNC deployment (VERDICT r2 #4): the docs/SCALING.md
"Async rules across hosts" recipe run verbatim as OS processes — one
``tmserver`` parameter service + two ``tmlocal GOSGD`` worker-group
processes sharing its gossip hub via ``--server-addr --session-id
--n-total-workers --rank-offset``.

Asserted: both groups converge, the gossip weight-sum invariant holds
ACROSS groups (sum over all 4 global ranks == 1), and a second session
displacing the store makes the first fail fast instead of silently
training against a stranger's hub.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import pytest

from theanompi_tpu.parallel.service import ServiceClient

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KEY = "test-multihost-async-key"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(devices: int) -> dict:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["THEANOMPI_TPU_SERVICE_KEY"] = KEY
    return env


@pytest.fixture()
def tmserver(monkeypatch):
    """A real tmserver process; yields its address."""
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_KEY", KEY)
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "theanompi_tpu.parallel.service",
         "--host", "127.0.0.1", "--port", str(port), "--platform", "cpu"],
        env=_env(1), stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    addr = f"127.0.0.1:{port}"
    deadline = time.monotonic() + 30
    while True:
        try:
            c = ServiceClient(addr)
            assert c.call("ping") == "pong"
            c.close()
            break
        except (ConnectionRefusedError, OSError):
            assert proc.poll() is None, (
                f"tmserver died:\n{proc.stdout.read().decode()[-2000:]}")
            assert time.monotonic() < deadline, "tmserver never came up"
            time.sleep(0.3)
    yield addr
    proc.kill()
    proc.wait()


def _worker_group(addr, session, rank_offset, tmp_path, tag,
                  epochs=8, extra=None):
    """One host's worker group: tmlocal GOSGD per the SCALING.md recipe
    (2 local workers of 4 global)."""
    out = os.path.join(tmp_path, f"result_{tag}.json")
    # Hyperparameters tuned for the STARVED gossip cadence of two OS
    # processes sharing ONE CPU core — the regime Blot et al.'s merge
    # (weighted average of peers) does NOT assume.  Findings from
    # tuning this, documented in docs/SCALING.md:
    # * stale momentum diverges: when a low-weight worker receives a
    #   high-weight push its params teleport to the sender's, and a
    #   momentum buffer built for the OLD params then drags it to
    #   divergence (observed: loss 5.3-9.4 vs 2.3 initial).  The
    #   default --merge-momentum scale fixes this (A/B: keep -> 5.9,
    #   scale -> 2.25-2.28 in this exact recipe), so momentum 0.9
    #   stays ON here and this test exercises the fix.
    # * p_push high: tighter coupling ≈ continuous averaging.
    cmd = [sys.executable, "-m", "theanompi_tpu.launcher", "GOSGD",
           "-m", "tests._tiny_models", "-c", "TinyCifar",
           "--platform", "cpu", "-D", "2",
           "--epochs", str(epochs), "--batch-size", "16", "--lr", "0.01",
           "--p-push", "0.9",
           "--server-addr", addr, "--session-id", session,
           "--n-total-workers", "4", "--rank-offset", str(rank_offset),
           "--snapshot-dir", os.path.join(tmp_path, f"snap_{tag}"),
           "--result-json", out] + (extra or [])
    proc = subprocess.Popen(cmd, env=_env(2), cwd=REPO_ROOT,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    return proc, out


@pytest.mark.slow
def test_gosgd_two_worker_groups_one_service(tmp_path, tmserver):
    pa, outa = _worker_group(tmserver, "run-a", 0, str(tmp_path), "a")
    pb, outb = _worker_group(tmserver, "run-a", 2, str(tmp_path), "b")
    try:
        logs = {}
        for tag, p in (("a", pa), ("b", pb)):
            stdout, _ = p.communicate(timeout=600)
            logs[tag] = stdout.decode()
            assert p.returncode == 0, (
                f"group {tag} failed (rc={p.returncode}):\n"
                f"{logs[tag][-4000:]}")
    finally:
        for p in (pa, pb):  # a failed assert must not orphan a trainer
            if p.poll() is None:
                p.kill()
                p.wait()
    ra = json.load(open(outa))
    rb = json.load(open(outb))
    # This test owns the DEPLOYMENT invariants.  It deliberately does
    # NOT assert a per-run accuracy bar: under 1-core scheduling the
    # gossip interleaving is chaotic — a group whose weight drains
    # early spends the run teleporting onto peers' params instead of
    # accumulating its own progress, and whether that happens is
    # scheduler luck (observed errors 0.66-0.93 across identical
    # configs).  Convergence is owned by the deterministic tests:
    # in-process GOSGD (test_async_rules), the exact remote-hub wire
    # arithmetic (test_service), and EASGD-over-DCN convergence with
    # the server in another process (test_service, slow).
    # (1) nobody diverged — the catastrophic stale-momentum failure
    #     mode reads 3.1-9.4 against the 2.303 random-net floor, while
    #     healthy runs transiently reach ~2.6 mid-teleport-chain
    assert ra["val"]["loss"] < 3.0 and rb["val"]["loss"] < 3.0
    # (2) gossip weight conservation ACROSS groups: each group starts
    #     at 2/4 = 0.5 total; halving pushes move weight between global
    #     ranks but the global sum over all 4 ranks must still be 1
    wa, wb = ra["weights"], rb["weights"]
    assert len(wa) == len(wb) == 2
    # 1e-5, not the in-process tests' 1e-6: ~900 float32 merge
    # roundings accumulate here (8 epochs x 32 iters x 4 workers
    # x p_push 0.9)
    assert sum(wa) + sum(wb) == pytest.approx(1.0, abs=1e-5)
    # (3) weight actually crossed the hub: each group's total share
    #     moved off its initial 0.5 (p_push=0.9 over 8x32 iterations
    #     x 4 workers, 2/3 of pushes cross-group — an untouched share
    #     is astronomically unlikely)
    assert abs(sum(wa) - 0.5) > 1e-6 and abs(sum(wb) - 0.5) > 1e-6


@pytest.mark.slow
def test_displaced_session_fails_fast_across_processes(tmp_path, tmserver):
    """SCALING.md trust/session model at the process level: a NEW
    session id re-creating the store must make the first session's
    worker processes fail loudly, not train against the new hub."""
    pa, _ = _worker_group(tmserver, "victim", 0, str(tmp_path), "victim",
                          epochs=50)
    pb = None
    try:
        # wait for an OBSERVABLE, not a clock: the `join` op succeeds
        # exactly once the victim's gosgd_init registered its session
        deadline = time.monotonic() + 180
        client = ServiceClient(tmserver)
        while True:
            try:
                client.call("join", "gosgd", "victim")
                break
            except RuntimeError:
                assert pa.poll() is None, (
                    f"victim died before registering:\n"
                    f"{pa.communicate()[0].decode()[-2000:]}")
                assert time.monotonic() < deadline, (
                    "victim never registered its session")
                time.sleep(0.5)
        client.close()
        pb, _ = _worker_group(tmserver, "usurper", 0, str(tmp_path),
                              "usurper", epochs=1)
        out_b, _ = pb.communicate(timeout=600)
        assert pb.returncode == 0, out_b.decode()[-4000:]
        out_a, _ = pa.communicate(timeout=600)
        assert pa.returncode != 0, (
            "victim kept training against a displaced session:\n"
            + out_a.decode()[-2000:])
        assert "displaced" in out_a.decode()
    finally:
        for p in (pa, pb):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
