"""Shared-memory wire lane (parallel/shm.py + wire v2, ISSUE 20).

The acceptance pins:

* **byte identity** — trees, RawArrays batches, ingest streams, and
  prefill→decode KV pages delivered over the shm lane are EXACTLY the
  in-band bytes (the lane ships leaves at their original dtype — no
  bf16 re-encode, no compression);
* **negotiation / silent fallback** — a remote peer, a legacy server,
  a disabled knob, and a grant whose arena then fails to allocate all
  degrade to plain in-band v2 with no caller-visible difference;
* **lease refusal matrix** — stale generation, double decref, foreign
  segment, and expired lease are TYPED refusals that ride the wire's
  ``("err", "ClassName: ...")`` discipline; the connection survives
  and the client disables its lane and retries in-band;
* **no leaked segments** — lease expiry sweeps, channel close, and
  the dead-owner orphan probe each reclaim everything (the conftest
  ``shm_segment_leak_guard`` enforces this for every test here);
* **AF_UNIX** — ``unix:/path`` addresses serve and connect on both
  RPC loops.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from theanompi_tpu import monitor
from theanompi_tpu.parallel import rpc, shm, wire
from theanompi_tpu.parallel.service import (
    RemoteEASGD,
    ServiceClient,
    serve,
)
from theanompi_tpu.parallel.server import EASGDServer
from theanompi_tpu.parallel.shards import ShardedEASGD, serve_shard

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _assert_bytes_equal(a, b, msg=""):
    fa, ta = jax.tree.flatten(a)
    fb, tb = jax.tree.flatten(b)
    assert ta == tb, f"treedef mismatch {msg}"
    for x, y in zip(fa, fb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype and x.shape == y.shape, msg
        assert x.tobytes() == y.tobytes(), msg


@pytest.fixture()
def shm_env(monkeypatch):
    """v2 wire + a low out-of-band threshold so the small test trees
    actually take the lane."""
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_KEY", "shm-test")
    monkeypatch.setenv("THEANOMPI_TPU_WIRE_PROTOCOL", "v2")
    monkeypatch.setenv("THEANOMPI_TPU_WIRE_SHM", "1")
    monkeypatch.setenv("THEANOMPI_TPU_SHM_MIN_BYTES", "1024")


def _big_tree(seed: int = 0) -> dict:
    """Leaves straddling the 1024-byte lane threshold: f32/f64/u8
    above it (out-of-band), an i32 and an empty leaf below (in-band)."""
    rng = np.random.default_rng(seed)
    return {"w": rng.standard_normal((64, 16)).astype(np.float32),
            "f64": rng.standard_normal((300,)),
            "px": rng.integers(0, 255, (40, 40), dtype=np.uint8),
            "step": np.arange(8, dtype=np.int32),
            "empty": np.zeros((0, 3), np.float32)}


# ---------------------------------------------------------------------------
# Arena + map_payload units (no sockets)
# ---------------------------------------------------------------------------


class TestArena:
    def test_alloc_put_map_decref_roundtrip(self, shm_env):
        a = shm.arena()
        payload = os.urandom(5000)
        lease = a.alloc(len(payload))
        assert lease is not None
        off = lease.put(payload)
        assert off is not None and off % 64 == 0
        m = shm.map_payload(lease.name, lease.generation)
        try:
            assert bytes(m[off:off + len(payload)]) == payload
        finally:
            m.close()
        a.decref(lease.name, lease.generation)
        assert a.outstanding() == 0
        # the ack proves the receiver is done -> the segment PARKS for
        # reuse instead of unlinking ...
        assert lease.name in shm.segment_names()
        # ... and the next same-size frame recycles it under a bumped
        # generation (steady state: one warm memcpy, no create cycle)
        lease2 = a.alloc(len(payload))
        assert lease2.name == lease.name
        assert lease2.generation > lease.generation
        # a reader holding the OLD generation's descriptor is refused
        with pytest.raises(shm.StaleGeneration):
            shm.map_payload(lease.name, lease.generation)
        a.decref(lease2.name, lease2.generation)
        # release_all unlinks parked segments too (test-fence path)
        a.release_all()
        assert lease.name not in shm.segment_names()

    def test_decref_refusal_matrix(self, shm_env):
        a = shm.arena()
        with pytest.raises(shm.ForeignSegment):
            a.decref(f"{shm.SEG_PREFIX}_999999_dead_1", 1)
        lease = a.alloc(100)
        with pytest.raises(shm.StaleGeneration):
            a.decref(lease.name, lease.generation + 7)
        a.decref(lease.name, lease.generation)
        with pytest.raises(shm.DoubleDecref):
            a.decref(lease.name, lease.generation)

    def test_map_refusal_matrix(self, shm_env):
        with pytest.raises(shm.ForeignSegment):
            shm.map_payload("not_a_lane_segment", 1)
        with pytest.raises(shm.LeaseExpired):
            shm.map_payload(f"{shm.SEG_PREFIX}_1_nothere_1", 1)
        # a lane-named file with no lane header: refused, not mapped
        bogus = f"{shm.SEG_PREFIX}_{os.getpid()}_bogus_1"
        path = os.path.join("/dev/shm", bogus)
        with open(path, "wb") as f:
            f.write(b"\0" * 128)
        try:
            with pytest.raises(shm.ForeignSegment, match="no lane header"):
                shm.map_payload(bogus, 1)
        finally:
            os.unlink(path)
        # wrong generation against a real segment
        lease = shm.arena().alloc(100)
        try:
            with pytest.raises(shm.StaleGeneration):
                shm.map_payload(lease.name, lease.generation + 1)
        finally:
            shm.arena().decref(lease.name, lease.generation)

    def test_lease_expiry_swept(self, shm_env, monkeypatch):
        monkeypatch.setenv("THEANOMPI_TPU_SHM_LEASE_S", "0.05")
        a = shm.arena()
        lease = a.alloc(100)
        name = lease.name
        time.sleep(0.1)
        assert a.sweep() >= 1
        assert a.outstanding() == 0
        assert name not in shm.segment_names()
        # the receiver-side read of the swept lease is the typed expiry
        with pytest.raises(shm.LeaseExpired):
            shm.map_payload(name, lease.generation)

    def test_alloc_cap_degrades_not_raises(self, shm_env, monkeypatch):
        monkeypatch.setenv("THEANOMPI_TPU_SHM_MAX_BYTES", "4096")
        assert shm.arena().alloc(1 << 20) is None

    def test_orphans_of_dead_owner_swept(self, shm_env):
        """The kill leg's cleanup: a subprocess leases a segment and is
        SIGKILLed mid-lease; the survivor's orphan probe reclaims it."""
        code = ("import os, sys, time\n"
                "sys.path.insert(0, %r)\n"
                "from theanompi_tpu.parallel import shm\n"
                "lease = shm.arena().alloc(4096)\n"
                "print(lease.name, flush=True)\n"
                "time.sleep(60)\n" % REPO_ROOT)
        p = subprocess.Popen([sys.executable, "-c", code],
                             stdout=subprocess.PIPE, text=True)
        try:
            name = p.stdout.readline().strip()
            assert name in shm.segment_names()
            p.kill()
            p.wait(timeout=10)
            deadline = time.monotonic() + 10
            while name in shm.segment_names():
                shm.sweep_orphans()
                assert time.monotonic() < deadline, \
                    f"orphan {name} survived the sweep"
                time.sleep(0.05)
        finally:
            p.kill()
            p.wait(timeout=10)


# ---------------------------------------------------------------------------
# Codec: out-of-band frames without sockets
# ---------------------------------------------------------------------------


def _lane_pair():
    """A negotiated connection's two endpoints, in-process: the hello
    really runs, so this covers offer → grant → channel construction."""
    offer = shm.client_offer()
    assert offer is not None
    server_ch, reply_grant = shm.server_grant(offer)
    assert server_ch is not None
    client_ch = shm.client_channel(offer, {"shm": reply_grant})
    assert client_ch is not None
    return (wire.WireOptions(allow_pickle=False, shm=client_ch),
            wire.WireOptions(allow_pickle=False, shm=server_ch))


class TestCodec:
    def test_roundtrip_byte_identical_and_acked(self, shm_env):
        send_opts, recv_opts = _lane_pair()
        tree = _big_tree()
        head, bufs, stats = wire.encode_frame(tree, send_opts)
        # the three >=1KiB leaves left the band; small ones stayed in
        assert stats._shm_oob == sum(
            tree[k].nbytes for k in ("w", "f64", "px"))
        assert len(bufs) == 2  # step + empty ship in-band
        back = wire.decode_frame(head, [bytes(b) for b in bufs],
                                 recv_opts)
        _assert_bytes_equal(back, tree)
        assert not back["w"].flags.writeable  # PROT_READ view
        # while the decoded views LIVE, no ack is queued: the sender
        # must not recycle the segment under them
        assert shm.arena().outstanding() == 1
        h_live, b_live, _ = wire.encode_frame(("ok", None), recv_opts)
        assert wire.decode_frame(h_live, b_live, send_opts) \
            == ("ok", None)
        assert shm.arena().outstanding() == 1
        # dropping the last view fires the decref; the ack piggybacks
        # on the receiver's next frame and the segment parks for reuse
        del back
        h2, b2, _ = wire.encode_frame(("ok", None), recv_opts)
        assert wire.decode_frame(h2, b2, send_opts) == ("ok", None)
        assert shm.arena().outstanding() == 0
        send_opts.shm.close()
        recv_opts.shm.close()

    def test_rawarrays_ride_the_lane(self, shm_env):
        send_opts, recv_opts = _lane_pair()
        x = np.arange(4096, dtype=np.uint8).reshape(64, 64) % 251
        y = np.arange(64, dtype=np.int64)
        head, bufs, stats = wire.encode_frame(
            ("batch", 3, wire.RawArrays(x, y)), send_opts)
        assert stats._shm_oob == x.nbytes  # y is under the threshold
        op, idx, (bx, by) = wire.decode_frame(head, bufs, recv_opts)
        assert (op, idx) == ("batch", 3)
        assert bx.tobytes() == x.tobytes() and bx.dtype == x.dtype
        assert by.tobytes() == y.tobytes() and by.dtype == y.dtype
        del bx, by  # release views; close() reclaims the lease
        send_opts.shm.close()
        recv_opts.shm.close()

    def test_oob_leaves_skip_bf16_rewrite(self, shm_env):
        """The lane ships ORIGINAL dtypes: under the bf16 wire dtype a
        lane-eligible f32 leaf still arrives byte-exact, while a small
        in-band f32 leaf pays the usual bf16 round trip."""
        offer = shm.client_offer()
        ch_s, grant = shm.server_grant(offer)
        ch_c = shm.client_channel(offer, {"shm": grant})
        send = wire.WireOptions(dtype="bf16", allow_pickle=False,
                                shm=ch_c)
        recv = wire.WireOptions(dtype="bf16", allow_pickle=False,
                                shm=ch_s)
        rng = np.random.default_rng(5)
        tree = {"big": rng.standard_normal(1000).astype(np.float32),
                "small": rng.standard_normal(17).astype(np.float32)}
        head, bufs, _ = wire.encode_frame(tree, send)
        back = wire.decode_frame(head, bufs, recv)
        assert back["big"].tobytes() == tree["big"].tobytes()
        assert back["small"].dtype == np.float32
        assert back["small"].tobytes() != tree["small"].tobytes()
        np.testing.assert_allclose(back["small"], tree["small"],
                                   rtol=2 ** -8)
        del back  # release views; close() reclaims the lease
        ch_c.close()
        ch_s.close()

    def test_refusals_without_negotiated_lane(self, shm_env):
        send_opts, _ = _lane_pair()
        head, bufs, _ = wire.encode_frame(_big_tree(), send_opts)
        plain = wire.WireOptions(allow_pickle=False)
        with pytest.raises(wire.ShmRefusal, match="no shm lane"):
            wire.decode_frame(head, bufs, plain)
        send_opts.shm.close()

    def test_descriptor_for_expired_lease_is_typed(self, shm_env,
                                                   monkeypatch):
        send_opts, recv_opts = _lane_pair()
        head, bufs, _ = wire.encode_frame(_big_tree(), send_opts)
        shm.release_all()  # the owner swept before the receiver mapped
        with pytest.raises(wire.ShmRefusal, match="LeaseExpired"):
            wire.decode_frame(head, bufs, recv_opts)
        send_opts.shm.close()
        recv_opts.shm.close()

    def test_foreign_and_double_acks_are_typed(self, shm_env):
        send_opts, recv_opts = _lane_pair()
        tree = _big_tree()
        head, bufs, _ = wire.encode_frame(tree, send_opts)
        back = wire.decode_frame(head, bufs, recv_opts)
        del back  # release the views -> the decref ack queues
        # replaying the SAME piggybacked ack is a DoubleDecref; an ack
        # for a segment this arena never leased is ForeignSegment
        with recv_opts.shm._lock:
            acks = [list(a) for a in recv_opts.shm._acks]
        assert acks, "view release queued no ack"
        h2, b2, _ = wire.encode_frame(("ok",), recv_opts)
        wire.decode_frame(h2, b2, send_opts)
        with recv_opts.shm._lock:
            recv_opts.shm._acks = list(acks)
        h3, b3, _ = wire.encode_frame(("ok",), recv_opts)
        with pytest.raises(wire.ShmRefusal, match="DoubleDecref"):
            wire.decode_frame(h3, b3, send_opts)
        with recv_opts.shm._lock:
            recv_opts.shm._acks = [[f"{shm.SEG_PREFIX}_1_x_1", 1]]
        h4, b4, _ = wire.encode_frame(("ok",), recv_opts)
        with pytest.raises(wire.ShmRefusal, match="ForeignSegment"):
            wire.decode_frame(h4, b4, send_opts)
        send_opts.shm.close()
        recv_opts.shm.close()

    def test_grant_then_alloc_failure_ships_in_band(self, shm_env,
                                                    monkeypatch):
        """The negotiated-but-broken case: the grant landed, then the
        arena cannot create a segment — every frame silently ships
        in-band, byte-identical."""
        send_opts, recv_opts = _lane_pair()
        monkeypatch.setattr(shm.Arena, "alloc",
                            lambda self, n: None)
        tree = _big_tree()
        head, bufs, stats = wire.encode_frame(tree, send_opts)
        assert getattr(stats, "_shm_oob", 0) == 0
        assert len(bufs) == len(jax.tree.flatten(tree)[0])
        _assert_bytes_equal(
            wire.decode_frame(head, bufs, recv_opts), tree)
        send_opts.shm.close()
        recv_opts.shm.close()

    def test_channel_close_releases_unacked_leases(self, shm_env):
        send_opts, recv_opts = _lane_pair()
        wire.encode_frame(_big_tree(), send_opts)  # never delivered
        assert shm.arena().outstanding() == 1
        send_opts.shm.close()
        assert shm.arena().outstanding() == 0
        recv_opts.shm.close()


# ---------------------------------------------------------------------------
# Negotiation matrix (hello level)
# ---------------------------------------------------------------------------


class TestNegotiation:
    def test_happy_path_grants_both_ends(self, shm_env):
        offer = shm.client_offer()
        payload = wire.hello_payload(wire.WireOptions(), shm_offer=offer)
        opts, reply, _ = wire.accept_hello(payload, allow_shm=True)
        assert opts.shm is not None and opts.shm.role == "server"
        assert reply["shm"]["granted"] is True
        ch = shm.client_channel(offer, reply)
        assert ch is not None and ch.role == "client"
        opts.shm.close()
        ch.close()

    def test_remote_peer_refused(self, shm_env):
        offer = dict(shm.client_offer(), boot_id="some-other-host")
        opts, reply, _ = wire.accept_hello(
            wire.hello_payload(wire.WireOptions(), shm_offer=offer),
            allow_shm=True)
        assert opts.shm is None and "shm" not in reply
        assert shm.client_channel(offer, reply) is None
        offer = dict(shm.client_offer(), uid=-1)
        opts, reply, _ = wire.accept_hello(
            wire.hello_payload(wire.WireOptions(), shm_offer=offer),
            allow_shm=True)
        assert opts.shm is None and "shm" not in reply

    def test_legacy_server_ignores_offer(self, shm_env):
        """allow_shm=False is the pre-lane accept path (and the
        per-connection threaded v1 fallback): the reply simply has no
        grant and the client stays in-band."""
        offer = shm.client_offer()
        opts, reply, _ = wire.accept_hello(
            wire.hello_payload(wire.WireOptions(), shm_offer=offer),
            allow_shm=False)
        assert opts.shm is None and "shm" not in reply
        assert shm.client_channel(offer, reply) is None

    def test_nonce_mismatch_refused_client_side(self, shm_env):
        offer = shm.client_offer()
        _, grant = shm.server_grant(dict(offer, nonce="replayed"))
        assert shm.client_channel(offer, {"shm": grant}) is None

    def test_disabled_knob_never_offers_or_grants(self, shm_env,
                                                  monkeypatch):
        monkeypatch.setenv("THEANOMPI_TPU_WIRE_SHM", "0")
        assert shm.client_offer() is None
        assert shm.server_grant({"boot_id": shm.boot_id(),
                                 "uid": os.getuid(),
                                 "nonce": "n"}) == (None, None)


# ---------------------------------------------------------------------------
# Service end-to-end (real sockets, both loops)
# ---------------------------------------------------------------------------


@pytest.fixture()
def local_service(shm_env, rpc_loop):
    port = _free_port()
    ready, stop = threading.Event(), threading.Event()
    t = threading.Thread(target=serve,
                         args=("127.0.0.1", port, ready, stop),
                         daemon=True)
    t.start()
    assert ready.wait(10)
    yield f"127.0.0.1:{port}"
    stop.set()
    try:
        ServiceClient(f"127.0.0.1:{port}").call("shutdown")
    except Exception:
        pass
    t.join(timeout=5)


class TestServiceE2E:
    def test_exchange_byte_identical_with_grant(self, local_service,
                                                tmp_path):
        """The headline pin: an EASGD exchange sequence over a granted
        lane is byte-identical to the in-process oracle, and the
        monitor proves the frames actually left the band."""
        with monitor.session(str(tmp_path)):
            tree = _big_tree(1)
            oracle = EASGDServer(tree, alpha=0.5)
            srv = RemoteEASGD(local_service, tree, alpha=0.5,
                              session_id="shm-e2e")
            try:
                assert srv.wire_protocol == "v2"
                for n in range(1, 4):
                    w = jax.tree.map(
                        lambda x: x + x.dtype.type(1) * n, tree)
                    _assert_bytes_equal(
                        srv.exchange(w),
                        jax.tree.map(np.asarray,
                                     jax.device_get(oracle.exchange(w))),
                        f"exchange {n}")
                _assert_bytes_equal(
                    srv.get_center(),
                    jax.tree.map(np.asarray,
                                 jax.device_get(oracle.get_center())),
                    "center")
            finally:
                srv.close()
            reg = monitor.registry()
            assert (reg.value("shm/grants_total", role="server")
                    or 0) >= 1
            assert (reg.value("shm/oob_bytes_total", dir="send")
                    or 0) > 0
            assert (reg.value("shm/oob_bytes_total", dir="recv")
                    or 0) > 0

    def test_refusal_disables_lane_and_call_survives(self,
                                                     local_service):
        """A typed ShmRefusal from the server (here: a poisoned
        piggybacked ack) must never surface to the caller — the client
        disables its lane, reconnects, and the SAME call succeeds
        in-band."""
        c = ServiceClient(local_service)
        try:
            c.call("ping")
            ch = c._wire.shm
            assert ch is not None  # the grant landed
            with ch._lock:
                ch._acks.append([f"{shm.SEG_PREFIX}_1_poison_1", 3])
            assert c.call("ping") == "pong"
            assert c._shm_on is False
            assert c._wire is None or c._wire.shm is None
            assert c.call("ping") == "pong"  # still in-band, still up
        finally:
            c.close()

    def test_forced_off_client_runs_in_band(self, local_service,
                                            monkeypatch):
        monkeypatch.setenv("THEANOMPI_TPU_WIRE_SHM", "0")
        tree = _big_tree(2)
        srv = RemoteEASGD(local_service, tree, alpha=0.5,
                          session_id="inband")
        try:
            assert srv.wire_protocol == "v2"
            _assert_bytes_equal(srv.get_center(), tree, "center")
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# Sharded K=2 + AF_UNIX + ingest + KV migration over the lane
# ---------------------------------------------------------------------------


def test_sharded_k2_byte_identical_over_lane(shm_env, rpc_loop):
    tree = _big_tree(3)
    oracle = EASGDServer(tree, alpha=0.5)
    fleet = []
    for i in range(2):
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(target=serve_shard,
                             args=("127.0.0.1", port, i, ready, stop),
                             daemon=True)
        t.start()
        assert ready.wait(10)
        fleet.append((f"127.0.0.1:{port}", stop, t))
    try:
        srv = ShardedEASGD([a for a, _, _ in fleet], tree, alpha=0.5,
                           session_id="shm-k2")
        try:
            for n in range(1, 4):
                w = jax.tree.map(lambda x: x + x.dtype.type(n), tree)
                _assert_bytes_equal(
                    srv.exchange(w),
                    jax.tree.map(np.asarray,
                                 jax.device_get(oracle.exchange(w))),
                    f"exchange {n} (K=2, shm)")
        finally:
            srv.close()
    finally:
        for addr, stop, t in fleet:
            stop.set()
            try:
                ServiceClient(addr).call("shutdown")
            except Exception:
                pass
            t.join(timeout=5)


@pytest.mark.parametrize("loop", ["threaded", "selector"])
def test_unix_address_serves_both_loops(shm_env, monkeypatch, tmp_path,
                                        loop):
    """``unix:/path`` through serve() and every client path: the
    listener binds the socket file, clients round-trip, and shutdown
    unlinks it."""
    if not rpc.have_af_unix():  # pragma: no cover - linux CI has it
        pytest.skip("no AF_UNIX on this platform")
    monkeypatch.setenv("THEANOMPI_TPU_RPC_LOOP", loop)
    path = str(tmp_path / "svc.sock")
    addr = f"{rpc.UNIX_PREFIX}{path}"
    ready, stop = threading.Event(), threading.Event()
    t = threading.Thread(target=serve, args=(addr, 0, ready, stop),
                         daemon=True)
    t.start()
    assert ready.wait(10)
    assert os.path.exists(path)
    tree = _big_tree(4)
    srv = RemoteEASGD(addr, tree, alpha=0.5, session_id="unix")
    try:
        assert srv.wire_protocol == "v2"
        _assert_bytes_equal(srv.get_center(), tree, "center over unix")
    finally:
        srv.close()
        stop.set()
        try:
            ServiceClient(addr).call("shutdown")
        except Exception:
            pass
        t.join(timeout=5)
    deadline = time.monotonic() + 5
    while os.path.exists(path) and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not os.path.exists(path), "shutdown left the socket file"


def test_ingest_stream_byte_identical_over_lane(shm_env, rpc_loop,
                                                tmp_path):
    """The ingest plane: a remote stream whose pixel batches ride the
    lane equals the in-process loader batch for batch."""
    from theanompi_tpu.data.imagenet import (
        ImageNet_data,
        prepare_imagenet_shards,
    )
    from theanompi_tpu.ingest.client import RemoteBatchSource
    from theanompi_tpu.ingest.reader import IngestReader, serve_reader

    d = str(tmp_path / "shards")
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, size=(200, 8, 8, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, size=200).astype(np.int64)
    prepare_imagenet_shards(imgs, labels, d, shard_size=100)
    dataset = ImageNet_data(data_dir=d, crop=8, seed=7,
                            augment_on_device=True)
    port = _free_port()
    reader = IngestReader(d, seed=7, reader_id=0)
    ready = threading.Event()
    t = threading.Thread(target=serve_reader,
                         args=("127.0.0.1", port, reader, ready),
                         daemon=True)
    t.start()
    assert ready.wait(30)
    addr = f"127.0.0.1:{port}"
    try:
        with monitor.session(str(tmp_path / "mon")):
            with RemoteBatchSource([addr], data=dataset, epoch=1,
                                   global_batch=32) as src:
                remote = list(src)
            local = list(dataset.train_batches(1, 32, 0, 1))
            assert len(remote) == len(local)
            for i, ((rx, ry), (lx, ly)) in enumerate(zip(remote, local)):
                assert rx.dtype == lx.dtype and np.array_equal(rx, lx), i
                assert ry.dtype == ly.dtype and np.array_equal(ry, ly), i
            reg = monitor.registry()
            assert (reg.value("shm/oob_bytes_total", dir="recv")
                    or 0) > 0
    finally:
        c = ServiceClient(addr)
        try:
            c.call("shutdown")
        except Exception:
            pass
        c.close()
        t.join(timeout=10)


@pytest.mark.slow
def test_prefill_to_decode_pages_over_lane(shm_env, tmp_path,
                                           monkeypatch):
    """The KV plane: prefill exports pages, the client receives them
    over the lane BYTE-identically, and the decode server adopts them
    into a stream equal to the uncached full-forward oracle."""
    import jax.numpy as jnp

    from theanompi_tpu.frontdoor import PrefillClient, PrefillServer
    from theanompi_tpu.frontdoor import prefill as prefill_mod
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.transformer import TransformerLM
    from theanompi_tpu.serving import (
        InferenceClient,
        InferenceServer,
        export_model,
    )
    from theanompi_tpu.serving import serve as serve_inference

    monkeypatch.setenv("THEANOMPI_TPU_SHM_MIN_BYTES", "256")
    cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                      compute_dtype="float32", optimizer="adamw",
                      learning_rate=1e-3, weight_decay=0.0,
                      lr_schedule="constant")
    model = TransformerLM(config=cfg, vocab=32, seq_len=16, n_layers=2,
                          d_model=16, n_heads=2, verbose=False)
    params = jax.device_get(model.state.params)
    export_dir = str(tmp_path / "export")
    export_model(model, export_dir, version=0)
    geo = dict(page_size=4, pages_per_seq=8, max_seqs=4,
               prefill_buckets=(8,))
    pre = PrefillServer(export_dir, model=model, max_pending=8, **geo)
    dec = InferenceServer(export_dir, replicas=1, reload_poll_s=0,
                          model=model, decode=True,
                          decode_opts=geo).start()
    sent = {}
    orig = pre.prefill

    def spy(prompt):
        man, raw = orig(prompt)
        sent["k"], sent["v"] = raw
        return man, raw

    pre.prefill = spy
    threads, stops, addrs = [], [], {}
    for name, target, obj in (("prefill", prefill_mod.serve, pre),
                              ("decode", serve_inference, dec)):
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(target=target,
                             args=(obj, "127.0.0.1", port, ready, stop),
                             daemon=True)
        t.start()
        assert ready.wait(30)
        threads.append(t)
        stops.append(stop)
        addrs[name] = f"127.0.0.1:{port}"
    try:
        with monitor.session(str(tmp_path / "mon")):
            prompt = np.asarray([3, 1, 4, 1, 5], np.int32)
            pc = PrefillClient(addrs["prefill"])
            try:
                man, k, v = pc.prefill(prompt)
            finally:
                pc.close()
            assert k.tobytes() == sent["k"].tobytes()
            assert v.tobytes() == sent["v"].tobytes()
            dc = InferenceClient(addrs["decode"])
            try:
                toks = dc.adopt(man, k, v, 6)
            finally:
                dc.close()
            cur, expect = [int(t) for t in prompt], []
            for _ in range(6):
                logits = np.asarray(model.module.apply(
                    {"params": params}, jnp.asarray([cur], jnp.int32),
                    train=False, seq_axis=None))
                tok = int(np.argmax(logits[0, -1]))
                expect.append(tok)
                cur.append(tok)
            assert list(toks) == expect
            reg = monitor.registry()
            assert (reg.value("shm/oob_bytes_total", dir="recv")
                    or 0) > 0
    finally:
        for stop in stops:
            stop.set()
        for name in ("prefill", "decode"):
            try:
                ServiceClient(addrs[name]).call("shutdown")
            except Exception:
                pass
        for t in threads:
            t.join(timeout=10)
        dec.stop()
