"""Unit tests for tools/analyze_xplane.py's pure aggregation core.

The round-4 verdict flagged that the tool shipped untested despite its
docstring promising the aggregation "unit-tests without tensorflow"
(weak #2), and that ``conv_spatial_bucket`` labelled weight-gradient
convs by their *kernel* shape (first-regex-match), mis-attributing ~8%
of the step (weak #3).  These tests pin the fixed behavior on synthetic
event dicts — no tensorflow, no proto.
"""

from __future__ import annotations

import os
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

from analyze_xplane import (  # noqa: E402
    SUB_RESOLUTION_MS,
    aggregate,
    conv_spatial_bucket,
    pick_n_steps,
    roofline,
)

# Shape/layout text mimicking real v5e HLO from the r3 capture
# (artifacts/tpu_trace): batch 128, NHWC activations, HWIO kernels.
FPROP = ("%convert_reduce_fusion.34 = (f32[64]{0}, f32[64]{0}, "
         "bf16[128,56,56,64]{0,3,2,1:T(8,128)(2,1)}) fusion("
         "bf16[128,56,56,64]{0,3,2,1:T(8,128)(2,1)S(1)} %fusion.2003, "
         "f32[1,1,64,64]{3,2,1,0:T(8,128)S(1)} %copy-done.171), "
         "kind=kOutput, calls=%fused_computation.271")
WGRAD = ("%copy_add_fusion = bf16[7,7,3,64]{3,1,2,0:T(8,128)(2,1)} "
         "fusion(bf16[128,224,224,3]{0,2,3,1} %a, "
         "bf16[128,112,112,64]{0,3,2,1} %b), kind=kOutput, "
         "calls=%fused_computation.9")
DGRAD = ("%fusion.99 = (f32[256]{0}, f32[256]{0}, "
         "bf16[128,56,56,256]{3,0,2,1}) fusion("
         "bf16[128,28,28,512]{3,0,2,1} %g, "
         "bf16[3,3,256,512]{3,2,1,0} %k), kind=kOutput, calls=%fc.1")


class TestConvSpatialBucket:
    def test_fprop_buckets_by_activation(self):
        # input act 56x56x64 (max spatial among batch-led shapes)
        assert conv_spatial_bucket(FPROP, "jit(s)/jvp(ResNet)/Conv_0/"
                                   "conv_general_dilated:") == "56x56x64:fprop"

    def test_wgrad_not_labelled_by_kernel_shape(self):
        # r4 bug: first 4-D shape is the kernel-grad [7,7,3,64] ->
        # bucket "7x3x64".  Fixed: bucket by the streamed activation
        # (224x224x3), kind wgrad because no output shape is batch-led.
        b = conv_spatial_bucket(
            WGRAD, "jit(s)/transpose(jvp(ResNet))/conv_general_dilated:")
        assert b == "224x224x3:wgrad"

    def test_dgrad_from_transpose_path(self):
        b = conv_spatial_bucket(
            DGRAD, "jit(s)/transpose(jvp(ResNet))/conv_general_dilated:")
        assert b == "56x56x256:dgrad"

    def test_no_tf_op_defaults_to_fprop(self):
        assert conv_spatial_bucket(FPROP).endswith(":fprop")

    def test_no_4d_shape_is_other(self):
        assert conv_spatial_bucket("%r = f32[128]{0} fusion(f32[128] %x)") \
            == "other"

    def test_kernel_only_text_falls_back_to_first_shape(self):
        # pathological: only the kernel appears; batch = modal dim (7)
        b = conv_spatial_bucket("%k = bf16[7,7,3,64]{3,1,2,0} copy(...)")
        assert b == "7x3x64:fprop"


def _ev(name, cat, dur_ms, flops=0, nbytes=0, tf_op="", display=None):
    return {"name": name, "display": display or name.split(" ")[0],
            "category": cat, "dur_ps": int(dur_ms * 1e9),
            "flops": flops, "bytes": nbytes, "tf_op": tf_op}


class TestAggregate:
    def test_bucket_table_sums_to_conv_total(self):
        tfo = "jit(s)/transpose(jvp(R))/conv_general_dilated:"
        events = [
            _ev(FPROP, "convolution fusion", 2.0, flops=4e9, nbytes=1e8),
            _ev(WGRAD, "convolution fusion", 1.0, flops=1e9, nbytes=5e7,
                tf_op=tfo),
            _ev(DGRAD, "convolution fusion", 1.5, flops=2e9, nbytes=8e7,
                tf_op=tfo),
            _ev("%add = bf16[128,56,56,256]{3,0,2,1} fusion(...)",
                "loop fusion", 0.9, nbytes=6e8),
        ]
        rep = aggregate(events, n_steps=1)
        conv_ms = rep["categories"]["convolution fusion"]["ms_per_step"]
        bucket_ms = sum(b["ms_per_step"]
                        for b in rep["conv_buckets"].values())
        assert conv_ms == pytest.approx(4.5, abs=1e-6)
        assert bucket_ms == pytest.approx(conv_ms, abs=1e-3)
        assert set(rep["conv_buckets"]) == {
            "56x56x64:fprop", "224x224x3:wgrad", "56x56x256:dgrad"}

    def test_per_step_normalisation(self):
        events = [_ev(FPROP, "convolution fusion", 4.0, flops=8e9)
                  for _ in range(3)]
        rep = aggregate(events, n_steps=2)
        c = rep["categories"]["convolution fusion"]
        assert c["ms_per_step"] == pytest.approx(6.0)
        assert c["events_per_step"] == 1  # 3 // 2
        assert rep["totals"]["device_busy_ms_per_step"] == pytest.approx(6.0)

    def test_measured_rates(self):
        # 1 ms at 1e11 flops and 8e8 bytes -> 100 TF/s, 800 GB/s
        rep = aggregate([_ev(FPROP, "convolution fusion", 1.0,
                             flops=1e11, nbytes=8e8)], n_steps=1)
        c = rep["categories"]["convolution fusion"]
        assert c["tflops_per_s"] == pytest.approx(100.0)
        assert c["gbytes_per_s"] == pytest.approx(800.0)

    def test_sub_resolution_rates_suppressed(self):
        # r4 account printed 5.77e6 GB/s for a 1 us async-start row
        dur = SUB_RESOLUTION_MS / 50
        rep = aggregate([_ev("%as = ... async-start(...)", "async-start",
                             dur, nbytes=6e9)], n_steps=1)
        c = rep["categories"]["async-start"]
        assert c["rates_unreliable"] is True
        assert c["gbytes_per_s"] == 0.0 and c["tflops_per_s"] == 0.0


class TestRoofline:
    def test_bandwidth_bound_slice(self):
        rep = aggregate([_ev(FPROP, "convolution fusion", 1.0,
                             flops=8e10, nbytes=7.5e8)], n_steps=1)
        rl = roofline(rep, peak_tflops=200.0, peak_hbm_gbps=800.0)
        r = rl["convolution fusion"]
        assert r["hbm_fraction"] == pytest.approx(0.938, abs=1e-3)
        assert r["mxu_fraction"] == pytest.approx(0.4)
        # ceiling = tfs / hbm_fraction = 80 / 0.9375
        assert r["hbm_implied_tflops_ceiling"] == pytest.approx(85.3,
                                                               abs=0.1)

    def test_accounting_artifact_guard(self):
        # 3270 GB/s against an 819 GB/s chip is bookkeeping, not HBM
        rep = aggregate([_ev("%ad = ...", "async-done", 0.6,
                             nbytes=2e9)], n_steps=1)
        rl = roofline(rep, 200.0, 819.0)
        r = rl["async-done"]
        assert r["accounting_artifact"] is True
        assert r["hbm_implied_tflops_ceiling"] is None

    def test_unreliable_rows_skipped(self):
        rep = aggregate([_ev("%x = ...", "copy-start", 0.001,
                             nbytes=5e8)], n_steps=1)
        rl = roofline(rep, 200.0, 819.0)
        assert rl["copy-start"]["rates_unreliable"] is True
        assert rl["copy-start"]["hbm_fraction"] is None


from fusion_deepdive import (  # noqa: E402
    copy_size_class,
    deepdive,
    shrink_tf_op,
)


class TestDeepdive:
    def test_copy_size_classes(self):
        assert copy_size_class(
            "%cd = f32[256]{0} copy-done((f32[256]{0:T(256)}, "
            "f32[256]{0:T(256)S(1)}, u32[]) %cs)") == "param_vec"
        assert copy_size_class(
            "%cd = f32[3,3,256,256]{3,2,1,0} copy-done(("
            "f32[3,3,256,256]{3,2,1,0}, f32[3,3,256,256]{3,2,1,0:S(1)},"
            " u32[]) %cs)") == "kernel"
        assert copy_size_class(
            "%cd = bf16[128,224,224,3]{0,2,3,1} copy-done(("
            "bf16[128,224,224,3]{0,2,3,1}, bf16[128,224,224,3]{0,2,3,1}"
            ", u32[]) %cs)") == "activation"
        assert copy_size_class("no copy here") == "unknown"

    def test_shrink_tf_op(self):
        assert shrink_tf_op(
            "jit(shard_step)/jvp(ResNet)/BottleneckBlock_1/add:") \
            == "fwd/ResNet/BottleneckBlock_1/add"
        assert shrink_tf_op(
            "jit(shard_step)/transpose(jvp(ResNet))/stem_bn/"
            "reduce_sum:") == "bwd/ResNet/stem_bn/reduce_sum"

    def test_deepdive_totals(self):
        add = _ev("%f = bf16[128,56,56,256]{3,0,2,1} fusion("
                  "bf16[128,56,56,256] %a, bf16[128,56,56,256] %b), "
                  "kind=kLoop", "loop fusion", 0.9, nbytes=6e8,
                  tf_op="jit(s)/jvp(ResNet)/BottleneckBlock_0/add:")
        cp = _ev("%cd = f32[64]{0} copy-done((f32[64]{0}, "
                 "f32[64]{0:S(1)}, u32[]) %cs)", "copy-done", 0.0012)
        rep = deepdive([add, cp], n_steps=1, peak_hbm_gbps=819.0)
        assert rep["loop_fusion_total_ms"] == pytest.approx(0.9)
        assert rep["copy_done_total_ms"] == pytest.approx(0.001, abs=1e-3)
        row = rep["loop_fusions_by_source_op"][0]
        assert row["key"].startswith("fwd/ResNet/BottleneckBlock_0/add")
        assert row["hbm_fraction"] == pytest.approx(6e8 / 0.0009 / 1e9
                                                    / 819.0, abs=1e-3)
        assert rep["copy_done_by_size_class"][0]["key"] == "param_vec"


from analyze_xplane import attribute_copies, copy_endpoints  # noqa: E402

# real v5e copy-done text shapes from the r3 capture: a param-vector
# prefetch INTO the alternate memory space (dest S(1)), a big
# activation written back OUT of it (src S(1)), and a space-less move
CD_PREFETCH = ("%copy-done.1261 = f32[64]{0:T(128)S(1)} copy-done(("
               "f32[64]{0:T(128)S(1)}, f32[64]{0:T(128)}, u32[]{:S(2)})"
               " %copy-start.1261)")
CD_WRITEBACK = ("%copy-done.27 = bf16[128,224,224,3]{0,2,3,1:T(8,128)"
                "(2,1)} copy-done((bf16[128,224,224,3]{0,2,3,1:T(8,128)"
                "(2,1)}, bf16[128,224,224,3]{0,2,3,1:T(8,128)(2,1)S(1)}"
                ", u32[]{:S(2)}) %copy-start.27)")
CD_MOVE = ("%copy-done.9 = s32[128]{0:T(128)} copy-done((s32[128]"
           "{0:T(128)}, s32[128]{0:T(128)}, u32[]{:S(2)}) "
           "%copy-start.9)")


class TestCopyAttribution:
    def test_endpoints_direction_and_bytes(self):
        d, shape, _lay, nbytes = copy_endpoints(CD_PREFETCH)
        assert (d, shape, nbytes) == ("prefetch", "f32[64]", 256)
        d, shape, _lay, nbytes = copy_endpoints(CD_WRITEBACK)
        assert d == "writeback" and shape == "bf16[128,224,224,3]"
        assert nbytes == 128 * 224 * 224 * 3 * 2
        assert copy_endpoints(CD_MOVE)[0] == "move"
        assert copy_endpoints("%f = f32[8]{0} fusion(...)")[0] \
            == "unknown"

    def test_attribution_rows_and_totals(self):
        events = [
            _ev(CD_PREFETCH, "copy-done", 0.002) for _ in range(6)
        ] + [
            _ev(CD_WRITEBACK, "copy-done", 0.4),
            _ev(CD_MOVE, "copy-done", 0.01),
            _ev("%cs = ... copy-start(...)", "copy-start", 0.001),
            _ev(FPROP, "convolution fusion", 2.0),   # ignored
        ]
        rep = attribute_copies(events, n_steps=2)
        assert rep["copy_done_events_per_step"] == 4  # 8 // 2
        assert rep["copy_done_ms_per_step"] == pytest.approx(
            (6 * 0.002 + 0.4 + 0.01) / 2, abs=1e-6)
        assert rep["copy_start_events_per_step"] == 0  # 1 // 2
        top = rep["rows"][0]
        assert top["producer"] == \
            "writeback:activation:bf16[128,224,224,3]"
        assert top["ms_per_step"] == pytest.approx(0.2)
        assert top["pct_of_copy_done"] == pytest.approx(
            100 * 0.4 / 0.422, abs=0.1)
        by_key = {r["producer"]: r for r in rep["rows"]}
        pv = by_key["prefetch:param_vec:f32[64]"]
        assert pv["events_per_step"] == 3
        assert pv["us_per_event"] == pytest.approx(2.0)
        assert "move:param_vec:s32[128]" in by_key

    def test_empty_capture(self):
        rep = attribute_copies([], n_steps=1)
        assert rep["rows"] == [] and rep["copy_done_ms_per_step"] == 0


from xla_sweep import SWEEPS, ab_report, build_entries  # noqa: E402


class TestXlaSweep:
    def test_entries_are_queue_ready(self):
        entries = build_entries()
        names = [e[0] for e in entries]
        # flags x models throughput points + the A/B profile pair
        assert "sweep_resnet_k4_b128_lhs" in names
        assert "resnet_ab_before_profile" in names
        assert "resnet_ab_after_fused_profile" in names
        for name, argv, timeout in entries:
            assert isinstance(name, str) and isinstance(timeout, int)
            assert isinstance(argv, list) and len(argv) >= 2
        ab = dict((e[0], e[1]) for e in entries)
        after = ab["resnet_ab_after_fused_profile"]
        assert "--bn-act-impl" in after and "pallas" in after
        before = ab["resnet_ab_before_profile"]
        assert "--bn-act-impl" not in before
        # every non-base sweep entry carries its flags
        lhs = ab["sweep_resnet_k4_b128_lhs"]
        assert "--xla-flags" in lhs
        assert SWEEPS["lhs"] in lhs

    def test_entries_respect_config_override(self):
        entries = build_entries(sweeps={"only": "--xla_foo=1"})
        names = [e[0] for e in entries]
        assert "sweep_resnet_k4_b128_only" in names
        assert not any("_lhs" in n for n in names)

    def test_ab_report_deltas(self):
        def account(conv, copy, copy_rows):
            return {
                "report": {
                    "totals": {"device_busy_ms_per_step": conv + copy},
                    "categories": {
                        "convolution fusion": {
                            "ms_per_step": conv, "events_per_step": 10},
                        "copy-done": {
                            "ms_per_step": copy,
                            "events_per_step": 100},
                    },
                },
                "copy_attribution": {
                    "copy_done_ms_per_step": copy,
                    "rows": [
                        {"producer": k, "ms_per_step": v}
                        for k, v in copy_rows.items()],
                },
            }

        before = account(36.9, 2.4, {"prefetch:param_vec:f32[64]": 1.4,
                                     "writeback:activation:x": 1.0})
        after = account(36.9, 1.5, {"prefetch:param_vec:f32[64]": 1.4,
                                    "writeback:activation:x": 0.1})
        rep = ab_report(before, after)
        assert rep["totals"]["delta_ms"] == pytest.approx(-0.9)
        assert rep["categories"]["copy-done"]["delta_ms"] == \
            pytest.approx(-0.9)
        assert rep["categories"]["convolution fusion"]["delta_ms"] == 0
        assert rep["copy_producers"]["writeback:activation:x"][
            "delta_ms"] == pytest.approx(-0.9)
        assert rep["copy_totals"]["delta_ms"] == pytest.approx(-0.9)

    def test_ab_report_accepts_bare_reports(self):
        bare = {"totals": {"device_busy_ms_per_step": 10.0},
                "categories": {"loop fusion": {"ms_per_step": 5.0}}}
        rep = ab_report(bare, bare)
        assert rep["totals"]["delta_ms"] == 0.0
        assert "copy_producers" not in rep


class TestPickNSteps:
    def test_prefers_xla_modules(self):
        assert pick_n_steps({"XLA Modules": 5, "Steps": 7}) == 5

    def test_falls_back_to_steps(self):
        assert pick_n_steps({"XLA Modules": 0, "Steps": 7}) == 7
        assert pick_n_steps({"Steps": 7}) == 7

    def test_warns_and_returns_one_when_absent(self, capsys):
        assert pick_n_steps({"XLA Ops": 100}) == 1
        assert "WARNING" in capsys.readouterr().err
