"""Sequence/context parallelism: ring / all-gather / ulysses attention
sharded over the 'seq' mesh axis must match single-device attention —
values AND gradients — on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.mesh import AXIS_SEQ, MeshSpec, make_training_mesh
from theanompi_tpu.parallel.sequence import (
    attention_reference,
    sequence_attention,
)

B, T, H, D = 2, 32, 8, 16      # T shards 8 ways -> T_local = 4


@pytest.fixture(scope="module")
def seq_mesh():
    devs = jax.devices()[:8]
    return make_training_mesh(MeshSpec(data=1, seq=8), devs)


def make_qkv(seed=0):
    rng = np.random.RandomState(seed)
    return tuple(
        jnp.asarray(rng.randn(B, T, H, D).astype(np.float32)) * 0.3
        for _ in range(3)
    )


def sharded_attn(mesh, strategy, causal):
    spec = P(None, AXIS_SEQ, None, None)

    def fn(q, k, v):
        return sequence_attention(q, k, v, causal=causal, strategy=strategy)

    return jax.jit(jax.shard_map(fn, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec, check_vma=False))


@pytest.mark.parametrize("strategy", ["ring", "allgather", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_reference(seq_mesh, strategy, causal):
    q, k, v = make_qkv()
    want = attention_reference(q, k, v, causal=causal)
    got = sharded_attn(seq_mesh, strategy, causal)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("strategy", ["ring", "allgather", "ulysses"])
def test_gradients_match_reference(seq_mesh, strategy):
    q, k, v = make_qkv(1)
    ct = jnp.asarray(np.random.RandomState(2).randn(B, T, H, D)
                     .astype(np.float32))

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=True) * ct).sum()

    attn = sharded_attn(seq_mesh, strategy, causal=True)

    def loss_sp(q, k, v):
        return (attn(q, k, v) * ct).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_sp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-6)


def test_ulysses_rejects_bad_heads(seq_mesh):
    q = k = v = jnp.zeros((1, 16, 6, 4))  # 6 heads not divisible by 8
    attn = sharded_attn(seq_mesh, "ulysses", causal=False)
    with pytest.raises(ValueError, match="divisible"):
        attn(q, k, v)


def test_unknown_strategy_rejected():
    with pytest.raises(ValueError, match="unknown sequence-parallel"):
        sequence_attention(jnp.zeros((1, 4, 2, 2)), jnp.zeros((1, 4, 2, 2)),
                           jnp.zeros((1, 4, 2, 2)), strategy="nccl")


def test_ring_long_context_memory_shape(seq_mesh):
    # the point of the ring: per-device K/V residency is T/n — check
    # the op runs at a T where full T x T scores per device would be
    # 8x the blockwise working set (smoke, not a memory assertion)
    rng = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rng.randn(1, 256, 4, 8).astype(np.float32))
               for _ in range(3))
    got = sharded_attn(seq_mesh, "ring", causal=True)(q, k, v)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)
