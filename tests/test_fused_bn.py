"""ops/fused_bn + layers.BatchNormAct/BiasAct: the fused
scale-bias(-residual)-ReLU epilogue (ISSUE 3 tentpole), oracle-tested
in interpret mode against the unfused XLA reference path — forward AND
gradient — so correctness is provable without the tunnel.

Three layers of contract:
- kernel vs jnp fallback (scale_bias_act impl='pallas' vs 'xla');
- BatchNormAct impl='xla' BIT-IDENTICAL to flax nn.BatchNorm (+relu /
  +residual-add) including running-stat updates — the default path is
  numerically unchanged by this refactor;
- the model seam: ResNet/VGG/GoogLeNet built with
  ModelConfig.bn_act_impl='pallas' match their 'xla' builds end to end
  (same params, tolerance for the folded-affine association).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.models import layers as L
from theanompi_tpu.ops.fused_bn import scale_bias_act


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.key(key), shape, dtype)


class TestScaleBiasActKernel:
    @pytest.mark.parametrize("shape,dtype", [
        ((2, 7, 5, 16), jnp.float32),       # ragged rows vs tile
        ((3, 4, 4, 130), jnp.float32),      # C not lane-aligned
        ((2, 8, 8, 32), jnp.bfloat16),      # compute dtype of the zoo
    ])
    @pytest.mark.parametrize("with_res", [False, True])
    def test_fwd_and_grad_match_xla(self, shape, dtype, with_res):
        c = shape[-1]
        x = _rand(0, shape, dtype)
        s = _rand(1, (c,))
        b = _rand(2, (c,))
        res = _rand(3, shape, dtype) if with_res else None
        bf16 = dtype == jnp.bfloat16
        ref = scale_bias_act(x, s, b, res, act="relu", impl="xla")
        got = scale_bias_act(x, s, b, res, act="relu", impl="pallas")
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(ref, np.float32),
            rtol=2e-2 if bf16 else 1e-6, atol=1e-6)

        def loss(impl):
            def f(*args):
                y = scale_bias_act(args[0], args[1], args[2],
                                   args[3] if with_res else None,
                                   act="relu", impl=impl)
                return (y.astype(jnp.float32) ** 2).sum()
            return f

        args = (x, s, b) + ((res,) if with_res else ())
        nums = tuple(range(len(args)))
        gr = jax.grad(loss("xla"), argnums=nums)(*args)
        gp = jax.grad(loss("pallas"), argnums=nums)(*args)
        for a, g in zip(gr, gp):
            np.testing.assert_allclose(
                np.asarray(g, np.float32), np.asarray(a, np.float32),
                rtol=2e-2 if bf16 else 1e-5,
                atol=1e-3 if bf16 else 1e-5)

    def test_act_none_is_affine(self):
        x = _rand(5, (2, 6, 6, 24))
        y = scale_bias_act(x, jnp.ones(24), jnp.zeros(24), act=None,
                           impl="pallas")
        np.testing.assert_allclose(np.asarray(y), np.asarray(x),
                                   rtol=1e-6)

    def test_relu_grad_zero_at_negative(self):
        # the mask must be computed from z = x*s+b, not from x
        x = jnp.full((1, 1, 1, 8), 2.0)
        s = jnp.full((8,), -1.0)
        b = jnp.zeros(8)
        for impl in ("xla", "pallas"):
            g = jax.grad(lambda x: scale_bias_act(
                x, s, b, act="relu", impl=impl).sum())(x)
            np.testing.assert_array_equal(np.asarray(g),
                                          np.zeros_like(np.asarray(g)))

    def test_jit_composes(self):
        x = _rand(6, (2, 8, 8, 16))
        s, b = _rand(7, (16,)), _rand(8, (16,))
        np.testing.assert_allclose(
            np.asarray(jax.jit(lambda x: scale_bias_act(
                x, s, b, act="relu", impl="pallas"))(x)),
            np.asarray(scale_bias_act(x, s, b, act="relu", impl="xla")),
            rtol=1e-6, atol=1e-6)

    def test_validation(self):
        x = _rand(9, (2, 4, 4, 8))
        with pytest.raises(ValueError, match="unknown act"):
            scale_bias_act(x, jnp.ones(8), jnp.zeros(8), act="gelu")
        with pytest.raises(ValueError, match="channel vectors"):
            scale_bias_act(x, jnp.ones(4), jnp.zeros(8))
        with pytest.raises(ValueError, match="residual"):
            scale_bias_act(x, jnp.ones(8), jnp.zeros(8),
                           residual=jnp.zeros((2, 4, 4, 4)))
        with pytest.raises(ValueError, match="unknown impl"):
            scale_bias_act(x, jnp.ones(8), jnp.zeros(8), impl="cudnn")


class _FlaxRef(nn.Module):
    """The pre-seam composition: nn.BatchNorm -> (+res) -> relu."""

    dtype: jnp.dtype = jnp.float32
    act: bool = True

    @nn.compact
    def __call__(self, x, residual=None, train=True):
        y = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype)(x)
        if residual is not None:
            y = y + residual
        return nn.relu(y) if self.act else y


class _ActMod(nn.Module):
    dtype: jnp.dtype = jnp.float32
    act: str | None = "relu"
    impl: str = "xla"

    @nn.compact
    def __call__(self, x, residual=None, train=True):
        # name pinned exactly like the models do, so variables from
        # the _FlaxRef module load unchanged
        return L.BatchNormAct(use_running_average=not train,
                              momentum=0.9, epsilon=1e-5,
                              dtype=self.dtype, act=self.act,
                              impl=self.impl,
                              name="BatchNorm_0")(x, residual=residual)


class TestBatchNormAct:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("with_res", [False, True])
    def test_xla_impl_bit_identical_to_flax(self, dtype, with_res):
        """The refactor's default path must not move a single bit:
        same variables, same output, same running-stat update."""
        x = _rand(0, (4, 6, 6, 32), dtype)
        res = _rand(1, (4, 6, 6, 32), dtype) if with_res else None
        ref = _FlaxRef(dtype=dtype)
        v = ref.init({"params": jax.random.key(1)}, x, res)
        got_m = _ActMod(dtype=dtype, impl="xla")
        yr, sr = ref.apply(v, x, res, mutable=["batch_stats"])
        yg, sg = got_m.apply(v, x, res, mutable=["batch_stats"])
        np.testing.assert_array_equal(np.asarray(yr, np.float32),
                                      np.asarray(yg, np.float32))
        for a, b in zip(jax.tree.leaves(sr), jax.tree.leaves(sg)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # eval path (running averages) too
        ye = ref.apply(v, x, res, False)
        ge = got_m.apply(v, x, res, False)
        np.testing.assert_array_equal(np.asarray(ye, np.float32),
                                      np.asarray(ge, np.float32))

    @pytest.mark.parametrize("with_res", [False, True])
    def test_pallas_impl_matches_flax_fwd_and_grad(self, with_res):
        """Folded-affine kernel vs the full unfused BN — through the
        batch statistics, so the custom_vjp's dscale/dbias cotangents
        chain into the TRUE BN gradient (incl. d/dmean, d/dvar)."""
        x = _rand(2, (4, 6, 6, 32))
        res = _rand(3, (4, 6, 6, 32)) if with_res else None
        ref = _FlaxRef()
        v = ref.init({"params": jax.random.key(2)}, x, res)
        pal = _ActMod(impl="pallas")
        yr, sr = ref.apply(v, x, res, mutable=["batch_stats"])
        yp, sp = pal.apply(v, x, res, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(yp), np.asarray(yr),
                                   rtol=1e-5, atol=1e-5)
        for a, b in zip(jax.tree.leaves(sr), jax.tree.leaves(sp)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-6, atol=1e-6)

        def loss(mod):
            def f(params, x, res):
                y, _ = mod.apply(
                    {"params": params,
                     "batch_stats": v["batch_stats"]}, x, res,
                    mutable=["batch_stats"])
                return (y.astype(jnp.float32) ** 2).sum()
            return f

        gr = jax.grad(loss(ref), argnums=(0, 1, 2) if with_res
                      else (0, 1))(v["params"], x, res)
        gp = jax.grad(loss(pal), argnums=(0, 1, 2) if with_res
                      else (0, 1))(v["params"], x, res)
        for a, b in zip(jax.tree.leaves(gr), jax.tree.leaves(gp)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-4)

    def test_layers_batchnorm_wrapper_keeps_tree(self):
        """layers.BatchNorm (now BatchNormAct-backed) still stores its
        variables where the old nn.BatchNorm wrapper did."""
        x = _rand(4, (2, 4, 4, 8))
        v = L.BatchNorm().init({"params": jax.random.key(3)}, x)
        assert set(v["params"]["BatchNorm_0"]) == {"scale", "bias"}
        assert set(v["batch_stats"]["BatchNorm_0"]) == {"mean", "var"}


class TestBiasAct:
    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_matches_conv_bias_relu(self, impl):
        """conv(use_bias) + relu == conv(no bias) -> BiasAct, given
        the same kernel/bias values (the VGG/GoogLeNet seam)."""
        x = _rand(5, (2, 8, 8, 3))
        ref = nn.Sequential([nn.Conv(16, (3, 3)), nn.relu])
        vr = ref.init(jax.random.key(4), x)
        kernel = vr["params"]["layers_0"]["kernel"]
        bias = vr["params"]["layers_0"]["bias"]

        conv = nn.Conv(16, (3, 3), use_bias=False)
        ba = L.BiasAct(16, act="relu", impl=impl)
        vb = ba.init(jax.random.key(5), jnp.zeros((1, 1, 1, 16)))
        y_ref = ref.apply(vr, x)
        y_got = ba.apply(
            {"params": {"bias": bias}},
            conv.apply({"params": {"kernel": kernel}}, x))
        np.testing.assert_allclose(np.asarray(y_got), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-6)
        assert set(vb["params"]) == {"bias"}


class TestEvalModeParity:
    """Serving (theanompi_tpu/serving) runs the EVAL path exclusively —
    ``use_running_average=True``, stats frozen at whatever training
    left them — which PR 3's oracles only pinned for the xla impl.
    These pin pallas == xla on that path, with NON-TRIVIAL running
    stats (the init zeros/ones would let a mean/var mix-up pass)."""

    def _stats_vars(self, c=32, key=20):
        return {
            "params": {"scale": _rand(key, (c,)),
                       "bias": _rand(key + 1, (c,))},
            "batch_stats": {"mean": _rand(key + 2, (c,)),
                            "var": jnp.abs(_rand(key + 3, (c,))) + 0.3},
        }

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("with_res", [False, True])
    def test_batchnormact_eval_pallas_matches_xla(self, dtype, with_res):
        x = _rand(21, (4, 6, 6, 32), dtype)
        res = _rand(22, (4, 6, 6, 32), dtype) if with_res else None
        v = self._stats_vars()
        outs = {}
        for impl in ("xla", "pallas"):
            mod = L.BatchNormAct(use_running_average=True, act="relu",
                                 impl=impl, dtype=dtype)
            # NOT mutable: the eval path must never touch the stats
            outs[impl] = mod.apply(v, x, residual=res)
        bf16 = dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(outs["pallas"], np.float32),
            np.asarray(outs["xla"], np.float32),
            # the folded affine (scale*rsqrt(var+eps) precomputed)
            # reassociates the bf16 rounding vs normalize-then-scale;
            # atol covers near-zero outputs at the relu knee, where
            # one bf16 ulp (~8e-3 at |y|~1) dwarfs any rtol
            rtol=2e-2 if bf16 else 1e-5, atol=1e-2 if bf16 else 1e-5)

    def test_batchnormact_eval_leaves_stats_untouched(self):
        """Both impls: applying with use_running_average=True and the
        stats collection MUTABLE still writes back the input values —
        a serving step can never drift the frozen statistics."""
        x = _rand(23, (4, 6, 6, 32))
        v = self._stats_vars()
        for impl in ("xla", "pallas"):
            mod = L.BatchNormAct(use_running_average=True, act="relu",
                                 impl=impl)
            _, upd = mod.apply(v, x, mutable=["batch_stats"])
            for key in ("mean", "var"):
                np.testing.assert_array_equal(
                    np.asarray(upd["batch_stats"][key]),
                    np.asarray(v["batch_stats"][key]))

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_biasact_pallas_matches_xla_eval(self, dtype):
        """BiasAct has no train/eval split of its own, but serving
        runs it at the zoo's bf16 compute dtype — pin the impls
        against each other there too."""
        x = _rand(24, (2, 8, 8, 16), dtype)
        b = _rand(25, (16,))
        y_x = L.BiasAct(16, act="relu", impl="xla").apply(
            {"params": {"bias": b}}, x)
        y_p = L.BiasAct(16, act="relu", impl="pallas").apply(
            {"params": {"bias": b}}, x)
        bf16 = dtype == jnp.bfloat16
        # bf16 atol: the xla path adds in bf16, the kernel in f32
        # before the final cast — near-zero relu outputs differ by up
        # to one bf16 ulp
        np.testing.assert_allclose(
            np.asarray(y_p, np.float32), np.asarray(y_x, np.float32),
            rtol=2e-2 if bf16 else 1e-6, atol=1e-2 if bf16 else 1e-6)


class TestModelSeam:
    def test_resnet_pallas_equals_xla_fwd_and_grad(self):
        """ResNet built with bn_act_impl='pallas' matches the 'xla'
        build on the SAME params — the integration contract behind
        ModelConfig.bn_act_impl (mirrors the pool_impl test)."""
        from theanompi_tpu.models.resnet50 import ResNet

        kw = dict(stage_sizes=(1, 1), width=8, n_classes=4,
                  dtype=jnp.float32)
        mx = ResNet(**kw, bn_act_impl="xla")
        mp = ResNet(**kw, bn_act_impl="pallas")
        x = _rand(6, (2, 16, 16, 3))
        v = mx.init({"params": jax.random.key(6)}, x, train=True)
        # identical variable trees: the impl knob moves no leaves
        assert (jax.tree_util.tree_structure(v) ==
                jax.tree_util.tree_structure(
                    mp.init({"params": jax.random.key(6)}, x,
                            train=True)))
        np.testing.assert_allclose(
            np.asarray(mp.apply(v, x, train=False)),
            np.asarray(mx.apply(v, x, train=False)),
            rtol=1e-5, atol=1e-5)

        def loss(m):
            def f(params):
                y, _ = m.apply(
                    {"params": params,
                     "batch_stats": v["batch_stats"]},
                    x, train=True, mutable=["batch_stats"])
                return (y.astype(jnp.float32) ** 2).sum()
            return f

        gx = jax.grad(loss(mx))(v["params"])
        gp = jax.grad(loss(mp))(v["params"])
        for a, b in zip(jax.tree.leaves(gx), jax.tree.leaves(gp)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=5e-4, atol=5e-4)

    def test_vgg_googlenet_pallas_seam_builds_and_runs(self):
        """The BN-free zoo members accept the knob: a tiny VGG/
        GoogLeNet built with the fused bias-act epilogue runs fwd+bwd
        and produces finite values (their param tree legitimately
        differs between impls — layers.BiasAct docstring)."""
        from theanompi_tpu.models.googlenet import GoogLeNetCNN
        from theanompi_tpu.models.vgg16 import VGGCNN

        x = _rand(7, (2, 32, 32, 3))
        for mod in (VGGCNN(blocks=((1, 8), (1, 8)), n_classes=4,
                           act_impl="pallas"),
                    GoogLeNetCNN(n_classes=4, width_mult=0.05,
                                 act_impl="pallas")):
            v = mod.init({"params": jax.random.key(8),
                          "dropout": jax.random.key(9)}, x, train=True)

            def f(params):
                y = mod.apply({"params": params}, x, train=True,
                              rngs={"dropout": jax.random.key(0)})
                if isinstance(y, (tuple, list)):
                    y = y[0]
                return (y.astype(jnp.float32) ** 2).sum()

            val, grads = jax.value_and_grad(f)(v["params"])
            assert np.isfinite(float(val))
            assert all(np.isfinite(np.asarray(g)).all()
                       for g in jax.tree.leaves(grads))
            # the fused seam actually engaged: a BiasAct scope exists
            flat = jax.tree_util.tree_flatten_with_path(v["params"])[0]
            assert any("BiasAct" in jax.tree_util.keystr(p)
                       for p, _ in flat)

    def test_config_threads_bn_act_impl(self):
        """ModelConfig.bn_act_impl reaches every zoo builder."""
        from theanompi_tpu.data.cifar10 import Cifar10_data
        from theanompi_tpu.models.base import ModelConfig
        from theanompi_tpu.models.resnet50 import ResNet50

        class TinyResNet(ResNet50):
            stage_sizes = (1,)

            def build_data(self):
                return Cifar10_data(synthetic_n=16)

        cfg = ModelConfig(batch_size=2, bn_act_impl="pallas",
                          compute_dtype="float32")
        m = TinyResNet(config=cfg, verbose=False)
        assert m.module.bn_act_impl == "pallas"
