"""Gradient accumulation (parallel/bsp.py make_bsp_accum_step +
ModelConfig.grad_accum_steps): a microbatches -> one update, exactly
the big-batch gradient."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.parallel.bsp import (
    TrainState,
    make_bsp_accum_step,
    make_bsp_train_step,
)
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import data_mesh, shard_batch
from theanompi_tpu.utils.helper_funcs import build_sgd_optimizer
from theanompi_tpu.utils.recorder import Recorder


def _linreg_loss(params, model_state, batch, rng):
    x, y = batch
    pred = x @ params["w"]
    loss = jnp.mean((pred - y) ** 2)
    return loss, (model_state, {"loss": loss, "error": loss})


def _setup(mesh):
    tx = build_sgd_optimizer(0.05, momentum=0.9)
    params = {"w": jnp.arange(4, dtype=jnp.float32)}
    state = TrainState.create(params, tx)
    rng_np = np.random.default_rng(0)
    x = rng_np.standard_normal((64, 4)).astype(np.float32)
    y = (x @ np.arange(4.0, 8.0)).astype(np.float32)
    return tx, state, x, y


def test_accum_matches_big_batch(mesh8):
    """4 microbatches of 16 == one batch of 64 (same update), because
    the loss is a per-microbatch mean and grads are averaged."""
    from jax.sharding import PartitionSpec as P

    from theanompi_tpu.parallel.mesh import AXIS_DATA

    tx, state0, x, y = _setup(mesh8)
    rng = jax.random.key(3)

    big = make_bsp_train_step(_linreg_loss, tx, mesh8, donate=False)
    s_big, m_big = big(state0, shard_batch((x, y), mesh8), rng)

    accum = make_bsp_accum_step(_linreg_loss, tx, mesh8, donate=False)
    stacked = (x.reshape(4, 16, 4), y.reshape(4, 16))
    s_acc, m_acc = accum(state0, shard_batch(stacked, mesh8,
                                             spec=P(None, AXIS_DATA)), rng)

    for a, b in zip(jax.tree.leaves(s_big.params),
                    jax.tree.leaves(s_acc.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    # metrics: mean over microbatches == the big-batch mean loss
    assert float(m_acc["loss"]) == pytest.approx(float(m_big["loss"]),
                                                 rel=1e-6)
    assert int(s_acc.step) == 1  # ONE optimizer update


def test_accum_donates_staged_batch(mesh8):
    """The accum cadence donates the stacked microbatch buffers like
    the multi-step one (ISSUE 3 copy-done fix); the opt-out withholds
    exactly the two batch leaves for batch-replaying callers."""
    from jax.sharding import PartitionSpec as P

    from tests.test_multi_step import _donated_inputs
    from theanompi_tpu.parallel.mesh import AXIS_DATA

    tx, state0, x, y = _setup(mesh8)
    stacked_np = (x.reshape(4, 16, 4), y.reshape(4, 16))

    def donors(**kw):
        accum = make_bsp_accum_step(_linreg_loss, tx, mesh8, **kw)
        stacked = shard_batch(stacked_np, mesh8, spec=P(None, AXIS_DATA))
        lowered = accum.lower(
            TrainState.create({"w": jnp.arange(4.0)}, tx), stacked,
            jax.random.key(0))
        return _donated_inputs(lowered.as_text())

    assert donors() == donors(donate_batch=False) + 2
    assert donors(donate=False) == 0


def test_accum_rejects_param_averaging(mesh8):
    tx, _, _, _ = _setup(mesh8)
    with pytest.raises(ValueError, match="exchange_what='grads'"):
        make_bsp_accum_step(_linreg_loss, tx, mesh8,
                            BSP_Exchanger(exchange_what="params"))


def test_model_plumbing_counts_and_trains(mesh8, tmp_path):
    from tests._tiny_models import TinyCifar128

    cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                      print_freq=0, grad_accum_steps=4,
                      snapshot_dir=str(tmp_path))
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    rec = Recorder(rank=0, size=8, print_freq=0)
    n_iters = m.begin_epoch(0)
    assert n_iters % 4 == 0 and n_iters > 0
    steps_before = int(m.state.step)
    it = 0
    while it < n_iters:
        consumed = m.train_iter(it, rec)
        assert consumed == 4
        it += consumed
    m._flush_metrics(rec)
    # one optimizer update per 4 consumed iterations
    assert int(m.state.step) - steps_before == n_iters // 4
    # recorder saw every image despite averaged metrics
    assert rec.n_images == n_iters * m.global_batch
    assert np.isfinite(rec.train_losses).all()
    m.cleanup()


def test_both_cadences_rejected(mesh8):
    from tests._tiny_models import TinyCifar

    cfg = ModelConfig(batch_size=4, print_freq=0, grad_accum_steps=2,
                      steps_per_call=2)
    m = TinyCifar(config=cfg, mesh=mesh8, verbose=False)
    with pytest.raises(ValueError, match="stacked-batch cadences"):
        m.compile_iter_fns("avg")


def test_async_rules_refuse_accum(tmp_path):
    from theanompi_tpu import EASGD

    cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                      grad_accum_steps=2, snapshot_dir=str(tmp_path))
    rule = EASGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=cfg, checkpoint=False)
    with pytest.raises(ValueError, match="grad_accum_steps"):
        rule.wait()


def test_custom_step_models_reject_accum(mesh8):
    """Models with their own step builders reject the knob at compile
    time instead of crashing mid-epoch."""
    from theanompi_tpu.models.transformer import TransformerLM_TP
    from theanompi_tpu.parallel.mesh import MeshSpec, make_training_mesh

    mesh = make_training_mesh(MeshSpec(data=2, model=4),
                              jax.devices()[:8])
    cfg = ModelConfig(batch_size=4, print_freq=0, grad_accum_steps=2,
                      weight_decay=0.0)
    m = TransformerLM_TP(config=cfg, mesh=mesh, verbose=False,
                         n_layers=1, d_model=32, n_heads=4, seq_len=16)
    with pytest.raises(ValueError, match="grad_accum_steps>1 is not"):
        m.compile_iter_fns("avg")
