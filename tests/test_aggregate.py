"""Hierarchical intra-host aggregation (parallel/aggregate.py,
ISSUE 14).

The acceptance bar is EXACTNESS plus the fault matrix: the aggregated
center math must equal N independent exchanges at the same center
version — BITWISE on the exact-arithmetic f32 lattice (ASGD's
delta-sum; EASGD's closed-form elastic composition) — and a killed
aggregator must fail its workers over to direct exchange within the
same period (no idle-all-workers gap), with a relaunch rejoining the
periods that follow.
"""

from __future__ import annotations

import socket
import threading
import time

import jax
import numpy as np
import pytest

from theanompi_tpu.parallel.aggregate import (
    AggregatedExchange,
    AggregatorDown,
    LocalAggregator,
)
from theanompi_tpu.parallel.server import ASGDServer, EASGDServer
from theanompi_tpu.utils.helper_funcs import build_optimizer

ALPHA = 0.25  # N*ALPHA <= 1 at N=4 (docs/DESIGN.md stability note)


def lattice(shape, rng, lo=-2**12, hi=2**12):
    """Exact-arithmetic f32 values: integer multiples of 2**-10 with
    |x| <= 4 — every sum/mean/elastic-pull below stays exactly
    representable, so equality asserts are bitwise, not tolerances.
    ``+ 0.0`` flushes signed zeros (cancellation yields +0.0 while a
    propagated -0.0 keeps its sign — numerically equal, bitwise
    noise)."""
    return (rng.integers(lo, hi, shape) * 2.0**-10 + 0.0) \
        .astype(np.float32)


def tree(seed: int) -> dict:
    rng = np.random.default_rng(seed)
    return {"a": lattice((8, 4), rng),
            "b": {"c": lattice((33,), rng)},
            "d": lattice((2, 2, 2), rng)}


def grad_tree(seed: int) -> dict:
    rng = np.random.default_rng(seed)
    return {"a": lattice((8, 4), rng, -8, 9),
            "b": {"c": lattice((33,), rng, -8, 9)},
            "d": lattice((2, 2, 2), rng, -8, 9)}


def assert_tree_bytes_equal(t1, t2, msg=""):
    f1, d1 = jax.tree.flatten(t1)
    f2, d2 = jax.tree.flatten(t2)
    assert d1 == d2, f"treedef mismatch {msg}"
    for x, y in zip(f1, f2):
        x, y = np.asarray(x), np.asarray(y)
        assert x.tobytes() == y.tobytes(), msg


def closed_form_easgd(center, workers, alpha):
    """N independent exchanges at ONE center version: the reference
    the aggregate is pinned against."""
    a = np.float32(alpha)
    new_c = jax.tree.map(
        lambda c, *ws: c + a * sum(w - c for w in ws), center, *workers)
    new_ws = [jax.tree.map(lambda w, c: w - a * (w - c), w, center)
              for w in workers]
    return new_c, new_ws


# ---------------------------------------------------------------------------
# Store-level aggregate math
# ---------------------------------------------------------------------------


class TestAggregateStoreMath:
    def test_easgd_exchange_n_is_closed_form(self):
        c0 = tree(0)
        ws = [tree(10 + i) for i in range(4)]
        srv = EASGDServer(c0, alpha=ALPHA)
        mean = jax.tree.map(
            lambda *xs: sum(xs[1:], xs[0]) / np.float32(4), *ws)
        pre = srv.exchange_n(mean, 4)
        ref_c, _ = closed_form_easgd(c0, ws, ALPHA)
        assert_tree_bytes_equal(pre, c0, "pre-update center")
        assert_tree_bytes_equal(jax.device_get(srv.get_center()), ref_c,
                                "aggregated center vs closed form")
        assert srv.n_exchanges == 4  # n logical exchanges

    def test_easgd_n1_matches_direct_exchange(self):
        c0, w = tree(1), tree(2)
        direct = EASGDServer(c0, alpha=ALPHA)
        agg = EASGDServer(c0, alpha=ALPHA)
        new_w = direct.exchange(w)
        pre = agg.exchange_n(w, 1)
        # the aggregator-side worker pull against the pre-update center
        ported = jax.tree.map(
            lambda x, c: x - np.float32(ALPHA) * (x - c), w, pre)
        assert_tree_bytes_equal(jax.device_get(new_w), ported,
                                "n=1 worker pull")
        assert_tree_bytes_equal(jax.device_get(direct.get_center()),
                                jax.device_get(agg.get_center()),
                                "n=1 center")

    def test_asgd_push_pull_n_delta_sums_exactly(self):
        c0 = tree(3)
        gs = [grad_tree(20 + i) for i in range(4)]
        tx = build_optimizer(learning_rate=0.125, optimizer="sgd")
        direct = ASGDServer({k: v for k, v in c0.items()}, tx)
        agg = ASGDServer({k: v for k, v in c0.items()}, tx)
        for _ in range(3):
            for g in gs:
                direct.push_pull(g)
            gsum = jax.tree.map(lambda *xs: sum(xs[1:], xs[0]), *gs)
            agg.push_pull_n(gsum, 4)
        assert_tree_bytes_equal(jax.device_get(direct.get_center()),
                                jax.device_get(agg.get_center()),
                                "delta-sum vs sequential pushes")
        assert direct.n_updates == agg.n_updates == 12

    def test_n_below_one_refused(self):
        srv = EASGDServer(tree(0), alpha=ALPHA)
        with pytest.raises(ValueError, match="n >= 1"):
            srv.exchange_n(tree(1), 0)
        asrv = ASGDServer(tree(0),
                          build_optimizer(learning_rate=0.1))
        with pytest.raises(ValueError, match="n >= 1"):
            asrv.push_pull_n(grad_tree(1), 0)


# ---------------------------------------------------------------------------
# LocalAggregator periods
# ---------------------------------------------------------------------------


def _run_period(ports, payloads):
    """All workers exchange concurrently; returns their results."""
    outs = [None] * len(ports)
    errs = [None] * len(ports)

    def run(i):
        try:
            outs[i] = ports[i].exchange(payloads[i])
        except BaseException as e:  # pragma: no cover - surfaced below
            errs[i] = e

    ths = [threading.Thread(target=run, args=(i,))
           for i in range(len(ports))]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert all(e is None for e in errs), errs
    return outs


class TestLocalAggregator:
    def test_periods_match_closed_form(self):
        c0 = tree(0)
        srv = EASGDServer(c0, alpha=ALPHA)
        agg = LocalAggregator("easgd", srv, alpha=ALPHA)
        ports = [AggregatedExchange(agg, i, lambda: srv)
                 for i in range(4)]
        workers = [tree(10 + i) for i in range(4)]
        ref_c, ref_ws = c0, workers
        for _ in range(3):
            outs = _run_period(ports, workers)
            ref_c, ref_ws = closed_form_easgd(ref_c, ref_ws, ALPHA)
            for out, ref in zip(outs, ref_ws):
                assert_tree_bytes_equal(out, ref, "worker pull")
            workers = outs
        assert_tree_bytes_equal(jax.device_get(srv.get_center()), ref_c,
                                "3-period center vs closed form")
        assert srv.n_exchanges == 12
        for p in ports:
            p.close()

    def test_asgd_fan_out_shares_fresh_center(self):
        tx = build_optimizer(learning_rate=0.125, optimizer="sgd")
        srv = ASGDServer(tree(0), tx)
        agg = LocalAggregator("asgd", srv)
        ports = [AggregatedExchange(agg, i, lambda: srv)
                 for i in range(3)]
        gs = [grad_tree(30 + i) for i in range(3)]
        outs = [None] * 3
        ths = [threading.Thread(
            target=lambda i=i: outs.__setitem__(
                i, ports[i].push_pull(gs[i]))) for i in range(3)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        center = jax.device_get(srv.get_center())
        for out in outs:
            assert_tree_bytes_equal(out, center, "fanned-out center")
        assert srv.n_updates == 3
        for p in ports:
            p.close()

    def test_leave_shrinks_period_quorum(self):
        srv = EASGDServer(tree(0), alpha=ALPHA)
        agg = LocalAggregator("easgd", srv, alpha=ALPHA)
        ports = [AggregatedExchange(agg, i, lambda: srv)
                 for i in range(4)]
        ports[3].close()  # worker 3 is gone before the period
        outs = _run_period(ports[:3], [tree(10 + i) for i in range(3)])
        assert all(o is not None for o in outs)
        assert srv.n_exchanges == 3
        for p in ports[:3]:
            p.close()

    def test_timeout_withdraws_and_falls_back(self):
        srv = EASGDServer(tree(0), alpha=ALPHA)
        agg = LocalAggregator("easgd", srv, alpha=ALPHA,
                              wait_timeout_s=0.3)
        agg.register(0)
        agg.register(1)  # never submits -> period can't complete
        port = AggregatedExchange(agg, 0, lambda: srv)
        out = port.exchange(tree(5))  # falls back direct after timeout
        assert out is not None
        assert srv.n_exchanges == 1  # the DIRECT exchange, not a flight
        port.close()

    def test_gosgd_kind_refused(self):
        with pytest.raises(ValueError, match="easgd/asgd only"):
            LocalAggregator("gosgd", object())

    def test_easgd_requires_alpha(self):
        with pytest.raises(ValueError, match="alpha"):
            LocalAggregator("easgd", object())


# ---------------------------------------------------------------------------
# Fault matrix: kill -> direct fallback within one period -> rejoin
# ---------------------------------------------------------------------------


class TestAggregatorFaultMatrix:
    def test_kill_mid_wait_falls_back_within_period_then_rejoins(self):
        """Workers parked on the period barrier when the aggregator
        dies must complete THAT period via direct exchange (no
        idle-all-workers gap), and a restarted aggregator serves the
        periods that follow."""
        srv = EASGDServer(tree(0), alpha=ALPHA)
        agg = LocalAggregator("easgd", srv, alpha=ALPHA)
        ports = [AggregatedExchange(agg, i, lambda: srv)
                 for i in range(4)]
        workers = [tree(10 + i) for i in range(4)]

        # period 1: aggregated (sanity)
        workers = _run_period(ports, workers)
        assert srv.n_exchanges == 4

        # period 2: three workers park on the barrier, then the kill
        # lands before the fourth ever submits
        outs = [None] * 4
        started = threading.Barrier(4)

        def run(i):
            started.wait()
            outs[i] = ports[i].exchange(workers[i])

        ths = [threading.Thread(target=run, args=(i,)) for i in range(3)]
        for t in ths:
            t.start()
        started.wait()  # all three are inside exchange (or about to be)
        agg.kill("fault-matrix kill")
        for t in ths:
            t.join(timeout=30)
        assert all(t.is_alive() is False for t in ths)
        # worker 3 exchanges AFTER the kill: immediate direct fallback
        outs[3] = ports[3].exchange(workers[3])
        assert all(o is not None for o in outs)
        # every worker's period completed via the direct path
        assert srv.n_exchanges == 8

        # relaunch rejoins: the next period aggregates again
        agg.restart()
        workers = [jax.tree.map(np.asarray, o) for o in outs]
        outs = _run_period(ports, workers)
        assert all(o is not None for o in outs)
        # ONE aggregate flight = 4 logical exchanges (not 4 directs —
        # proves the ports rejoined the plane rather than staying on
        # their fallback clients)
        assert srv.n_exchanges == 12
        assert agg.alive()
        for p in ports:
            p.close()

    def test_wire_failure_fails_over_that_period(self):
        """An aggregate wire op that raises must surface as
        AggregatorDown to EVERY submitted worker of that period (the
        port then goes direct); the plane itself stays usable."""

        class FlakyStore:
            def __init__(self, inner):
                self.inner = inner
                self.fail_next = False

            def exchange_n(self, mean, n):
                if self.fail_next:
                    self.fail_next = False
                    raise ConnectionError("injected wire failure")
                return self.inner.exchange_n(mean, n)

            def exchange(self, w):
                return self.inner.exchange(w)

        srv = EASGDServer(tree(0), alpha=ALPHA)
        flaky = FlakyStore(srv)
        agg = LocalAggregator("easgd", flaky, alpha=ALPHA)
        agg.register(0)
        agg.register(1)
        flaky.fail_next = True
        errs = []

        def direct_exchange(rank, payload):
            try:
                return agg.exchange(rank, payload)
            except AggregatorDown as e:
                errs.append(e)
                return srv.exchange(payload)

        outs = [None, None]
        ths = [threading.Thread(
            target=lambda i=i: outs.__setitem__(
                i, direct_exchange(i, tree(10 + i)))) for i in range(2)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert len(errs) == 2  # both workers of the period failed over
        assert all(o is not None for o in outs)
        # next period succeeds (the failure was one period's, not a
        # permanent down-state)
        outs = [None, None]
        ths = [threading.Thread(
            target=lambda i=i: outs.__setitem__(
                i, agg.exchange(i, tree(20 + i)))) for i in range(2)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert all(o is not None for o in outs)

    def test_kill_restart_racing_inflight_aggregate_never_wedges(self):
        """kill() + immediate restart() landing while the aggregate
        wire op is IN FLIGHT: the kill watermark stops the stale
        flight publishing, so a waiter that slept through the brief
        down window must still get a typed AggregatorDown (its
        generation's result will never arrive) — not re-extend its
        deadline forever.  The documented at-least-once window: the
        in-flight aggregate may still apply, exactly like a re-sent
        exchange after a lost reply."""

        class SlowStore:
            def __init__(self, inner):
                self.inner = inner
                self.flying = threading.Event()
                self.release = threading.Event()

            def exchange_n(self, mean, n):
                self.flying.set()
                assert self.release.wait(10)
                return self.inner.exchange_n(mean, n)

        srv = EASGDServer(tree(0), alpha=ALPHA)
        slow = SlowStore(srv)
        agg = LocalAggregator("easgd", slow, alpha=ALPHA,
                              wait_timeout_s=2.0)
        agg.register(0)
        agg.register(1)
        res = {}

        def worker(i):
            try:
                res[i] = ("ok", agg.exchange(i, tree(10 + i)))
            except AggregatorDown as e:
                res[i] = ("down", e)

        ths = [threading.Thread(target=worker, args=(i,))
               for i in range(2)]
        for t in ths:
            t.start()
        assert slow.flying.wait(10)  # the flyer is inside the wire op
        agg.kill("restart drill")
        agg.restart()  # faster than the waiter's 50 ms cv poll
        slow.release.set()  # the stale flight lands post-restart
        for t in ths:
            t.join(timeout=8)
        assert not any(t.is_alive() for t in ths), \
            "a worker wedged waiting on the killed flight's result"
        # the flyer keeps its own (applied) result; the waiter got the
        # typed failover signal
        kinds = sorted(k for k, _ in res.values())
        assert kinds == ["down", "ok"], kinds
        # the plane aggregates again after the drill
        outs = [None, None]
        ths = [threading.Thread(
            target=lambda i=i: outs.__setitem__(
                i, agg.exchange(i, tree(20 + i)))) for i in range(2)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=10)
        assert all(o is not None for o in outs)

    def test_kill_restart_racing_parked_waiter_never_wedges(self):
        """kill() + immediate restart() landing BEFORE any flyer takes
        off (quorum not yet met): the kill discards the parked
        waiter's pending payload, so the waiter must get a typed
        AggregatorDown on its next wakeup (payload never applied —
        safe direct fallback) even though it never observed the down
        window — not wait out the full quorum timeout."""
        srv = EASGDServer(tree(0), alpha=ALPHA)
        agg = LocalAggregator("easgd", srv, alpha=ALPHA,
                              wait_timeout_s=60.0)
        agg.register(0)
        agg.register(1)  # never submits: quorum stays unmet
        res = {}

        def worker():
            try:
                res[0] = ("ok", agg.exchange(0, tree(10)))
            except AggregatorDown as e:
                res[0] = ("down", e)

        t = threading.Thread(target=worker)
        t.start()
        for _ in range(200):  # wait until the payload is parked
            if 0 in agg._pending:
                break
            time.sleep(0.01)
        agg.kill("restart drill")
        agg.restart()
        t.join(timeout=5)  # well below the 60 s quorum timeout
        assert not t.is_alive(), \
            "parked waiter wedged after kill+restart discarded its " \
            "payload"
        assert res[0][0] == "down"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def shard_env(monkeypatch):
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_KEY", "aggregate-test")


def _start_fleet(k: int):
    from theanompi_tpu.parallel.service import ServiceClient
    from theanompi_tpu.parallel.shards import serve_shard

    fleet = []
    for i in range(k):
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(target=serve_shard,
                             args=("127.0.0.1", port, i, ready, stop),
                             daemon=True)
        t.start()
        assert ready.wait(10)
        fleet.append({"addr": f"127.0.0.1:{port}", "stop": stop,
                      "thread": t})

    def teardown():
        for s in fleet:
            s["stop"].set()
            try:
                ServiceClient(s["addr"]).call("shutdown")
            except Exception:
                pass
            s["thread"].join(timeout=5)

    return [s["addr"] for s in fleet], teardown


class TestShardedAggregate:
    def test_sharded_exchange_n_byte_identical_to_inprocess(
            self, shard_env):
        from theanompi_tpu.parallel.shards import ShardedEASGD

        addrs, teardown = _start_fleet(2)
        try:
            c0 = tree(0)
            ws = [tree(10 + i) for i in range(4)]
            mean = jax.tree.map(
                lambda *xs: sum(xs[1:], xs[0]) / np.float32(4), *ws)
            ref = EASGDServer(c0, alpha=ALPHA)
            ref_pre = ref.exchange_n(mean, 4)
            srv = ShardedEASGD(addrs, c0, alpha=ALPHA,
                               session_id="agg-bytes")
            pre = srv.exchange_n(mean, 4)
            assert_tree_bytes_equal(pre, jax.device_get(ref_pre),
                                    "sharded pre-update center")
            assert_tree_bytes_equal(srv.get_center(),
                                    jax.device_get(ref.get_center()),
                                    "sharded aggregated center")
            srv.close()
        finally:
            teardown()

    def test_fence_counts_aggregate_as_n_exchanges(self, shard_env):
        """The version fence's applied counter must advance by n for
        one aggregate op — byte-identical accounting to n independent
        exchanges at the same version."""
        from theanompi_tpu.parallel.service import ServiceClient
        from theanompi_tpu.parallel.shards import ShardedEASGD

        addrs, teardown = _start_fleet(1)
        try:
            c0 = tree(0)
            srv = ShardedEASGD(addrs, c0, alpha=ALPHA,
                               session_id="agg-fence")
            srv.exchange_n(tree(1), 4)
            c = ServiceClient(addrs[0])
            info = c.call("shard_freeze", "easgd", "agg-fence", "tkn")
            c.call("shard_release", "easgd", "agg-fence", "tkn")
            assert info["applied"] == 4, info
            # ONE seq in the vector clock: one full-tree op
            assert list(info["vclock"].values()) == [1], info
            c.close()
            srv.close()
        finally:
            teardown()


# ---------------------------------------------------------------------------
# Rules integration
# ---------------------------------------------------------------------------


def tiny_cfg(tmp_path, **kw):
    from theanompi_tpu.models.base import ModelConfig

    base = dict(batch_size=8, n_epochs=1, learning_rate=0.01,
                snapshot_dir=str(tmp_path), print_freq=0)
    base.update(kw)
    return ModelConfig(**base)


def test_easgd_session_with_local_aggregation(tmp_path):
    """The rules-level wiring: a short aggregated EASGD session runs,
    its ONE aggregate flight per period still counts every worker's
    logical exchange, and validation is finite."""
    from theanompi_tpu import EASGD

    rule = EASGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=tiny_cfg(tmp_path),
              tau=4, alpha=0.25, checkpoint=False,
              local_aggregation=True)
    res = rule.wait()
    assert res["n_exchanges"] > 0
    assert np.isfinite(res["val"]["loss"])


@pytest.mark.slow
def test_asgd_session_with_local_aggregation(tmp_path):
    from theanompi_tpu import ASGD

    rule = ASGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=tiny_cfg(tmp_path),
              checkpoint=False, local_aggregation=True)
    res = rule.wait()
    assert res["n_updates"] > 0
    assert np.isfinite(res["val"]["loss"])


def test_easgd_aggregation_refuses_unstable_alpha(tmp_path):
    """n*alpha > 1 makes the composed center move overshoot the worker
    mean every period — the rule refuses at wiring time (the repo's
    refusal-over-silent-divergence policy) instead of training a run
    that oscillates: default alpha=0.5 with 4 local workers is the
    trap this guards."""
    from theanompi_tpu import EASGD

    rule = EASGD()
    rule.init(devices=4, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=tiny_cfg(tmp_path),
              tau=4, alpha=0.5, checkpoint=False,
              local_aggregation=True)
    with pytest.raises(ValueError, match=r"n\*alpha"):
        rule.wait()


def test_gosgd_refuses_local_aggregation(tmp_path):
    from theanompi_tpu import GOSGD

    rule = GOSGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=tiny_cfg(tmp_path),
              checkpoint=False, local_aggregation=True)
    with pytest.raises(ValueError, match="refuses hierarchical"):
        rule.wait()
