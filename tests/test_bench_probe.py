"""bench.py backend-probe retry (VERDICT r2 weak #1).

Round 2's official record was zeroed by a single 300 s probe attempt
hitting a transient tunnel wedge.  The probe now retries at SHORT
cadence (a client that starts during a wedge fails ~25 min later even
if the tunnel recovers meanwhile, so one long blocked attempt would
sleep through a serving window) inside an env-capped window; these
tests drive that loop with a mocked probe runner so the policy is
covered without a tunnel (the real-backend path is exercised by the
driver's bench run).  The probe runner itself is file-backed +
process-group-killed because ``subprocess.run(capture_output=True)``
deadlocks on axon helper grandchildren holding the stdout pipe; its
real-subprocess behavior is covered by
tests/test_perf_tools.py::test_run_tpu_queue_requeue_and_forwarding
driving the queue runner's identical helper.
"""

import subprocess
import sys

import pytest

import bench


def _ok(platform="axon"):
    return (0, platform + "\n", "", False)


def _fail(stderr):
    return (1, "", stderr, False)


_HANG = (None, "", "", True)


def test_probe_success_first_try(monkeypatch):
    monkeypatch.setattr(bench, "_run_probe_sub", lambda *a, **k: _ok())
    platform, err = bench._probe_backend(window_s=60)
    assert platform == "axon" and err == ""


def test_probe_retries_past_fast_failures(monkeypatch):
    calls = []

    def fake(argv, timeout):
        calls.append(timeout)
        if len(calls) < 3:
            return _fail("UNAVAILABLE: lease wedged\n")
        return _ok()

    monkeypatch.setattr(bench, "_run_probe_sub", fake)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    platform, err = bench._probe_backend(window_s=3600)
    assert platform == "axon" and err == ""
    assert len(calls) == 3
    # every attempt is capped at the short cadence, not the window
    assert all(t is not None and t <= bench.PROBE_ATTEMPT_S
               for t in calls)


@pytest.mark.parametrize("stderr", [
    "RuntimeError: Backend 'axon' is not in the list of known backends\n",
    "RuntimeError: Unknown backend: 'axno' requested\n",
    "ModuleNotFoundError: No module named 'axon_plugin'\n",
])
def test_probe_bails_on_deterministic_signatures(monkeypatch, stderr):
    """Misconfigs that are deterministic BY CONSTRUCTION (the round-2
    PYTHONPATH-clobber and bad-platform-name failures) must not burn
    the 30 min window; everything else — including fast UNAVAILABLE
    bursts — keeps retrying (see the retry tests)."""
    calls = []

    def fake(argv, timeout):
        calls.append(1)
        return _fail(stderr)

    monkeypatch.setattr(bench, "_run_probe_sub", fake)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    platform, err = bench._probe_backend(window_s=3600)
    assert platform is None
    assert len(calls) == 1
    assert "not retrying" in err


def test_probe_gives_up_when_window_exhausted(monkeypatch):
    clock = [0.0]
    monkeypatch.setattr(bench.time, "monotonic", lambda: clock[0])
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: clock.__setitem__(0, clock[0] + s))

    def fake(argv, timeout):
        clock[0] += 20.0  # each failed attempt burns 20 s
        return _fail("UNAVAILABLE: pool lease\n")

    monkeypatch.setattr(bench, "_run_probe_sub", fake)
    platform, err = bench._probe_backend(window_s=100)
    assert platform is None
    assert "UNAVAILABLE" in err and "attempt" in err


def test_probe_hang_retries_at_short_cadence(monkeypatch):
    """A blocked device init means wedged RIGHT NOW — kill at the
    attempt cap and re-probe with a fresh client (the only thing that
    ever succeeds) instead of letting one blocked attempt eat the
    whole window."""
    clock = [0.0]
    monkeypatch.setattr(bench.time, "monotonic", lambda: clock[0])
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: clock.__setitem__(0, clock[0] + s))
    timeouts = []

    def fake(argv, timeout):
        timeouts.append(timeout)
        clock[0] += timeout  # the kill fires at the attempt cap
        return _HANG

    monkeypatch.setattr(bench, "_run_probe_sub", fake)
    platform, err = bench._probe_backend(window_s=700)
    assert platform is None
    assert "hung past" in err and "wedged tunnel" in err
    assert len(timeouts) >= 3  # kept re-probing, not one terminal hang
    assert all(t <= bench.PROBE_ATTEMPT_S for t in timeouts)


def test_probe_hang_then_recovery_is_caught(monkeypatch):
    """The reason for the short cadence: a window that opens mid-probe
    must be caught by a later fresh client."""
    clock = [0.0]
    monkeypatch.setattr(bench.time, "monotonic", lambda: clock[0])
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: clock.__setitem__(0, clock[0] + s))
    calls = []

    def fake(argv, timeout):
        calls.append(timeout)
        if len(calls) < 3:
            clock[0] += timeout
            return _HANG
        clock[0] += 20.0
        return _ok()

    monkeypatch.setattr(bench, "_run_probe_sub", fake)
    platform, err = bench._probe_backend(window_s=1800)
    assert platform == "axon" and err == ""
    assert len(calls) == 3


def test_probe_timeline_lands_in_failure_json(monkeypatch):
    """A device-init hang must leave a machine-readable probe timeline
    (attempt starts, per-attempt wait durations, last phase) in the
    failure JSON's detail — not just a prose error string."""
    import json

    clock = [0.0]
    monkeypatch.setattr(bench.time, "monotonic", lambda: clock[0])
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: clock.__setitem__(0, clock[0] + s))
    monkeypatch.setitem(bench._STATUS, "timeline", [])
    monkeypatch.setitem(bench._STATUS, "t0", 0.0)

    def fake(argv, timeout):
        clock[0] += timeout
        return _HANG

    monkeypatch.setattr(bench, "_run_probe_sub", fake)
    platform, err = bench._probe_backend(window_s=700)
    assert platform is None
    detail = json.loads(bench._failure_json(err))["detail"]
    tl = detail["probe_timeline"]
    starts = [e for e in tl if e["event"] == "probe_attempt_start"]
    hangs = [e for e in tl if e["event"] == "probe_attempt_hang"]
    assert len(starts) >= 3 and len(hangs) >= 3
    assert starts[0]["attempt"] == 1
    assert all(h["waited_s"] <= bench.PROBE_ATTEMPT_S for h in hangs)
    # every event is JSON-scalar (machine-comparable across rounds)
    assert all(isinstance(e["t"], (int, float)) for e in tl)


FAKE_JAX = '''\
"""Fake jax for bench envelope tests: imports fine, device init hangs
forever — the observable signature of a wedged axon tunnel."""
import time


class _Cfg:
    def update(self, *a, **k):
        pass


config = _Cfg()


def devices(*a, **k):
    time.sleep(600)


def default_backend():
    return "fake"


def __getattr__(name):  # PEP 562: any other attr is a harmless no-op
    def _noop(*a, **k):
        return _noop
    return _noop
'''


def _bench_env(tmp_path, **extra):
    import os

    (tmp_path / "jax.py").write_text(FAKE_JAX)
    env = dict(os.environ)
    # without POOL_IPS the image's sitecustomize touches nothing, so
    # the fake jax shadows the real one cleanly in every child
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = (str(tmp_path) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    env.update(extra)
    return env


def _repo_root():
    import pathlib

    return str(pathlib.Path(bench.__file__).parent)


def _parse_stdout_json(stdout):
    import json

    lines = [ln for ln in stdout.splitlines() if ln.lstrip().startswith("{")]
    assert lines, f"no JSON line on stdout; got: {stdout!r}"
    return json.loads(lines[-1])


def test_default_probe_window_fits_driver_patience():
    """Round 3's record was an rc=124 empty tail because the default
    1800 s probe window exceeded the driver's own capture timeout.
    The driver-invoked default must resolve well inside it."""
    import subprocess as sp

    out = sp.run([sys.executable, "-c",
                  "import bench; print(bench.PROBE_WINDOW_S)"],
                 capture_output=True, text=True, cwd=_repo_root(),
                 env=_bench_env_no_override(), timeout=60)
    assert out.returncode == 0, out.stderr
    assert int(out.stdout.strip()) <= 240


def _bench_env_no_override():
    import os

    env = dict(os.environ)
    env.pop("THEANOMPI_TPU_BENCH_PROBE_S", None)
    return env


@pytest.mark.slow
def test_sigterm_mid_probe_flushes_failure_json(tmp_path):
    """THE round-3 failure mode, reproduced end-to-end: driver-style
    `timeout -s TERM` lands while bench is blocked probing a wedged
    tunnel.  The kill handler must flush one parseable JSON line to
    stdout (and the heartbeat a diagnostic tail to stderr) instead of
    dying output-empty."""
    env = _bench_env(
        tmp_path,
        THEANOMPI_TPU_BENCH_PROBE_S="600",
        THEANOMPI_TPU_BENCH_HEARTBEAT_S="1",
    )
    p = subprocess.run(
        ["timeout", "-s", "TERM", "6", sys.executable, "bench.py"],
        capture_output=True, text=True, cwd=_repo_root(), env=env,
        timeout=90)
    # `timeout` exits 124 whenever the limit fired, even when the child
    # handled the TERM and exited on its own; 137 means it had to
    # escalate to SIGKILL — i.e. our handler wedged — which is the one
    # unacceptable outcome
    assert p.returncode != 137, (
        f"timeout escalated to SIGKILL; stderr tail: {p.stderr[-500:]}")
    obj = _parse_stdout_json(p.stdout)
    assert obj["value"] == 0.0 and obj["unit"] == "images/sec/chip"
    assert "killed by SIGTERM" in obj["detail"]["error"]
    assert obj["detail"]["phase"] == "probe"
    assert obj["detail"]["probe_attempts"] >= 1
    assert "[bench +" in p.stderr  # heartbeat tail survived the kill


@pytest.mark.slow
def test_exhausted_window_emits_failure_json(tmp_path):
    """No TERM involved: a wedge that outlasts the whole (short)
    window must still end in rc=1 + one parseable JSON line."""
    env = _bench_env(
        tmp_path,
        THEANOMPI_TPU_BENCH_PROBE_S="5",
        THEANOMPI_TPU_BENCH_PROBE_ATTEMPT_S="2",
        THEANOMPI_TPU_BENCH_HEARTBEAT_S="1",
    )
    p = subprocess.run([sys.executable, "bench.py"],
                       capture_output=True, text=True, cwd=_repo_root(),
                       env=env, timeout=90)
    assert p.returncode == 1
    obj = _parse_stdout_json(p.stdout)
    assert obj["value"] == 0.0
    assert "hung past" in obj["detail"]["error"]
    assert obj["detail"]["probe_attempts"] >= 1


def test_run_probe_sub_real_timeout_kills_group():
    """The file-backed runner must return on timeout even when the
    child's own child keeps the (nonexistent) pipe alive — the exact
    deadlock subprocess.run(capture_output=True) hit on axon."""
    code = ("import subprocess, sys, time\n"
            "subprocess.Popen([sys.executable, '-c',"
            " 'import time; time.sleep(60)'])\n"
            "print('parent up', flush=True)\n"
            "time.sleep(60)\n")
    # 12s, not 3: the one-core box under suite load can take >3s just
    # to exec the child interpreter, and a pre-print kill makes the
    # output assertion below fail spuriously (seen round 5)
    rc, out, err, timed_out = bench._run_probe_sub(
        [sys.executable, "-c", code], timeout=12)
    assert timed_out and rc is None
    assert "parent up" in out  # pre-kill output still readable
