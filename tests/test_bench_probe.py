"""bench.py backend-probe retry (VERDICT r2 weak #1).

Round 2's official record was zeroed by a single 300 s probe attempt
hitting a transient tunnel wedge.  The probe now retries fast failures
inside an env-capped window and only gives up when the window is
exhausted; these tests drive that loop with a mocked subprocess so the
policy is covered without a tunnel (the real-backend path is exercised
by the driver's bench run).
"""

import subprocess

import pytest

import bench


class _Result:
    def __init__(self, rc, out="", err=""):
        self.returncode, self.stdout, self.stderr = rc, out, err


def test_probe_success_first_try(monkeypatch):
    monkeypatch.setattr(bench.subprocess, "run",
                        lambda *a, **k: _Result(0, "axon\n"))
    platform, err = bench._probe_backend(window_s=60)
    assert platform == "axon" and err == ""


def test_probe_retries_past_fast_failures(monkeypatch):
    calls = []

    def fake_run(*a, timeout=None, **k):
        calls.append(timeout)
        if len(calls) < 3:
            return _Result(1, "", "UNAVAILABLE: lease wedged\n")
        return _Result(0, "axon\n")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    platform, err = bench._probe_backend(window_s=3600)
    assert platform == "axon" and err == ""
    assert len(calls) == 3
    # every attempt must be bounded by the remaining window, not ∞
    assert all(t is not None and t <= 3600 for t in calls)


@pytest.mark.parametrize("stderr", [
    "RuntimeError: Backend 'axon' is not in the list of known backends\n",
    "RuntimeError: Unknown backend: 'axno' requested\n",
    "ModuleNotFoundError: No module named 'axon_plugin'\n",
])
def test_probe_bails_on_deterministic_signatures(monkeypatch, stderr):
    """Misconfigs that are deterministic BY CONSTRUCTION (the round-2
    PYTHONPATH-clobber and bad-platform-name failures) must not burn
    the 30 min window; everything else — including fast UNAVAILABLE
    bursts — keeps retrying (see the retry tests)."""
    calls = []

    def fake_run(*a, timeout=None, **k):
        calls.append(1)
        return _Result(1, "", stderr)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    platform, err = bench._probe_backend(window_s=3600)
    assert platform is None
    assert len(calls) == 1
    assert "not retrying" in err


def test_probe_gives_up_when_window_exhausted(monkeypatch):
    clock = [0.0]
    monkeypatch.setattr(bench.time, "monotonic", lambda: clock[0])
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: clock.__setitem__(0, clock[0] + s))

    def fake_run(*a, timeout=None, **k):
        clock[0] += 20.0  # each failed attempt burns 20 s
        return _Result(1, "", "UNAVAILABLE: pool lease\n")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    platform, err = bench._probe_backend(window_s=100)
    assert platform is None
    assert "UNAVAILABLE" in err and "attempt" in err


def test_probe_hang_retries_at_short_cadence(monkeypatch):
    """A blocked device init means wedged RIGHT NOW — and a client that
    starts during a wedge fails ~25 min later even if the tunnel
    recovers meanwhile, so the probe must kill at short cadence and
    re-probe (a fresh client is the only thing that ever succeeds)
    instead of letting one blocked attempt eat the whole window."""
    clock = [0.0]
    monkeypatch.setattr(bench.time, "monotonic", lambda: clock[0])
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: clock.__setitem__(0, clock[0] + s))
    timeouts = []

    def fake_run(*a, timeout=None, **k):
        timeouts.append(timeout)
        clock[0] += timeout  # the kill fires at the attempt cap
        raise subprocess.TimeoutExpired(cmd="probe", timeout=timeout)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    platform, err = bench._probe_backend(window_s=700)
    assert platform is None
    assert "hung past" in err and "wedged tunnel" in err
    assert len(timeouts) >= 3  # kept re-probing, not one terminal hang
    assert all(t <= bench.PROBE_ATTEMPT_S for t in timeouts)


def test_probe_hang_then_recovery_is_caught(monkeypatch):
    """The reason for the short cadence: a window that opens mid-probe
    must be caught by a later fresh client."""
    clock = [0.0]
    monkeypatch.setattr(bench.time, "monotonic", lambda: clock[0])
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: clock.__setitem__(0, clock[0] + s))
    calls = []

    def fake_run(*a, timeout=None, **k):
        calls.append(timeout)
        if len(calls) < 3:
            clock[0] += timeout
            raise subprocess.TimeoutExpired(cmd="probe", timeout=timeout)
        clock[0] += 20.0
        return _Result(0, "axon\n")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    platform, err = bench._probe_backend(window_s=1800)
    assert platform == "axon" and err == ""
    assert len(calls) == 3
