"""CLI smoke tests for the perf tooling: the probes the next chip
window depends on must not rot between rounds (each runs as a REAL
subprocess, synthetic data, tiny shapes)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_tool(args, timeout=540):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, timeout=timeout, env=env,
                          cwd=REPO_ROOT)


def test_host_pipeline_probe_smoke():
    r = _run_tool([os.path.join(REPO_ROOT, "tools/host_pipeline_probe.py"),
                   "--batch", "16", "--batches", "4", "--store", "40",
                   "--crop", "32"])
    assert r.returncode == 0, r.stdout + r.stderr
    recs = [json.loads(line) for line in r.stdout.splitlines() if line]
    assert [rec["mode"] for rec in recs] == ["device", "host"]
    assert all(rec["img_per_sec"] > 0 and rec["synthetic"] for rec in recs)
    assert recs[0]["dtype"] == "uint8" and recs[1]["dtype"] == "float32"


def test_harvest_queue_smoke(tmp_path):
    log = tmp_path / "q.jsonl"
    log.write_text(
        '{"exp": "resnet50", "batch_per_chip": 128, "steps_per_call": 1, '
        '"stem": "conv7", "img_per_sec_per_chip": 2600.0, '
        '"dispatch_ms": 49.2, "step_ms": 49.2, "compile_s": 180.0}\n'
        '{"exp": "h2d", "error": "RuntimeError", "tb": "..."}\n')
    r = _run_tool([os.path.join(REPO_ROOT, "tools/harvest_queue.py"),
                   str(log)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "THEANOMPI_TPU_BENCH_K=1" in r.stdout
    assert "1 failed experiment(s)" in r.stdout
    # an empty log exits nonzero so automated harvests notice — assert
    # the intended message too: a crash also exits 1, and this smoke
    # must not report an unhandled exception as the designed exit path
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    r = _run_tool([os.path.join(REPO_ROOT, "tools/harvest_queue.py"),
                   str(empty)])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "no ResNet ladder points" in r.stderr


@pytest.mark.slow
def test_bench_lm_smoke():
    r = _run_tool([os.path.join(REPO_ROOT, "tools/bench_lm.py"),
                   "--batch", "2", "--seq", "32", "--layers", "1",
                   "--d-model", "32", "--heads", "2", "--steps", "2",
                   "--dtype", "float32"])
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "transformer_lm_tokens_per_sec_per_chip"
    # the 1-layer d=32 smoke model's GF/seq rounds to 0.00 at 2dp —
    # assert shape/liveness, not magnitude
    assert rec["value"] > 0 and rec["detail"]["step_ms"] > 0
    assert rec["detail"]["train_gflops_per_seq"] >= 0


@pytest.mark.slow
def test_conv_ladder_smoke():
    r = _run_tool([os.path.join(REPO_ROOT, "tools/conv_ladder.py"),
                   "--batch", "1", "--iters", "1", "--dtype", "float32"],
                  timeout=560)
    assert r.returncode == 0, r.stdout + r.stderr
    lines = [json.loads(line) for line in r.stdout.splitlines() if line]
    summary = lines[-1]
    assert summary["event"] == "ladder_summary"
    # canonical ResNet-50: 8.18 GF/img fwd in 2xMAC units
    assert abs(summary["sum_gflops_fwd"] - 8.18) < 0.2


def test_run_tpu_queue_requeue_and_forwarding(tmp_path):
    """Drive the queue runner's real machinery (subprocess per
    experiment, timeout kill, requeue-to-back, JSON/stdout forwarding)
    with stub commands via --exps-json; the built-in on-chip ladder
    itself can only run against the tunnel."""
    ok = ("import json; print(json.dumps({'img_per_sec_per_chip': 1.0}));"
          "print('plain text line')")
    exps = [
        ["stub_ok", [sys.executable, "-c", ok], 60],
        ["stub_fail", [sys.executable, "-c", "raise SystemExit(3)"], 60],
        ["stub_hang", [sys.executable, "-c",
                       "import time; time.sleep(120)"], 2],
    ]
    exps_file = tmp_path / "exps.json"
    exps_file.write_text(json.dumps(exps))
    out = tmp_path / "queue.jsonl"
    r = _run_tool([os.path.join(REPO_ROOT, "tools/run_tpu_queue.py"),
                   "--out", str(out), "--exps-json", str(exps_file),
                   "--smoke-dir", str(tmp_path / "smoke")],
                  timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    recs = [json.loads(line) for line in out.read_text().splitlines()]

    # success: its JSON line is forwarded with exp defaulted to the
    # experiment name; non-JSON stdout is wrapped, not dropped
    fwd = [x for x in recs if x.get("exp") == "stub_ok"]
    assert any(x.get("img_per_sec_per_chip") == 1.0 for x in fwd)
    assert any(x.get("text") == "plain text line" for x in fwd)

    # failure and hang: recorded with the error, requeued to the BACK
    # up to 3 attempts, never marked done
    for name, err_frag in (("stub_fail", "rc=3"), ("stub_hang", "timeout")):
        fails = [x for x in recs if x.get("exp") == name and "error" in x]
        assert len(fails) == 3, (name, fails)
        assert all(err_frag in x["error"] for x in fails)
        assert [x["attempt"] for x in fails] == [1, 2, 3]
        assert all(x.get("requeued") for x in fails[:2])
        assert not fails[2].get("requeued")
    # attempt-2 records come after every attempt-1 record (requeue goes
    # to the back of the queue, preserving ladder priority order)
    idx = {(x.get("exp"), x.get("attempt")): i for i, x in enumerate(recs)
           if "error" in x}
    assert idx[("stub_fail", 2)] > idx[("stub_hang", 1)]

    starts = [x for x in recs if x.get("event") == "start"]
    dones = [x for x in recs if x.get("event") == "done"]
    assert len(starts) == 7  # 3 + 2 requeues each for fail and hang
    assert [d["name"] for d in dones] == ["stub_ok"]
    assert recs[-1]["event"] == "queue_done"


def test_bench_maxpool_smoke():
    r = _run_tool([os.path.join(REPO_ROOT, "tools/bench_maxpool.py"),
                   "2", "16", "8"])
    assert r.returncode == 0, r.stdout + r.stderr
    recs = [json.loads(line) for line in r.stdout.splitlines() if line]
    impls = [rec.get("impl") for rec in recs if "impl" in rec]
    assert impls == ["xla", "pallas"]
    assert all(rec["fwd_bwd_ms"] > 0 for rec in recs if "impl" in rec)
    assert recs[-1]["event"] == "summary" and recs[-1]["speedup_pallas"] > 0


def test_bench_exchange_buckets_shards_conflict():
    """ISSUE 13 satellite: --buckets with --shards must fail FAST with
    the typed FlagConflict (exit 2) instead of silently ignoring one
    flag, both in-process and as a subprocess."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        import bench_exchange
    finally:
        sys.path.pop(0)
    with pytest.raises(bench_exchange.FlagConflict) as ei:
        bench_exchange.main(["--buckets", "4", "--shards", "2"])
    assert ei.value.code == 2
    r = _run_tool([os.path.join(REPO_ROOT, "tools/bench_exchange.py"),
                   "--buckets", "4", "--shards", "2"], timeout=120)
    assert r.returncode == 2
    assert "mutually exclusive" in r.stderr


def test_queue_resnet_point_buckets_flag(tmp_path):
    """The queued bucketed profile pair's lever: --buckets reaches
    ModelConfig.exchange_buckets and lands in the JSON row (tiny crop
    wiring-check shape so CPU can afford it)."""
    env_extra = {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra)
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools/queue_resnet_point.py"),
         "--k", "2", "--batch", "2", "--crop", "64", "--steps", "2",
         "--buckets", "4"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=REPO_ROOT)
    assert r.returncode == 0, r.stdout + r.stderr
    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["exchange_buckets"] == 4
    assert row["exp"] == "resnet50_wiring"  # shrunken crop never ladders
