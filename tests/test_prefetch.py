"""DevicePrefetcher: staging, exhaustion, error propagation, and the
round-5 ``stats`` hook (the in-session ingest measurement —
tools/ingest_session_probe.py reads ``stats`` to separate the loader's
critical path from consumer compute that shares the host core)."""

from __future__ import annotations

import numpy as np
import pytest

from theanompi_tpu.data.prefetch import DevicePrefetcher
from theanompi_tpu.parallel.mesh import data_mesh


@pytest.fixture(scope="module")
def mesh():
    return data_mesh(8)


def _batches(n, global_batch=16):
    for i in range(n):
        yield (np.full((global_batch, 4), i, np.float32),
               np.arange(global_batch, dtype=np.int32))


class TestDevicePrefetcher:
    def test_yields_all_batches_sharded(self, mesh):
        pf = DevicePrefetcher(_batches(5), mesh)
        got = list(pf)
        assert len(got) == 5
        x0, y0 = got[0]
        assert x0.shape == (16, 4) and y0.shape == (16,)
        assert float(np.asarray(x0)[0, 0]) == 0.0
        assert float(np.asarray(got[4][0])[0, 0]) == 4.0
        # sharded over the data axis, not replicated
        assert len(x0.sharding.device_set) == 8

    def test_stats_account_batches_and_images(self, mesh):
        pf = DevicePrefetcher(_batches(3), mesh)
        list(pf)
        assert pf.stats["batches"] == 3
        assert pf.stats["images"] == 3 * 16
        assert pf.stats["busy_s"] > 0.0

    def test_error_propagates_to_consumer(self, mesh):
        def bad():
            yield from _batches(1)
            raise RuntimeError("loader exploded")

        pf = DevicePrefetcher(bad(), mesh)
        it = iter(pf)
        next(it)
        with pytest.raises(RuntimeError, match="loader exploded"):
            while True:
                next(it)

    def test_close_stops_early(self, mesh):
        pf = DevicePrefetcher(_batches(100), mesh)
        next(iter(pf))
        pf.close()  # must not hang or raise
