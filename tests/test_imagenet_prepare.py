"""Raw-image ingestion: JPEG tree -> npz shards -> training batches
(VERDICT r1 next-round #8; reference hickle prep per SURVEY.md §2.9)."""

import glob
import json
import os

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402

from theanompi_tpu.data.imagenet import (  # noqa: E402
    ImageNet_data,
    decode_image,
    prepare_imagenet_from_images,
)


def make_jpeg_tree(root, n_classes=3, per_class=6, size=(40, 30)):
    """Tiny ImageFolder tree of solid-color JPEGs (color encodes the
    class, so content survives JPEG compression recognizably)."""
    colors = [(250, 10, 10), (10, 250, 10), (10, 10, 250)]
    for c in range(n_classes):
        d = os.path.join(root, f"class_{c}")
        os.makedirs(d)
        for i in range(per_class):
            img = Image.new("RGB", size, colors[c % len(colors)])
            img.save(os.path.join(d, f"img_{i}.jpeg"), quality=90)


def test_decode_image_resizes_and_center_crops(tmp_path):
    p = os.path.join(tmp_path, "x.jpeg")
    Image.new("RGB", (100, 60), (200, 50, 50)).save(p)
    out = decode_image(str(p), store=32)
    assert out.shape == (32, 32, 3) and out.dtype == np.uint8
    # solid color survives resize+crop+jpeg within tolerance
    assert abs(int(out[..., 0].mean()) - 200) < 15


@pytest.mark.parametrize("shard_format", ["npy", "npz"])
def test_prepare_from_images_roundtrip(tmp_path, shard_format):
    src = tmp_path / "raw"
    out = tmp_path / "shards"
    os.makedirs(src)
    make_jpeg_tree(str(src), n_classes=3, per_class=6)

    paths = prepare_imagenet_from_images(str(src), str(out), prefix="train",
                                         store=24, shard_size=8, workers=2,
                                         shard_format=shard_format)
    # 18 images at shard_size 8 -> 3 shards (8+8+2)
    assert len(paths) == 3
    suffix = ".x.npy" if shard_format == "npy" else ".npz"
    assert all(p.endswith(suffix) for p in paths)
    with open(out / "manifest.json") as fh:
        manifest = json.load(fh)
    assert sum(manifest.values()) == 18
    with open(out / "classes.json") as fh:
        classes = json.load(fh)
    assert classes == {"class_0": 0, "class_1": 1, "class_2": 2}

    # shards are class-mixed thanks to the prep-time shuffle
    from theanompi_tpu.data.imagenet import _load_shard

    _, y0 = _load_shard(paths[0])
    assert len(set(y0.tolist())) > 1

    # same tree prepared as val with the train mapping
    prepare_imagenet_from_images(str(src), str(out), prefix="val",
                                 store=24, shard_size=8,
                                 class_to_idx=classes, workers=2,
                                 shard_format=shard_format)

    # the full Dataset path consumes the shards
    ds = ImageNet_data(data_dir=str(out), crop=16)
    assert not ds.synthetic
    assert ds.n_train == 18 and ds.n_val == 18
    batches = list(ds.train_batches(epoch=0, global_batch=4))
    assert len(batches) == ds.n_train_batches_for(0, 4) == 4
    x, y = batches[0]
    assert x.shape == (4, 16, 16, 3) and y.shape == (4,)
    # normalized floats, labels in range
    assert np.isfinite(x).all() and set(y) <= {0, 1, 2}

    # color -> class is preserved through decode/shard/crop: red images
    # (class 0) keep channel 0 dominant after normalization
    for xb, yb in batches:
        for img, label in zip(xb, yb):
            chan = np.argmax([img[..., c].mean() for c in range(3)])
            assert chan == label


def test_prepare_rerun_removes_stale_shards(tmp_path):
    """A second prep into the same out_dir must not leave the first
    run's shards — in EITHER format (training globs both and would
    silently mix stale data)."""
    src_big = tmp_path / "raw_big"
    src_small = tmp_path / "raw_small"
    out = tmp_path / "shards"
    os.makedirs(src_big)
    os.makedirs(src_small)
    make_jpeg_tree(str(src_big), n_classes=3, per_class=6)    # 18 imgs
    make_jpeg_tree(str(src_small), n_classes=3, per_class=2)  # 6 imgs

    # first run in the legacy npz format, rerun in npy: the rerun must
    # remove every stale npz AND leave no orphan .y.npy anywhere
    prepare_imagenet_from_images(str(src_big), str(out), prefix="train",
                                 store=24, shard_size=8, workers=2,
                                 shard_format="npz")
    paths2 = prepare_imagenet_from_images(str(src_small), str(out),
                                          prefix="train", store=24,
                                          shard_size=8, workers=2)
    on_disk = sorted(glob.glob(str(out / "train_*.npz"))
                     + glob.glob(str(out / "train_*.x.npy")))
    assert on_disk == sorted(paths2) and len(on_disk) == 1
    with open(out / "manifest.json") as fh:
        manifest = json.load(fh)
    assert sum(manifest.values()) == 6
    # and back: a rerun in npz removes the npy pair files entirely
    paths3 = prepare_imagenet_from_images(str(src_big), str(out),
                                          prefix="train", store=24,
                                          shard_size=8, workers=2,
                                          shard_format="npz")
    assert sorted(glob.glob(str(out / "train_*.npz"))) == sorted(paths3)
    assert glob.glob(str(out / "train_*.npy")) == []


def test_prepare_rejects_flat_dir(tmp_path):
    Image.new("RGB", (10, 10)).save(tmp_path / "img.jpeg")
    with pytest.raises(FileNotFoundError):
        prepare_imagenet_from_images(str(tmp_path), str(tmp_path / "o"))


@pytest.mark.slow
def test_prepare_then_train_one_epoch(tmp_path, mesh8):
    """The full real-data path actually TRAINS (VERDICT r2 #5): JPEG
    tree -> parallel decode to mmap shards -> ImageNet_data ->
    device-side augmentation -> jitted BSP step -> recorder/val.  The
    fixture classes are solid colors, so two epochs must already cut
    training loss (color->class is linearly separable)."""
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.resnet50 import ResNet, ResNet50
    from theanompi_tpu.rules.bsp import run_bsp_session

    src, out = tmp_path / "raw", tmp_path / "shards"
    os.makedirs(src)
    make_jpeg_tree(str(src), n_classes=3, per_class=16)
    classes = None
    for prefix in ("train", "val"):
        prepare_imagenet_from_images(
            str(src), str(out), prefix=prefix, store=24, shard_size=16,
            class_to_idx=classes, workers=2, shard_format="npy")
        if classes is None:
            with open(out / "classes.json") as fh:
                classes = json.load(fh)

    class ShardResNet(ResNet50):
        def build_data(self):
            return ImageNet_data(data_dir=str(out), crop=16,
                                 augment_on_device=True)

        def build_module(self):
            return ResNet(stage_sizes=(1, 1, 1, 1), width=8,
                          n_classes=self.data.n_classes)

    # gentle lr: 3 steps/epoch with momentum 0.9 oscillates at 0.05
    cfg = ModelConfig(batch_size=2, n_epochs=5, learning_rate=0.01,
                      snapshot_dir=str(tmp_path / "snap"), print_freq=0,
                      track_top5=False)
    model = ShardResNet(config=cfg, mesh=mesh8)
    assert not model.data.synthetic and model.data.n_classes == 3
    res = run_bsp_session(model, checkpoint=False)
    assert res["epochs_run"] == 5
    losses = [r["train_loss"] for r in res["records"]]
    errs = [r["val_error"] for r in res["records"]]
    assert all(np.isfinite(losses)) and all(np.isfinite(errs))
    assert losses[-1] < losses[0], f"no learning on real shards: {losses}"
    # color IS the class: 15 steps must beat chance (2/3) on val
    assert errs[-1] < 0.67, f"val stuck at chance: {errs}"


def test_gather_assembly_matches_naive_reference(tmp_path):
    """The round-5 single-gather batch assembly (_file_batches) must
    produce byte-identical batches to the naive materialize-and-
    concatenate formulation it replaced, including across unequal
    shard boundaries and with the seeded in-shard shuffle."""
    import numpy as np

    from theanompi_tpu.data.imagenet import ImageNet_data, _write_shard

    rng = np.random.default_rng(7)
    sizes = [8, 5, 8, 3]  # unequal shards force multi-part batches
    xs, ys = [], []
    for i, n in enumerate(sizes):
        x = rng.integers(0, 256, size=(n, 12, 12, 3), dtype=np.uint8)
        y = rng.integers(0, 10, size=n).astype(np.int64)
        _write_shard(str(tmp_path), "train", i, x, y, "npy")
        xs.append(x)
        ys.append(y)

    ds = ImageNet_data(data_dir=str(tmp_path), crop=12, seed=3,
                       augment_on_device=True)  # raw uint8: exact compare
    B = 6
    got = list(ds.train_batches(epoch=0, global_batch=B))

    # naive reference: same file order, same per-shard permutation
    # stream, materialized then concatenated then sliced
    files = ds._sharded_files(ds.train_files, 0, 0, 1)
    order = {f: i for i, f in enumerate(
        str(tmp_path) + f"/train_{i:04d}.x.npy" for i in range(4))}
    shuf = np.random.default_rng(ds.seed + 9000 + 7919 * 0 + 0)
    all_x, all_y = [], []
    for f in files:
        i = order[f]
        p = shuf.permutation(len(ys[i]))
        all_x.append(xs[i][p])
        all_y.append(ys[i][p])
    cat_x, cat_y = np.concatenate(all_x), np.concatenate(all_y)
    n_batches = len(cat_y) // B
    assert len(got) == n_batches
    for b, (xb, yb) in enumerate(got):
        np.testing.assert_array_equal(xb, cat_x[b * B:(b + 1) * B])
        np.testing.assert_array_equal(yb, cat_y[b * B:(b + 1) * B])
