"""Telemetry subsystem (theanompi_tpu/monitor): registry math, span
nesting + device fence, heartbeat freshness, straggler detection,
postmortem dump, and the strict disabled no-op contract."""

import json
import os
import threading
import time

import numpy as np
import pytest

from theanompi_tpu import monitor
from theanompi_tpu.monitor.health import HeartbeatReporter, StragglerDetector
from theanompi_tpu.monitor.registry import (
    Histogram,
    MetricsRegistry,
    tree_bytes,
    tree_dtypes,
)
from theanompi_tpu.monitor.spans import Span, open_spans


@pytest.fixture(autouse=True)
def fresh_monitor():
    monitor.reset_for_tests()
    yield
    monitor.reset_for_tests()


# ---------------------------------------------------------------------------
# registry math
# ---------------------------------------------------------------------------


def test_counter_and_gauge():
    r = MetricsRegistry()
    r.inc("req")
    r.inc("req", 4)
    assert r.value("req") == 5
    r.set_gauge("clients", 3)
    r.add_gauge("clients", -1)
    assert r.value("clients") == 2


def test_label_isolation():
    r = MetricsRegistry()
    r.inc("rpc", 1, op="a")
    r.inc("rpc", 10, op="b")
    r.inc("rpc", 100, op="a")
    assert r.value("rpc", op="a") == 101
    assert r.value("rpc", op="b") == 10
    # label ORDER must not split series
    r.inc("multi", 1, x="1", y="2")
    r.inc("multi", 1, y="2", x="1")
    assert r.value("multi", x="1", y="2") == 2


def test_kind_conflict_raises():
    r = MetricsRegistry()
    r.inc("metric")
    with pytest.raises(TypeError):
        r.observe("metric", 1.0)


def test_histogram_math_and_percentiles():
    h = Histogram()
    for v in range(1, 101):  # 1..100
        h.observe(float(v))
    assert h.count == 100
    assert h.sum == pytest.approx(5050.0)
    assert h.min == 1.0 and h.max == 100.0
    assert h.percentile(50) == 50.0
    assert h.percentile(95) == 95.0
    assert h.percentile(99) == 99.0


def test_histogram_percentile_edges():
    h = Histogram()
    # empty: no percentile, None min/max in state
    assert h.percentile(50) is None
    st = h.state()
    assert st["count"] == 0 and st["p50"] is None and st["min"] is None
    # single observation: every percentile IS that value
    h.observe(7.5)
    assert h.percentile(50) == 7.5
    assert h.percentile(99) == 7.5
    assert h.state()["mean"] == 7.5


def test_histogram_ring_bounds_memory():
    h = Histogram(ring=8)
    for v in range(1000):
        h.observe(float(v))
    assert h.count == 1000          # exact count survives
    assert h.sum == pytest.approx(sum(range(1000)))
    assert h.percentile(50) >= 992.0  # ring only holds the newest 8


def test_registry_thread_safety():
    r = MetricsRegistry()

    def work():
        for _ in range(1000):
            r.inc("n")

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert r.value("n") == 8000
    assert r.write_count == 8000


def test_snapshot_jsonl_and_prometheus(tmp_path):
    r = MetricsRegistry()
    r.inc("service/requests_total", 3, op="ping")
    r.observe("rpc_ms", 1.5, op="ping")
    path = r.write_jsonl(str(tmp_path / "m.jsonl"))
    recs = [json.loads(l) for l in open(path)]
    by_name = {rec["name"]: rec for rec in recs}
    assert by_name["service/requests_total"]["value"] == 3
    assert by_name["rpc_ms"]["count"] == 1
    prom = r.to_prometheus()
    assert 'theanompi_service_requests_total{op="ping"} 3' in prom
    assert "# TYPE theanompi_rpc_ms summary" in prom


def test_prometheus_escapes_label_values():
    # a client-supplied label value (service op names come off the
    # wire) must not be able to corrupt the exposition format
    r = MetricsRegistry()
    r.inc("errs", 1, op='get"x\\y\nz')
    prom = r.to_prometheus()
    assert 'op="get\\"x\\\\y\\nz"' in prom
    assert "\nz\"" not in prom  # no raw newline inside a label value


def test_tree_bytes_and_dtypes():
    tree = {"a": np.zeros((4, 4), np.float32), "b": np.zeros(3, np.uint8)}
    assert tree_bytes(tree) == 4 * 4 * 4 + 3
    assert tree_dtypes(tree) == "float32,uint8"
    assert tree_bytes({"s": "not-an-array"}) == 0


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_nesting_and_registry_feed():
    r = MetricsRegistry()
    with Span("outer", registry=r):
        with Span("inner", registry=r):
            time.sleep(0.01)
    snap = {(s["name"], s["labels"].get("name")): s
            for s in r.snapshot()}
    assert ("span_ms", "outer") in snap
    assert ("span_ms", "outer/inner") in snap
    inner = snap[("span_ms", "outer/inner")]
    assert inner["count"] == 1 and inner["sum"] >= 10.0
    # outer covers inner
    assert snap[("span_ms", "outer")]["sum"] >= inner["sum"]


def test_span_fence_on_cpu_arrays():
    import jax.numpy as jnp

    r = MetricsRegistry()
    with Span("fenced", registry=r, fence={"x": jnp.ones((32,)),
                                           "y": jnp.zeros((4, 4))}):
        pass
    assert r.get("span_ms", name="fenced").count == 1


def test_open_spans_visible_across_threads():
    release = threading.Event()
    started = threading.Event()

    def worker():
        with Span("worker-phase"):
            started.set()
            release.wait(timeout=5)

    t = threading.Thread(target=worker, name="spanthread")
    t.start()
    try:
        assert started.wait(timeout=5)
        names = [s["name"] for s in open_spans()]
        assert "worker-phase" in names
    finally:
        release.set()
        t.join()
    assert "worker-phase" not in [s["name"] for s in open_spans()]


def test_span_records_on_exception():
    r = MetricsRegistry()
    with pytest.raises(ValueError):
        with Span("dies", registry=r):
            raise ValueError("boom")
    assert r.get("span_ms", name="dies").count == 1
    assert r.value("span_errors_total", name="dies") == 1
    assert open_spans() == []  # cleaned up despite the exception


# ---------------------------------------------------------------------------
# heartbeat / watchdog / straggler
# ---------------------------------------------------------------------------


def test_heartbeat_file_freshness(tmp_path):
    hb = HeartbeatReporter(str(tmp_path), rank=3, interval=0.05,
                           stall_after=60)
    hb.start()
    try:
        hb.progress(phase="train", step=12)
        time.sleep(0.15)  # at least one reporter tick
        rec = json.load(open(tmp_path / "heartbeat_rank3.json"))
    finally:
        hb.stop()
    assert rec["rank"] == 3
    assert rec["phase"] == "train" and rec["step"] == 12
    assert rec["stalled"] is False
    assert time.time() - rec["written"] < 5.0  # fresh
    assert rec["progress_age_s"] < 5.0


def test_watchdog_flags_stall(tmp_path, capsys):
    r = MetricsRegistry()
    hb = HeartbeatReporter(str(tmp_path), rank=0, registry=r,
                           interval=0.05, stall_after=0.15)
    hb.start()
    try:
        hb.progress(phase="device_init")
        time.sleep(0.4)  # exceed stall_after with no progress
        rec = json.load(open(tmp_path / "heartbeat_rank0.json"))
        assert rec["stalled"] is True
        assert r.value("health/stalls_total",
                       phase="device_init") >= 1
        # progress clears the flag (read state() directly: immediate,
        # no reporter-tick race)
        hb.progress(phase="train", step=1)
        assert hb.state()["stalled"] is False
        assert r.value("health/stall_recoveries_total") >= 1
    finally:
        hb.stop()
    assert "WATCHDOG" in capsys.readouterr().err


def test_heartbeat_tracks_workers(tmp_path):
    hb = HeartbeatReporter(str(tmp_path), rank=0, interval=5)
    hb.progress(phase="train", step=4, worker=1)
    hb.progress(phase="train", step=9, worker=2)
    state = hb.state()
    assert state["workers"]["1"]["step"] == 4
    assert state["workers"]["2"]["step"] == 9


def test_straggler_detection_flags_slow_worker():
    r = MetricsRegistry()
    det = StragglerDetector(factor=2.0, window=16, min_samples=4,
                            registry=r)
    # two healthy workers at ~10ms, one at 100ms
    for _ in range(8):
        det.observe(0, 0.010)
        det.observe(1, 0.011)
    flagged = [det.observe(2, 0.100) for _ in range(8)]
    assert flagged[-1] is True
    assert det.stragglers() == [2]
    assert r.value("health/straggler_flags_total", worker="2") == 1
    # recovery un-flags
    for _ in range(16):
        det.observe(2, 0.010)
    assert det.stragglers() == []


def test_straggler_needs_two_workers():
    det = StragglerDetector(min_samples=2)
    for _ in range(10):
        assert det.observe(0, 1.0) is False  # solo: never a straggler


def test_straggler_persistent_two_worker_case():
    # the fleet median must EXCLUDE the candidate: with a pooled median
    # a 2-worker straggler whose window is as full as its peer's could
    # never exceed factor x the median, however slow it is
    det = StragglerDetector(factor=2.0, window=8, min_samples=4)
    for _ in range(16):  # both windows saturated
        det.observe(0, 0.010)
        det.observe(1, 0.100)
    assert det.observe(1, 0.100) is True
    assert det.stragglers() == [1]


# ---------------------------------------------------------------------------
# facade: sessions, the no-op contract, postmortem
# ---------------------------------------------------------------------------


def test_disabled_is_noop(monkeypatch):
    """The acceptance contract: with monitoring off, instrumented code
    paths produce ZERO registry writes."""
    monkeypatch.delenv(monitor.ENV_VAR, raising=False)
    with monitor.session():  # no dir anywhere -> disabled
        monitor.inc("a")
        monitor.set_gauge("b", 1)
        monitor.observe("c", 2.0)
        monitor.observe_step(0.01, phase="train", step=1, worker=0)
        monitor.progress(phase="x")
        with monitor.span("s", fence=np.ones(3)):
            pass
        assert monitor.flush() is None
        assert monitor.dump_postmortem(RuntimeError("x")) is None
    assert monitor.registry().write_count == 0
    assert monitor.registry().series_names() == set()


def test_env_var_enables(tmp_path, monkeypatch):
    monkeypatch.setenv(monitor.ENV_VAR, str(tmp_path))
    with monitor.session() as live:
        assert live and monitor.enabled()
        monitor.inc("via_env")
    assert not monitor.enabled()
    recs = [json.loads(l)
            for l in open(tmp_path / "metrics_rank0.jsonl")]
    assert any(r["name"] == "via_env" for r in recs)
    assert (tmp_path / "metrics_rank0.prom").exists()
    assert (tmp_path / "heartbeat_rank0.json").exists()


def test_consecutive_sessions_get_fresh_registries(tmp_path):
    # a sweep running two monitored sessions in one process: run 2's
    # snapshot must not merge run 1's series
    with monitor.session(run_dir=str(tmp_path / "run1")):
        monitor.inc("steps", 5)
    with monitor.session(run_dir=str(tmp_path / "run2")):
        monitor.inc("steps", 2)
    r2 = [json.loads(l)
          for l in open(tmp_path / "run2" / "metrics_rank0.jsonl")]
    assert next(r for r in r2 if r["name"] == "steps")["value"] == 2


def test_session_activation_failure_does_not_leak_depth(tmp_path,
                                                        monkeypatch):
    # a bad knob (or unwritable dir) must fail THAT session, not poison
    # every later one into a silent it-looks-live-but-records-nothing
    # state
    monkeypatch.setenv("THEANOMPI_TPU_MONITOR_INTERVAL", "5s")  # bad
    with pytest.raises(ValueError):
        with monitor.session(run_dir=str(tmp_path)):
            pass
    monkeypatch.delenv("THEANOMPI_TPU_MONITOR_INTERVAL")
    with monitor.session(run_dir=str(tmp_path)) as live:
        assert live and monitor.enabled()
        monitor.inc("recovered")
    assert monitor.registry().value("recovered") == 1


def test_nested_sessions_share_state(tmp_path):
    with monitor.session(run_dir=str(tmp_path)):
        with monitor.session(run_dir=str(tmp_path / "ignored")):
            monitor.inc("n")
        assert monitor.enabled()  # inner exit must not tear down
        monitor.inc("n")
    assert not monitor.enabled()
    recs = [json.loads(l)
            for l in open(tmp_path / "metrics_rank0.jsonl")]
    assert next(r for r in recs if r["name"] == "n")["value"] == 2
    assert not (tmp_path / "ignored").exists()


def test_postmortem_on_injected_exception(tmp_path):
    # a worker thread sits inside a span during the crash — its span
    # must appear in the dump's open-spans section (the crashing
    # thread's own spans unwind with the exception, by design: their
    # durations + error counts are already in the registry)
    release = threading.Event()
    started = threading.Event()

    def worker():
        with Span("worker/exchange"):
            started.set()
            release.wait(timeout=10)

    t = threading.Thread(target=worker)
    t.start()
    try:
        assert started.wait(timeout=5)
        with pytest.raises(RuntimeError, match="injected"):
            with monitor.session(run_dir=str(tmp_path)):
                monitor.observe_step(0.020, phase="train", step=1)
                monitor.observe_step(0.021, phase="train", step=2)
                with monitor.span("train/epoch0"):
                    raise RuntimeError("injected failure")
    finally:
        release.set()
        t.join()
    pm = json.load(open(tmp_path / "postmortem_rank0.json"))
    assert pm["exception"]["type"] == "RuntimeError"
    assert "injected failure" in pm["exception"]["message"]
    assert "RuntimeError" in pm["exception"]["traceback"]
    assert "worker/exchange" in [s["name"] for s in pm["open_spans"]]
    assert pm["recent_step_ms"] == [20.0, 21.0]
    assert any(m["name"] == "step_ms" for m in pm["metrics"])
    # the crashed span's timing + error count made it into the dump
    span_recs = [m for m in pm["metrics"] if m["name"] == "span_errors_total"]
    assert any(m["labels"]["name"] == "train/epoch0" for m in span_recs)


# ---------------------------------------------------------------------------
# rule-loop integration (the acceptance contract)
# ---------------------------------------------------------------------------


def _tiny_bsp_model(mesh8):
    from theanompi_tpu.data.cifar10 import Cifar10_data
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.cifar10 import Cifar10_model

    class Tiny(Cifar10_model):
        def build_data(self):
            return Cifar10_data(synthetic_n=80)  # 5 iters at batch 2x8

    cfg = ModelConfig(batch_size=2, n_epochs=1, print_freq=10**9,
                      compute_dtype="float32")
    return Tiny(config=cfg, mesh=mesh8)


def test_bsp_session_emits_telemetry(tmp_path, mesh8):
    """5-step CPU BSP run with monitoring on: parseable snapshot with
    the step-time histogram + section span totals, fresh heartbeat."""
    from theanompi_tpu.rules.bsp import run_bsp_session

    run_bsp_session(_tiny_bsp_model(mesh8), max_epochs=1,
                    checkpoint=False, monitor_dir=str(tmp_path))
    recs = [json.loads(l)
            for l in open(tmp_path / "metrics_rank0.jsonl")]
    by = {}
    for r in recs:
        by.setdefault(r["name"], []).append(r)
    # step-time histogram: 5 steps observed
    (steps,) = by["step_ms"]
    assert steps["kind"] == "histogram" and steps["count"] == 5
    assert steps["p50"] is not None and steps["sum"] > 0
    # section span totals (recorder as registry client + phase spans)
    sections = {r["labels"]["section"] for r in by["recorder/section_ms"]}
    assert {"calc", "wait"} <= sections
    span_names = {r["labels"]["name"] for r in by["span_ms"]}
    assert "bsp/compile" in span_names and "bsp/epoch" in span_names
    # exchange shape counters (traced once per compile)
    assert by["exchange/bytes_per_call"][0]["value"] > 0
    # fresh heartbeat that reached the end of the epoch
    hb = json.load(open(tmp_path / "heartbeat_rank0.json"))
    assert time.time() - hb["written"] < 60
    assert hb["stalled"] is False and hb["phase"] == "epoch_end"
    # prometheus dump parses to the same series
    prom = open(tmp_path / "metrics_rank0.prom").read()
    assert "theanompi_step_ms_count" in prom


def test_bsp_session_disabled_zero_writes(monkeypatch, mesh8):
    """With monitoring disabled the instrumented rule loop performs
    ZERO registry writes — the no-op fast path."""
    from theanompi_tpu.rules.bsp import run_bsp_session

    monkeypatch.delenv(monitor.ENV_VAR, raising=False)
    run_bsp_session(_tiny_bsp_model(mesh8), max_epochs=1,
                    checkpoint=False)
    assert monitor.registry().write_count == 0
    assert monitor.registry().series_names() == set()


def test_bsp_crash_writes_postmortem(tmp_path, mesh8):
    from theanompi_tpu.rules.bsp import run_bsp_session

    model = _tiny_bsp_model(mesh8)
    calls = {"n": 0}
    orig = model.train_iter

    def dying_train_iter(it, recorder):
        calls["n"] += 1
        if calls["n"] >= 3:
            raise RuntimeError("injected step crash")
        return orig(it, recorder)

    model.train_iter = dying_train_iter
    with pytest.raises(RuntimeError, match="injected step crash"):
        run_bsp_session(model, max_epochs=1, checkpoint=False,
                        monitor_dir=str(tmp_path))
    pm = json.load(open(tmp_path / "postmortem_rank0.json"))
    assert pm["exception"]["type"] == "RuntimeError"
    assert len(pm["recent_step_ms"]) == 2  # the steps that completed
    assert any(m["name"] == "step_ms" for m in pm["metrics"])


def test_observe_step_feeds_histogram_and_straggler(tmp_path):
    with monitor.session(run_dir=str(tmp_path)):
        for _ in range(8):
            monitor.observe_step(0.010, worker=0)
            monitor.observe_step(0.010, worker=1)
        flagged = False
        for _ in range(8):
            flagged = monitor.observe_step(0.100, worker=2)
        assert flagged is True
        reg = monitor.registry()
        assert reg.get("step_ms", worker="0").count == 8
        assert reg.get("step_ms", worker="2").count == 8
