"""Tensor parallelism (parallel/tensor.py + TransformerLM_TP): params
really shard over the ``model`` axis, the GSPMD step trains, and the
(data x model) trajectory matches pure data parallelism."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.parallel.mesh import MeshSpec, make_training_mesh


def lm_cfg(**kw):
    base = dict(batch_size=4, n_epochs=1, learning_rate=0.1,
                momentum=0.9, weight_decay=0.0, lr_schedule="constant",
                print_freq=0)
    base.update(kw)
    return ModelConfig(**base)


def make_tp_lm(mesh, **net):
    from theanompi_tpu.models.transformer import TransformerLM_TP

    net.setdefault("vocab", 32)
    net.setdefault("seq_len", 16)
    net.setdefault("n_layers", 2)
    net.setdefault("d_model", 32)
    net.setdefault("n_heads", 4)
    return TransformerLM_TP(config=lm_cfg(), mesh=mesh, verbose=False, **net)


class TestSpecs:
    def test_megatron_rules(self, devices8):
        mesh = make_training_mesh(MeshSpec(data=2, model=4), devices8)
        m = make_tp_lm(mesh)
        specs = m.param_specs
        blk = specs["Block_0"]
        for col in ("q_proj", "k_proj", "v_proj", "mlp_up"):
            assert blk[col]["kernel"] == P(None, "model"), col
        for row in ("o_proj", "mlp_down"):
            assert blk[row]["kernel"] == P("model", None), row
        assert blk["mlp_up"]["bias"] == P("model")
        assert blk["mlp_down"]["bias"] == P()
        assert specs["Embed_0"]["embedding"] == P()
        assert specs["pos_emb"] == P()

    def test_params_physically_sharded(self, devices8):
        mesh = make_training_mesh(MeshSpec(data=2, model=4), devices8)
        m = make_tp_lm(mesh, d_model=32, n_heads=4)
        q = m.state.params["Block_0"]["q_proj"]["kernel"]
        assert q.shape == (32, 32)
        # each model-shard holds out/4 columns, replicated over data
        shard_shapes = {s.data.shape for s in q.addressable_shards}
        assert shard_shapes == {(32, 8)}
        # momentum buffers inherited the sharding (no replicated bloat):
        # every mlp_up-kernel-shaped leaf in the optimizer state is
        # sharded exactly like the parameter
        up = m.state.params["Block_0"]["mlp_up"]["kernel"]
        mom_leaves = [l for l in jax.tree.leaves(m.state.opt_state)
                      if getattr(l, "shape", None) == up.shape]
        assert mom_leaves, "no momentum buffer found for the mlp_up kernel"
        for ml in mom_leaves:
            assert {s.data.shape for s in ml.addressable_shards} == \
                {s.data.shape for s in up.addressable_shards}


class TestTraining:
    @pytest.mark.slow
    def test_tp_trains_and_matches_dp(self, devices8, tmp_path):
        """Same seed, same data: a (data=2, model=4) GSPMD TP run must
        track the pure-DP (data=2) run on the shard_map spine —
        identical math through a DIFFERENT code path (explicit psum
        exchange vs compiler-inserted collectives), so a gradient-
        reduction bug in either path breaks the match."""
        from theanompi_tpu.rules.bsp import run_bsp_session
        from theanompi_tpu.models.transformer import TransformerLM

        net = dict(vocab=32, seq_len=16, n_layers=1, d_model=32, n_heads=4)

        tp_mesh = make_training_mesh(MeshSpec(data=2, model=4), devices8)
        tp = make_tp_lm(tp_mesh, **net)
        res_tp = run_bsp_session(tp, checkpoint=False)

        # pure-DP baseline: shard_map spine, seq axis of size 1 (ring
        # attention over one shard = full attention), same global batch
        dp_mesh = make_training_mesh(MeshSpec(data=2, seq=1),
                                     devices8[:2])
        dp = TransformerLM(config=lm_cfg(), mesh=dp_mesh, verbose=False,
                           **net)
        res_dp = run_bsp_session(dp, checkpoint=False)

        assert np.isfinite(res_tp["val"]["loss"])
        np.testing.assert_allclose(res_tp["val"]["loss"],
                                   res_dp["val"]["loss"], rtol=2e-2)
        # both learned the synthetic grammar about equally
        assert res_tp["val"]["error"] < 0.9

    def test_tp_multi_step_and_load_preserve_sharding(self, devices8,
                                                      tmp_path):
        """steps_per_call works on the TP path (scanned GSPMD program)
        and the contract save/load round-trip keeps params sharded."""
        from theanompi_tpu.models.transformer import TransformerLM_TP
        from theanompi_tpu.utils.recorder import Recorder

        mesh = make_training_mesh(MeshSpec(data=2, model=4), devices8)
        m = TransformerLM_TP(config=lm_cfg(steps_per_call=2), mesh=mesh,
                             verbose=False, vocab=32, seq_len=16,
                             n_layers=1, d_model=32, n_heads=4)
        m.compile_iter_fns()
        rec = Recorder(rank=0, size=8, print_freq=0)
        n = m.begin_epoch(0)
        assert n % 2 == 0
        assert m.train_iter(0, rec) == 2
        m._flush_metrics(rec)
        assert len(rec.train_losses) == 2  # one entry per sub-step
        m.cleanup()

        path = m.save(str(tmp_path / "tp_params.npz"))
        before = {s.data.shape for s in
                  m.state.params["Block_0"]["q_proj"]["kernel"]
                  .addressable_shards}
        m.load(path)
        after = {s.data.shape for s in
                 m.state.params["Block_0"]["q_proj"]["kernel"]
                 .addressable_shards}
        assert before == after == {(32, 8)}

    def test_tp_rejects_indivisible_heads(self, devices8):
        mesh = make_training_mesh(MeshSpec(data=1, model=8), devices8)
        with pytest.raises(ValueError, match="divide n_heads"):
            make_tp_lm(mesh, n_heads=4)  # 4 heads over model=8

    def test_orbax_resume_preserves_tp_sharding(self, devices8, tmp_path):
        """VERIFY the resume path re-shards: a checkpointed TP session
        resumed via run_bsp_session must come back with model-sharded
        params, not replicated restored arrays."""
        from theanompi_tpu.rules.bsp import run_bsp_session

        mesh = make_training_mesh(MeshSpec(data=2, model=4), devices8)
        cfg = lm_cfg(n_epochs=1, snapshot_dir=str(tmp_path))
        from theanompi_tpu.models.transformer import TransformerLM_TP

        net = dict(vocab=32, seq_len=16, n_layers=1, d_model=32, n_heads=4)
        m = TransformerLM_TP(config=cfg, mesh=mesh, verbose=False, **net)
        run_bsp_session(m, checkpoint=True)

        cfg2 = lm_cfg(n_epochs=2, snapshot_dir=str(tmp_path))
        m2 = TransformerLM_TP(config=cfg2, mesh=mesh, verbose=False, **net)
        res = run_bsp_session(m2, resume=True, checkpoint=True)
        assert res["epochs_run"] == 1  # resumed at epoch 1 of 2
        q = m2.state.params["Block_0"]["q_proj"]["kernel"]
        assert {s.data.shape for s in q.addressable_shards} == {(32, 8)}

    def test_gspmd_step_decreases_loss(self, devices8):
        mesh = make_training_mesh(MeshSpec(data=2, model=4), devices8)
        m = make_tp_lm(mesh)
        m.compile_iter_fns()
        from theanompi_tpu.utils.recorder import Recorder

        rec = Recorder(rank=0, size=8, print_freq=0)
        n = m.begin_epoch(0)
        first = last = None
        for it in range(min(n, 20)):
            m.train_iter(it, rec)
        m._flush_metrics(rec)
        first, last = rec.train_losses[0], rec.train_losses[-1]
        assert np.isfinite(last) and last < first
        m.cleanup()
