"""Tests for the analysis subsystem (docs/ANALYSIS.md).

Three layers:

* **corpus** — every seeded bug in ``tests/analysis_corpus/`` must be
  flagged with the right check ID at the right file:line, and each
  known-good twin must stay silent (the checkers' own regression
  fence);
* **lockgraph** — the AB/BA inversion is caught at acquire time with
  the full cycle in the error, Condition-wait composes, and the
  make_lock seam actually wires TrackedLock into the threaded classes
  under ``THEANOMPI_TPU_LOCKCHECK=1`` (which tests/conftest.py sets);
* **repo gate** — ``tmlint --gate`` on this repo with the committed
  baseline is green, and stays under its runtime budget.
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from theanompi_tpu.analysis import donation, guarded_by, jit_hygiene, \
    site_coverage
from theanompi_tpu.analysis.cli import main as tmlint_main, run_checks
from theanompi_tpu.analysis.common import (
    SourceFile,
    load_baseline,
    split_by_baseline,
)
from theanompi_tpu.analysis.lockgraph import (
    LockGraph,
    LockOrderError,
    TrackedLock,
    make_condition,
    make_lock,
)

CORPUS = os.path.join(os.path.dirname(__file__), "analysis_corpus")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def corpus_file(name: str) -> SourceFile:
    return SourceFile(os.path.join(CORPUS, name), f"corpus/{name}")


def seeded_lines(name: str, check_id: str) -> list[int]:
    with open(os.path.join(CORPUS, name)) as f:
        return [i for i, line in enumerate(f, start=1)
                if f"SEED: {check_id}" in line]


def lines_of(findings, check_id):
    return sorted(f.line for f in findings if f.check_id == check_id)


# ---------------------------------------------------------------------------
# Corpus: TM101 guarded-by
# ---------------------------------------------------------------------------


def test_guarded_by_flags_every_seeded_bug():
    findings = guarded_by.run([corpus_file("guarded_bad.py")])
    assert {f.check_id for f in findings} == {"TM101"}
    assert lines_of(findings, "TM101") == \
        seeded_lines("guarded_bad.py", "TM101")
    # file:line and stable key both carried
    f0 = findings[0]
    assert f0.path == "corpus/guarded_bad.py" and f0.key.startswith(
        "TM101:corpus/guarded_bad.py:")


def test_guarded_by_silent_on_good_twin():
    assert guarded_by.run([corpus_file("guarded_good.py")]) == []


# ---------------------------------------------------------------------------
# Corpus: TM201 donation
# ---------------------------------------------------------------------------


def test_donation_flags_every_seeded_bug():
    src = corpus_file("donation_bad.py")
    findings = donation.run([src])
    assert {f.check_id for f in findings} == {"TM201"}
    assert lines_of(findings, "TM201") == \
        seeded_lines("donation_bad.py", "TM201")


def test_donation_silent_on_good_twin():
    # registry includes the bad file's donating fns: same names, so the
    # good twin proves the DATAFLOW exonerates, not a registry miss
    reg = donation.build_registry([corpus_file("donation_bad.py"),
                                   corpus_file("donation_good.py")])
    assert reg.get("update") == (0,)
    # the explicit no-donate spec donate_argnums=() must NOT register
    assert "keep_step" not in reg
    assert donation.run([corpus_file("donation_good.py")],
                        registry=reg) == []


# ---------------------------------------------------------------------------
# Corpus: TM301/TM302 jit hygiene + pickle
# ---------------------------------------------------------------------------


def test_jit_hygiene_flags_every_seeded_bug():
    findings = jit_hygiene.run([corpus_file("jit_bad.py")])
    assert lines_of(findings, "TM301") == \
        seeded_lines("jit_bad.py", "TM301")
    assert lines_of(findings, "TM302") == \
        seeded_lines("jit_bad.py", "TM302")


def test_jit_hygiene_silent_on_good_twin():
    assert jit_hygiene.run([corpus_file("jit_good.py")]) == []


# ---------------------------------------------------------------------------
# Corpus: TM401–TM404 site coverage
# ---------------------------------------------------------------------------


def test_site_coverage_all_four_directions():
    code = corpus_file("coverage_code.py")
    doc = os.path.join(CORPUS, "coverage_docs.md")
    findings = site_coverage.run([code], doc, "corpus/coverage_docs.md")
    by_id = {f.check_id: f for f in findings}
    assert set(by_id) == {"TM401", "TM402", "TM403", "TM404"}
    # code-side findings land at the seeded code lines...
    assert by_id["TM401"].line == \
        seeded_lines("coverage_code.py", "TM401")[0]
    assert by_id["TM403"].line == \
        seeded_lines("coverage_code.py", "TM403")[0]
    # ...docs-side findings at the stale docs rows
    assert by_id["TM402"].path == "corpus/coverage_docs.md"
    assert "beta" in by_id["TM402"].message
    assert by_id["TM404"].path == "corpus/coverage_docs.md"
    assert "corpus/ghost_total" in by_id["TM404"].message


def test_inventory_reflects_repo_emissions():
    from theanompi_tpu.analysis.common import iter_source_files

    files = list(iter_source_files(
        os.path.join(REPO, "theanompi_tpu"), REPO))
    names = {e.name for e in site_coverage.collect_metrics(files)}
    # spot-pin a few series every subsystem owns
    assert {"step_ms", "serving/request_ms", "service/wire_bytes_pre",
            "resilience/worker_restarts_total"} <= names
    sites = {f.site for f in site_coverage.collect_fires(files)}
    assert {"worker_step", "service_call", "exchange", "checkpoint",
            "serve_step", "serve_rpc", "decode_step", "ingest_batch",
            "ingest_pull", "router_route", "page_migrate"} == sites


# ---------------------------------------------------------------------------
# Lockgraph: runtime lock-order detection
# ---------------------------------------------------------------------------


def test_lock_inversion_caught_with_full_cycle():
    """The acceptance inversion: thread 1 takes A then B, thread 2
    takes B then A — thread 2's acquire of A must raise with the whole
    cycle, BEFORE blocking (no deadlock, no timeout)."""
    g = LockGraph()
    lock_a = TrackedLock("site.A", graph=g)
    lock_b = TrackedLock("site.B", graph=g)

    def order_ab():
        with lock_a:
            with lock_b:
                pass

    t1 = threading.Thread(target=order_ab)
    t1.start()
    t1.join(5)

    errs: list[BaseException] = []

    def order_ba():
        try:
            with lock_b:
                with lock_a:
                    pass
        except LockOrderError as e:
            errs.append(e)

    t2 = threading.Thread(target=order_ba)
    t2.start()
    t2.join(5)
    assert not t2.is_alive(), "inversion deadlocked instead of raising"
    assert errs, "AB/BA inversion was not detected"
    msg = str(errs[0])
    assert "cycle" in msg and "site.A" in msg and "site.B" in msg
    # the full cycle chain is spelled out
    assert "site.B -> site.A -> site.B" in msg \
        or "site.A -> site.B -> site.A" in msg


def test_consistent_order_never_raises():
    g = LockGraph()
    lock_a = TrackedLock("c.A", graph=g)
    lock_b = TrackedLock("c.B", graph=g)
    for _ in range(3):
        with lock_a:
            with lock_b:
                pass
    assert g.edges() == {"c.A": {"c.B"}}


def test_same_thread_reacquire_raises():
    lock = TrackedLock("r.lock", graph=LockGraph())
    with lock:
        with pytest.raises(LockOrderError, match="re-acquire"):
            lock.acquire()
    # and the lock still works afterwards
    with lock:
        pass


def test_same_site_distinct_instances_nest_freely():
    """Two locks constructed at the same site (two batcher replicas)
    are distinct objects: nesting them is legal and must neither raise
    nor corrupt the held stack."""
    g = LockGraph()
    rep_a = TrackedLock("dup.site", graph=g)
    rep_b = TrackedLock("dup.site", graph=g)
    other = TrackedLock("dup.other", graph=g)
    with rep_a:
        with rep_b:
            with other:
                pass
    # stack bookkeeping survived: a fresh cycle-free nesting still
    # works and the graph recorded the cross-site edge only
    with rep_a:
        with other:
            pass
    assert g.edges() == {"dup.site": {"dup.other"}}


def test_condition_wait_composes_with_tracked_lock():
    g = LockGraph()
    lock = TrackedLock("cv.lock", graph=g)
    cond = threading.Condition(lock)
    box: list[int] = []

    def waiter():
        with cond:
            while not box:
                cond.wait(0.05)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:
        box.append(1)
        cond.notify_all()
    t.join(5)
    assert not t.is_alive()


def test_make_lock_seam(monkeypatch):
    monkeypatch.setenv("THEANOMPI_TPU_LOCKCHECK", "0")
    assert not isinstance(make_lock("x"), TrackedLock)
    monkeypatch.setenv("THEANOMPI_TPU_LOCKCHECK", "1")
    assert isinstance(make_lock("x"), TrackedLock)
    cond = make_condition(make_lock("y"))
    assert isinstance(cond, threading.Condition)


def test_threaded_classes_run_tracked_under_tier1():
    """conftest sets THEANOMPI_TPU_LOCKCHECK=1, so the host plane's
    locks must actually BE tracked in this suite."""
    from theanompi_tpu.resilience.supervisor import WorkerSupervisor
    from theanompi_tpu.serving.batcher import DynamicBatcher

    b = DynamicBatcher(lambda x: x)
    assert isinstance(b._lock, TrackedLock)
    sup = WorkerSupervisor(n_workers=1)
    assert isinstance(sup._lock, TrackedLock)


# ---------------------------------------------------------------------------
# Regression tests for the violations the checkers surfaced
# ---------------------------------------------------------------------------


def test_supervisor_restart_ordinal_from_under_lock():
    """TM101 fix: the backoff ordinal is returned by _handle_failure
    (computed under its lock) instead of a bare _restarts read."""
    from theanompi_tpu.resilience.supervisor import WorkerSupervisor

    sup = WorkerSupervisor(n_workers=2, max_restarts=2, min_workers=1,
                           restart_from=lambda rank: None)
    errors: list[BaseException] = []
    abort = threading.Event()
    assert sup._handle_failure(0, ValueError("x"), errors, abort) == 1
    assert sup._handle_failure(0, ValueError("x"), errors, abort) == 2
    # budget spent -> lost (returns 0), quorum still held
    assert sup._handle_failure(0, ValueError("x"), errors, abort) == 0
    assert sup.lost_workers() == [0]
    assert sup.restart_counts() == {0: 2}
    assert not abort.is_set() and errors == []


def test_batcher_alive_and_dead_rejection():
    """TM101 fix: alive reads _dead under the lock; a dead replica
    rejects immediately with Overloaded."""
    import numpy as np

    from theanompi_tpu.serving.batcher import DynamicBatcher, Overloaded

    b = DynamicBatcher(lambda x: x)
    assert b.alive
    b._mark_dead()
    assert not b.alive
    with pytest.raises(Overloaded):
        b.submit(np.zeros((1, 2), np.float32))
    assert b.stats()["alive"] is False


def test_exchange_pipe_barrier_and_sticky_error():
    """TM101 fix: outstanding/_err are lock-guarded; semantics pinned:
    double submit raises, an exchange error re-raises at collect and
    stays sticky for later submits."""
    from theanompi_tpu.rules.async_rules import _ExchangePipe

    calls: list[int] = []

    def fn(payload):
        calls.append(payload)
        if payload < 0:
            raise ValueError("boom")
        return payload * 10

    pipe = _ExchangePipe(fn, "test/exchange", worker=0)
    try:
        pipe.submit(1)
        with pytest.raises(RuntimeError, match="outstanding"):
            pipe.submit(2)
        payload, result = pipe.collect()
        assert (payload, result) == (1, 10)
        pipe.submit(-1)
        with pytest.raises(ValueError, match="boom"):
            pipe.collect()
        with pytest.raises(ValueError, match="boom"):
            pipe.submit(3)  # sticky error
    finally:
        pipe.close()
    assert calls == [1, -1]


# ---------------------------------------------------------------------------
# Thread-leak fixture
# ---------------------------------------------------------------------------


def test_leak_detector_sees_a_leak_and_clears():
    import conftest

    before = set(threading.enumerate())
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="deliberate-leak",
                         daemon=False)
    t.start()
    try:
        leaked = conftest.leaked_threads(before, grace_s=0.2)
        assert any(th.name == "deliberate-leak" for th in leaked)
    finally:
        stop.set()
        t.join(5)
    assert conftest.leaked_threads(before, grace_s=0.2) == []


# ---------------------------------------------------------------------------
# The repo gate itself
# ---------------------------------------------------------------------------


def test_repo_gate_green_with_committed_baseline():
    t0 = time.monotonic()
    findings = run_checks(REPO)
    dt = time.monotonic() - t0
    baseline = load_baseline(os.path.join(
        REPO, "theanompi_tpu", "analysis", "baseline.json"))
    new, stale = split_by_baseline(findings, baseline)
    assert new == [], "new findings: " + "; ".join(
        f.render() for f in new)
    assert stale == [], f"stale baseline keys: {stale}"
    assert dt < 30, f"checker suite took {dt:.1f}s (budget: 30s)"


def test_tmlint_cli_gate_exit_code():
    assert tmlint_main(["--gate", "--root", REPO]) == 0


def test_tmlint_script_gate_runs_without_jax(tmp_path):
    """tools/tmlint.py must run the gate on a box where `import jax`
    raises (broken plugin, half-installed venv): it loads the analysis
    subpackage behind a parent-package stub so theanompi_tpu/__init__
    (which imports jax via compat) never executes."""
    import subprocess
    import sys as _sys

    (tmp_path / "jax.py").write_text(
        'raise ImportError("poisoned jax - the gate must not import me")')
    env = dict(os.environ, PYTHONPATH=str(tmp_path))
    p = subprocess.run(
        [_sys.executable, os.path.join(REPO, "tools", "tmlint.py"),
         "--gate"],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
        timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "0 new finding(s)" in p.stdout


def test_site_coverage_suppression_covers_all_sites_of_a_name(tmp_path):
    """An inline `# lint: ok TM403` on ANY emission of a metric covers
    the metric, regardless of file-walk order (the suppression is
    about the name, not one call site)."""
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text('monitor.inc("twice/emitted_total")\n')
    b.write_text('monitor.inc("twice/emitted_total")  # lint: ok TM403\n')
    doc = tmp_path / "obs.md"
    doc.write_text("## Metric catalog\n\n| Series |\n|---|\n\n"
                   "## Fault sites\n\n| Site |\n|---|\n")
    for order in ([a, b], [b, a]):
        files = [SourceFile(str(p), p.name) for p in order]
        found = site_coverage.run(files, str(doc), "obs.md")
        assert [f for f in found if f.check_id == "TM403"] == [], order
