"""Subprocess entry for the multi-host tests.

Each OS process gets ``--devices-per-proc`` virtual CPU devices; with
``--nprocs > 1`` the processes join one ``jax.distributed`` job and the
BSP session forms a single global mesh over all of them — the TPU-native
equivalent of the reference's ``tmlauncher``-over-mpirun deployment
(SURVEY.md §2.1/§3.1; mount empty, no file:line).

Emits JSON to ``--out``: per-step train losses (in order), final val
metrics, and mesh facts the parent asserts on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--proc-id", type=int, default=0)
    ap.add_argument("--nprocs", type=int, default=1)
    ap.add_argument("--port", type=int, default=45701)
    ap.add_argument("--devices-per-proc", type=int, default=4)
    ap.add_argument("--out", required=True)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--snapshot-dir", default="/tmp/tm_multihost_snap")
    ap.add_argument("--checkpoint", action="store_true")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO-1: optimizer state sharded over 'data' "
                         "across the process boundary")
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.devices_per_proc}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    if args.nprocs > 1:
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{args.port}",
            num_processes=args.nprocs,
            process_id=args.proc_id,
        )

    from theanompi_tpu.data.cifar10 import Cifar10_data
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.parallel.mesh import data_mesh, is_multiprocess
    from theanompi_tpu.rules.bsp import run_bsp_session
    from theanompi_tpu.utils.recorder import Recorder

    class SmallCifar(Cifar10_model):
        def build_data(self):
            return Cifar10_data(synthetic_n=1024, seed=self.config.seed)

    class CaptureRecorder(Recorder):
        """Keeps every per-step loss across epochs (train_losses resets
        at each epoch summary)."""

        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self.all_losses: list[float] = []

        def train_metrics(self, loss, error, n_images):
            self.all_losses.append(float(loss))
            super().train_metrics(loss, error, n_images)

    cfg = ModelConfig(batch_size=8, n_epochs=100, learning_rate=0.05,
                      print_freq=0, snapshot_dir=args.snapshot_dir,
                      zero_sharding=args.zero)
    devs = jax.devices()
    mesh = data_mesh(len(devs), devs)
    model = SmallCifar(config=cfg, mesh=mesh, verbose=False)
    rec = CaptureRecorder(rank=model.host_rank, size=model.n_workers,
                          print_freq=0)
    result = run_bsp_session(model, resume=args.resume, recorder=rec,
                             max_epochs=args.epochs,
                             checkpoint=args.checkpoint)
    with open(args.out, "w") as f:
        json.dump({
            "proc_id": args.proc_id,
            "n_global_devices": len(devs),
            "n_local_devices": len(jax.local_devices()),
            "multiprocess": is_multiprocess(mesh),
            "losses": rec.all_losses,
            "val": {k: float(v) for k, v in result["val"].items()},
            "epochs_run": result["epochs_run"],
        }, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
