"""Subprocess entry for the multi-host tests.

Each OS process gets ``--devices-per-proc`` virtual CPU devices; with
``--nprocs > 1`` the processes join one ``jax.distributed`` job and the
BSP session forms a single global mesh over all of them — the TPU-native
equivalent of the reference's ``tmlauncher``-over-mpirun deployment
(SURVEY.md §2.1/§3.1; mount empty, no file:line).

Emits JSON to ``--out``: per-step train losses (in order), final val
metrics, and mesh facts the parent asserts on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _donate_race(args, mesh) -> dict:
    """Regression for the async-save/donation seam (ADVICE r2,
    utils/checkpoint.py): save() keeps non-fully-addressable leaves as
    live jax.Arrays whose device buffers the NEXT donating train step
    consumes — correctness rests on Orbax completing the device-to-host
    copy before save() returns.  Here that contract is exercised, not
    assumed: save a cross-process-sharded ZeRO state, immediately
    donate its buffers through more train steps, then restore and
    demand the pre-save values."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from theanompi_tpu.parallel.bsp import TrainState
    from theanompi_tpu.parallel.mesh import shard_batch
    from theanompi_tpu.parallel.zero import (
        init_zero_opt_state,
        make_bsp_zero_step,
    )
    from theanompi_tpu.utils.checkpoint import Checkpointer
    from theanompi_tpu.utils.helper_funcs import build_optimizer

    def loss_fn(params, model_state, batch, rng):
        x, y = batch
        pred = jnp.tanh(x @ params["w1"]) @ params["w2"] + params["b"]
        loss = jnp.mean((pred - y) ** 2)
        return loss, (model_state, {"loss": loss, "error": loss})

    k1, k2 = jax.random.split(jax.random.key(0))
    params = {"w1": jax.random.normal(k1, (5, 7)),
              "w2": jax.random.normal(k2, (7, 3)),
              "b": jnp.zeros((3,))}
    tx = build_optimizer(0.05, optimizer="adamw", momentum=0.9,
                         weight_decay=1e-4)
    opt0, _ = init_zero_opt_state(tx, params, mesh)
    warm = make_bsp_zero_step(loss_fn, tx, mesh, params, donate=False)
    hot = make_bsp_zero_step(loss_fn, tx, mesh, params, donate=True)

    rng_np = np.random.default_rng(1)
    batch = shard_batch(
        (rng_np.standard_normal((32, 5)).astype(np.float32),
         rng_np.standard_normal((32, 3)).astype(np.float32)), mesh)
    rng = jax.random.key(2)
    state0 = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                        opt_state=opt0, model_state={})
    state, _ = warm(state0, batch, rng)  # state0's template stays live

    def shard_values(tree):
        # logical value where one host can hold it; otherwise this
        # host's shards keyed by global index tuple (replicas collapse
        # to one entry; a restored leaf may come back as host numpy —
        # indexing it with the key recovers the comparable slice)
        def leaf_repr(leaf):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                return {s.index: np.asarray(s.data)
                        for s in leaf.addressable_shards}
            return np.asarray(leaf)

        return [leaf_repr(leaf) for leaf in jax.tree.leaves(tree)]

    before = shard_values({"p": state.params, "o": state.opt_state})
    any_global = any(isinstance(l, jax.Array) and not l.is_fully_addressable
                     for l in jax.tree.leaves(state.opt_state))

    ckpt = Checkpointer(args.snapshot_dir, async_save=True)
    ckpt.save(0, {"params": state.params, "opt_state": state.opt_state,
                  "model_state": {}, "epoch": 0, "step": 1})
    # donate the just-saved buffers IMMEDIATELY — a lazy d2h copy in
    # the async save would now read torn/garbage values
    for _ in range(4):
        state, _ = hot(state, batch, rng)
    jax.block_until_ready(jax.tree.leaves(state.params)[0])

    like = {"params": params, "opt_state": opt0, "model_state": {},
            "epoch": 0, "step": 0}
    restored = ckpt.restore(0, like=like)  # fences the background write
    ckpt.close()
    after = shard_values({"p": restored["params"],
                          "o": restored["opt_state"]})
    for b, a in zip(before, after):
        if isinstance(b, dict):
            for key, val in b.items():
                if isinstance(a, dict):
                    assert key in a, (key, sorted(a))
                    got = a[key]
                else:  # restored fully to host — slice out the shard
                    got = np.asarray(a)[key]
                np.testing.assert_allclose(got, val, rtol=0, atol=0)
        else:
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0, atol=0)
    return {"proc_id": args.proc_id, "donate_race_ok": True,
            "state_spans_processes": bool(any_global)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--proc-id", type=int, default=0)
    ap.add_argument("--nprocs", type=int, default=1)
    ap.add_argument("--port", type=int, default=45701)
    ap.add_argument("--devices-per-proc", type=int, default=4)
    ap.add_argument("--out", required=True)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--snapshot-dir", default="/tmp/tm_multihost_snap")
    ap.add_argument("--checkpoint", action="store_true")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO-1: optimizer state sharded over 'data' "
                         "across the process boundary")
    ap.add_argument("--fsdp", action="store_true",
                    help="FSDP: params + optimizer state sharded over "
                         "'data' across the process boundary (GSPMD)")
    ap.add_argument("--donate-race", action="store_true",
                    help="regression (ADVICE r2): async-save sharded "
                         "state, then IMMEDIATELY donate its buffers — "
                         "the restored values must be the pre-save ones")
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.devices_per_proc}"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    if args.nprocs > 1:
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{args.port}",
            num_processes=args.nprocs,
            process_id=args.proc_id,
        )

    from theanompi_tpu.data.cifar10 import Cifar10_data
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.parallel.mesh import data_mesh, is_multiprocess
    from theanompi_tpu.rules.bsp import run_bsp_session
    from theanompi_tpu.utils.recorder import Recorder

    if args.donate_race:
        devs = jax.devices()
        out = _donate_race(args, data_mesh(len(devs), devs))
        with open(args.out, "w") as f:
            json.dump(out, f)
        return 0

    class SmallCifar(Cifar10_model):
        def build_data(self):
            return Cifar10_data(synthetic_n=1024, seed=self.config.seed)

    class CaptureRecorder(Recorder):
        """Keeps every per-step loss across epochs (train_losses resets
        at each epoch summary)."""

        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self.all_losses: list[float] = []

        def train_metrics(self, loss, error, n_images):
            self.all_losses.append(float(loss))
            super().train_metrics(loss, error, n_images)

    cfg = ModelConfig(batch_size=8, n_epochs=100, learning_rate=0.05,
                      print_freq=0, snapshot_dir=args.snapshot_dir,
                      zero_sharding=args.zero, fsdp_sharding=args.fsdp)
    devs = jax.devices()
    mesh = data_mesh(len(devs), devs)
    model = SmallCifar(config=cfg, mesh=mesh, verbose=False)
    rec = CaptureRecorder(rank=model.host_rank, size=model.n_workers,
                          print_freq=0)
    result = run_bsp_session(model, resume=args.resume, recorder=rec,
                             max_epochs=args.epochs,
                             checkpoint=args.checkpoint)
    with open(args.out, "w") as f:
        json.dump({
            "proc_id": args.proc_id,
            "n_global_devices": len(devs),
            "n_local_devices": len(jax.local_devices()),
            "multiprocess": is_multiprocess(mesh),
            "losses": rec.all_losses,
            "val": {k: float(v) for k, v in result["val"].items()},
            "epochs_run": result["epochs_run"],
        }, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
