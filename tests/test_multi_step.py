"""Scanned multi-step training (parallel/bsp.py make_bsp_multi_step):
k iterations in one device program must produce the exact trajectory
of k single-step calls, and the model/epoch plumbing must account
iterations correctly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.parallel.bsp import (
    TrainState,
    make_bsp_multi_step,
    make_bsp_train_step,
)
from theanompi_tpu.parallel.mesh import shard_batch
from theanompi_tpu.utils.helper_funcs import build_sgd_optimizer


def linear_loss(params, model_state, batch, rng):
    x, y = batch
    pred = x @ params["w"] + params["b"]
    loss = jnp.mean((pred - y) ** 2)
    return loss, (model_state, {"loss": loss, "error": loss})


class TestMultiStepEquivalence:
    def test_matches_k_single_steps(self, mesh8):
        k = 3
        tx = build_sgd_optimizer(0.05, momentum=0.9)
        params = {"w": jnp.zeros((4, 1)), "b": jnp.zeros(1)}
        single = make_bsp_train_step(linear_loss, tx, mesh8, donate=False)
        multi = make_bsp_multi_step(linear_loss, tx, mesh8, donate=False)

        rng = np.random.default_rng(0)
        xs = rng.standard_normal((k, 16, 4)).astype(np.float32)
        ys = (xs @ np.array([[1.0], [2.0], [-1.0], [0.5]])).astype(np.float32)
        key = jax.random.key(7)

        # trajectory A: k single steps, rng folded per step
        state_a = TrainState.create(params, tx)
        losses_a = []
        for i in range(k):
            batch = shard_batch((xs[i], ys[i][:, 0]), mesh8)
            state_a, m = single(state_a, batch, jax.random.fold_in(key, i))
            losses_a.append(float(m["loss"]))

        # trajectory B: one scanned program over the stacked batches
        state_b = TrainState.create(params, tx)
        stacked = shard_batch((xs, ys[:, :, 0]), mesh8, spec=P(None, "data"))
        state_b, metrics = multi(state_b, stacked, key)
        losses_b = np.asarray(metrics["loss"])

        np.testing.assert_allclose(losses_b, losses_a, rtol=1e-6)
        for la, lb in zip(jax.tree.leaves(state_a.params),
                          jax.tree.leaves(state_b.params)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=1e-6)
        assert int(state_b.step) == k


def _donated_inputs(lowered_text: str) -> int:
    """Inputs the lowering marks for donation — either already aliased
    to an output (``tf.aliasing_output``) or handed to XLA as a
    reusable buffer (``jax.buffer_donor``; the compiler decides the
    alias at HLO level)."""
    return (lowered_text.count("tf.aliasing_output")
            + lowered_text.count("jax.buffer_donor"))


class TestStagedBatchDonation:
    """ISSUE 3 copy-done fix: the stacked cadence must DONATE the
    staged batch (donate_argnums covers arg 1, not just the state) so
    XLA can reuse its HBM instead of copying around a live input —
    the r3 account charges 2.37 ms/step to 1 334 copy events."""

    def _donors(self, mesh8, **kw):
        tx = build_sgd_optimizer(0.05, momentum=0.9)
        params = {"w": jnp.zeros((4, 1)), "b": jnp.zeros(1)}
        multi = make_bsp_multi_step(linear_loss, tx, mesh8, **kw)
        rng = np.random.default_rng(1)
        xs = rng.standard_normal((2, 16, 4)).astype(np.float32)
        ys = (xs @ np.ones((4, 1)))[:, :, 0].astype(np.float32)
        stacked = shard_batch((xs, ys), mesh8, spec=P(None, "data"))
        state = TrainState.create(params, tx)
        lowered = multi.lower(state, stacked, jax.random.key(0))
        return _donated_inputs(lowered.as_text()), len(
            jax.tree.leaves(state))

    def test_batch_buffers_donated_by_default(self, mesh8):
        donors, n_state = self._donors(mesh8)
        # every state leaf plus BOTH batch leaves (x and y)
        assert donors == n_state + 2

    def test_donate_batch_false_keeps_buffers(self, mesh8):
        # bench.py's device-step leg replays pre-staged batches; the
        # opt-out must really withhold the batch from donation
        donors, n_state = self._donors(mesh8, donate_batch=False)
        assert donors == n_state

    def test_donate_false_overrides_batch_donation(self, mesh8):
        donors, _ = self._donors(mesh8, donate=False)
        assert donors == 0

    def test_bucketed_exchange_keeps_donation(self, mesh8):
        """ISSUE 13: embedding the bucketed collectives in the backward
        (custom_vjp boundary tags) must not change what the cadence
        donates — state leaves AND both batch leaves, same as B=1."""
        from theanompi_tpu.parallel.exchanger import BSP_Exchanger

        base, n_state = self._donors(mesh8)
        bucketed, _ = self._donors(
            mesh8, exchanger=BSP_Exchanger(exchange_buckets=4, avg=True))
        assert bucketed == base == n_state + 2

    def test_model_config_threads_donate_batch(self, mesh8):
        """ModelConfig.donate_batch reaches the compiled cadence."""
        from tests._tiny_models import TinyCifar128

        def donors(**cfg_kw):
            cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                              steps_per_call=2, **cfg_kw)
            m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
            m.compile_iter_fns("avg")
            x = np.zeros((2, 32, 32, 32, 3), np.float32)
            y = np.zeros((2, 32), np.int64)
            lowered = m.train_step_multi.lower(
                m.state, (x, y), jax.random.key(0))
            n = _donated_inputs(lowered.as_text())
            m.cleanup()
            return n

        assert donors() == donors(donate_batch=False) + 2


class TestModelPlumbing:
    def test_cifar_trains_with_steps_per_call(self, mesh8, tmp_path):
        """The contract path: begin_epoch stacks host batches, train_iter
        reports k consumed, the recorder sees every sub-step's metrics."""
        from tests._tiny_models import TinyCifar128
        from theanompi_tpu.utils.recorder import Recorder

        cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                          print_freq=0, steps_per_call=4,
                          snapshot_dir=str(tmp_path))
        m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
        m.compile_iter_fns("avg")
        rec = Recorder(rank=0, size=8, print_freq=0)
        n_iters = m.begin_epoch(0)
        assert n_iters % 4 == 0 and n_iters > 0
        it = 0
        while it < n_iters:
            consumed = m.train_iter(it, rec)
            assert consumed == 4
            it += consumed
        m._flush_metrics(rec)
        # every sub-step produced a metric entry
        assert len(rec.train_losses) == n_iters
        assert np.isfinite(rec.train_losses).all()
        m.cleanup()

    def test_async_rules_reject_steps_per_call(self, tmp_path):
        """Multi-step scanning would skip the async rules' between-
        iteration exchange points — they must refuse it loudly."""
        from theanompi_tpu import EASGD

        cfg = ModelConfig(batch_size=4, n_epochs=1, steps_per_call=2,
                          snapshot_dir=str(tmp_path))
        rule = EASGD()
        with pytest.raises(ValueError, match="steps_per_call"):
            rule.init(devices=2, modelfile="tests._tiny_models",
                      modelclass="TinyCifar", config=cfg, checkpoint=False)
            rule.wait()

    @pytest.mark.slow
    def test_run_bsp_session_with_multi_step(self, mesh8, tmp_path):
        from tests._tiny_models import TinyCifar
        from theanompi_tpu.rules.bsp import run_bsp_session

        cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                          print_freq=0, steps_per_call=2,
                          snapshot_dir=str(tmp_path))
        m = TinyCifar(config=cfg, mesh=mesh8, verbose=False)
        res = run_bsp_session(m, checkpoint=False)
        assert np.isfinite(res["val"]["loss"])
