"""Profiler hook (SURVEY.md §5.1) + show_record output tool."""

import json
import os
import subprocess
import sys

import numpy as np


def test_step_profiler_writes_trace(tmp_path, mesh8):
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.rules.bsp import run_bsp_session
    from theanompi_tpu.data.cifar10 import Cifar10_data

    class Tiny(Cifar10_model):
        def build_data(self):
            return Cifar10_data(synthetic_n=256)

    cfg = ModelConfig(batch_size=2, n_epochs=1, print_freq=10**9,
                      compute_dtype="float32")
    m = Tiny(config=cfg, mesh=mesh8)
    trace_dir = str(tmp_path / "trace")
    run_bsp_session(m, max_epochs=1, checkpoint=False,
                    profile_dir=trace_dir)
    # jax.profiler writes plugins/profile/<ts>/*; just require non-empty
    found = [os.path.join(dp, f) for dp, _, fs in os.walk(trace_dir)
             for f in fs]
    assert found, f"no trace files under {trace_dir}"


def test_step_profiler_noop_without_dir(monkeypatch):
    from theanompi_tpu.utils.profiling import StepProfiler

    monkeypatch.delenv("THEANOMPI_TPU_PROFILE", raising=False)
    p = StepProfiler()
    assert not p.enabled
    p.maybe_start(); p.step(); p.stop()  # all no-ops


def test_show_record_tool(tmp_path):
    recs = [
        {"epoch": i, "wall_time_s": 10.0, "images_per_sec": 100.0 + i,
         "train_loss": 2.0 - 0.1 * i, "train_error": 0.5,
         "val_loss": 1.9 - 0.1 * i, "val_error": 0.4 - 0.02 * i,
         "time": {"calc": 8.0, "comm": 0.0, "wait": 0.5, "load": 0.2}}
        for i in range(5)
    ]
    with open(tmp_path / "record_rank0.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    out = subprocess.run(
        [sys.executable, os.path.join("tools", "show_record.py"),
         str(tmp_path)],
        capture_output=True, text=True, cwd=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert "images/sec" in out.stdout and "train_loss" in out.stdout
    assert "4" in out.stdout  # last epoch row present


def test_step_profiler_context_manager_flushes_on_crash(tmp_path,
                                                        monkeypatch):
    # a crash mid-capture must still stop the trace (stop_trace is what
    # flushes the files) — the context manager guarantees it
    from theanompi_tpu.utils.profiling import StepProfiler

    calls = []
    monkeypatch.setattr("jax.profiler.start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr("jax.profiler.stop_trace",
                        lambda: calls.append(("stop",)))
    try:
        with StepProfiler(str(tmp_path), n_steps=100) as p:
            p.step()
            raise RuntimeError("mid-capture crash")
    except RuntimeError:
        pass
    assert calls == [("start", str(tmp_path)), ("stop",)]

    # and a no-dir profiler stays a no-op as a context manager too
    monkeypatch.delenv("THEANOMPI_TPU_PROFILE", raising=False)
    with StepProfiler() as p:
        p.step()
    assert not any(c[0] == "start" for c in calls[2:])


def test_step_profiler_spans_epochs(tmp_path, monkeypatch):
    # n_steps larger than one epoch: the trace must keep running into
    # the next epoch instead of silently truncating at the boundary
    from theanompi_tpu.utils.profiling import StepProfiler

    calls = []
    monkeypatch.setattr("jax.profiler.start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr("jax.profiler.stop_trace",
                        lambda: calls.append(("stop",)))
    p = StepProfiler(str(tmp_path), n_steps=5)
    p.maybe_start()
    for _ in range(3):   # epoch 0: 3 iters — must NOT stop
        p.step()
    assert calls == [("start", str(tmp_path))]
    for _ in range(2):   # epoch 1 continues the same trace
        p.step()
    assert calls[-1] == ("stop",)
    p.maybe_start()      # done: no restart
    assert sum(c[0] == "start" for c in calls) == 1
