"""Falsifiable accuracy oracles (VERDICT r2 #5).

The round-2 synthetic convergence artifacts hit val error 0.000 —
memorization of a noiseless generator proves the spine, not learning,
and no optimization regression could ever fail it.  These oracles have
a COMPUTABLE NONZERO floor: labels carry irreducible noise ρ, so the
Bayes-optimal val error is the realized flipped-label fraction
(≈ ρ·(C-1)/C).  A converged model must land ON the floor from above —
below it the oracle leaks, stuck above it the stack (LR schedule,
augment, BN, optimizer) regressed.  Train noise is a fixed draw
(memorizable — train error may dip under the floor) while val draws
are disjoint with independent noise, so memorization shows up on the
train side only.
"""

from __future__ import annotations

import numpy as np
import pytest

from theanompi_tpu.data.cifar10 import Cifar10_data
from theanompi_tpu.data.imagenet import ImageNet_data


def test_cifar_noise_floor_realized_and_disjoint():
    d = Cifar10_data(synthetic_n=8192, label_noise=0.2, seed=3)
    assert d.synthetic
    # realized floor near the ρ·(C-1)/C = 0.18 expectation (binomial
    # slack at n_val = 1024)
    assert d.val_noise_frac == pytest.approx(0.18, abs=0.04)
    assert d.train_noise_frac == pytest.approx(0.18, abs=0.02)
    # val draws are disjoint from train (different images, not a split)
    assert d.x_train.shape[0] == 8192 and d.x_val.shape[0] == 1024
    assert not np.array_equal(d.x_train[:1024], d.x_val)
    # the noiseless default keeps a zero floor
    clean = Cifar10_data(synthetic_n=512, seed=3)
    assert clean.val_noise_frac == 0.0 and clean.train_noise_frac == 0.0


def test_imagenet_per_draw_noise_rate():
    """Pool images recur, so ImageNet noise is re-drawn PER BATCH —
    with a single-image pool (true label 0) the flipped fraction over
    many draws must match ρ·(C-1)/C."""
    d = ImageNet_data(crop=32, synthetic_n=4096, synthetic_pool=1,
                      synthetic_store=40, label_noise=0.3, seed=5)
    ys = np.concatenate(
        [y for _, y in d.train_batches(epoch=0, global_batch=256)])
    assert ys.size == 4096
    frac = float((ys != 0).mean())
    assert frac == pytest.approx(0.3 * 999 / 1000, abs=0.03)
    # and the SAME image carries different labels across draws —
    # per-draw noise is not memorizable
    assert len(set(ys.tolist())) > 10


def test_label_noise_refused_on_real_data(tmp_path):
    """label_noise is a synthetic-oracle knob; silently corrupting a
    real dataset's labels would be a training-data bug."""
    x = np.zeros((8, 40, 40, 3), np.uint8)
    y = np.zeros(8, np.int64)
    np.savez(tmp_path / "train_000.npz", x=x, y=y)
    np.savez(tmp_path / "val_000.npz", x=x, y=y)
    with pytest.raises(ValueError, match="synthetic-oracle knob"):
        ImageNet_data(data_dir=str(tmp_path), crop=32, label_noise=0.1)


@pytest.mark.slow
def test_cifar_converges_to_noise_floor(tmp_path, mesh8):
    """The CNN stack must converge TO the floor, not through it: val
    error within statistical slack of the realized flipped fraction.
    A broken LR schedule / augment / BN leaves it far above; a leaky
    oracle (val noise visible at train time) would dive below."""
    from tests._tiny_models import NoisyTinyCifar
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.rules.bsp import run_bsp_session

    # the round-2 "modern stack" recipe (artifacts/cpu_convergence_
    # modern reached 0.0078 clean in 10 epochs): AdamW + 2-epoch
    # warmup into cosine + label smoothing
    cfg = ModelConfig(batch_size=8, n_epochs=15, learning_rate=0.002,
                      optimizer="adamw", weight_decay=0.01,
                      lr_schedule="cosine", warmup_epochs=2,
                      label_smoothing=0.05,
                      print_freq=0, snapshot_dir=str(tmp_path))
    model = NoisyTinyCifar(config=cfg, mesh=mesh8, verbose=False)
    floor = model.data.val_noise_frac
    assert 0.12 < floor < 0.24  # sanity: the oracle is actually noisy
    res = run_bsp_session(model, checkpoint=False)
    err = float(res["val"]["error"])
    # the val noise realization is FIXED, so a Bayes-optimal model
    # scores EXACTLY the floor; below it only by model mistakes that
    # happen to coincide with flipped labels (tiny) — anything more
    # means the oracle leaks.  Above: generous convergence slack.
    # (observed: the CLI artifact run landed at floor + 0.002)
    assert floor - 0.02 <= err <= floor + 0.075, (err, floor)


@pytest.mark.slow
def test_lm_converges_to_grammar_entropy_floor(tmp_path, mesh8):
    """The LM oracle was falsifiable all along — its floor just went
    uncomputed: SeqLM_data emits ``table[tok]`` w.p. 1-noise, else a
    uniform token, so the Bayes next-token error is noise·(V-1)/V and
    the optimal CE is the grammar's conditional entropy.  Round 2's
    'plateau at 0.099' (VERDICT r2 what's-missing #3) is EXACTLY the
    noise=0.1, V=256 floor (0.0996) — the model had converged to
    Bayes-optimal.  Here: both-sided assertion at V=32 that a broken
    schedule/attention/SP regression would fail."""
    import math

    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.models.transformer import TransformerLM
    from theanompi_tpu.rules.bsp import run_bsp_session

    vocab, noise = 32, 0.1
    cfg = ModelConfig(batch_size=8, n_epochs=4, learning_rate=0.5,
                      momentum=0.9, weight_decay=0.0,
                      lr_schedule="constant", print_freq=0,
                      snapshot_dir=str(tmp_path))
    model = TransformerLM(config=cfg, mesh=mesh8, vocab=vocab,
                          seq_len=32, n_layers=2, d_model=64, n_heads=4)
    assert model.data.noise == noise  # floor math matches the data
    err_floor = noise * (vocab - 1) / vocab
    p_correct = 1 - noise + noise / vocab
    p_other = noise / vocab
    ce_floor = -(p_correct * math.log(p_correct)
                 + (vocab - 1) * p_other * math.log(p_other))

    res = run_bsp_session(model, checkpoint=False)
    err = float(res["val"]["error"])
    loss = float(res["val"]["loss"])
    # val = 512 seqs x 32 tokens ⇒ binomial σ ≈ 0.0023; the slack is
    # model imperfection headroom, the LOWER bound is the oracle
    assert err_floor - 0.01 <= err <= err_floor + 0.03, (err, err_floor)
    assert ce_floor - 0.02 <= loss <= ce_floor + 0.15, (loss, ce_floor)


@pytest.mark.slow
def test_resnet_recipe_90_epochs_hits_floor(tmp_path, mesh8):
    """The bundled 90-epoch ResNet recipe SHAPE (step decays at
    30/60/80 + momentum + weight decay + bf16 + device augment + BN)
    at tiny width against the per-draw ρ=0.25 oracle: after the full
    schedule, val error must sit on the ≈0.25 floor — proving the
    schedule trains and the oracle can fail."""
    import dataclasses

    from tests._tiny_models import TinyRecipeResNet
    from theanompi_tpu.rules.bsp import run_bsp_session

    cfg = dataclasses.replace(
        TinyRecipeResNet.default_config(),
        batch_size=8,              # x8 devices = global 64
        learning_rate=0.02,        # per-batch-128 rate, linearly scaled
        print_freq=0,
        snapshot_dir=str(tmp_path))
    assert cfg.n_epochs == 90 and cfg.lr_decay_epochs == (30, 60, 80)
    model = TinyRecipeResNet(config=cfg, mesh=mesh8, verbose=False)
    res = run_bsp_session(model, checkpoint=False)
    err = float(res["val"]["error"])
    # floor 0.25·999/1000; the val rng is epoch-independent, so ONE
    # binomial realization (n_val=256 ⇒ σ≈0.027) applies to every
    # eval; chance for an untrained net is ≈0.98
    assert 0.25 - 0.085 <= err <= 0.25 + 0.085, err


@pytest.mark.slow
@pytest.mark.gate  # preflight's slow-subset gate: this e2e is the one
# slow test whose silent breakage has actually happened (round 3
# committed it never-run and failing; round-4 verdict weak #6)
def test_jpeg_tree_to_training_end_to_end(tmp_path, mesh8):
    """VERDICT r2 #5: the real-data loaders driven through an actual
    training run — JPEG tree → npz shards → ImageNet_data → 8 BSP
    epochs (~1 min on the 1-core host) — not just fixture
    round-trips."""
    import dataclasses
    import os

    PIL = pytest.importorskip("PIL")  # noqa: F841
    from tests._tiny_models import TinyRecipeResNet
    from tests.test_imagenet_prepare import make_jpeg_tree
    from theanompi_tpu.data.imagenet import prepare_imagenet_from_images
    from theanompi_tpu.rules.bsp import run_bsp_session

    src = tmp_path / "raw"
    shards = tmp_path / "shards"
    os.makedirs(src)
    make_jpeg_tree(str(src), n_classes=3, per_class=64, size=(40, 40))
    classes = None
    for prefix in ("train", "val"):
        prepare_imagenet_from_images(
            str(src), str(shards), prefix=prefix, store=40, shard_size=32,
            class_to_idx=classes, workers=2)
        if classes is None:
            import json

            with open(shards / "classes.json") as fh:
                classes = json.load(fh)

    class JpegResNet(TinyRecipeResNet):
        def build_data(self):
            return ImageNet_data(data_dir=str(shards), crop=32,
                                 seed=self.config.seed,
                                 augment_on_device=self.config.
                                 augment_on_device)

    cfg = dataclasses.replace(
        JpegResNet.default_config(), batch_size=4, n_epochs=8,
        learning_rate=0.005,   # per-128 rate; linear x8 workers = 0.04
        # per-device batch 4 is too small for per-shard BN statistics:
        # running stats never match eval-time distributions (chance val
        # error at converged train loss — the round-3 latent failure).
        # Cross-replica BN computes stats over the global batch of 32
        sync_bn=True,
        print_freq=0, snapshot_dir=str(tmp_path))
    model = JpegResNet(config=cfg, mesh=mesh8, verbose=False)
    assert not model.data.synthetic
    res = run_bsp_session(model, checkpoint=False)
    # 3 solid-color classes: a working loader+train path separates
    # them quickly (chance error ≈ 0.67)
    assert float(res["val"]["error"]) < 0.34, res["val"]