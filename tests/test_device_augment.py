"""Device-side augmentation (ops/augment.py): the TPU-native data-path
inversion — host ships raw uint8, the jitted step crops/flips/
normalizes (round-2 redesign; the 1-core host cannot augment at device
rate)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.data.imagenet import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    ImageNet_data,
)
from theanompi_tpu.data.utils import center_normalize
from theanompi_tpu.ops.augment import make_device_augment


def u8_images(n=8, hw=20, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (n, hw, hw, 3), np.uint8)


class TestMakeDeviceAugment:
    def test_train_shape_dtype_and_bounds(self):
        t = make_device_augment(16, mean=IMAGENET_MEAN, std=IMAGENET_STD)
        x = u8_images()
        out = t(jnp.asarray(x), jax.random.key(0), train=True)
        assert out.shape == (8, 16, 16, 3) and out.dtype == jnp.float32
        # normalized uint8 stays within the analytic bounds
        lo = (0.0 - max(IMAGENET_MEAN)) / min(IMAGENET_STD)
        hi = (1.0 - min(IMAGENET_MEAN)) / min(IMAGENET_STD)
        assert float(out.min()) >= lo - 1e-5
        assert float(out.max()) <= hi + 1e-5

    def test_train_deterministic_in_rng(self):
        t = make_device_augment(16)
        x = jnp.asarray(u8_images())
        a = t(x, jax.random.key(7), train=True)
        b = t(x, jax.random.key(7), train=True)
        c = t(x, jax.random.key(8), train=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_eval_matches_host_center_normalize(self):
        """The device eval path must agree with the host oracle
        (data/utils.center_normalize) to fp32 tolerance."""
        t = make_device_augment(16, mean=IMAGENET_MEAN, std=IMAGENET_STD)
        x = u8_images(n=4)
        got = np.asarray(t(jnp.asarray(x), None, train=False))
        want = center_normalize(x, 16, 16, mean=IMAGENET_MEAN,
                                std=IMAGENET_STD)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_crops_are_windows_of_source(self):
        """Every train output must be an exact window (possibly
        mirrored) of its source image."""
        t = make_device_augment(16, flip=True)
        x = u8_images(n=6, hw=20)
        out = np.asarray(t(jnp.asarray(x), jax.random.key(3), train=True))
        restored = np.rint(out * 255.0).astype(np.int64)
        for i in range(len(x)):
            found = False
            for y0 in range(5):
                for x0 in range(5):
                    win = x[i, y0:y0 + 16, x0:x0 + 16].astype(np.int64)
                    if (np.array_equal(win, restored[i])
                            or np.array_equal(win[:, ::-1], restored[i])):
                        found = True
            assert found, f"crop {i} is not a window of its source"

    def test_pad_reflect(self):
        t = make_device_augment(20, pad=2, flip=False)
        x = u8_images(n=2, hw=20)
        out = t(jnp.asarray(x), jax.random.key(0), train=True)
        assert out.shape == (2, 20, 20, 3)

    def test_too_small_rejected(self):
        t = make_device_augment(32)
        with pytest.raises(ValueError):
            t(jnp.asarray(u8_images(hw=20)), jax.random.key(0), train=True)


class TestImageNetDeviceAugment:
    def test_batches_stay_uint8_at_store_size(self):
        d = ImageNet_data(crop=16, synthetic_n=128, synthetic_pool=8,
                          synthetic_store=20, augment_on_device=True)
        assert d.device_transform is not None
        x, y = next(iter(d.train_batches(0, 32)))
        assert x.dtype == np.uint8 and x.shape == (32, 20, 20, 3)
        xv, _ = next(iter(d.val_batches(32)))
        assert xv.dtype == np.uint8 and xv.shape == (32, 20, 20, 3)
        # sample_shape still advertises the post-transform (crop) shape
        assert d.sample_shape == (16, 16, 3)

    def test_host_path_unchanged_by_default(self):
        d = ImageNet_data(crop=16, synthetic_n=128, synthetic_pool=8,
                          synthetic_store=20)
        assert d.device_transform is None
        x, _ = next(iter(d.train_batches(0, 32)))
        assert x.dtype == np.float32 and x.shape == (32, 16, 16, 3)


class TestCifarDeviceAugment:
    def test_uint8_batches_and_pad_crop(self):
        from theanompi_tpu.data.cifar10 import Cifar10_data

        d = Cifar10_data(synthetic_n=256, augment_on_device=True)
        assert d.device_transform is not None
        x, y = next(iter(d.train_batches(0, 32)))
        assert x.dtype == np.uint8 and x.shape == (32, 32, 32, 3)
        out = d.device_transform(jnp.asarray(x), jax.random.key(0),
                                 train=True)
        assert out.shape == (32, 32, 32, 3) and out.dtype == jnp.float32

    def test_eval_transform_matches_host_val(self):
        """With pad=4 and crop=32, the eval center crop of the padded
        image IS the original image — the device val path must equal
        the host val path exactly."""
        from theanompi_tpu.data.cifar10 import CIFAR_MEAN, CIFAR_STD, \
            Cifar10_data

        d_dev = Cifar10_data(synthetic_n=256, augment_on_device=True)
        d_host = Cifar10_data(synthetic_n=256)
        (x_dev, _), (x_host, _) = (next(iter(d.val_batches(32)))
                                   for d in (d_dev, d_host))
        got = np.asarray(d_dev.device_transform(jnp.asarray(x_dev), None,
                                                train=False))
        np.testing.assert_allclose(got, x_host, rtol=1e-6, atol=1e-6)


class TestEndToEnd:
    def test_resnet_trains_on_device_augmented_batches(self, mesh8):
        """Full BSP step over the 8-device mesh with uint8 batches:
        the transform runs inside the jitted step, loss decreases-ish
        (finite), eval path works."""
        from theanompi_tpu.models.base import ModelConfig
        from theanompi_tpu.models.resnet50 import ResNet50

        class TinyResNet(ResNet50):
            stage_sizes = (1, 1)

            def build_module(self):
                import flax.linen as nn

                from theanompi_tpu.models.resnet50 import ResNet

                return ResNet(stage_sizes=self.stage_sizes, width=8,
                              n_classes=self.data.n_classes,
                              dtype=self._compute_dtype())

            def build_data(self):
                return ImageNet_data(crop=16, synthetic_n=128,
                                     synthetic_pool=8, synthetic_store=20,
                                     augment_on_device=True,
                                     seed=self.config.seed)

        cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.01,
                          print_freq=0, augment_on_device=True)
        m = TinyResNet(config=cfg, mesh=mesh8, verbose=False)
        m.compile_iter_fns("avg")
        from theanompi_tpu.utils.recorder import Recorder

        import jax

        before = jax.tree.map(np.asarray, m.state.model_state)
        rec = Recorder(rank=0, size=8, print_freq=0)
        n = m.begin_epoch(0)
        for it in range(min(n, 3)):
            m.train_iter(it, rec)
        m._flush_metrics(rec)
        assert np.isfinite(rec.train_losses).all()
        # BN running stats moved through the train_iter path (the
        # fast-set home of the contract test_bn_state_updates pins in
        # the slow set)
        after = jax.tree.map(np.asarray, m.state.model_state)
        assert any(not np.allclose(a, b)
                   for a, b in zip(jax.tree.leaves(after),
                                   jax.tree.leaves(before)))
        val = m.val_epoch(rec)
        assert np.isfinite(val["loss"])
        m.cleanup()
