"""serving/: export → verified read-only load → dynamic batching →
multi-replica server, on the CPU mesh (ISSUE 4 acceptance):

* N concurrent clients get BIT-identical answers to single-request
  serving (same bucket shape → same compiled program; pad rows are
  row-independent in eval mode);
* dynamic batches with occupancy > 1 actually form;
* queue-depth overload returns ``Overloaded`` instead of queueing
  unboundedly;
* a hot reload to a newer export completes with zero failed in-flight
  requests;
plus replica restart-from-export under an injected ``serve_step``
fault, the wire protocol, and the launcher's SERVE surface.
"""

from __future__ import annotations

import os
import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu import monitor
from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.resilience import faults
from theanompi_tpu.serving import (
    BatchPolicy,
    DynamicBatcher,
    InferenceClient,
    InferenceServer,
    InferenceSession,
    Overloaded,
    default_buckets,
    export_model,
    latest_export_version,
    load_export,
    pick_bucket,
    serve,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def tiny_export(tmp_path_factory):
    """One untrained TinyCifar export (v0) shared by the module: the
    (model, export_dir, request rows) triple every test builds on."""
    from tests._tiny_models import TinyCifar

    model = TinyCifar(config=ModelConfig(batch_size=8, n_epochs=1,
                                         print_freq=0), verbose=False)
    export_dir = str(tmp_path_factory.mktemp("serving") / "export")
    export_model(model, export_dir, version=0)
    x = np.asarray(model.data.x_val[:8])
    return model, export_dir, x


@pytest.fixture()
def wire_server(tiny_export):
    """A 2-replica server on a real socket; yields (client-factory,
    server).  Buckets pinned to (4,): every batch — single-request or
    coalesced — runs the SAME compiled program, the bit-identity
    precondition."""
    model, export_dir, _ = tiny_export
    key_before = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
    policy = BatchPolicy(max_batch=4, max_delay_ms=30.0, buckets=(4,),
                         max_queue=16)
    server = InferenceServer(export_dir, replicas=2, policy=policy,
                             reload_poll_s=0, model=model).start()
    port = _free_port()
    ready, stop = threading.Event(), threading.Event()
    t = threading.Thread(target=serve,
                         args=(server, "127.0.0.1", port, ready, stop),
                         daemon=True)
    t.start()
    assert ready.wait(30)
    addr = f"127.0.0.1:{port}"
    clients: list[InferenceClient] = []

    def make_client() -> InferenceClient:
        c = InferenceClient(addr)
        clients.append(c)
        return c

    yield make_client, server
    try:
        InferenceClient(addr).shutdown()
    except Exception:
        stop.set()
    for c in clients:
        c.close()
    t.join(timeout=5)
    server.stop()
    faults.clear()
    if key_before is None:
        os.environ.pop("THEANOMPI_TPU_SERVICE_KEY", None)
    else:
        os.environ["THEANOMPI_TPU_SERVICE_KEY"] = key_before


# ---------------------------------------------------------------------------
# export.py
# ---------------------------------------------------------------------------


class TestExport:
    def test_versioned_verified_export_round_trips(self, tiny_export):
        model, export_dir, _ = tiny_export
        assert latest_export_version(export_dir) == 0
        assert os.path.exists(os.path.join(export_dir,
                                           "manifest_0.json"))
        loaded = load_export(export_dir)
        assert loaded.version == 0
        assert loaded.meta["modelclass"] == "TinyCifar"
        for a, b in zip(jax.tree.leaves(loaded.params),
                        jax.tree.leaves(jax.device_get(
                            model.state.params))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_reexporting_a_version_refuses(self, tiny_export, tmp_path):
        model, _, _ = tiny_export
        d = str(tmp_path / "exp")
        export_model(model, d, version=3)
        with pytest.raises(ValueError, match="immutable"):
            export_model(model, d, version=3)

    def test_serving_load_leaves_export_dir_byte_identical(
            self, tiny_export):
        """The read-only reader contract end to end: a full verified
        serving load (manifest digests + Orbax restore) mutates
        NOTHING — sizes, hashes, mtimes-of-content, file set all
        unchanged (the satellite's pin lives in test_checkpoint.py at
        the Checkpointer layer; this is the serving-path version)."""
        import hashlib

        _, export_dir, _ = tiny_export

        def digest_tree(root):
            out = {}
            for r, dirs, files in os.walk(root):
                for name in files:
                    full = os.path.join(r, name)
                    with open(full, "rb") as f:
                        out[os.path.relpath(full, root)] = (
                            hashlib.sha256(f.read()).hexdigest())
            return out

        before = digest_tree(export_dir)
        InferenceSession.from_export(export_dir)
        assert digest_tree(export_dir) == before

    def test_half_published_version_falls_back_to_meta(
            self, tiny_export, tmp_path):
        """Exporter killed between the checkpoint publish and the meta
        sidecar write: that version must cost a FALLBACK (and not be
        offered to the reload watcher), never a server that crashes on
        meta={} at every (re)start."""
        model, _, _ = tiny_export
        d = str(tmp_path / "exp")
        export_model(model, d, version=0)
        export_model(model, d, version=1)
        os.unlink(os.path.join(d, "export_meta_1.json"))  # the kill
        # publish marker is the meta (written last): v1 isn't offered
        assert latest_export_version(d) == 0
        loaded = load_export(d)
        assert loaded.version == 0
        assert loaded.meta["modelclass"] == "TinyCifar"

    def test_swap_is_monotonic(self, tiny_export):
        """A replica restart that loaded the export while a concurrent
        hot reload published a newer version must not roll the session
        back; same-version swaps (the restart itself) are allowed."""
        model, export_dir, x = tiny_export
        loaded = load_export(export_dir)
        s = InferenceSession(model, params=loaded.params,
                             model_state=loaded.model_state,
                             version=5, donate=False)
        assert not s.swap(3, loaded.params, loaded.model_state)
        assert s.version == 5
        assert s.swap(5, loaded.params, loaded.model_state)
        assert s.swap(6, loaded.params, loaded.model_state)
        assert s.version == 6

    def test_session_matches_model_eval_path(self, tiny_export):
        """The frozen inference fn IS the model's eval path: same
        module, eval transform, train=False running-stat BN."""
        model, export_dir, x = tiny_export
        sess = InferenceSession(model)
        got = sess.infer(x)
        transform = getattr(model.data, "device_transform", None)
        xe = (transform(jnp.asarray(x), None, train=False)
              if transform is not None else jnp.asarray(x))
        want = model.module.apply(
            {"params": model.state.params,
             **jax.device_get(model.state.model_state)},
            xe, train=False)
        np.testing.assert_allclose(got, np.asarray(want, np.float32),
                                   rtol=1e-6, atol=1e-6)

    def test_infer_input_is_donated(self, tiny_export):
        """The export contract says donation ON: the request batch
        buffer is handed to XLA for reuse (aliased when shapes allow,
        else at least marked ``jax.buffer_donor``)."""
        model, _, x = tiny_export
        sess = InferenceSession(model)
        _, params, ms = sess._live
        text = sess._jit.lower(params, ms, jnp.asarray(x)).as_text()
        assert (text.count("tf.aliasing_output")
                + text.count("jax.buffer_donor")) >= 1

    def test_swap_changes_output_without_recompile(self, tiny_export):
        model, _, x = tiny_export
        sess = InferenceSession(model)
        y0 = sess.infer(x)
        zeroed = jax.tree.map(np.zeros_like,
                              jax.device_get(model.state.params))
        sess.swap(1, zeroed, jax.device_get(model.state.model_state))
        y1 = sess.infer(x)
        assert sess.version == 1
        assert not np.allclose(y0, y1)
        # zero params → identical logits per class for every row
        np.testing.assert_allclose(y1, y1[:1].repeat(len(x), 0),
                                   atol=1e-6)


# ---------------------------------------------------------------------------
# batcher.py (no wire, no model — a row-wise fake)
# ---------------------------------------------------------------------------


def _row_fn(delay_s: float = 0.0):
    """Row-independent fake inference recording each padded shape."""
    shapes: list[tuple] = []

    def run(x):
        shapes.append(x.shape)
        if delay_s:
            time.sleep(delay_s)
        return x * 2.0
    run.shapes = shapes
    return run


class TestBatcher:
    def test_default_buckets_and_pick(self):
        assert default_buckets(8) == (1, 2, 4, 8)
        assert default_buckets(6) == (1, 2, 4, 6)
        assert pick_bucket(3, (1, 2, 4, 8)) == 4
        with pytest.raises(ValueError, match="exceed"):
            pick_bucket(9, (1, 2, 4, 8))

    def test_bucket_must_cover_max_batch(self):
        with pytest.raises(ValueError, match="max_batch"):
            BatchPolicy(max_batch=8, buckets=(1, 4)).resolved_buckets()

    def test_concurrent_requests_coalesce_and_split(self):
        run = _row_fn(delay_s=0.01)
        b = DynamicBatcher(run, BatchPolicy(max_batch=8,
                                            max_delay_ms=50.0)).start()
        try:
            xs = [np.full((1, 3), i, np.float32) for i in range(6)]
            outs = [None] * 6
            ths = [threading.Thread(
                target=lambda i=i: outs.__setitem__(i, b.submit(xs[i])))
                for i in range(6)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            for i in range(6):
                np.testing.assert_array_equal(outs[i], xs[i] * 2.0)
            assert b.max_occupancy > 1
            # every dispatched shape was a bucket shape
            assert {s[0] for s in run.shapes} <= set(b.buckets)
        finally:
            b.stop()

    def test_overload_rejects_fast_and_bounded(self):
        run = _row_fn(delay_s=0.2)  # slow replica
        b = DynamicBatcher(run, BatchPolicy(
            max_batch=1, max_delay_ms=0.0, buckets=(1,),
            max_queue=2)).start()
        try:
            results = []
            lock = threading.Lock()

            def go(i):
                t0 = time.monotonic()
                try:
                    b.submit(np.ones((1, 2), np.float32))
                    out = "ok"
                except Overloaded:
                    out = "overloaded"
                with lock:
                    results.append((out, time.monotonic() - t0))

            ths = [threading.Thread(target=go, args=(i,))
                   for i in range(10)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            kinds = [r[0] for r in results]
            assert "overloaded" in kinds and "ok" in kinds
            # rejections are O(1), not queue-the-world: far faster
            # than serving the whole flood serially (10 x 0.2s)
            rejected = [dt for k, dt in results if k == "overloaded"]
            assert max(rejected) < 0.5
            assert b.alive
        finally:
            b.stop()

    def test_oversize_request_rejected(self):
        b = DynamicBatcher(_row_fn(), BatchPolicy(max_batch=4))
        with pytest.raises(ValueError, match="split"):
            b.submit(np.ones((5, 2), np.float32))

    def test_timeout_reclaims_admission_slot(self):
        """A submit() timeout must pull the abandoned request back out
        of the queue: zombie entries must not hold max_queue slots
        (starving live requests) nor burn device batches nobody
        awaits."""
        gate = threading.Event()

        def wedged(x):
            gate.wait(10)  # first batch wedges the collector
            return x * 2.0
        b = DynamicBatcher(wedged, BatchPolicy(
            max_batch=1, max_delay_ms=0.0, buckets=(1,), max_queue=1,
            submit_timeout_s=0.3)).start()
        try:
            x = np.ones((1, 2), np.float32)
            t1 = threading.Thread(
                target=lambda: pytest.raises(TimeoutError,
                                             b.submit, x))
            t1.start()
            time.sleep(0.05)  # t1's request is now IN-FLIGHT (wedged)
            # this one stays QUEUED behind it and times out
            with pytest.raises(TimeoutError, match="timed out"):
                b.submit(x)
            # the slot came back: a fresh request is ADMITTED (queued),
            # not rejected with Overloaded
            assert b.queue_depth() == 0
            t2 = threading.Thread(target=lambda: b.submit(x))
            t2.start()
            time.sleep(0.05)
            assert b.queue_depth() == 1  # admitted, no Overloaded
            gate.set()
            t1.join(timeout=5)
            t2.join(timeout=5)
        finally:
            gate.set()
            b.stop()

    def test_batch_error_fails_batch_and_hook_decides(self):
        calls = {"n": 0}

        def boom(x):
            raise RuntimeError("bad batch")

        def on_err(e):
            calls["n"] += 1
            return False  # lose the replica

        b = DynamicBatcher(boom, BatchPolicy(max_batch=2,
                                             max_delay_ms=0.0),
                           on_batch_error=on_err).start()
        try:
            with pytest.raises(RuntimeError, match="bad batch"):
                b.submit(np.ones((1, 2), np.float32))
            assert calls["n"] == 1
            assert not b.alive
            with pytest.raises(Overloaded):
                b.submit(np.ones((1, 2), np.float32))
        finally:
            b.stop()


# ---------------------------------------------------------------------------
# server.py — the CPU integration acceptance tests
# ---------------------------------------------------------------------------


class TestServerIntegration:
    def test_concurrent_bit_identical_with_occupancy(self, wire_server,
                                                     tiny_export):
        """Acceptance #1 + #2: concurrent answers are BIT-identical to
        single-request serving, and multi-request batches form."""
        _, _, x = tiny_export
        make_client, server = wire_server
        client = make_client()
        # single-request serving, one at a time (occupancy 1)
        singles = [client.infer(x[i:i + 1]) for i in range(8)]
        # the same 8 rows from 8 concurrent clients
        outs = [None] * 8
        clients = [make_client() for _ in range(8)]
        ths = [threading.Thread(
            target=lambda i=i: outs.__setitem__(
                i, clients[i].infer(x[i:i + 1])))
            for i in range(8)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        for i in range(8):
            np.testing.assert_array_equal(outs[i], singles[i])
        st = client.stats()
        assert st["max_occupancy"] > 1
        assert st["version"] == 0
        assert st["live_replicas"] == 2

    def test_overload_returns_typed_rejection(self, tiny_export):
        """Acceptance #3: with every live replica's queue full the
        server answers ``Overloaded`` — fast — instead of queueing
        unboundedly; accepted requests still complete."""
        model, export_dir, x = tiny_export
        key_before = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
        policy = BatchPolicy(max_batch=1, max_delay_ms=0.0,
                             buckets=(1,), max_queue=1)
        server = InferenceServer(export_dir, replicas=1, policy=policy,
                                 reload_poll_s=0, model=model).start()
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(
            target=serve, args=(server, "127.0.0.1", port, ready, stop),
            daemon=True)
        t.start()
        assert ready.wait(30)
        faults.install([{"site": "serve_step", "action": "delay",
                         "delay_s": 0.15, "times": -1}])
        try:
            addr = f"127.0.0.1:{port}"
            results = []
            lock = threading.Lock()
            # pre-connect so the flood's ARRIVALS are tight — the HMAC
            # handshake must not spread them past the service rate
            pool = [InferenceClient(addr) for _ in range(10)]

            def go(c):
                try:
                    c.infer(x[:1])
                    r = "ok"
                except Overloaded:
                    r = "overloaded"
                finally:
                    c.close()
                with lock:
                    results.append(r)

            ths = [threading.Thread(target=go, args=(c,))
                   for c in pool]
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            # the flood was SPLIT: the bounded queue accepted some and
            # typed-rejected the rest (nothing hung, nothing errored —
            # every client got an answer).  The O(1)-rejection LATENCY
            # bound is pinned socket-free in
            # TestBatcher::test_overload_rejects_fast_and_bounded;
            # wall-clock asserts on the 1-core CI box are noise.
            assert len(results) == 10
            assert "overloaded" in results and "ok" in results
            # the server is still healthy after the flood
            c = InferenceClient(addr)
            np.testing.assert_array_equal(
                c.infer(x[:1]).shape, (1, 10))
            c.close()
        finally:
            faults.clear()
            try:
                InferenceClient(f"127.0.0.1:{port}").shutdown()
            except Exception:
                stop.set()
            t.join(timeout=5)
            server.stop()
            if key_before is None:
                os.environ.pop("THEANOMPI_TPU_SERVICE_KEY", None)
            else:
                os.environ["THEANOMPI_TPU_SERVICE_KEY"] = key_before

    def test_hot_reload_zero_failed_inflight(self, tiny_export,
                                             tmp_path):
        """Acceptance #4: publish v1 while a request storm is in
        flight, force the reload, and finish the storm — zero failed
        requests, the server ends up serving v1's numbers.  Runs on a
        COPY of the module export so the shared fixture's version
        history stays pristine under randomized test order."""
        import shutil

        from tests._tiny_models import TinyCifar

        model, export_dir0, x = tiny_export
        export_dir = str(tmp_path / "export")
        shutil.copytree(export_dir0, export_dir)
        key_before = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
        policy = BatchPolicy(max_batch=4, max_delay_ms=30.0,
                             buckets=(4,), max_queue=16)
        server = InferenceServer(export_dir, replicas=2, policy=policy,
                                 reload_poll_s=0, model=model).start()
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        srv_t = threading.Thread(
            target=serve, args=(server, "127.0.0.1", port, ready, stop),
            daemon=True)
        srv_t.start()
        assert ready.wait(30)
        addr = f"127.0.0.1:{port}"
        made: list[InferenceClient] = []

        def make_client() -> InferenceClient:
            c = InferenceClient(addr)
            made.append(c)
            return c

        client = make_client()
        before = client.infer(x[:1])

        errors: list[BaseException] = []
        n_done = [0]
        stop_storm = threading.Event()
        lock = threading.Lock()

        def storm():
            c = make_client()
            while not stop_storm.is_set():
                try:
                    c.infer(x[:2])
                except BaseException as e:  # noqa: BLE001
                    with lock:
                        errors.append(e)
                    return
                with lock:
                    n_done[0] += 1

        try:
            ths = [threading.Thread(target=storm) for _ in range(4)]
            for t in ths:
                t.start()
            time.sleep(0.1)  # storm established
            # v1: same architecture, different params (fresh seed)
            model2 = TinyCifar(config=ModelConfig(
                batch_size=8, n_epochs=1, print_freq=0, seed=77),
                verbose=False)
            export_model(model2, export_dir, version=1)
            assert client.reload() == 1
            time.sleep(0.2)  # storm keeps running THROUGH the swap
            stop_storm.set()
            for t in ths:
                t.join(timeout=30)
            assert errors == []
            assert n_done[0] > 8
            st = client.stats()
            assert st["version"] == 1
            assert all(r["version"] == 1 for r in st["replicas"])
            after = client.infer(x[:1])
            assert not np.allclose(before, after)
            want = InferenceSession(model2).infer(x[:1])
            np.testing.assert_allclose(after, want, rtol=1e-5,
                                       atol=1e-5)
        finally:
            stop_storm.set()
            try:
                client.shutdown()
            except Exception:
                stop.set()
            for c in made:
                c.close()
            srv_t.join(timeout=5)
            server.stop()
            if key_before is None:
                os.environ.pop("THEANOMPI_TPU_SERVICE_KEY", None)
            else:
                os.environ["THEANOMPI_TPU_SERVICE_KEY"] = key_before

    def test_replica_restarts_from_export_on_fault(self, tiny_export, rpc_loop):
        """resilience wiring: an injected ``serve_step`` crash fails
        that batch (surfaced to its client), the replica reloads the
        verified export, and serving continues."""
        from theanompi_tpu.parallel.service import ServiceError

        model, export_dir, x = tiny_export
        key_before = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
        policy = BatchPolicy(max_batch=4, max_delay_ms=0.0,
                             buckets=(4,), max_queue=8)
        server = InferenceServer(export_dir, replicas=1, policy=policy,
                                 reload_poll_s=0, max_restarts=1,
                                 model=model).start()
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(
            target=serve, args=(server, "127.0.0.1", port, ready, stop),
            daemon=True)
        t.start()
        assert ready.wait(30)
        client = InferenceClient(f"127.0.0.1:{port}")
        try:
            ok = client.infer(x[:1])
            faults.install([{"site": "serve_step", "action": "raise"}])
            with pytest.raises(ServiceError, match="FaultInjected"):
                client.infer(x[:1])
            faults.clear()
            # restarted from export: serving continues, same numbers
            np.testing.assert_array_equal(client.infer(x[:1]), ok)
            st = client.stats()
            assert st["replicas"][0]["restarts"] == 1
            assert st["live_replicas"] == 1
        finally:
            faults.clear()
            try:
                client.shutdown()
            except Exception:
                stop.set()
            client.close()
            t.join(timeout=5)
            server.stop()
            if key_before is None:
                os.environ.pop("THEANOMPI_TPU_SERVICE_KEY", None)
            else:
                os.environ["THEANOMPI_TPU_SERVICE_KEY"] = key_before

    def test_fault_plan_does_not_crash_warmup(self, tiny_export):
        """A ``serve_step`` raise plan must take down SERVED batches
        (supervised restart), not server construction: warmup bypasses
        the fault site (batcher.warmup(fn=session.infer))."""
        model, export_dir, x = tiny_export
        policy = BatchPolicy(max_batch=4, max_delay_ms=0.0,
                             buckets=(4,), max_queue=8)
        faults.install([{"site": "serve_step", "action": "raise"}])
        try:
            server = InferenceServer(
                export_dir, replicas=1, policy=policy, reload_poll_s=0,
                max_restarts=1, model=model, warmup=True).start()
        finally:
            faults.clear()
        try:
            assert server.submit(x[:1]).shape == (1, 10)
            # warmup fired no fault, so no restart was consumed
            assert server.stats()["replicas"][0]["restarts"] == 0
        finally:
            server.stop()

    def test_corrupt_newer_export_skipped_until_superseded(
            self, tiny_export, tmp_path, monkeypatch):
        """A published-but-corrupt newest version must cost ONE
        verified-load attempt, not one per poll: the watcher remembers
        the bad version and waits for a strictly newer manifest."""
        import theanompi_tpu.serving.server as srv
        from theanompi_tpu.resilience.recovery import find_step_dir
        from theanompi_tpu.utils.checkpoint import _truncate_largest_file

        model, _, x = tiny_export
        d = str(tmp_path / "exp")
        export_model(model, d, version=0)
        server = InferenceServer(d, replicas=1, reload_poll_s=0,
                                 model=model, warmup=False)
        try:
            export_model(model, d, version=1)
            _truncate_largest_file(find_step_dir(d, 1))
            calls = {"n": 0}
            orig = srv.load_export

            def counting(path):
                calls["n"] += 1
                return orig(path)

            monkeypatch.setattr(srv, "load_export", counting)
            assert server.check_reload() == 0  # v1 fell back -> skip
            assert calls["n"] == 1
            for _ in range(3):  # further polls never re-load v1
                assert server.check_reload() == 0
            assert calls["n"] == 1
            # a strictly newer GOOD version resets the skip
            export_model(model, d, version=2)
            assert server.check_reload() == 2
            assert server.stats()["replicas"][0]["version"] == 2
        finally:
            server.stop()

    def test_serving_metrics_reach_the_monitor(self, tiny_export,
                                               tmp_path):
        """The monitor wiring end to end (in-process, no wire): the
        request-latency histogram, batch formation series, and
        per-replica heartbeat land in the registry snapshot."""
        import json

        model, export_dir, x = tiny_export
        monitor.reset_for_tests()
        run_dir = str(tmp_path / "mon")
        with monitor.session(run_dir=run_dir):
            policy = BatchPolicy(max_batch=4, max_delay_ms=20.0,
                                 buckets=(4,), max_queue=8)
            server = InferenceServer(export_dir, replicas=1,
                                     policy=policy, reload_poll_s=0,
                                     model=model).start()
            try:
                ths = [threading.Thread(
                    target=lambda i=i: server.submit(x[i:i + 1]))
                    for i in range(4)]
                for t in ths:
                    t.start()
                for t in ths:
                    t.join()
            finally:
                server.stop()
        recs = [json.loads(l) for l in
                open(os.path.join(run_dir, "metrics_rank0.jsonl"))]
        names = {r["name"] for r in recs}
        for want in ("serving/request_ms", "serving/batch_occupancy",
                     "serving/batches_total",
                     "serving/replica_heartbeat",
                     "serving/model_version"):
            assert want in names, f"missing {want}: {sorted(names)}"
        lat = next(r for r in recs if r["name"] == "serving/request_ms")
        assert lat["count"] == 4 and "p99" in lat
        monitor.reset_for_tests()
