"""EASGD / ASGD / GOSGD: 4 worker threads on 4 CPU devices each, tiny
synthetic cifar — verifies the rules run, converge, and keep their
invariants (GOSGD weight conservation, EASGD exchange counts)."""

import numpy as np
import pytest

from theanompi_tpu.models.base import ModelConfig


def tiny_cfg(tmp_path, **kw):
    base = dict(batch_size=8, n_epochs=2, learning_rate=0.01,
                snapshot_dir=str(tmp_path), print_freq=0)
    base.update(kw)
    return ModelConfig(**base)


@pytest.mark.slow
def test_easgd(tmp_path):
    from theanompi_tpu import EASGD

    rule = EASGD()
    rule.init(devices=4, modelfile="theanompi_tpu.models.cifar10",
              modelclass="Cifar10_model", config=tiny_cfg(tmp_path),
              tau=5, alpha=0.5, checkpoint=False)
    res = rule.wait()
    assert res["n_exchanges"] > 0
    assert res["val"], "no validation ran"
    assert res["val"]["error"] < 0.85  # learned something
    # center params are finite
    for leaf in np.asarray(res["center"]["Dense_1"]["Dense_0"]["kernel"]).ravel():
        assert np.isfinite(leaf)


@pytest.mark.slow
def test_asgd(tmp_path):
    from theanompi_tpu import ASGD

    rule = ASGD()
    rule.init(devices=4, modelfile="theanompi_tpu.models.cifar10",
              modelclass="Cifar10_model", config=tiny_cfg(tmp_path))
    res = rule.wait()
    assert res["n_updates"] > 0
    assert res["val"]["error"] < 0.85


@pytest.mark.slow
def test_gosgd(tmp_path):
    from theanompi_tpu import GOSGD

    rule = GOSGD()
    rule.init(devices=4, modelfile="theanompi_tpu.models.cifar10",
              modelclass="Cifar10_model", config=tiny_cfg(tmp_path),
              p_push=0.3)
    res = rule.wait()
    # gossip weight conservation: in-flight items are merged at
    # shutdown and dead-peer pushes are refused, so the sum is exactly 1
    assert all(w > 0 for w in res["weights"])
    assert sum(res["weights"]) == pytest.approx(1.0, abs=1e-6)
    assert res["val"]["error"] < 0.85


@pytest.mark.slow
def test_easgd_center_checkpoint_loads_into_bsp(tmp_path, mesh8):
    """Cross-rule checkpoint invariant (SURVEY.md §5.4)."""
    from theanompi_tpu import EASGD
    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.rules.bsp import run_bsp_session

    cfg = tiny_cfg(tmp_path, n_epochs=1)
    rule = EASGD()
    rule.init(devices=2, modelfile="theanompi_tpu.models.cifar10",
              modelclass="Cifar10_model", config=cfg, tau=5,
              checkpoint=True)
    rule.wait()

    # BSP resume from the EASGD center checkpoint
    cfg2 = tiny_cfg(tmp_path, n_epochs=2)
    model = Cifar10_model(config=cfg2, mesh=mesh8)
    res = run_bsp_session(model, resume=True, checkpoint=True)
    assert res["epochs_run"] == 1  # resumed at epoch 1 of 2


@pytest.mark.slow
def test_asgd_checkpoint_resume(tmp_path):
    """ASGD resume restores the SERVER's center + optimizer state
    (VERDICT r1 next-round #5; cross-rule payload, SURVEY.md §5.4)."""
    from theanompi_tpu import ASGD

    rule = ASGD()
    rule.init(devices=2, modelfile="theanompi_tpu.models.cifar10",
              modelclass="Cifar10_model",
              config=tiny_cfg(tmp_path, n_epochs=1), checkpoint=True)
    res1 = rule.wait()
    assert res1["n_updates"] > 0

    rule2 = ASGD()
    rule2.init(devices=2, modelfile="theanompi_tpu.models.cifar10",
               modelclass="Cifar10_model",
               config=tiny_cfg(tmp_path, n_epochs=2), checkpoint=True,
               resume=True)
    res2 = rule2.wait()
    # resumed at epoch 1 → only epoch 1 ran; training continued sanely
    assert res2["val"]["error"] < 0.85
    assert np.isfinite(res2["val"]["loss"])


@pytest.mark.slow
def test_gosgd_checkpoint_resume(tmp_path):
    """GOSGD resume restores per-worker params + gossip weights from
    the sidecars; the weight-conservation invariant survives."""
    from theanompi_tpu import GOSGD

    rule = GOSGD()
    rule.init(devices=2, modelfile="theanompi_tpu.models.cifar10",
              modelclass="Cifar10_model",
              config=tiny_cfg(tmp_path, n_epochs=1), p_push=0.5,
              checkpoint=True)
    res1 = rule.wait()
    w1 = res1["weights"]
    assert sum(w1) == pytest.approx(1.0, abs=1e-6)

    rule2 = GOSGD()
    rule2.init(devices=2, modelfile="theanompi_tpu.models.cifar10",
               modelclass="Cifar10_model",
               config=tiny_cfg(tmp_path, n_epochs=2), p_push=0.5,
               checkpoint=True, resume=True)
    res2 = rule2.wait()
    assert sum(res2["weights"]) == pytest.approx(1.0, abs=1e-6)
    assert res2["val"]["error"] < 0.85


@pytest.mark.slow
def test_bsp_checkpoint_resumes_into_gosgd(tmp_path, mesh8):
    """Cross-rule: a BSP checkpoint (no gosgd sidecars) seeds all GOSGD
    workers with its params at equal weights (SURVEY.md §5.4)."""
    from theanompi_tpu import GOSGD
    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.rules.bsp import run_bsp_session

    model = Cifar10_model(config=tiny_cfg(tmp_path, n_epochs=1), mesh=mesh8)
    run_bsp_session(model, checkpoint=True)

    rule = GOSGD()
    rule.init(devices=2, modelfile="theanompi_tpu.models.cifar10",
              modelclass="Cifar10_model",
              config=tiny_cfg(tmp_path, n_epochs=2), checkpoint=True,
              resume=True)
    res = rule.wait()
    assert sum(res["weights"]) == pytest.approx(1.0, abs=1e-6)
    assert np.isfinite(res["val"]["loss"])


def test_easgd_fast(tmp_path):
    """Fast-set representative of the async-rule e2e contract: a short
    EASGD session (2 workers, tiny data) runs, exchanges, validates."""
    from theanompi_tpu import EASGD

    rule = EASGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=tiny_cfg(tmp_path, n_epochs=1),
              tau=4, alpha=0.5, checkpoint=False)
    res = rule.wait()
    assert res["n_exchanges"] > 0
    assert np.isfinite(res["val"]["loss"])


@pytest.mark.slow
def test_easgd_straggler_worker0(tmp_path):
    """Worker 0 as the STRAGGLER (VERDICT r1 weak #5): the orchestrator
    validates/checkpoints on worker 0's epoch cadence, so a slow worker
    0 must not deadlock the session or skip validations, and the fast
    workers keep exchanging with the center meanwhile."""
    from theanompi_tpu import EASGD

    n_epochs = 2
    rule = EASGD()
    rule.init(devices=3, modelfile="tests._tiny_models",
              modelclass="StragglerTinyCifar",
              config=tiny_cfg(tmp_path, n_epochs=n_epochs),
              tau=4, alpha=0.5, checkpoint=False)
    res = rule.wait()
    # one validation per worker-0 epoch, never fewer
    assert len(res["val_curve"]) == n_epochs
    assert np.isfinite(res["val"]["loss"])
    # every worker exchanged at least ceil(n_iters/tau) times per epoch;
    # with 512 samples / batch 8 / 3 shards = 21 iters -> >= 6/epoch each
    assert res["n_exchanges"] >= 3 * n_epochs * (21 // 4)


def test_asgd_lr_schedule_reaches_server(tmp_path):
    """The per-epoch LR schedule must land on the SERVER's optimizer
    (it applies the updates; VERDICT r1 weak #6).  Rank 0 forwards the
    decayed LR after its epoch — other workers may be mid-epoch, so the
    decay can reach their remaining pushes up to one epoch early; with
    a step schedule that skew is bounded and harmless (documented in
    rules/async_rules.py)."""
    from theanompi_tpu import ASGD
    from theanompi_tpu.utils.helper_funcs import get_learning_rate

    cfg = tiny_cfg(tmp_path, n_epochs=2, learning_rate=0.02,
                   lr_schedule="step", lr_decay_epochs=(1,),
                   lr_decay_factor=0.1)
    rule = ASGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=cfg, checkpoint=False)
    rule.wait()
    final_lr = get_learning_rate(rule.server.get_opt_state())
    # after epoch 1 the step schedule is 0.02 * 0.1 (epoch 2 >= decay
    # epoch 1), forwarded by rank 0's end-of-epoch set_lr
    assert final_lr == pytest.approx(0.002, rel=1e-5)


def test_asgd_resume_fast(tmp_path):
    """Fast-set representative of async resume: ASGD checkpoints its
    server state and a second session picks up from it."""
    from theanompi_tpu import ASGD

    rule = ASGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=tiny_cfg(tmp_path, n_epochs=1),
              checkpoint=True)
    res1 = rule.wait()
    assert res1["n_updates"] > 0

    rule2 = ASGD()
    rule2.init(devices=2, modelfile="tests._tiny_models",
               modelclass="TinyCifar",
               config=tiny_cfg(tmp_path, n_epochs=2), checkpoint=True,
               resume=True)
    res2 = rule2.wait()
    assert np.isfinite(res2["val"]["loss"])


@pytest.mark.slow
def test_worker_fault_aborts_session_fast(tmp_path):
    """Failure detection (SURVEY §5.3): one worker raising mid-epoch
    must abort the WHOLE session promptly — the other workers stop at
    the abort event rather than training out their 50 epochs — and the
    original exception surfaces from wait()."""
    import time

    from theanompi_tpu import GOSGD

    rule = GOSGD()
    t0 = time.monotonic()
    rule.init(devices=4, modelfile="tests._tiny_models",
              modelclass="FaultyTinyCifar",
              config=tiny_cfg(tmp_path, n_epochs=50), p_push=0.3,
              checkpoint=False)
    with pytest.raises(RuntimeError, match="injected worker fault"):
        rule.wait()
    # 50 epochs x 4 workers takes minutes; fail-fast means the session
    # dies within the first epoch's compile + a few iterations
    assert time.monotonic() - t0 < 120
