"""Multi-host BSP: 2 controller processes x 4 virtual CPU devices form
ONE 8-device global mesh, and the loss curve matches the single-process
8-device run step for step.

This is the acceptance test for the reference's multi-node deployment
surface (``tmlauncher`` over mpirun — SURVEY.md §2.1/§3.1/§7-6; mount
empty, no file:line): psum crosses the process boundary (gloo on CPU,
DCN on real TPU pods), each host feeds only its slice of the global
batch (``jax.make_array_from_process_local_data``), and rank-0 gating
covers printing and the JSONL curve.

Runs real OS processes — the same discipline the reference needed a
cluster for, executable on one box.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np
import pytest

RUNNER = os.path.join(os.path.dirname(__file__), "_multihost_runner.py")


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env() -> dict:
    env = dict(os.environ)
    # the runner sets its own device-count flag; drop the conftest's
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _run_procs(nprocs: int, port: int, outdir: str, devices_per_proc: int,
               epochs: int = 2, extra: list[str] | None = None) -> list[dict]:
    procs = []
    outs = []
    for pid in range(nprocs):
        out = os.path.join(outdir, f"out_{nprocs}p_{pid}.json")
        outs.append(out)
        cmd = [sys.executable, RUNNER, "--proc-id", str(pid),
               "--nprocs", str(nprocs), "--port", str(port),
               "--devices-per-proc", str(devices_per_proc),
               "--epochs", str(epochs), "--out", out,
               "--snapshot-dir", os.path.join(outdir, "snap")]
        procs.append(subprocess.Popen(cmd + (extra or []), env=_clean_env(),
                                      stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT))
    results = []
    for p in procs:
        stdout, _ = p.communicate(timeout=600)
        assert p.returncode == 0, (
            f"runner failed (rc={p.returncode}):\n{stdout.decode()[-4000:]}")
    for out in outs:
        with open(out) as f:
            results.append(json.load(f))
    return results


@pytest.fixture(scope="module")
def workdir():
    d = tempfile.mkdtemp(prefix="tm_multihost_")
    yield d
    shutil.rmtree(d, ignore_errors=True)


@pytest.mark.slow
def test_two_process_bsp_matches_single_process(workdir):
    two = _run_procs(2, port=45711, outdir=workdir, devices_per_proc=4)
    one = _run_procs(1, port=45712, outdir=workdir, devices_per_proc=8)

    # both processes saw one global 8-device mesh, 4 local each
    for r in two:
        assert r["n_global_devices"] == 8
        assert r["n_local_devices"] == 4
        assert r["multiprocess"] is True
    assert one[0]["n_global_devices"] == 8
    assert one[0]["multiprocess"] is False

    # every process computes the same (replicated) loss sequence
    l0, l1 = np.array(two[0]["losses"]), np.array(two[1]["losses"])
    np.testing.assert_allclose(l0, l1, rtol=1e-6)

    # ... and it matches the single-process global-mesh run step for
    # step (same data order, same math; gloo vs single-process psum
    # reduction order can differ in the last ulp)
    single = np.array(one[0]["losses"])
    assert len(single) == len(l0) > 0
    np.testing.assert_allclose(l0, single, rtol=1e-4, atol=1e-6)

    # val path (host-sliced val batches + pmean) agrees too
    assert two[0]["val"]["error"] == pytest.approx(
        one[0]["val"]["error"], rel=1e-3, abs=1e-5)


@pytest.mark.slow
def test_two_process_checkpoint_resume(workdir):
    d = os.path.join(workdir, "resume")
    os.makedirs(d, exist_ok=True)
    # continuous 2-epoch reference
    cont = _run_procs(2, port=45713, outdir=d, devices_per_proc=4, epochs=2)
    # 1 epoch with checkpoint, then resume for 1 more
    d2 = os.path.join(workdir, "resume_split")
    os.makedirs(d2, exist_ok=True)
    _run_procs(2, port=45714, outdir=d2, devices_per_proc=4, epochs=1,
               extra=["--checkpoint"])
    resumed = _run_procs(2, port=45715, outdir=d2, devices_per_proc=4,
                         epochs=1, extra=["--checkpoint", "--resume"])

    assert resumed[0]["epochs_run"] == 1
    n = len(cont[0]["losses"]) // 2
    np.testing.assert_allclose(np.array(resumed[0]["losses"]),
                               np.array(cont[0]["losses"])[n:],
                               rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_two_process_zero_sharding_matches_plain(workdir):
    """ZeRO-1 across the process boundary: psum_scatter + all_gather
    ride the gloo/DCN collectives, the sharded optimizer state spans
    both processes' devices, and the loss curve matches plain BSP."""
    zero = _run_procs(2, port=45717, outdir=workdir, devices_per_proc=4,
                      epochs=1, extra=["--zero"])
    plain = _run_procs(2, port=45718, outdir=workdir, devices_per_proc=4,
                       epochs=1)
    lz = np.array(zero[0]["losses"])
    lp = np.array(plain[0]["losses"])
    assert len(lz) == len(lp) > 0
    # elementwise-optimizer ZeRO is step-equal to plain BSP
    np.testing.assert_allclose(lz, lp, rtol=1e-4, atol=1e-6)
    # both ranks agree with each other
    np.testing.assert_allclose(lz, np.array(zero[1]["losses"]), rtol=1e-6)


@pytest.mark.slow
def test_tmlauncher_cli_two_processes(workdir):
    """The actual ``tmlauncher`` CLI as real OS processes (VERDICT r2
    #3): argv → --platform ordering → jax.distributed.initialize →
    global mesh → session.  Two hosts × 4 devices must produce the
    same epoch record as one 8-device host running the same command —
    covering the one seam (launcher.py ``_run``) the runner-based
    multihost tests bypass."""
    d = os.path.join(workdir, "cli")
    os.makedirs(d, exist_ok=True)

    def run_cli(nhosts, host_id, port, devices, snap):
        env = _clean_env()
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devices}")
        cmd = [sys.executable, "-m", "theanompi_tpu.launcher",
               "--multihost", "BSP", "-m", "tests._tiny_models",
               "-c", "TinyCifar", "--platform", "cpu",
               "--epochs", "1", "--batch-size", "16", "--lr", "0.02",
               "--snapshot-dir", snap,
               "--coordinator", f"127.0.0.1:{port}",
               "--nhosts", str(nhosts), "--host-id", str(host_id)]
        return subprocess.Popen(cmd, env=env, cwd=REPO_ROOT,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    snap2, snap1 = os.path.join(d, "snap2"), os.path.join(d, "snap1")
    procs = [run_cli(2, i, 45727, 4, snap2) for i in range(2)]
    try:
        for p in procs:
            stdout, _ = p.communicate(timeout=600)
            assert p.returncode == 0, (
                f"tmlauncher failed (rc={p.returncode}):\n"
                f"{stdout.decode()[-4000:]}")
            assert "final val:" in stdout.decode()
    finally:
        for p in procs:  # a failed host-0 assert must not orphan host 1
            if p.poll() is None:
                p.kill()
                p.wait()
    p1 = run_cli(1, 0, 45728, 8, snap1)
    out1, _ = p1.communicate(timeout=600)
    assert p1.returncode == 0, out1.decode()[-4000:]

    def epoch_rec(snap, rank):
        with open(os.path.join(snap, f"record_rank{rank}.jsonl")) as f:
            return [json.loads(line) for line in f if line.strip()][-1]

    two, one = epoch_rec(snap2, 0), epoch_rec(snap1, 0)
    assert two["train_loss"] == pytest.approx(one["train_loss"], rel=1e-4)
    assert two["val_error"] == pytest.approx(one["val_error"],
                                             rel=1e-3, abs=1e-5)
    # rank-0 gating (SURVEY §3.5): ONLY host 0 writes the JSONL curve
    assert not os.path.exists(
        os.path.join(snap2, "record_rank1.jsonl"))


@pytest.mark.slow
def test_two_process_async_save_survives_donation(workdir):
    """The async-save/donation seam (ADVICE r2): save() returns while
    Orbax writes in the background, and the very next train step
    DONATES the saved state's device buffers.  Each process saves its
    cross-process-sharded ZeRO state, immediately donates, restores,
    and asserts bit-equal pre-save values — so the Orbax contract
    (d2h copy completes before save() returns) is tested, not assumed."""
    d = os.path.join(workdir, "donate_race")
    os.makedirs(d, exist_ok=True)
    res = _run_procs(2, port=45725, outdir=d, devices_per_proc=4,
                     extra=["--donate-race"])
    for r in res:
        assert r["donate_race_ok"] is True
        assert r["state_spans_processes"] is True


@pytest.mark.slow
def test_two_process_zero_checkpoint_resume(workdir):
    """Checkpointing a cross-process-SHARDED optimizer state: Orbax
    writes each process's addressable shards (no single host can fetch
    the whole array), and resume restores into the same sharding.
    1 epoch + checkpoint, then 1 more from resume == 2 continuous."""
    d = os.path.join(workdir, "zero_resume")
    os.makedirs(d, exist_ok=True)
    cont = _run_procs(2, port=45721, outdir=d, devices_per_proc=4,
                      epochs=2, extra=["--zero", "--checkpoint"])
    d2 = os.path.join(workdir, "zero_resume2")
    os.makedirs(d2, exist_ok=True)
    first = _run_procs(2, port=45722, outdir=d2, devices_per_proc=4,
                       epochs=1, extra=["--zero", "--checkpoint"])
    second = _run_procs(2, port=45723, outdir=d2, devices_per_proc=4,
                        epochs=1, extra=["--zero", "--checkpoint",
                                         "--resume"])
    # the resumed epoch-2 losses equal the continuous run's epoch 2
    lc = np.array(cont[0]["losses"])
    l1 = np.array(first[0]["losses"])
    l2 = np.array(second[0]["losses"])
    n = len(l1)
    np.testing.assert_allclose(l1, lc[:n], rtol=1e-6)
    np.testing.assert_allclose(l2, lc[n:n + len(l2)], rtol=1e-5,
                               atol=1e-7)


@pytest.mark.slow
def test_two_process_fsdp_matches_plain(workdir):
    """FSDP across the process boundary: params + optimizer state live
    1/8 per device SPANNING both processes, GSPMD's gathers and
    reduce-scatters ride the gloo/DCN collectives, and the trajectory
    is step-equal to plain BSP."""
    fsdp = _run_procs(2, port=45727, outdir=workdir, devices_per_proc=4,
                      epochs=1, extra=["--fsdp"])
    plain = _run_procs(2, port=45728, outdir=workdir, devices_per_proc=4,
                       epochs=1)
    lf = np.array(fsdp[0]["losses"])
    lp = np.array(plain[0]["losses"])
    assert len(lf) == len(lp) > 0
    np.testing.assert_allclose(lf, lp, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(lf, np.array(fsdp[1]["losses"]), rtol=1e-6)
    assert fsdp[0]["val"]["error"] == pytest.approx(
        plain[0]["val"]["error"], rel=1e-3, abs=1e-5)
