"""Model zoo: AlexNet / VGG16 / GoogLeNet on the 8-device CPU mesh
(tiny crops so CI-speed; full geometry is exercised by bench/real-chip
runs).  Reference zoo per SURVEY.md §2.8."""

import numpy as np
import pytest

from theanompi_tpu.data.imagenet import ImageNet_data
from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.utils.recorder import Recorder


def tiny_imagenet(crop, **kw):
    kw.setdefault("synthetic_n", 256)
    kw.setdefault("synthetic_pool", 8)
    kw.setdefault("synthetic_store", max(crop + 12, 20))
    return ImageNet_data(crop=crop, **kw)


def run_short_training(model, n_iters=3):
    model.compile_iter_fns("avg")
    rec = Recorder(rank=1, size=8, print_freq=100)
    model.begin_epoch(0)
    for i in range(n_iters):
        model.train_iter(i, rec)
    model._flush_metrics(rec)
    assert np.isfinite(model.current_info["loss"])
    val = model.val_epoch(rec)
    assert 0.0 <= val["error"] <= 1.0
    model.cleanup()
    return val


class TestAlexNet:
    def make(self, mesh8):
        from theanompi_tpu.models.alex_net import AlexNet

        class TinyAlex(AlexNet):
            def build_data(self):
                # 67 → conv11/4 valid 15 → pool 7 → pool 3 → pool 1
                return tiny_imagenet(67)

        cfg = ModelConfig(batch_size=2, n_epochs=1, compute_dtype="float32",
                          print_freq=100)
        return TinyAlex(config=cfg, mesh=mesh8)

    def test_grouped_conv_param_shapes(self):
        # full-width AlexNet, but abstractly: eval_shape costs nothing
        # while still pinning the real (ungrouped vs grouped) kernels
        import jax
        import jax.numpy as jnp

        from theanompi_tpu.models.alex_net import AlexNetCNN

        tree = jax.eval_shape(AlexNetCNN().init, jax.random.key(0),
                              jnp.zeros((1, 227, 227, 3)))
        shapes = [v.shape for v in jax.tree.leaves(tree)]
        # conv2 has 2 groups: kernel in-channels = 96/2 = 48
        assert any(s == (5, 5, 48, 256) for s in shapes), shapes

    @pytest.mark.slow
    def test_train_and_val(self, mesh8):
        run_short_training(self.make(mesh8))


class TestVGG16:
    def make(self, mesh8):
        import jax.numpy as jnp
        from theanompi_tpu.models.vgg16 import VGG16, VGGCNN

        class TinyVGG(VGG16):
            def build_data(self):
                return tiny_imagenet(32)

            def build_module(self):
                return VGGCNN(blocks=((1, 8), (1, 16), (2, 16)),
                              n_classes=self.data.n_classes,
                              dtype=jnp.float32)

        cfg = ModelConfig(batch_size=2, n_epochs=1, compute_dtype="float32",
                          print_freq=100)
        return TinyVGG(config=cfg, mesh=mesh8)

    @pytest.mark.slow
    def test_train_and_val(self, mesh8):
        run_short_training(self.make(mesh8))

    def test_full_blocks_shape(self):
        from theanompi_tpu.models.vgg16 import VGG16_BLOCKS
        assert sum(n for n, _ in VGG16_BLOCKS) == 13  # conf. D: 13 convs


class TestGoogLeNet:
    def make(self, mesh8):
        from theanompi_tpu.models.googlenet import GoogLeNet

        class TinyGoogLeNet(GoogLeNet):
            def build_data(self):
                # 64 → stem/2 32 → pool 16 → pool 8 (4a at 8x8: aux
                # 5x5/3 avg-pool valid → 2x2, still well-formed)
                return tiny_imagenet(64)

            def build_module(self):
                from theanompi_tpu.models.googlenet import GoogLeNetCNN

                # width-scaled: the aux/LRN/inception structure under
                # test is width-independent (VERDICT r1 next-round #7)
                return GoogLeNetCNN(n_classes=self.data.n_classes,
                                    dtype=self._compute_dtype(),
                                    width_mult=0.125)

        cfg = ModelConfig(batch_size=2, n_epochs=1, compute_dtype="float32",
                          print_freq=100)
        return TinyGoogLeNet(config=cfg, mesh=mesh8)

    @pytest.mark.slow
    def test_aux_heads_exist_and_train(self, mesh8):
        m = self.make(mesh8)
        assert "aux1" in m.state.params and "aux2" in m.state.params
        run_short_training(m)

    @pytest.mark.slow
    def test_eval_path_skips_aux(self, mesh8):
        import jax.numpy as jnp
        m = self.make(mesh8)
        x = jnp.zeros((2, 64, 64, 3))
        variables = {"params": m.state.params, **m.state.model_state}
        out = m.module.apply(variables, x, train=False)
        assert out.shape == (2, m.data.n_classes)  # plain logits at eval


class TestLayersBatchNormSyncWiring:
    """The sync_bn wiring gap (ADVICE r4 / ISSUE 2 satellite):
    ``layers.BatchNorm`` honors ``ModelConfig.sync_bn`` ONLY when a
    ``build_module()`` threads ``_bn_axis()`` into ``axis_name`` — the
    knob is not wired automatically.  These regressions pin both
    halves: the wrapper's ``axis_name`` path really computes
    cross-replica stats, and the default (axis_name=None) really does
    not — so the documented obligation in models/base.py ``sync_bn``
    and layers.py stays true rather than silently rotting."""

    def _stats_after_one_fwd(self, mesh8, axis_name):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from theanompi_tpu.models import layers

        bn = layers.BatchNorm(axis_name=axis_name, dtype=jnp.float32)
        # sharded batch whose per-shard mean differs strongly from the
        # whole-batch mean: shard i is centered at i
        x = (jnp.arange(32, dtype=jnp.float32)[:, None] // 4)[
            :, :, None, None] * jnp.ones((32, 1, 2, 3))
        variables = bn.init({"params": jax.random.key(0)}, x[:4])

        def fwd(variables, xs):
            _, upd = bn.apply(variables, xs, mutable=["batch_stats"])
            # pmean like the BSP step does to per-shard model_state
            return jax.tree.map(lambda v: jax.lax.pmean(v, "data"), upd)

        sharded = jax.jit(jax.shard_map(
            fwd, mesh=mesh8, in_specs=(P(), P("data")), out_specs=P(),
            check_vma=False))
        upd = sharded(variables, x)
        return np.asarray(
            upd["batch_stats"]["BatchNorm_0"]["var"]).ravel()

    def test_axis_name_gives_cross_replica_var(self, mesh8):
        # whole-batch variance of values {0..7}x4 is 5.25; with init
        # running var 1.0 and momentum 0.9 one step lands at
        # 0.9 + 0.1*batch_var > 1.2.  axis_name='data' must see the
        # whole batch, not its zero-variance shard.
        var = self._stats_after_one_fwd(mesh8, axis_name="data")
        assert var.max() > 1.2, var

    def test_default_keeps_per_shard_stats(self, mesh8):
        # control: without axis_name each shard is CONSTANT (batch var
        # 0), so the running var only decays toward 0 from its init of
        # 1.0: 0.9*1.0 + 0.1*0 = 0.9 — the gap the docs warn about is
        # real, not hypothetical
        var = self._stats_after_one_fwd(mesh8, axis_name=None)
        np.testing.assert_allclose(var, 0.9, atol=1e-3)

    def test_bn_axis_returns_data_axis_only_when_sync_bn(self):
        from theanompi_tpu.parallel.mesh import AXIS_DATA
        from tests._tiny_models import TinyCifar

        cfg = TinyCifar.default_config()
        assert cfg.sync_bn is False

        class _Probe:  # _bn_axis only reads self.config
            pass

        from theanompi_tpu.models.base import TpuModel

        probe = _Probe()
        probe.config = cfg
        assert TpuModel._bn_axis(probe) is None
        import dataclasses

        probe.config = dataclasses.replace(cfg, sync_bn=True)
        assert TpuModel._bn_axis(probe) == AXIS_DATA


def test_zoo_registry_resolves():
    from theanompi_tpu.models import MODEL_ZOO
    from theanompi_tpu.rules import resolve_model_class

    for shortname, (mod, cls) in MODEL_ZOO.items():
        klass = resolve_model_class(mod, cls)
        assert isinstance(klass, type), shortname


class TestZooVariants:
    def test_vgg19_blocks(self):
        from theanompi_tpu.models.model_zoo import VGG19_BLOCKS
        assert sum(n for n, _ in VGG19_BLOCKS) == 16  # conf. E: 16 convs

    def test_resnet_variant_depths(self):
        from theanompi_tpu.models.model_zoo import ResNet101, ResNet152

        # depth = 3*sum(stages)+2 (bottleneck) — 101 and 152
        assert 3 * sum(ResNet101.stage_sizes) + 2 == 101
        assert 3 * sum(ResNet152.stage_sizes) + 2 == 152

    @pytest.mark.slow
    def test_resnet_variant_trains(self, mesh8):
        from theanompi_tpu.models.model_zoo import ResNet101
        from theanompi_tpu.models.resnet50 import ResNet

        class TinyR101(ResNet101):
            def build_data(self):
                return tiny_imagenet(16)

            def build_module(self):
                import jax.numpy as jnp
                return ResNet(stage_sizes=(1, 1, 1, 1), width=8,
                              n_classes=self.data.n_classes,
                              dtype=jnp.float32)

        cfg = ModelConfig(batch_size=2, n_epochs=1, compute_dtype="float32",
                          print_freq=100)
        run_short_training(TinyR101(config=cfg, mesh=mesh8), n_iters=2)


class TestResNet50LargeBatch:
    def test_zoo_resolution_and_recipe(self):
        from theanompi_tpu.models import MODEL_ZOO
        from theanompi_tpu.models.model_zoo import ResNet50_LargeBatch

        assert MODEL_ZOO["resnet50_large"] == (
            "theanompi_tpu.models.model_zoo", "ResNet50_LargeBatch")
        cfg = ResNet50_LargeBatch.default_config()
        assert (cfg.optimizer, cfg.lr_schedule) == ("lars", "cosine")
        assert cfg.warmup_epochs == 5 and cfg.resnet_stem == "s2d"
        # b=128/chip is the measured-best point of the round-3 on-chip
        # ladder (b=256 lost at every k); the 8k+ global batch of the
        # published LARS recipes comes from the shard count
        assert cfg.batch_size == 128 and cfg.compute_dtype == "bfloat16"

    def test_lars_s2d_trains_width_scaled(self, mesh8):
        """The recipe's moving parts (LARS + warmup + s2d stem) drive
        the BSP spine together on a width-scaled network."""
        import dataclasses

        import jax.numpy as jnp

        from theanompi_tpu.models.model_zoo import ResNet50_LargeBatch
        from theanompi_tpu.models.resnet50 import ResNet
        from theanompi_tpu.utils.recorder import Recorder

        class Tiny(ResNet50_LargeBatch):
            def build_data(self):
                return tiny_imagenet(16, synthetic_store=20)

            def build_module(self):
                return ResNet(stage_sizes=(1, 1), width=8,
                              n_classes=self.data.n_classes,
                              dtype=jnp.float32,
                              stem=self.config.resnet_stem)

        cfg = dataclasses.replace(
            ResNet50_LargeBatch.default_config(), batch_size=2,
            n_epochs=2, compute_dtype="float32", print_freq=0,
            learning_rate=0.1)
        m = Tiny(config=cfg, mesh=mesh8, verbose=False)
        # sqrt worker scaling (8 data shards) then the 5-epoch warmup
        assert m.adjust_hyperp(0) == pytest.approx(0.1 * 8 ** 0.5 / 5)
        m.compile_iter_fns("avg")
        rec = Recorder(rank=0, size=8, print_freq=0)
        m.begin_epoch(0)
        for i in range(3):
            m.train_iter(i, rec)
        m._flush_metrics(rec)
        assert np.isfinite(rec.train_losses).all()
        m.cleanup()


def test_cnn_zoo_declares_flops():
    """Every ImageNet CNN declares its trained FLOPs so the recorder's
    TFLOP/s column is populated; values ordered sanely by depth."""
    from theanompi_tpu.models.alex_net import AlexNet
    from theanompi_tpu.models.googlenet import GoogLeNet
    from theanompi_tpu.models.model_zoo import (
        ResNet101,
        ResNet152,
        VGG19,
    )
    from theanompi_tpu.models.resnet50 import ResNet50
    from theanompi_tpu.models.vgg16 import VGG16

    flops = {c.name: c.train_flops_per_sample
             for c in (AlexNet, GoogLeNet, VGG16, VGG19, ResNet50,
                       ResNet101, ResNet152)}
    assert all(v and v > 1e9 for v in flops.values()), flops
    assert flops["resnet50"] < flops["resnet101"] < flops["resnet152"]
    assert flops["vgg16"] < flops["vgg19"]
    assert flops["alexnet"] < flops["googlenet"] < flops["resnet50"]


class TestZooBatchNormVariants:
    """ADVICE r4 closure (ISSUE 5 satellite): ``ModelConfig.batch_norm``
    builds the BN variant of every layer-toolkit CNN with
    ``_bn_axis()`` threaded into the REAL ``build_module()`` — so
    ``sync_bn=True`` is honored across the zoo, not just ResNet.  One
    regression per model: the module's ``bn_axis`` field tracks
    ``sync_bn``, BatchNorm state actually exists, and the
    ``uses_batchnorm`` warning hook sees the variant."""

    def _models(self):
        import jax.numpy as jnp  # noqa: F401
        from theanompi_tpu.models.alex_net import AlexNet
        from theanompi_tpu.models.googlenet import GoogLeNet
        from theanompi_tpu.models.vgg16 import VGG16

        class TinyAlex(AlexNet):
            def build_data(self):
                return tiny_imagenet(67)

        class TinyVGG(VGG16):
            blocks = ((1, 8), (1, 16), (2, 16))  # real build_module

            def build_data(self):
                return tiny_imagenet(32)

        class TinyGoogLeNet(GoogLeNet):
            width_mult = 0.125                   # real build_module

            def build_data(self):
                return tiny_imagenet(64)

        return {"alexnet": TinyAlex, "vgg16": TinyVGG,
                "googlenet": TinyGoogLeNet}

    @pytest.mark.parametrize("name", ["alexnet", "vgg16", "googlenet"])
    def test_bn_axis_threads_from_sync_bn(self, mesh8, name):
        from theanompi_tpu.parallel.mesh import AXIS_DATA

        klass = self._models()[name]
        cfg = ModelConfig(batch_size=16, n_epochs=1,
                          compute_dtype="float32", print_freq=100,
                          batch_norm=True, sync_bn=True)
        m = klass(config=cfg, mesh=mesh8, verbose=False)
        assert m.module.batch_norm is True
        assert m.module.bn_axis == AXIS_DATA        # the r4 obligation
        assert m.uses_batchnorm is True             # warning hook live
        assert "batch_stats" in m.state.model_state  # BN really built
        m.cleanup()

    @pytest.mark.parametrize("name", ["alexnet", "vgg16", "googlenet"])
    def test_bn_axis_none_without_sync_bn(self, mesh8, name):
        klass = self._models()[name]
        cfg = ModelConfig(batch_size=16, n_epochs=1,
                          compute_dtype="float32", print_freq=100,
                          batch_norm=True, sync_bn=False)
        m = klass(config=cfg, mesh=mesh8, verbose=False)
        assert m.module.batch_norm is True
        assert m.module.bn_axis is None  # per-shard stats, as documented
        m.cleanup()

    def test_default_stays_bn_free(self, mesh8):
        # batch_norm=False must keep the historical param tree (conv
        # biases, no batch_stats) — checkpoints predating the knob load
        klass = self._models()["alexnet"]
        cfg = ModelConfig(batch_size=16, n_epochs=1,
                          compute_dtype="float32", print_freq=100)
        m = klass(config=cfg, mesh=mesh8, verbose=False)
        assert "batch_stats" not in m.state.model_state
        assert m.uses_batchnorm is False
        assert "bias" in m.state.params["Conv_0"]["Conv_0"]
        m.cleanup()

    @pytest.mark.slow
    def test_bn_variant_trains_with_sync_bn(self, mesh8):
        cfg = ModelConfig(batch_size=2, n_epochs=1,
                          compute_dtype="float32", print_freq=100,
                          batch_norm=True, sync_bn=True)
        m = self._models()["alexnet"](config=cfg, mesh=mesh8,
                                      verbose=False)
        before = np.asarray(jax_tree_first(
            m.state.model_state["batch_stats"]))
        run_short_training(m)


def jax_tree_first(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)[0]
