"""The selector-driven RPC substrate (parallel/rpc.py, ISSUE 11).

Every plane's own suite already exercises the substrate end to end
(the selector loop is the default); this file pins the substrate's NEW
contracts — handshake deadline, abrupt-disconnect accounting,
backpressure, stream multiplexing, per-stream FIFO — on BOTH loops
where the contract is loop-agnostic.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time

import numpy as np
import pytest

from theanompi_tpu import monitor
from theanompi_tpu.parallel import rpc, wire
from theanompi_tpu.parallel.service import (
    ParamService,
    ServiceClient,
    ServiceError,
    serve,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class EchoService:
    """Minimal service: ops that echo, sleep, fail, or record
    concurrency — enough to probe the loop without jax stores."""

    RPC_CONTROL_OPS = frozenset({"ctl"})

    def __init__(self):
        self._lock = threading.Lock()
        self.active = 0
        self.max_active = 0
        self.per_stream_active: dict = {}

    def handle(self, op, *args):
        if op == "echo":
            return args[0] if args else None
        if op == "ctl":
            return "ctl-ok"
        if op == "boom":
            raise ValueError("boom goes the service")
        if op == "sleep":
            time.sleep(float(args[0]))
            return "slept"
        if op == "big":
            return np.zeros(int(args[0]), np.uint8)
        if op == "track":
            key = args[0]
            with self._lock:
                self.active += 1
                self.max_active = max(self.max_active, self.active)
                n = self.per_stream_active.get(key, 0) + 1
                self.per_stream_active[key] = n
                assert n == 1, f"stream {key} ran concurrently"
            time.sleep(0.02)
            with self._lock:
                self.active -= 1
                self.per_stream_active[key] -= 1
            return key
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown op {op!r}")


@pytest.fixture()
def echo_server(rpc_loop, monkeypatch):  # rpc_loop: tests/conftest.py
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_KEY", "rpc-test-key")
    svc = EchoService()
    port = _free_port()
    ready, stop = threading.Event(), threading.Event()
    t = threading.Thread(
        target=serve, args=("127.0.0.1", port, ready, stop),
        kwargs=dict(service=svc), daemon=True)
    t.start()
    assert ready.wait(10)
    yield f"127.0.0.1:{port}", svc, rpc_loop
    stop.set()
    try:
        ServiceClient(f"127.0.0.1:{port}").call("shutdown")
    except Exception:
        pass
    t.join(timeout=10)
    assert not t.is_alive(), f"{rpc_loop} serve loop did not exit"


class TestBothLoops:
    def test_round_trip_and_typed_errors(self, echo_server):
        addr, _, _ = echo_server
        c = ServiceClient(addr)
        try:
            assert c.call("echo", {"x": np.arange(5)})["x"].tolist() \
                == list(range(5))
            with pytest.raises(ServiceError, match="ValueError"):
                c.call("boom")
            # the connection survives a server-side error
            assert c.call("ping") == "pong"
        finally:
            c.close()

    def test_v1_round_trip(self, echo_server, monkeypatch):
        monkeypatch.setenv("THEANOMPI_TPU_WIRE_PROTOCOL", "v1")
        addr, _, _ = echo_server
        c = ServiceClient(addr)
        try:
            assert c.wire_protocol == "v1"
            out = c.call("echo", np.arange(7, dtype=np.float32))
            assert out.tobytes() == np.arange(
                7, dtype=np.float32).tobytes()
        finally:
            c.close()

    def test_handshake_deadline_reaps_silent_connect(
            self, echo_server, monkeypatch):
        """ISSUE 11 satellite: a client that connects and never sends
        the HMAC challenge reply is reaped after the deadline — it
        must neither wedge the accept path nor leak a handler until
        shutdown, on either loop."""
        addr, _, _ = echo_server
        host, _, port = addr.rpartition(":")
        monkeypatch.setenv("THEANOMPI_TPU_RPC_HANDSHAKE_TIMEOUT_S",
                           "0.5")
        silent = socket.create_connection((host, int(port)))
        try:
            # while the silent connect is parked, real clients work
            c = ServiceClient(addr)
            assert c.call("ping") == "pong"
            c.close()
            # ...and the server closes the silent peer at the deadline
            silent.settimeout(10)
            data = silent.recv(4096)  # the challenge arrives first
            assert data, "server never sent its challenge"
            assert silent.recv(4096) == b"", \
                "silent connection was not reaped at the deadline"
        finally:
            silent.close()

    def test_abrupt_disconnect_sweeps_clients_gauge(
            self, echo_server, tmp_path):
        """ISSUE 11 satellite: an RST mid-frame must run the same
        close sweep as a polite close — the ``service/clients`` gauge
        returns to its baseline on both loops."""
        addr, _, _ = echo_server
        host, _, port = addr.rpartition(":")

        def gauge():
            for e in monitor.registry().snapshot():
                if e["name"] == "service/clients":
                    return e["value"]
            return 0.0

        with monitor.session(str(tmp_path / "mon"),
                             stall_after=float("inf")):
            base = gauge()
            c = ServiceClient(addr)
            assert c.call("ping") == "pong"
            deadline = time.monotonic() + 5
            while gauge() < base + 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert gauge() == base + 1
            # abrupt kill: RST instead of FIN, mid-frame — send a
            # partial length prefix, then hard-reset the socket
            raw = c._conn if not isinstance(c._conn, rpc.MuxStream) \
                else None
            if raw is not None:
                s = socket.socket(fileno=os.dup(raw.fileno()))
                s.send(struct.pack("!i", 1 << 20))  # header, no body
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
                s.close()
            raw.close() if raw is not None else c.close()
            deadline = time.monotonic() + 5
            while gauge() > base and time.monotonic() < deadline:
                time.sleep(0.01)
            assert gauge() == base, \
                "clients gauge leaked after an abrupt disconnect"

    def test_large_zero_copy_frames(self, echo_server):
        addr, _, _ = echo_server
        c = ServiceClient(addr)
        try:
            out = c.call("big", 3_000_000)
            assert out.shape == (3_000_000,) and out.dtype == np.uint8
        finally:
            c.close()

    def test_concurrent_clients_all_answered(self, echo_server):
        addr, svc, _ = echo_server
        clients = [ServiceClient(addr) for _ in range(8)]
        outs = [None] * 8

        def run(i):
            outs[i] = clients[i].call("track", f"conn{i}")

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for c in clients:
            c.close()
        assert outs == [f"conn{i}" for i in range(8)]
        # handlers genuinely overlapped (the track op sleeps)
        assert svc.max_active > 1


class TestSelectorOnly:
    """Contracts only the event plane has: mux, control-pool routing,
    write-queue backpressure."""

    @pytest.fixture()
    def server(self, monkeypatch):
        monkeypatch.setenv("THEANOMPI_TPU_RPC_LOOP", "selector")
        monkeypatch.setenv("THEANOMPI_TPU_SERVICE_KEY", "rpc-test-key")
        svc = EchoService()
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(
            target=serve, args=("127.0.0.1", port, ready, stop),
            kwargs=dict(service=svc), daemon=True)
        t.start()
        assert ready.wait(10)
        yield f"127.0.0.1:{port}", svc
        stop.set()
        try:
            ServiceClient(f"127.0.0.1:{port}").call("shutdown")
        except Exception:
            pass
        t.join(timeout=10)
        assert not t.is_alive()

    def test_mux_streams_share_one_socket(self, server):
        addr, svc = server
        with rpc.MuxConnection(addr) as mc:
            assert mc.mux, "selector server must grant mux"
            clients = [ServiceClient(addr, transport=mc)
                       for i in range(6)]
            outs = [None] * 6

            def run(i):
                outs[i] = clients[i].call("track", f"stream{i}")

            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            assert outs == [f"stream{i}" for i in range(6)]
            # streams of ONE socket ran concurrently server-side
            assert svc.max_active > 1
            for c in clients:
                c.close()

    def test_mux_interleaved_large_frames_byte_exact(self, server):
        addr, _ = server
        with rpc.MuxConnection(addr) as mc:
            clients = [ServiceClient(addr, transport=mc)
                       for _ in range(4)]
            payloads = [np.random.default_rng(i).integers(
                0, 255, 1 << 20).astype(np.uint8) for i in range(4)]
            outs = [None] * 4

            def run(i):
                acc = []
                for _ in range(5):
                    acc.append(clients[i].call("echo", payloads[i]))
                outs[i] = acc

            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            for i in range(4):
                for got in outs[i]:
                    assert got.tobytes() == payloads[i].tobytes()
            for c in clients:
                c.close()

    def test_per_stream_requests_stay_fifo(self, server):
        """Pipelined requests on one stream are answered in order —
        the contract the ingest fetch loop's FIFO matching rides."""
        addr, _ = server
        with rpc.MuxConnection(addr) as mc:
            stream, opts = mc.connect_stream()
            assert opts is not None
            try:
                for i in range(20):
                    wire.send_msg(stream, ("echo", i), opts)
                for i in range(20):
                    status, payload = wire.recv_msg(stream, opts)
                    assert status == "ok" and payload == i
            finally:
                stream.close()

    def test_control_ops_dodge_a_saturated_pool(self, server,
                                                monkeypatch):
        """Ops in RPC_CONTROL_OPS answer while the default pool is
        parked — the starvation seam the shard fence rides."""
        addr, _ = server
        blockers = [ServiceClient(addr) for _ in range(20)]
        done = []

        def park(c):
            done.append(c.call("sleep", 1.0))

        threads = [threading.Thread(target=park, args=(c,))
                   for c in blockers]
        for t in threads:
            t.start()
        time.sleep(0.2)  # let the sleepers saturate the default pool
        c = ServiceClient(addr)
        t0 = time.monotonic()
        assert c.call("ctl") == "ctl-ok"
        elapsed = time.monotonic() - t0
        c.close()
        for t in threads:
            t.join(30)
        for b in blockers:
            b.close()
        assert elapsed < 0.9, \
            f"control op waited {elapsed:.2f}s behind parked workers"

    def test_backpressure_bounds_write_queue(self, server,
                                             monkeypatch, tmp_path):
        """A client that stops reading cannot balloon server memory:
        replies block at the write-queue budget and the connection is
        dropped at the deadline — the stall is counted, the close
        sweep runs, and the server stays healthy.  (The dropped
        client's own sends may keep succeeding for a while — the
        kernel lingers an orphaned socket while queued replies drain —
        so the assertions are server-side.)"""
        addr, _ = server
        import theanompi_tpu.parallel.rpc as rpc_mod

        monkeypatch.setattr(rpc_mod, "_WRITEQ_BYTES", 1 << 20)
        monkeypatch.setattr(rpc_mod, "_WRITEQ_TIMEOUT_S", 1.0)
        # a RAW pipelined connection that never reads (a mux transport
        # would not do: its reader thread always drains)
        from multiprocessing.connection import Client as MpClient

        def series(name):
            for e in monitor.registry().snapshot():
                if e["name"] == name:
                    return e["value"]
            return 0.0

        host, _, port = addr.rpartition(":")
        with monitor.session(str(tmp_path / "mon"),
                             stall_after=float("inf")):
            base_gauge = series("service/clients")
            base_stalls = series("rpc/backpressure_stalls_total")
            conn = MpClient((host, int(port)), authkey=b"rpc-test-key")
            try:
                want = wire.WireOptions()
                conn.send((wire.HELLO_OP, wire.hello_payload(want)))
                status, _ = conn.recv()
                assert status == "ok"
                opts = wire.WireOptions(allow_pickle=True)
                # pipeline many 4 MB replies and read NOTHING
                for _ in range(32):
                    wire.send_msg(conn, ("big", 4 << 20), opts)
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline and not (
                        series("rpc/backpressure_stalls_total")
                        > base_stalls
                        and series("service/clients") <= base_gauge):
                    time.sleep(0.05)
                assert series("rpc/backpressure_stalls_total") \
                    > base_stalls, "write queue never stalled"
                assert series("service/clients") <= base_gauge, \
                    "stalled connection was not swept"
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
            # and the server still serves others
            c = ServiceClient(addr)
            assert c.call("ping") == "pong"
            c.close()

    def test_mux_falls_back_on_threaded_server(self, monkeypatch):
        monkeypatch.setenv("THEANOMPI_TPU_SERVICE_KEY", "rpc-test-key")
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(
            target=serve, args=("127.0.0.1", port, ready, stop),
            kwargs=dict(service=EchoService(), loop="threaded"),
            daemon=True)
        t.start()
        assert ready.wait(10)
        try:
            with rpc.MuxConnection(f"127.0.0.1:{port}") as mc:
                assert not mc.mux
                c = ServiceClient(f"127.0.0.1:{port}", transport=mc)
                assert c.call("ping") == "pong"
                assert c.wire_protocol == "v2"
                c.close()
        finally:
            stop.set()
            try:
                ServiceClient(f"127.0.0.1:{port}").call("shutdown")
            except Exception:
                pass
            t.join(timeout=10)
            assert not t.is_alive()

    def test_wait_readable_mixes_streams_and_conns(self, server):
        addr, _ = server
        with rpc.MuxConnection(addr) as mc:
            s1, opts = mc.connect_stream()
            s2, _ = mc.connect_stream()
            try:
                assert rpc.wait_readable([s1, s2], timeout=0.05) == []
                wire.send_msg(s2, ("echo", "hi"), opts)
                deadline = time.monotonic() + 5
                ready = []
                while not ready and time.monotonic() < deadline:
                    ready = rpc.wait_readable([s1, s2], timeout=0.2)
                assert ready == [s2]
                status, payload = wire.recv_msg(s2, opts)
                assert (status, payload) == ("ok", "hi")
            finally:
                s1.close()
                s2.close()

    def test_malformed_pipelined_reply_stays_fifo(self, server):
        """Review regression: a malformed request's err reply must
        queue BEHIND the in-flight request's reply on its stream — an
        IO-thread shortcut would mispair a FIFO-matched client."""
        addr, _ = server
        with rpc.MuxConnection(addr) as mc:
            stream, opts = mc.connect_stream()
            try:
                wire.send_msg(stream, ("sleep", 0.3), opts)
                wire.send_msg(stream, "not-a-tuple", opts)
                wire.send_msg(stream, ("echo", "after"), opts)
                assert wire.recv_msg(stream, opts) == ("ok", "slept")
                status, diag = wire.recv_msg(stream, opts)
                assert status == "err" and "malformed" in diag
                assert wire.recv_msg(stream, opts) == ("ok", "after")
            finally:
                stream.close()

    def test_mux_grant_does_not_leak_open_streams_gauge(
            self, server, tmp_path):
        """Review regression: granting mux retires the pre-mux stream
        0 — its rpc/open_streams count must go with it."""
        addr, _ = server

        def gauge():
            for e in monitor.registry().snapshot():
                if e["name"] == "rpc/open_streams":
                    return e["value"]
            return 0.0

        with monitor.session(str(tmp_path / "mon"),
                             stall_after=float("inf")):
            base = gauge()
            with rpc.MuxConnection(addr) as mc:
                stream, opts = mc.connect_stream()
                wire.send_msg(stream, ("ping",), opts)
                assert wire.recv_msg(stream, opts) == ("ok", "pong")
                stream.close()
            deadline = time.monotonic() + 5
            while gauge() != base and time.monotonic() < deadline:
                time.sleep(0.02)
            assert gauge() == base, \
                "rpc/open_streams drifted across a mux connection"

    def test_corrupt_v2_frame_gets_typed_err_and_survives(
            self, server):
        """Selector-loop twin of the threaded loop's drained-frame
        discipline: a corrupt-but-aligned frame yields a typed err and
        the connection keeps working."""
        addr, _ = server
        with rpc.MuxConnection(addr) as mc:
            stream, opts = mc.connect_stream()
            try:
                # a header+skeleton chunk declaring 0 buffers with
                # garbage JSON: aligned (no buffers follow), corrupt
                head = wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION,
                                         0, 0, 9)
                stream.send_bytes(head + b"not json!")
                status, payload = wire.recv_msg(stream, opts)
                assert status == "err"
                assert "WireDecodeError" in payload
                wire.send_msg(stream, ("ping",), opts)
                assert wire.recv_msg(stream, opts) == ("ok", "pong")
            finally:
                stream.close()


class TestParamServiceOnSubstrate:
    """The real ParamService riding each loop (store arithmetic is
    pinned elsewhere; this pins the serve() plumbing)."""

    def test_param_service_both_loops(self, rpc_loop, monkeypatch):
        monkeypatch.setenv("THEANOMPI_TPU_SERVICE_KEY", "rpc-test-key")
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(
            target=serve, args=("127.0.0.1", port, ready, stop),
            daemon=True)
        t.start()
        assert ready.wait(10)
        try:
            from theanompi_tpu.parallel.service import RemoteEASGD

            tree = {"w": np.arange(6, dtype=np.float32)}
            srv = RemoteEASGD(f"127.0.0.1:{port}", tree, alpha=0.5,
                              session_id=f"sub-{rpc_loop}")
            back = srv.get_center()
            assert np.asarray(back["w"]).tobytes() == tree["w"].tobytes()
            srv.close()
        finally:
            stop.set()
            try:
                ServiceClient(f"127.0.0.1:{port}").call("shutdown")
            except Exception:
                pass
            t.join(timeout=10)
            assert not t.is_alive()
