"""Distributed tracing + telemetry collector (ISSUE 16).

The contract under test, end to end over real sockets:

* One logical operation — an EASGD exchange against a 2-shard fleet,
  a decode GENERATE — assembles into ONE trace with ZERO orphans: the
  trace context rides the wire-v2 ``TRACE_OP`` envelope, granted
  bilaterally in the hello, and server-side ``rpc_handle`` spans
  become children of the caller's open span.
* Tracing/export disabled is a strict no-op: no trace keys in the
  hello, no trace fields in open_spans, no event files, no new metric
  series — the pre-PR surface byte-for-byte.
* The export path is bounded and non-blocking: a full buffer drops
  and counts; a dead collector degrades to local-only with an error
  counter, never an exception into a hot path.
* Local event JSONLs rotate by size with a keep bound.
"""

from __future__ import annotations

import glob
import json
import os
import socket
import sys
import threading
import time

import numpy as np
import pytest

from theanompi_tpu import monitor
from theanompi_tpu.monitor import export as mexport
from theanompi_tpu.monitor import trace
from theanompi_tpu.monitor.collector import (
    TelemetryCollector,
    read_fleet,
    serve_collector,
)
from theanompi_tpu.monitor.export import Exporter, RotatingJsonlWriter
from theanompi_tpu.monitor.registry import MetricsRegistry
from theanompi_tpu.parallel import wire
from theanompi_tpu.parallel.service import ServiceClient
from theanompi_tpu.parallel.shards import ShardedEASGD, serve_shard

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
import traces as traces_tool  # noqa: E402  (tools/traces.py, stdlib-only)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(autouse=True)
def fresh_monitor():
    monitor.reset_for_tests()
    yield
    monitor.reset_for_tests()


@pytest.fixture()
def service_env(monkeypatch):
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_KEY", "trace-test")
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_RETRIES", "6")
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_RETRY_DEADLINE_S", "20")


def _counter(registry, name: str) -> float:
    return sum(r.get("value", 0.0) for r in registry.snapshot()
               if r["name"] == name)


def _tree(seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)
    return {"a": rng.standard_normal((8, 4)).astype(np.float32),
            "b": rng.standard_normal((9,)).astype(np.float32)}


# ---------------------------------------------------------------------------
# Hello negotiation: the grant is bilateral and off-by-default
# ---------------------------------------------------------------------------


class TestHelloNegotiation:
    def test_disabled_hello_has_no_trace_key(self):
        """Byte-identity at the negotiation layer: with tracing off,
        the hello payload and the accept reply carry exactly the
        pre-PR keys."""
        opts = wire.WireOptions()
        payload = wire.hello_payload(opts)
        assert "trace" not in payload
        _, reply, _ = wire.accept_hello(payload)
        assert "trace" not in reply

    def test_grant_requires_both_sides(self):
        opts = wire.WireOptions()
        # client asked, server tracing off -> no grant
        payload = dict(wire.hello_payload(opts), trace=True)
        _, reply, _ = wire.accept_hello(payload)
        assert "trace" not in reply
        trace.set_enabled(True)
        try:
            # both on -> granted
            _, reply, _ = wire.accept_hello(payload)
            assert reply.get("trace") is True
            # server on but client never asked -> still no grant (a
            # legacy client must never receive an unknown key)
            _, reply, _ = wire.accept_hello(wire.hello_payload(
                opts, trace=False))
            assert "trace" not in reply
        finally:
            trace.set_enabled(False)

    def test_attach_wire_rejects_malformed_ctx(self):
        trace.set_enabled(True)
        try:
            for bad in (None, {}, {"t": 7, "s": "a"},
                        {"t": "x" * 40, "s": "a"}, {"t": "", "s": "a"}):
                with trace.attach_wire(bad):
                    assert trace.inject() is None
        finally:
            trace.set_enabled(False)


# ---------------------------------------------------------------------------
# One EASGD exchange against a 2-shard fleet = ONE trace, zero orphans
# ---------------------------------------------------------------------------


def _start_shard_fleet(k: int):
    fleet = []
    for i in range(k):
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(target=serve_shard,
                             args=("127.0.0.1", port, i, ready, stop),
                             daemon=True)
        t.start()
        assert ready.wait(10)
        fleet.append({"addr": f"127.0.0.1:{port}", "thread": t,
                      "stop": stop})
    return fleet


def _stop_shard_fleet(fleet):
    for s in fleet:
        s["stop"].set()
        try:
            ServiceClient(s["addr"]).call("shutdown")
        except Exception:
            pass
        s["thread"].join(timeout=5)


class TestExchangeStitch:
    def test_two_shard_exchange_is_one_trace(self, service_env,
                                             rpc_loop, tmp_path,
                                             monkeypatch):
        """A root span wrapping one sharded exchange stitches the
        trainer's fan-out and BOTH shards' ``rpc_handle`` spans into
        one trace with zero orphans — under the threaded AND the
        selector RPC loop, over real sockets."""
        monkeypatch.setenv(trace.ENV_VAR, "1")
        tree = _tree(0)
        fleet = _start_shard_fleet(2)
        try:
            with monitor.session(run_dir=str(tmp_path)):
                srv = ShardedEASGD([s["addr"] for s in fleet], tree,
                                   alpha=0.5,
                                   session_id=f"tr-{rpc_loop}")
                w = {k: v + np.float32(0.1) for k, v in tree.items()}
                with monitor.span("exchange_period"):
                    srv.exchange(w)
                srv.close()
        finally:
            _stop_shard_fleet(fleet)

        records = traces_tool.load_events(str(tmp_path))
        assembled = traces_tool.assemble(records)
        ours = [spans for spans in assembled.values()
                if any(s["name"] == "exchange_period" for s in spans)]
        assert len(ours) == 1, \
            "the root span must appear in exactly one trace"
        spans = ours[0]
        assert traces_tool.orphans(spans) == []
        handled = [s for s in spans if s["name"] == "rpc_handle"]
        # one exchange fans out to BOTH shards under the same root
        assert len(handled) >= 2, [s["name"] for s in spans]
        root = [s for s in spans if s["name"] == "exchange_period"]
        assert len(root) == 1
        root_id = root[0]["span"]
        # every server span is REACHABLE from the root (zero orphans
        # made parents present; walk up to prove the chain ends at it)
        by_id = {s["span"]: s for s in spans}
        for s in handled:
            node = s
            while node["parent"] is not None:
                node = by_id[node["parent"]]
            assert node["span"] == root_id, \
                f"rpc_handle {s['span']} roots at {node['name']}"
        # the tool's critical path starts at the root and descends
        path = traces_tool.critical_path(spans)
        assert path and path[0]["span"] == root_id and len(path) >= 2


# ---------------------------------------------------------------------------
# Decode GENERATE: client -> server dispatch -> batcher, one trace
# ---------------------------------------------------------------------------


class TestGenerateStitch:
    @pytest.mark.slow
    def test_generate_stitches_client_to_replica(self, service_env,
                                                 tmp_path, monkeypatch,
                                                 tmp_path_factory):
        from theanompi_tpu.models.base import ModelConfig
        from theanompi_tpu.models.transformer import TransformerLM
        from theanompi_tpu.serving import (
            InferenceClient,
            InferenceServer,
            export_model,
            serve,
        )

        monkeypatch.setenv(trace.ENV_VAR, "1")
        cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                          compute_dtype="float32", optimizer="adamw",
                          learning_rate=1e-3, weight_decay=0.0,
                          lr_schedule="constant")
        model = TransformerLM(config=cfg, vocab=32, seq_len=16,
                              n_layers=1, d_model=16, n_heads=2,
                              verbose=False)
        export_dir = str(tmp_path_factory.mktemp("trace") / "export")
        export_model(model, export_dir, version=0)

        server = InferenceServer(
            export_dir, replicas=1, reload_poll_s=0, model=model,
            decode=True,
            decode_opts=dict(page_size=4, pages_per_seq=8, max_seqs=4,
                             prefill_buckets=(8,))).start()
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(target=serve,
                             args=(server, "127.0.0.1", port, ready,
                                   stop),
                             daemon=True)
        t.start()
        assert ready.wait(30)
        addr = f"127.0.0.1:{port}"
        c = None
        try:
            with monitor.session(run_dir=str(tmp_path)):
                c = InferenceClient(addr)
                with monitor.span("client_generate"):
                    out = c.generate(
                        np.asarray([1, 2, 3], np.int32), 4)
                assert out is not None and len(out) == 4
        finally:
            try:
                InferenceClient(addr).shutdown()
            except Exception:
                stop.set()
            if c is not None:
                c.close()
            t.join(timeout=5)
            server.stop()

        records = traces_tool.load_events(str(tmp_path))
        assembled = traces_tool.assemble(records)
        ours = [spans for spans in assembled.values()
                if any(s["name"] == "client_generate" for s in spans)]
        assert len(ours) == 1
        spans = ours[0]
        assert traces_tool.orphans(spans) == []
        names = [s["name"] for s in spans]
        assert "rpc_handle" in names, names
        assert any("decode_generate" in n for n in names), names


# ---------------------------------------------------------------------------
# Disabled-mode byte identity
# ---------------------------------------------------------------------------


class TestDisabledNoOp:
    def test_no_artifacts_no_series_no_span_fields(self, tmp_path):
        """With no trace env and no collector env, a live monitor
        session produces exactly the pre-PR artifact set, the span
        dicts carry no trace fields, and no export series exist."""
        assert not trace.enabled()
        with monitor.session(run_dir=str(tmp_path)):
            with monitor.span("step") as sp:
                opened = monitor.open_spans()
                assert opened and all(
                    "trace" not in d and "span" not in d
                    for d in opened)
                assert sp.trace_id is None
            snap = monitor._state.registry.snapshot()  # noqa: SLF001
            assert monitor._state.exporter is None  # noqa: SLF001
        names = {r["name"] for r in snap}
        assert not any(n.startswith("monitor/export") for n in names)
        assert "monitor/rotations_total" not in names
        files = sorted(os.listdir(tmp_path))
        assert not glob.glob(str(tmp_path / "events_*.jsonl")), files
        assert not (tmp_path / "fleet.jsonl").exists()

    def test_untraced_wire_messages_unchanged(self):
        """inject() without an open traced span is None, so the client
        would send the plain ``(op, *args)`` tuple — no envelope."""
        assert trace.inject() is None
        trace.set_enabled(True)
        try:
            # enabled but no open span and no remote ctx: still None —
            # tracing only ever roots at a span, never at a bare call
            assert trace.inject() is None
        finally:
            trace.set_enabled(False)


# ---------------------------------------------------------------------------
# Exporter: bounded drops, collector death, rotation
# ---------------------------------------------------------------------------


class TestExporter:
    def test_full_buffer_drops_and_counts(self, tmp_path):
        """A stalled exporter (thread never draining — the degenerate
        stalled-collector case) drops beyond capacity and counts every
        drop; emit never blocks or raises."""
        reg = MetricsRegistry()
        ex = Exporter(str(tmp_path), "t0", 0, reg, capacity=4)
        # deliberately NOT started: the buffer can only fill
        for i in range(10):
            ex.emit({"event": "span", "i": i})
        st = ex.stats()
        assert st["buffered"] == 4 and st["dropped"] == 6
        assert _counter(reg, "monitor/export_dropped_total") == 6.0
        ex.stop()

    def test_collector_death_degrades_to_local(self, service_env,
                                               tmp_path):
        """Ship to a live collector; kill it; keep emitting: events
        still land in the LOCAL file, errors are counted, nothing
        raises — then assert the collector's merged file carries the
        sender identity it stamped while alive."""
        col_dir = tmp_path / "col"
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(
            target=serve_collector,
            args=("127.0.0.1", port, str(col_dir), ready, stop),
            daemon=True)
        t.start()
        assert ready.wait(10)
        addr = f"127.0.0.1:{port}"

        reg = MetricsRegistry()
        ex = Exporter(str(tmp_path), "t9", 3, reg, collector=addr,
                      flush_s=0.05).start()
        try:
            ex.emit({"event": "span", "name": "alive", "trace": "aa",
                     "span": "bb", "t_wall": time.time(),
                     "dur_s": 0.01})
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if _counter(reg, "monitor/export_batches_total") >= 1:
                    break
                time.sleep(0.05)
            assert _counter(reg, "monitor/export_batches_total") >= 1
            fleet = read_fleet(str(col_dir / "fleet.jsonl"))
            spans = [r for r in fleet if r.get("event") == "span"]
            assert spans and spans[0]["role"] == "t9" \
                and spans[0]["rank"] == 3
            assert "offset_s" in spans[0]  # clock model rode the batch

            # kill the collector; the exporter must degrade silently
            stop.set()
            try:
                ServiceClient(addr).call("shutdown")
            except Exception:
                pass
            t.join(timeout=5)
            before_err = _counter(reg, "monitor/export_errors_total")
            for i in range(3):
                ex.emit({"event": "span", "name": f"after{i}"})
                time.sleep(0.1)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if _counter(reg,
                            "monitor/export_errors_total") > before_err:
                    break
                time.sleep(0.05)
            assert _counter(reg,
                            "monitor/export_errors_total") > before_err
        finally:
            if not stop.is_set():
                stop.set()
            ex.stop()
        local = traces_tool.load_events(str(tmp_path))
        names = {r.get("name") for r in local}
        assert "alive" in names and "after0" in names, \
            "local file must carry events from BOTH sides of the death"

    def test_rotation_keeps_n_and_counts(self, tmp_path):
        w = RotatingJsonlWriter(str(tmp_path / "e.jsonl"),
                                max_bytes=120, keep=2)
        for i in range(40):
            w.write_lines([json.dumps({"i": i, "pad": "x" * 40})])
        assert w.rotations >= 2
        assert os.path.exists(tmp_path / "e.jsonl")
        assert os.path.exists(tmp_path / "e.jsonl.1")
        assert os.path.exists(tmp_path / "e.jsonl.2")
        assert not os.path.exists(tmp_path / "e.jsonl.3")  # keep bound
        # the newest record is in the live file, in order
        last = traces_tool.load_events(str(tmp_path / "e.jsonl"))[-1]
        assert last["i"] == 39


# ---------------------------------------------------------------------------
# Collector service semantics
# ---------------------------------------------------------------------------


class TestCollector:
    def test_ingest_merges_identity_and_counts(self, tmp_path):
        col = TelemetryCollector(str(tmp_path))
        n = col.handle("collector_export",
                       {"pid": 7, "role": "rank0", "rank": 0,
                        "offset_s": 0.25, "rtt_s": 0.01},
                       [{"event": "span", "name": "a"},
                        {"event": "span", "name": "b"}, "garbage"])
        assert n == 2  # non-dict events are refused, not crashed on
        st = col.handle("collector_stats")
        assert st["events"] == 2 and st["batches"] == 1 \
            and st["senders"] == 1
        recs = read_fleet(str(tmp_path / "fleet.jsonl"))
        assert all(r["pid"] == 7 and r["offset_s"] == 0.25
                   for r in recs)

    def test_hello_answers_clocks(self, tmp_path):
        col = TelemetryCollector(str(tmp_path))
        reply = col.handle("collector_hello", {"pid": 1, "role": "x"})
        assert abs(reply["t_wall"] - time.time()) < 5.0
        assert "t_mono" in reply

    def test_malformed_batch_refused(self, tmp_path):
        col = TelemetryCollector(str(tmp_path))
        with pytest.raises(ValueError):
            col.handle("collector_export", "notadict", [])
        with pytest.raises(ValueError):
            col.handle("collector_export", {})


# ---------------------------------------------------------------------------
# tools/traces.py analysis semantics (synthetic fixtures)
# ---------------------------------------------------------------------------


def _span(trace_id, span_id, parent, name, t_wall, dur,
          offset=0.0, pid=1, role="r"):
    return {"event": "span", "trace": trace_id, "span": span_id,
            "parent": parent, "name": name, "t_wall": t_wall,
            "dur_s": dur, "offset_s": offset, "pid": pid, "role": role}


class TestTracesTool:
    def test_offset_correction_aligns_clocks(self):
        """A child whose raw wall clock is 100s ahead lands INSIDE the
        parent once its offset_s (estimated at the export handshake)
        is applied."""
        recs = [_span("t", "a", None, "root", 1000.0, 1.0),
                _span("t", "b", "a", "child", 1100.2, 0.1,
                      offset=-100.0, pid=2)]
        spans = traces_tool.assemble(recs)["t"]
        a = next(s for s in spans if s["span"] == "a")
        b = next(s for s in spans if s["span"] == "b")
        assert a["t0"] <= b["t0"] and b["t1"] <= a["t1"]

    def test_critical_path_follows_latest_ending_child(self):
        recs = [_span("t", "a", None, "root", 0.0, 1.0),
                _span("t", "b", "a", "fast", 0.1, 0.2),
                _span("t", "c", "a", "slow", 0.1, 0.8),
                _span("t", "d", "c", "leaf", 0.5, 0.3)]
        path = traces_tool.critical_path(
            traces_tool.assemble(recs)["t"])
        assert [s["name"] for s in path] == ["root", "slow", "leaf"]

    def test_orphans_detected(self):
        recs = [_span("t", "a", None, "root", 0.0, 1.0),
                _span("t", "z", "missing", "lost", 0.2, 0.1)]
        spans = traces_tool.assemble(recs)["t"]
        assert [s["span"] for s in traces_tool.orphans(spans)] == ["z"]

    def test_idle_gap_detection(self):
        recs = [_span("t", "a", None, "w1", 0.0, 1.0),
                _span("t", "b", None, "w2", 0.5, 0.6),
                # all workers idle from 1.1 to 2.0
                _span("t", "c", None, "w3", 2.0, 0.5)]
        spans = traces_tool.spans_of(recs)
        gaps = traces_tool.idle_gaps(spans, threshold_s=0.5)
        assert len(gaps) == 1
        g0, g1 = gaps[0]
        assert abs(g0 - 1.1) < 1e-9 and abs(g1 - 2.0) < 1e-9
        assert traces_tool.idle_gaps(spans, threshold_s=1.5) == []

    def test_cli_require_procs(self, tmp_path, capsys):
        path = tmp_path / "fleet.jsonl"
        recs = [_span("t", "a", None, "root", 0.0, 1.0, pid=1),
                _span("t", "b", "a", "mid", 0.1, 0.5, pid=2),
                _span("t", "c", "b", "leaf", 0.2, 0.2, pid=3)]
        path.write_text("".join(json.dumps(r) + "\n" for r in recs))
        assert traces_tool.main([str(path), "--require-procs", "3",
                                 "--require-zero-orphans"]) == 0
        out = capsys.readouterr().out
        assert "3 processes" in out and "critical path" in out
        assert traces_tool.main([str(path),
                                 "--require-procs", "4"]) == 1


# ---------------------------------------------------------------------------
# tools/tmtop.py: one frame from a synthetic fleet file
# ---------------------------------------------------------------------------


class TestTmtop:
    def test_once_renders_rates_and_drops(self, tmp_path, capsys):
        import tmtop

        def metrics(t, count, drops):
            return {"event": "metrics", "t_wall": t, "role": "rank0",
                    "pid": 11, "rank": 0,
                    "snapshot": [
                        {"name": "step_ms", "kind": "histogram",
                         "labels": {}, "count": count, "p50": 12.5,
                         "p99": 30.0},
                        {"name": "monitor/export_dropped_total",
                         "kind": "counter", "labels": {},
                         "value": drops}]}

        path = tmp_path / "fleet.jsonl"
        path.write_text(json.dumps(metrics(100.0, 10, 0)) + "\n"
                        + json.dumps(metrics(102.0, 30, 2)) + "\n")
        assert tmtop.main([str(tmp_path), "--once"]) == 0
        out = capsys.readouterr().out
        assert "rank0" in out and "1 processes" in out
        # (30-10 steps) / 2s = 10 steps/s, from consecutive snapshots
        assert "10.00" in out
        assert "12.5" in out  # step p50 ms

    def test_fleet_role_column(self, tmp_path, capsys):
        import tmtop

        # exporter-name prefix -> fleet role; service{pid} must NOT
        # read as a serving replica, unknown names fall back to train
        assert tmtop.fleet_of("router123") == "router"
        assert tmtop.fleet_of("prefill45") == "prefill"
        assert tmtop.fleet_of("serve67") == "serve"
        assert tmtop.fleet_of("service99") == "service"
        assert tmtop.fleet_of("ingest_reader0_89") == "ingest"
        assert tmtop.fleet_of("rank0") == "train"
        assert tmtop.fleet_of(None) == "train"

        def metrics(role, pid):
            return {"event": "metrics", "t_wall": 100.0, "role": role,
                    "pid": pid, "rank": None,
                    "snapshot": [{"name": "step_ms",
                                  "kind": "histogram", "labels": {},
                                  "count": 1, "p50": 1.0, "p99": 2.0}]}

        path = tmp_path / "fleet.jsonl"
        path.write_text("".join(
            json.dumps(metrics(r, p)) + "\n"
            for r, p in (("router1", 1), ("prefill2", 2),
                         ("serve3", 3))))
        assert tmtop.main([str(tmp_path), "--once"]) == 0
        out = capsys.readouterr().out
        assert "fleet" in out and "3 processes" in out
        rows = {ln.split()[1] for ln in out.splitlines()[2:] if ln}
        assert rows == {"router", "prefill", "serve"}
