"""Importable tiny model variants for fast e2e tests (the rules resolve
models by module path, so test-sized subclasses must live in a real
module, not a test function body)."""

from theanompi_tpu.data.cifar10 import Cifar10_data
from theanompi_tpu.data.imagenet import ImageNet_data
from theanompi_tpu.models.cifar10 import Cifar10_model
from theanompi_tpu.models.resnet50 import ResNet50


class TinyCifar(Cifar10_model):
    """Cifar10 CNN over a 512-sample synthetic set: one epoch at
    global batch 16 is 32 steps — seconds, not a minute."""

    def build_data(self):
        return Cifar10_data(synthetic_n=512, seed=self.config.seed)


class StragglerTinyCifar(TinyCifar):
    """Worker 0 sleeps every iteration, making it the session's
    straggler — exercises the async rules' heterogeneous-worker-speed
    behavior (EASGD validates on worker 0's epoch cadence)."""

    straggler_sleep_s = 0.01

    def train_iter(self, count, recorder):
        if self.shard_rank == 0:
            import time

            time.sleep(self.straggler_sleep_s)
        return super().train_iter(count, recorder)


class TinyCifar128(TinyCifar):
    """128-sample variant: a full epoch at global batch 32 is 4
    dispatches — for cadence-accounting tests that must walk a whole
    epoch."""

    def build_data(self):
        return Cifar10_data(synthetic_n=128, seed=self.config.seed)


class NoisyTinyCifar(TinyCifar):
    """Falsifiable-oracle variant (VERDICT r2 #5): 20% label noise with
    disjoint val draws — the Bayes val-error floor is the dataset's
    realized ``val_noise_frac`` (≈ 0.2 · 9/10 = 0.18), so a converged
    model must land ON the floor: below it means the oracle leaks,
    stuck above it means the training stack regressed."""

    label_noise = 0.2

    def build_data(self):
        return Cifar10_data(synthetic_n=4096, seed=self.config.seed,
                            label_noise=self.label_noise,
                            augment_on_device=self.config.augment_on_device)


class TinyRecipeResNet(ResNet50):
    """The bundled 90-epoch ResNet recipe SHAPE (step LR decays at
    30/60/80, momentum, weight decay, bf16 compute, device-side
    augment, BN) at width 8 / stage sizes (1,1,1,1) / 32 px crops over
    the noisy synthetic pool — small enough to run all 90 epochs on the
    CPU mesh, against a falsifiable per-draw ρ=0.25 label-noise oracle
    (Bayes val-error floor ≈ 0.25·999/1000)."""

    name = "tiny_recipe_resnet"
    train_flops_per_sample = None  # width-8 toy; 12.3e9 would be a lie

    def build_module(self):
        from theanompi_tpu.models.resnet50 import ResNet

        return ResNet(stage_sizes=(1, 1, 1, 1), width=8,
                      n_classes=self.data.n_classes,
                      dtype=self._compute_dtype(),
                      stem=self.config.resnet_stem,
                      bn_axis=self._bn_axis())

    def build_data(self):
        return ImageNet_data(crop=32, seed=self.config.seed,
                             synthetic_n=512, synthetic_pool=64,
                             synthetic_store=40,
                             augment_on_device=self.config.augment_on_device,
                             label_noise=0.25)


class FaultyTinyCifar(TinyCifar):
    """Worker shard_rank==1 raises mid-epoch — exercises the async
    rules' fail-fast abort propagation (SURVEY §5.3): every OTHER
    worker must stop at the abort event instead of training out its
    epochs, and the injected exception must surface from wait()."""

    fail_at_iter = 3

    def train_iter(self, count, recorder):
        if self.shard_rank == 1 and count >= self.fail_at_iter:
            raise RuntimeError("injected worker fault")
        return super().train_iter(count, recorder)
