"""Importable tiny model variants for fast e2e tests (the rules resolve
models by module path, so test-sized subclasses must live in a real
module, not a test function body)."""

from theanompi_tpu.data.cifar10 import Cifar10_data
from theanompi_tpu.models.cifar10 import Cifar10_model


class TinyCifar(Cifar10_model):
    """Cifar10 CNN over a 512-sample synthetic set: one epoch at
    global batch 16 is 32 steps — seconds, not a minute."""

    def build_data(self):
        return Cifar10_data(synthetic_n=512, seed=self.config.seed)
