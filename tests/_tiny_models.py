"""Importable tiny model variants for fast e2e tests (the rules resolve
models by module path, so test-sized subclasses must live in a real
module, not a test function body)."""

from theanompi_tpu.data.cifar10 import Cifar10_data
from theanompi_tpu.models.cifar10 import Cifar10_model


class TinyCifar(Cifar10_model):
    """Cifar10 CNN over a 512-sample synthetic set: one epoch at
    global batch 16 is 32 steps — seconds, not a minute."""

    def build_data(self):
        return Cifar10_data(synthetic_n=512, seed=self.config.seed)


class StragglerTinyCifar(TinyCifar):
    """Worker 0 sleeps every iteration, making it the session's
    straggler — exercises the async rules' heterogeneous-worker-speed
    behavior (EASGD validates on worker 0's epoch cadence)."""

    straggler_sleep_s = 0.01

    def train_iter(self, count, recorder):
        if self.shard_rank == 0:
            import time

            time.sleep(self.straggler_sleep_s)
        return super().train_iter(count, recorder)


class TinyCifar128(TinyCifar):
    """128-sample variant: a full epoch at global batch 32 is 4
    dispatches — for cadence-accounting tests that must walk a whole
    epoch."""

    def build_data(self):
        return Cifar10_data(synthetic_n=128, seed=self.config.seed)
