"""Distributed ingest (theanompi_tpu/ingest, ISSUE 9): byte-identical
remote streams, shuffle-epoch determinism across fleet sizes,
backpressure via typed Overloaded, and reader-death reassignment —
over REAL sockets (thread-hosted readers, the same wire loop the
standalone processes run)."""

from __future__ import annotations

import os
import socket
import threading
import time

import numpy as np
import pytest

os.environ.setdefault("THEANOMPI_TPU_SERVICE_KEY", "test-ingest")

from theanompi_tpu.data.imagenet import (
    ImageNet_data,
    prepare_imagenet_shards,
)
from theanompi_tpu.ingest import protocol
from theanompi_tpu.ingest.client import RemoteBatchSource
from theanompi_tpu.ingest.coordinator import (
    IngestCoordinator,
    serve_coordinator,
)
from theanompi_tpu.ingest.order import EpochOrder
from theanompi_tpu.ingest.reader import IngestReader, serve_reader
from theanompi_tpu.parallel.service import ServiceClient, ServiceError

SEED = 3
BATCH = 32


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def shard_tree(tmp_path_factory):
    """A real mmap shard tree: 700 samples in 7 files of 100 (batches
    straddle file boundaries at global batch 32)."""
    d = str(tmp_path_factory.mktemp("ingest_shards"))
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, size=(700, 8, 8, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, size=700).astype(np.int64)
    prepare_imagenet_shards(imgs, labels, d, shard_size=100)
    return d


@pytest.fixture()
def dataset(shard_tree):
    return ImageNet_data(data_dir=shard_tree, crop=8, seed=SEED,
                         augment_on_device=True)


class _Fleet:
    """Thread-hosted readers (+ optional coordinator) on real ports."""

    def __init__(self, data_dir: str, n: int, seed: int = SEED,
                 coordinator: bool = False, max_inflight: int = 8,
                 probe_interval_s: float = 0.3):
        self.readers: list[IngestReader] = []
        self.threads: list[threading.Thread] = []
        self.addrs: list[str] = []
        for i in range(n):
            port = _free_port()
            reader = IngestReader(data_dir, seed=seed, reader_id=i,
                                  max_inflight=max_inflight)
            ready = threading.Event()
            t = threading.Thread(
                target=serve_reader,
                args=("127.0.0.1", port, reader, ready),
                daemon=True)
            t.start()
            assert ready.wait(30)
            self.readers.append(reader)
            self.threads.append(t)
            self.addrs.append(f"127.0.0.1:{port}")
        self.coordinator = None
        self.coordinator_addr = None
        if coordinator:
            self.coordinator = IngestCoordinator(
                list(self.addrs), probe_interval_s=probe_interval_s)
            port = _free_port()
            ready = threading.Event()
            t = threading.Thread(
                target=serve_coordinator,
                args=("127.0.0.1", port, self.coordinator, ready),
                daemon=True)
            t.start()
            assert ready.wait(30)
            self.threads.append(t)
            self.coordinator_addr = f"127.0.0.1:{port}"

    @property
    def ingest_addrs(self) -> list[str]:
        return ([self.coordinator_addr] if self.coordinator_addr
                else list(self.addrs))

    def kill(self, addr: str) -> None:
        """Shut one server loop down (its conns close, like a process
        death from the clients' point of view)."""
        c = ServiceClient(addr)
        try:
            c.call("shutdown")
        except Exception:
            pass
        c.close()

    def stop(self) -> None:
        for addr in ([self.coordinator_addr] if self.coordinator_addr
                     else []) + list(self.addrs):
            self.kill(addr)
        for t in self.threads:
            t.join(timeout=10)
            assert not t.is_alive(), "server thread did not exit"


@pytest.fixture()
def fleet2(shard_tree):
    f = _Fleet(shard_tree, 2)
    yield f
    f.stop()


def _local_stream(dataset, epoch, rank=0, size=1):
    return list(dataset.train_batches(epoch, BATCH, rank, size))


def _assert_streams_equal(remote, local):
    assert len(remote) == len(local)
    for i, ((rx, ry), (lx, ly)) in enumerate(zip(remote, local)):
        assert rx.dtype == lx.dtype and np.array_equal(rx, lx), i
        assert ry.dtype == ly.dtype and np.array_equal(ry, ly), i


# ---------------------------------------------------------------------------
# Pure plan / order math
# ---------------------------------------------------------------------------


class TestPartition:
    def test_covers_contiguously(self):
        owners = protocol.partition_batches(10, ["a", "b", "c"])
        assert owners == [(0, 4, "a"), (4, 7, "b"), (7, 10, "c")]
        assert [protocol.owner_of(owners, i) for i in range(10)] == \
            ["a"] * 4 + ["b"] * 3 + ["c"] * 3

    def test_rotation_spreads_concurrent_ranks(self):
        """Rank-rotated plans start concurrent trainers on DIFFERENT
        readers (same ranges, rotated owner order) so a same-phase
        fleet serves in parallel instead of one reader at a time."""
        r0 = protocol.partition_batches(10, ["a", "b"], rotation=0)
        r1 = protocol.partition_batches(10, ["a", "b"], rotation=1)
        assert [(lo, hi) for lo, hi, _ in r0] == \
            [(lo, hi) for lo, hi, _ in r1]
        assert [a for _, _, a in r0] == ["a", "b"]
        assert [a for _, _, a in r1] == ["b", "a"]
        assert protocol.partition_batches(10, ["a", "b"], rotation=2) \
            == r0

    def test_more_readers_than_batches(self):
        owners = protocol.partition_batches(2, ["a", "b", "c"])
        assert owners == [(0, 1, "a"), (1, 2, "b"), (2, 2, "c")]

    def test_out_of_range(self):
        with pytest.raises(IndexError):
            protocol.owner_of(protocol.partition_batches(4, ["a"]), 4)

    def test_validation(self):
        with pytest.raises(ValueError):
            protocol.partition_batches(4, [])
        with pytest.raises(ValueError):
            protocol.partition_batches(-1, ["a"])

    def test_addresses_parse(self, monkeypatch):
        assert protocol.ingest_addresses("h:1, g:2,") == ["h:1", "g:2"]
        assert protocol.ingest_addresses("") is None
        monkeypatch.delenv(protocol.ENV_VAR, raising=False)
        assert protocol.ingest_addresses() is None
        monkeypatch.setenv(protocol.ENV_VAR, "x:9")
        assert protocol.ingest_addresses() == ["x:9"]
        with pytest.raises(ValueError):
            protocol.ingest_addresses("no-port")


class TestEpochOrder:
    @pytest.mark.parametrize("rank,size", [(0, 1), (0, 2), (1, 2)])
    def test_byte_identical_to_streaming_loader(self, dataset, rank,
                                                size):
        for epoch in (0, 2):
            local = _local_stream(dataset, epoch, rank, size)
            order = EpochOrder(dataset.train_files, dataset._file_sizes,
                               SEED, epoch, rank, size)
            assert order.n_batches(BATCH) == len(local) \
                == dataset.n_train_batches_for(epoch, BATCH, rank, size)
            remote = [order.assemble(i, BATCH)
                      for i in range(order.n_batches(BATCH))]
            _assert_streams_equal(remote, local)

    def test_out_of_range(self, dataset):
        order = EpochOrder(dataset.train_files, dataset._file_sizes,
                           SEED, 0)
        with pytest.raises(IndexError):
            order.assemble(order.n_batches(BATCH), BATCH)

    def test_files_for_batches(self, dataset):
        order = EpochOrder(dataset.train_files, dataset._file_sizes,
                           SEED, 0)
        n = order.n_batches(BATCH)
        everything = order.files_for_batches(0, n, BATCH)
        assert everything == list(range(len(order.files)))
        head = order.files_for_batches(0, 2, BATCH)
        # 2 batches of 32 touch only the first shard file (100 rows)
        assert head == [0]
        assert order.files_for_batches(3, 3, BATCH) == []


# ---------------------------------------------------------------------------
# Reader + client over real sockets
# ---------------------------------------------------------------------------


class TestRemoteStream:
    @pytest.mark.parametrize("n_readers", [1, 2, 3])
    def test_byte_identical_across_fleet_sizes(self, rpc_loop, shard_tree,
                                               dataset, n_readers):
        """The acceptance pin: every fleet size N yields EXACTLY the
        in-process loader's stream — same seed, one permutation per
        epoch, reassembled in epoch order."""
        fleet = _Fleet(shard_tree, n_readers)
        try:
            with RemoteBatchSource(fleet.ingest_addrs, data=dataset,
                                   epoch=1, global_batch=BATCH) as src:
                remote = list(src)
            _assert_streams_equal(remote, _local_stream(dataset, 1))
            if n_readers > 1:
                served = [r.stats()["served"] for r in fleet.readers]
                assert all(s > 0 for s in served), served
        finally:
            fleet.stop()

    def test_mux_pipes_byte_identical(self, fleet2, dataset,
                                      shard_tree, monkeypatch):
        """ISSUE 11: with mux on, the control clients and the pull
        pipeline to each reader share one multiplexed socket — and
        the stream stays byte-identical to the in-process loader."""
        monkeypatch.setenv("THEANOMPI_TPU_RPC_LOOP", "selector")
        with RemoteBatchSource(fleet2.ingest_addrs, data=dataset,
                               epoch=1, global_batch=BATCH,
                               mux=True) as src:
            remote = list(src)
            # one shared transport per reader peer, all mux-granted
            assert src._transports and all(
                t.mux for t in src._transports.values())
        _assert_streams_equal(remote, _local_stream(dataset, 1))

    def test_sharded_trainer_streams(self, fleet2, dataset, shard_tree):
        """Async-rule trainers (rank r of s) each see their own
        byte-identical stream from ONE fleet."""
        for rank in (0, 1):
            with RemoteBatchSource(fleet2.ingest_addrs, data=dataset,
                                   epoch=0, global_batch=BATCH,
                                   rank=rank, size=2) as src:
                remote = list(src)
            _assert_streams_equal(remote,
                                  _local_stream(dataset, 0, rank, 2))

    def test_meta_mismatch_refused(self, fleet2, shard_tree):
        """A trainer whose dataset seed differs from the fleet's must
        be refused at construction — not fed a silently different
        permutation."""
        other = ImageNet_data(data_dir=shard_tree, crop=8, seed=SEED + 1,
                              augment_on_device=True)
        with pytest.raises(ValueError, match="different dataset"):
            RemoteBatchSource(fleet2.ingest_addrs, data=other, epoch=0,
                              global_batch=BATCH)

    def test_host_augmented_dataset_refused(self, fleet2, shard_tree):
        ds = ImageNet_data(data_dir=shard_tree, crop=8, seed=SEED,
                           augment_on_device=False)
        with pytest.raises(ValueError, match="augment"):
            RemoteBatchSource(fleet2.ingest_addrs, data=ds, epoch=0,
                              global_batch=BATCH)

    def test_synthetic_dataset_refused(self, fleet2):
        ds = ImageNet_data(crop=8, seed=SEED, augment_on_device=True)
        assert ds.synthetic
        with pytest.raises(RuntimeError, match="synthetic"):
            RemoteBatchSource(fleet2.ingest_addrs, data=ds, epoch=0,
                              global_batch=BATCH)


class TestBackpressure:
    def test_overload_is_typed_and_bounded(self, shard_tree, dataset):
        """Admission past max_inflight rejects in O(1) with the typed
        Overloaded riding the err-reply prefix — the serving
        discipline on the reader."""
        fleet = _Fleet(shard_tree, 1, max_inflight=1)
        try:
            reader = fleet.readers[0]
            # hold the only admission slot: the next pull must be
            # rejected, not queued
            assert reader._admission.acquire(blocking=False)
            c = ServiceClient(fleet.addrs[0])
            try:
                with pytest.raises(ServiceError, match="Overloaded"):
                    c.call(protocol.OP_BATCH, 0, 0, 1, BATCH, 0)
                reader._admission.release()
                x, y = c.call(protocol.OP_BATCH, 0, 0, 1, BATCH, 0)
                assert x.shape == (BATCH, 8, 8, 3)
            finally:
                c.close()
        finally:
            fleet.stop()

    def test_client_backs_off_and_retries(self, shard_tree, dataset):
        """An overloaded reader sheds load; the client treats it as
        backpressure (retry with backoff), not failure."""
        fleet = _Fleet(shard_tree, 1, max_inflight=1)
        try:
            reader = fleet.readers[0]
            assert reader._admission.acquire(blocking=False)
            src = RemoteBatchSource(fleet.ingest_addrs, data=dataset,
                                    epoch=0, global_batch=BATCH,
                                    depth=2)
            try:
                time.sleep(0.3)  # fetchers are hitting Overloaded now
                assert reader.stats()["served"] == 0
                reader._admission.release()
                _assert_streams_equal(list(src),
                                      _local_stream(dataset, 0))
            finally:
                src.close()
        finally:
            fleet.stop()

    def test_slow_trainer_bounds_reader_memory(self, shard_tree,
                                               dataset):
        """A slow consumer stops the pipelined pulls at the reorder
        window — readers never run ahead unboundedly (no unbounded
        queue anywhere)."""
        fleet = _Fleet(shard_tree, 2)
        try:
            depth = 3
            src = RemoteBatchSource(fleet.ingest_addrs, data=dataset,
                                    epoch=0, global_batch=BATCH,
                                    depth=depth)
            try:
                next(iter(src))  # consume ONE batch, then stall
                time.sleep(0.5)
                served = sum(r.stats()["served"]
                             for r in fleet.readers)
                # 1 consumed + at most `depth` in the window
                assert served <= 1 + depth, served
                before = served
                time.sleep(0.3)
                assert sum(r.stats()["served"]
                           for r in fleet.readers) == before
            finally:
                src.close()
        finally:
            fleet.stop()


class TestReaderDeath:
    def test_static_failover_byte_identical(self, shard_tree, dataset):
        """Kill a reader mid-epoch with NO coordinator: the client
        re-partitions over the survivors and the stream stays
        byte-identical."""
        fleet = _Fleet(shard_tree, 2)
        killed = False
        try:
            local = _local_stream(dataset, 1)
            src = RemoteBatchSource(fleet.ingest_addrs, data=dataset,
                                    epoch=1, global_batch=BATCH,
                                    depth=2)
            remote = []
            try:
                it = iter(src)
                for _ in range(3):
                    remote.append(next(it))
                # the tail range's owner dies mid-epoch
                fleet.kill(fleet.addrs[1])
                killed = True
                for b in it:
                    remote.append(b)
            finally:
                src.close()
            _assert_streams_equal(remote, local)
        finally:
            if killed:
                fleet.addrs.pop(1)  # already shut down
                fleet.threads.pop(1).join(timeout=10)
            fleet.stop()

    def test_coordinator_reassigns_mid_epoch(self, shard_tree, dataset):
        """The coordinator verifies the report, reassigns the dead
        reader's ranges, and the stream stays byte-identical — the
        acceptance kill/reassign pin."""
        fleet = _Fleet(shard_tree, 2, coordinator=True)
        killed = False
        try:
            local = _local_stream(dataset, 1)
            src = RemoteBatchSource(fleet.ingest_addrs, data=dataset,
                                    epoch=1, global_batch=BATCH,
                                    depth=2)
            remote = []
            try:
                it = iter(src)
                for _ in range(3):
                    remote.append(next(it))
                fleet.kill(fleet.addrs[1])
                killed = True
                for b in it:
                    remote.append(b)
            finally:
                src.close()
            _assert_streams_equal(remote, local)
            stats = fleet.coordinator.stats()
            assert stats["reassignments"] >= 1
            assert stats["readers"][fleet.addrs[1]] is False
        finally:
            if killed:
                fleet.addrs.pop(1)
                fleet.threads.pop(1).join(timeout=10)
            fleet.stop()

    def test_report_dead_verifies_first(self, shard_tree):
        """A flaky trainer reporting a HEALTHY reader must not evict
        it."""
        fleet = _Fleet(shard_tree, 2, coordinator=True)
        try:
            c = ServiceClient(fleet.coordinator_addr)
            try:
                out = c.call(protocol.OP_REPORT_DEAD, fleet.addrs[0])
                assert out["dead"] is False
                assert fleet.coordinator.stats()["readers"][
                    fleet.addrs[0]] is True
            finally:
                c.close()
        finally:
            fleet.stop()

    def test_plan_pinned_until_membership_changes(self, shard_tree):
        fleet = _Fleet(shard_tree, 2, coordinator=True)
        try:
            c = ServiceClient(fleet.coordinator_addr)
            try:
                p1 = c.call(protocol.OP_PLAN, 0, 0, 1, BATCH, 10)
                p2 = c.call(protocol.OP_PLAN, 0, 0, 1, BATCH, 10)
                assert p1 == p2
                owners = [tuple(o) for o in p1["owners"]]
                assert owners == protocol.partition_batches(
                    10, fleet.addrs)
            finally:
                c.close()
        finally:
            fleet.stop()


class TestAssignRace:
    def test_concurrent_assigns_never_join_unstarted_thread(
            self, shard_tree):
        """T trainers hitting one epoch boundary push concurrent
        ingest_assign ops; replacement must never observe (and join) a
        stored-but-unstarted prefetch thread."""
        reader = IngestReader(shard_tree, seed=SEED, reader_id=0)
        errs: list = []

        def assign(i):
            try:
                for k in range(5):
                    reader._assign(0, i % 2, 2, BATCH, 0, 3)
            except BaseException as e:
                errs.append(e)

        threads = [threading.Thread(target=assign, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        reader.shutdown()
        assert not errs, errs


class TestLauncherFlag:
    def test_serve_refuses_ingest(self):
        from theanompi_tpu.launcher import tmlocal

        with pytest.raises(SystemExit, match="TRAINING"):
            tmlocal(["SERVE", "--export-dir", "/tmp/x",
                     "--ingest", "h:1"])

    def test_bad_spec_fails_fast(self):
        from theanompi_tpu.launcher import tmlocal

        with pytest.raises(SystemExit, match="--ingest"):
            tmlocal(["BSP", "--ingest", "not-an-address"])


class TestEndToEnd:
    def test_begin_epoch_switches_on_env(self, shard_tree, monkeypatch):
        """The rules-facing contract: with THEANOMPI_TPU_INGEST set
        (launcher --ingest), begin_epoch stages the SAME device
        batches through DevicePrefetcher as the local loader —
        nothing above the data layer changes."""
        import jax

        from tests._tiny_models import TinyRecipeResNet
        from theanompi_tpu.models.base import ModelConfig
        from theanompi_tpu.parallel import data_mesh

        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 255, size=(256, 40, 40, 3),
                            dtype=np.uint8)
        labels = rng.integers(0, 1000, size=256).astype(np.int64)
        d = os.path.join(shard_tree, "..", "e2e_shards")
        prepare_imagenet_shards(imgs, labels, d, shard_size=64)
        ds = ImageNet_data(data_dir=d, crop=32, seed=0,
                           augment_on_device=True)
        cfg = ModelConfig(batch_size=2, n_epochs=1, print_freq=0)
        model = TinyRecipeResNet(config=cfg, mesh=data_mesh(8),
                                 data=ds, verbose=False)

        monkeypatch.delenv(protocol.ENV_VAR, raising=False)
        n_local = model.begin_epoch(0)
        local = [jax.device_get(next(model._train_iter))
                 for _ in range(n_local)]
        model.cleanup_iter()

        fleet = _Fleet(d, 2, seed=0)
        try:
            monkeypatch.setenv(protocol.ENV_VAR,
                               ",".join(fleet.addrs))
            n_remote = model.begin_epoch(0)
            assert n_remote == n_local
            remote = [jax.device_get(next(model._train_iter))
                      for _ in range(n_remote)]
            assert model._ingest_source is not None
            model.cleanup_iter()
            assert model._ingest_source is None
            _assert_streams_equal(remote, local)
        finally:
            fleet.stop()
        model.cleanup()
