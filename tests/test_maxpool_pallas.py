"""ops.maxpool_stem / ops.maxpool_pallas: the argmax-saving stem pool
(round 5 — attacks the account's select-and-scatter slice,
artifacts/fusion_deepdive.json).  Interpret mode on CPU; semantics
pinned against the XLA oracle."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from theanompi_tpu.ops.maxpool import maxpool_stem
from theanompi_tpu.ops.maxpool_pallas import maxpool3x3s2


def _xla(x):
    return nn.max_pool(x, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])


class TestMaxpoolPallas:
    @pytest.mark.parametrize("shape,dtype", [
        ((2, 8, 8, 16), jnp.float32),
        ((2, 14, 10, 8), jnp.float32),      # H != W
        ((1, 112, 112, 64), jnp.bfloat16),  # the flagship stem shape
    ])
    def test_fwd_and_bwd_match_xla(self, shape, dtype):
        x = jax.random.normal(jax.random.key(0), shape, dtype)
        np.testing.assert_array_equal(np.asarray(maxpool3x3s2(x)),
                                      np.asarray(_xla(x)))
        # continuous random input: no ties — gradient ROUTING is
        # identical; cells fed by several overlapping windows may
        # accumulate in a different order than XLA's scatter, so
        # equality is to addition-order noise, not bitwise
        gr = jax.grad(lambda x: (_xla(x).astype(jnp.float32) ** 2).sum())(x)
        gp = jax.grad(
            lambda x: (maxpool3x3s2(x).astype(jnp.float32) ** 2).sum())(x)
        np.testing.assert_allclose(np.asarray(gp, np.float32),
                                   np.asarray(gr, np.float32),
                                   rtol=1e-5, atol=1e-5)

    def test_tie_gradient_mass_conserved(self):
        # all-equal input: every window has a 9-way tie; the gradient
        # must route each window's cotangent to exactly ONE input
        # (first max in row-major order), conserving total mass
        x = jnp.ones((1, 4, 4, 8))
        g = jax.grad(lambda x: maxpool3x3s2(x).sum())(x)
        assert float(g.sum()) == 2 * 2 * 8  # OH*OW*C windows
        assert float(g.max()) >= 1.0

    def test_jit_composes(self):
        x = jax.random.normal(jax.random.key(1), (2, 8, 8, 16))
        np.testing.assert_array_equal(
            np.asarray(jax.jit(maxpool3x3s2)(x)), np.asarray(_xla(x)))

    def test_neg_inf_window_matches_xla_and_conserves(self):
        # a window of true -inf must still pool to -inf (not a finite
        # sentinel), and its cotangent must route to a real pixel
        x = jnp.full((1, 4, 4, 8), -jnp.inf)
        np.testing.assert_array_equal(np.asarray(maxpool3x3s2(x)),
                                      np.asarray(_xla(x)))
        g = jax.grad(lambda x: jnp.where(jnp.isfinite(maxpool3x3s2(x)),
                                         maxpool3x3s2(x), 0.0).sum())(x)
        assert np.isfinite(np.asarray(g)).all()

    def test_odd_spatial_rejected(self):
        with pytest.raises(ValueError, match="even H and W"):
            maxpool3x3s2(jnp.zeros((1, 7, 8, 8)))

    def test_selector(self):
        x = jax.random.normal(jax.random.key(2), (1, 8, 8, 8))
        np.testing.assert_array_equal(
            np.asarray(maxpool_stem(x, impl="pallas")),
            np.asarray(maxpool_stem(x, impl="xla")))
        with pytest.raises(ValueError, match="unknown pool impl"):
            maxpool_stem(x, impl="cudnn")

    def test_resnet_stem_pallas_equals_xla(self):
        """The full tiny ResNet forward+grad with pool_impl='pallas'
        must match pool_impl='xla' exactly (same params, same batch) —
        the integration contract behind ModelConfig.pool_impl."""
        from theanompi_tpu.models.resnet50 import ResNet

        kw = dict(stage_sizes=(1,), width=8, n_classes=4,
                  dtype=jnp.float32)
        mx = ResNet(**kw, pool_impl="xla")
        mp = ResNet(**kw, pool_impl="pallas")
        x = jax.random.normal(jax.random.key(3), (2, 16, 16, 3))
        variables = mx.init({"params": jax.random.key(4)}, x, train=False)
        yx = mx.apply(variables, x, train=False)
        yp = mp.apply(variables, x, train=False)
        np.testing.assert_array_equal(np.asarray(yx), np.asarray(yp))

        def loss(m, v, x):
            return (m.apply(v, x, train=False) ** 2).sum()

        # to addition-order noise: multi-window cells accumulate in a
        # different order than select_and_scatter (measured ~1e-6 on
        # ~20-magnitude grads)
        gx = jax.grad(lambda v: loss(mx, v, x))(variables)
        gp = jax.grad(lambda v: loss(mp, v, x))(variables)
        for a, b in zip(jax.tree.leaves(gx), jax.tree.leaves(gp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5)

    def test_nan_propagates_like_xla(self):
        x = jax.random.normal(jax.random.key(5), (1, 8, 8, 8))
        x = x.at[0, 3, 3, 2].set(jnp.nan)
        ref = np.asarray(_xla(x))
        got = np.asarray(maxpool3x3s2(x))
        np.testing.assert_array_equal(np.isnan(got), np.isnan(ref))
        np.testing.assert_array_equal(got[~np.isnan(ref)],
                                      ref[~np.isnan(ref)])
        # and under grad (the argmax-saving fwd variant): nansum zeroes
        # the NaN windows' cotangents, so the routed gradient must be
        # finite everywhere — NaN windows route to the (one) NaN pixel
        # with weight 0, never smearing NaN into neighbors
        g = jax.grad(lambda x: jnp.nansum(maxpool3x3s2(x)))(x)
        assert np.isfinite(np.asarray(g)).all()
