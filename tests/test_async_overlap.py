"""Comm/compute overlap for the async rules (ISSUE 5 tentpole,
rules/async_rules._ExchangePipe): the worker computes iteration i+1
while iteration i's exchange RPC is in flight, bounded staleness 1.

The acceptance bar: monitor spans DEMONSTRATE the overlap — the
worker's compute span no longer encloses (or waits out) the exchange
RPC span, witnessed live via ``monitor.open_spans()`` on the 8-dev
CPU mesh — and an injected fault on the exchange path still lands
exactly like a synchronous failure.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from theanompi_tpu import monitor
from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.resilience import faults
from theanompi_tpu.rules.async_rules import _ExchangePipe


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def tiny_cfg(tmp_path, **kw):
    base = dict(batch_size=8, n_epochs=1, learning_rate=0.01,
                snapshot_dir=str(tmp_path), print_freq=0)
    base.update(kw)
    return ModelConfig(**base)


class TestExchangePipe:
    def test_overlap_hides_rpc_behind_compute(self, tmp_path):
        """With compute time ~ RPC time, the worker's collect wait is
        a small fraction of the RPC span, and session wall-clock is
        ~max(compute, rpc) per round, not the sum — the overlap the
        reference built its exchanger around."""
        rpc_s = compute_s = 0.15
        rounds = 3
        with monitor.session(str(tmp_path)):
            pipe = _ExchangePipe(
                lambda p: (time.sleep(rpc_s), p)[1], "test/exchange", 0)
            try:
                t0 = time.monotonic()
                for i in range(rounds):
                    pipe.submit({"x": i})
                    time.sleep(compute_s)  # the overlapped compute
                    with monitor.span("test/exchange_collect",
                                      worker="0"):
                        payload, result = pipe.collect()
                    assert result == {"x": i}
                wall = time.monotonic() - t0
            finally:
                pipe.close()
            reg = monitor.registry()
            rpc = reg.get("span_ms", name="test/exchange_rpc",
                          worker="0")
            col = reg.get("span_ms", name="test/exchange_collect",
                          worker="0")
            assert rpc.count == rounds
            # the worker paid a fraction of the wire cost...
            assert col.sum < 0.5 * rpc.sum, (col.sum, rpc.sum)
            # ...and the rounds pipelined instead of serializing
            assert wall < 0.75 * rounds * (rpc_s + compute_s), wall

    def test_bounded_staleness_barrier(self):
        pipe = _ExchangePipe(lambda p: p, "test/exchange", 0)
        try:
            pipe.submit(1)
            with pytest.raises(RuntimeError, match="outstanding"):
                pipe.submit(2)
            payload, result = pipe.collect()
            assert (payload, result) == (1, 1)
            pipe.submit(3)  # collect released the barrier
            assert pipe.collect() == (3, 3)
        finally:
            pipe.close()

    def test_exchange_error_carried_to_worker(self):
        """A failure inside the exchange thread (incl. an injected
        service_call fault — same code path) re-raises at collect()
        and poisons later submits: the supervisor sees it exactly like
        a synchronous exchange failure."""

        def boom(_):
            raise faults.FaultInjected("injected fault at service_call")

        pipe = _ExchangePipe(boom, "test/exchange", 1)
        try:
            pipe.submit({"g": 1})
            with pytest.raises(faults.FaultInjected, match="injected"):
                pipe.collect()
            with pytest.raises(faults.FaultInjected, match="injected"):
                pipe.submit({"g": 2})
        finally:
            pipe.close()

    def test_close_is_idempotent_with_uncollected_result(self):
        pipe = _ExchangePipe(lambda p: p, "test/exchange", 0)
        pipe.submit(1)  # never collected
        time.sleep(0.05)
        pipe.close()
        pipe.close()

    def test_close_with_queued_request_stops_thread(self):
        """close() racing a still-queued request must not drop the
        STOP sentinel: the exchange thread has to exit after draining
        the queue, not park on _req.get() forever (one leaked thread
        per supervisor restart otherwise)."""
        entered, release = threading.Event(), threading.Event()

        def fn(p):
            entered.set()
            release.wait(5)
            return p

        pipe = _ExchangePipe(fn, "test/exchange", 0)
        pipe.submit(1)
        assert entered.wait(5)
        # pin the race close() must survive: a request sitting in the
        # queue (undequeued) at close time — put_nowait(_STOP) would
        # see Full and, pre-fix, silently drop the sentinel
        pipe._req.put_nowait(2)
        pipe.close()
        release.set()
        payload, result = pipe.collect()  # frees the result slot
        assert (payload, result) == (1, 1)
        pipe._thread.join(timeout=5)
        assert not pipe._thread.is_alive()


def _overlap_witness_poller(stop: threading.Event, witnesses: list):
    """Sample open spans; record any instant where one worker has a
    compute span AND its exchange RPC span open SIMULTANEOUSLY —
    impossible when the worker blocks on the wire."""
    while not stop.is_set():
        by_worker: dict[str, set] = {}
        for s in monitor.open_spans():
            w = s["labels"].get("worker")
            if w is not None:
                by_worker.setdefault(w, set()).add(s["name"])
        for w, names in by_worker.items():
            if (any("exchange_rpc" in n for n in names)
                    and any("compute" in n for n in names)):
                witnesses.append((w, sorted(names)))
        time.sleep(0.002)


def test_easgd_overlap_e2e_spans_prove_overlap(tmp_path):
    """Overlapped EASGD on the 8-dev CPU mesh: the session still
    exchanges and validates finite, the RPC span exists OUTSIDE any
    compute span (nesting would produce a 'compute/.../exchange_rpc'
    full name), and a live sampler catches compute and RPC open at the
    same instant for the same worker.  Each exchange is slowed 50 ms
    via the fault plane's delay action so the witness is deterministic
    — with the worker blocking on the wire that delay would serialize,
    with overlap it hides behind the next tau iterations."""
    from theanompi_tpu import EASGD

    faults.install([{"site": "exchange", "kind": "easgd",
                     "action": "delay", "delay_s": 0.05, "times": -1}])
    witnesses: list = []
    stop = threading.Event()
    with monitor.session(str(tmp_path / "mon")):
        poller = threading.Thread(
            target=_overlap_witness_poller, args=(stop, witnesses),
            daemon=True)
        poller.start()
        try:
            rule = EASGD()
            rule.init(devices=8, modelfile="tests._tiny_models",
                      modelclass="TinyCifar128",
                      config=tiny_cfg(tmp_path), tau=4, alpha=0.5,
                      checkpoint=False, overlap=True)
            res = rule.wait()
        finally:
            stop.set()
            poller.join(timeout=5)
        assert res["n_exchanges"] > 0
        assert np.isfinite(res["val"]["loss"])
        snap = monitor.registry().snapshot()
        span_names = {e["labels"]["name"] for e in snap
                      if e["name"] == "span_ms"}
        assert any(n.endswith("easgd/exchange_rpc") for n in span_names)
        assert any("easgd/compute" in n for n in span_names)
        # the acceptance criterion, structurally: no RPC span was ever
        # nested inside a compute span (per-thread nesting would have
        # emitted 'easgd/compute/.../exchange_rpc')
        assert not any("compute" in n and "exchange_rpc" in n
                       for n in span_names), span_names
        # ...and behaviorally: compute and RPC were OPEN CONCURRENTLY
        assert witnesses, "no instant with compute || exchange_rpc"


def test_asgd_overlap_e2e(tmp_path):
    """Overlapped ASGD: per-iteration push_pull pipelines against the
    next gradient computation (staleness 1) and the session still
    learns on synthetic cifar."""
    from theanompi_tpu import ASGD

    with monitor.session(str(tmp_path / "mon")):
        rule = ASGD()
        rule.init(devices=4, modelfile="tests._tiny_models",
                  modelclass="TinyCifar128", config=tiny_cfg(tmp_path),
                  overlap=True)
        res = rule.wait()
        assert res["n_updates"] > 0
        assert np.isfinite(res["val"]["loss"])
        snap = monitor.registry().snapshot()
        span_names = {e["labels"]["name"] for e in snap
                      if e["name"] == "span_ms"}
        assert any(n.endswith("asgd/push_pull_rpc") for n in span_names)
        assert not any("compute" in n and "push_pull_rpc" in n
                       for n in span_names), span_names


def test_easgd_overlap_fault_still_lands(tmp_path):
    """Fault-site-awareness (tentpole requirement): an injected raise
    on the exchange path fires inside the exchange THREAD, is carried
    to the worker at collect/submit, and aborts the session with the
    reference's fail-fast semantics — overlap must not turn injected
    faults into silently-dropped exchanges."""
    from theanompi_tpu import EASGD

    faults.install([{"site": "exchange", "kind": "easgd",
                     "action": "raise", "nth": 2}])
    rule = EASGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar128", config=tiny_cfg(tmp_path),
              tau=4, alpha=0.5, checkpoint=False, overlap=True)
    with pytest.raises(faults.FaultInjected):
        rule.wait()
