"""decode/: paged KV-cache autoregressive serving (ISSUE 10).

The acceptance pins:

* greedy decode through the paged cache is TOKEN-IDENTICAL to the
  uncached full-forward argmax oracle — per prefill bucket, across a
  ring-eviction boundary (oracle = the same model under a
  sliding-window mask), and after a mid-stream admit;
* steady-state decode triggers ZERO recompiles (trace counters);
* a sequence admitted mid-stream shares a decode step with an
  in-flight one (iteration-level batching, `shared_steps`);
* bf16/int8 quantized exports hold their error bounds, and the
  hot-reload watcher REFUSES an incompatible export with the typed
  `IncompatibleExport` instead of swapping or crashing;
* the GENERATE wire op serves concurrent streams over a real socket.
"""

from __future__ import annotations

import os
import socket
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.decode import (
    CacheConfig,
    ContinuousBatcher,
    DecodePolicy,
    DecodeSession,
    PagePool,
    full_forward,
)
from theanompi_tpu.decode import kvcache
from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.models.transformer import TransformerLM
from theanompi_tpu.serving import (
    IncompatibleExport,
    InferenceClient,
    InferenceServer,
    Overloaded,
    dequantize_tree,
    export_model,
    load_export,
    quantize_tree,
    serve,
)
from theanompi_tpu.serving.server import ServiceError

N_LAYERS, N_HEADS, D_MODEL, VOCAB = 2, 2, 16, 32


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def tiny_lm(tmp_path_factory):
    """One untrained tiny TransformerLM + its f32 export (v0): the
    (model, host params, export_dir) triple the module builds on."""
    cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                      compute_dtype="float32", optimizer="adamw",
                      learning_rate=1e-3, weight_decay=0.0,
                      lr_schedule="constant")
    model = TransformerLM(config=cfg, vocab=VOCAB, seq_len=16,
                          n_layers=N_LAYERS, d_model=D_MODEL,
                          n_heads=N_HEADS, verbose=False)
    params = jax.device_get(model.state.params)
    export_dir = str(tmp_path_factory.mktemp("decode") / "export")
    export_model(model, export_dir, version=0)
    return model, params, export_dir


def _flax_greedy(model, params, prompt, n: int) -> list[int]:
    """The independent oracle: iterative FULL forward through the
    training module (no cache anywhere), argmax of the last position."""
    cur = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        logits = np.asarray(model.module.apply(
            {"params": params}, jnp.asarray([cur], jnp.int32),
            train=False, seq_axis=None))
        tok = int(np.argmax(logits[0, -1]))
        out.append(tok)
        cur.append(tok)
    return out


def _windowed_greedy(params, prompt, n: int, window: int) -> list[int]:
    """Eviction oracle: iterative full forward under the sliding-
    window mask — what the ring cache semantically IS."""
    cur = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        logits, _, _ = full_forward(params, jnp.asarray([cur], jnp.int32),
                                    N_LAYERS, N_HEADS, jnp.float32,
                                    window=window)
        tok = int(np.argmax(np.asarray(logits)[0, -1]))
        out.append(tok)
        cur.append(tok)
    return out


def _session_greedy(sess, prompt, n: int) -> list[int]:
    seq, logits = sess.admit(np.asarray(prompt, np.int32))
    out = [int(np.argmax(logits))]
    for _ in range(n - 1):
        lg = sess.decode([seq], np.asarray([out[-1]], np.int32))
        out.append(int(np.argmax(lg[0])))
    sess.release(seq)
    return out


# ---------------------------------------------------------------------------
# kvcache.py — ring math and the page pool
# ---------------------------------------------------------------------------


class TestKVCache:
    def test_stored_positions_and_mask(self):
        w = 4
        # length 0: nothing stored, nothing attendable
        pos = np.asarray(kvcache.stored_positions(jnp.asarray([0]), w))
        assert (pos < 0).all()
        assert not np.asarray(kvcache.cache_mask(jnp.asarray([0]), w)).any()
        # length 3 < window: slots 0..2 hold 0..2, slot 3 unwritten
        pos = np.asarray(kvcache.stored_positions(jnp.asarray([3]), w))[0]
        assert pos.tolist() == [0, 1, 2, -1]
        mask = np.asarray(kvcache.cache_mask(jnp.asarray([3]), w))[0]
        assert mask.tolist() == [True, True, True, False]
        # length 6 > window: ring wrapped — slots hold 4, 5, 2, 3; the
        # next token (position 6) may attend 3, 4, 5 only (window 4
        # including itself), so slot holding 2 (== 6-4) is masked
        pos = np.asarray(kvcache.stored_positions(jnp.asarray([6]), w))[0]
        assert pos.tolist() == [4, 5, 2, 3]
        mask = np.asarray(kvcache.cache_mask(jnp.asarray([6]), w))[0]
        assert mask.tolist() == [True, True, False, True]

    def test_ring_from_prompt_wraps_and_drops_pad(self):
        w = 4
        kv = jnp.arange(6, dtype=jnp.float32).reshape(6, 1, 1) + 1.0
        # length 6 through a window of 4: positions 2..5 survive in
        # slots 2,3,0,1; the padded tail (rows >= length) is dropped
        ring = np.asarray(kvcache.ring_from_prompt(kv, 6, w))[:, 0, 0]
        assert ring.tolist() == [5.0, 6.0, 3.0, 4.0]
        # length 2: slots 0,1 filled, rest stay zero
        ring = np.asarray(kvcache.ring_from_prompt(kv, 2, w))[:, 0, 0]
        assert ring.tolist() == [1.0, 2.0, 0.0, 0.0]

    def test_page_pool_alloc_free(self):
        cfg = CacheConfig(n_layers=1, n_heads=1, d_head=4, page_size=2,
                          pages_per_seq=2, max_seqs=2)
        pool = PagePool(cfg)
        assert pool.free_pages == 4
        a = pool.alloc_seq()
        b = pool.alloc_seq()
        assert pool.alloc_seq() is None and pool.free_pages == 0
        assert pool.used_fraction == 1.0
        pool.free_seq(a)
        assert pool.free_pages == 2
        with pytest.raises(ValueError):
            pool.free_seq(a)  # double free
        pool.free_seq(b)
        assert sorted(np.concatenate([a, b]).tolist()) == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# model.py — the shared-weights applier vs the training module
# ---------------------------------------------------------------------------


class TestFullForward:
    def test_matches_training_module(self, tiny_lm):
        model, params, _ = tiny_lm
        toks = np.random.default_rng(0).integers(
            0, VOCAB, (2, 10)).astype(np.int32)
        want = np.asarray(model.module.apply(
            {"params": params}, jnp.asarray(toks), train=False,
            seq_axis=None))
        got, ks, vs = full_forward(params, jnp.asarray(toks), N_LAYERS,
                                   N_HEADS, jnp.float32)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)
        assert len(ks) == N_LAYERS
        assert ks[0].shape == (2, 10, N_HEADS, D_MODEL // N_HEADS)

    def test_window_geq_len_is_plain_causal(self, tiny_lm):
        _, params, _ = tiny_lm
        toks = np.random.default_rng(1).integers(
            0, VOCAB, (1, 6)).astype(np.int32)
        a, _, _ = full_forward(params, jnp.asarray(toks), N_LAYERS,
                               N_HEADS, jnp.float32, window=None)
        b, _, _ = full_forward(params, jnp.asarray(toks), N_LAYERS,
                               N_HEADS, jnp.float32, window=6)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6)


# ---------------------------------------------------------------------------
# session.py — greedy token identity + the compile-counter pin
# ---------------------------------------------------------------------------


class TestGreedyIdentity:
    def test_token_identical_per_prefill_bucket(self, tiny_lm):
        """Prompts landing in DIFFERENT prefill buckets (8 and 16)
        decode token-identically to the uncached flax oracle."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=2,
                             prefill_buckets=(8, 16))
        rng = np.random.default_rng(2)
        for plen in (5, 12):  # buckets 8 and 16
            prompt = rng.integers(0, VOCAB, plen).astype(np.int32)
            got = _session_greedy(sess, prompt, 6)
            assert got == _flax_greedy(model, params, prompt, 6)
        assert sess.compiles == {"prefill": 2, "decode": 1}

    def test_token_identical_across_eviction_boundary(self, tiny_lm):
        """window = 8 (page_size 4 x 2 pages); 5-token prompt + 10
        generated crosses the ring boundary at position 8 — identical
        to the sliding-window full-forward oracle, including a prompt
        that ALONE overflows the window (prefill-side eviction)."""
        model, params, _ = tiny_lm
        rng = np.random.default_rng(3)
        for plen in (5, 12):
            sess = DecodeSession(model, params=params, page_size=4,
                                 pages_per_seq=2, max_seqs=2,
                                 prefill_buckets=(8, 16))
            assert sess.window == 8
            prompt = rng.integers(0, VOCAB, plen).astype(np.int32)
            got = _session_greedy(sess, prompt, 10)
            assert got == _windowed_greedy(params, prompt, 10, 8)

    def test_batched_decode_matches_sequential(self, tiny_lm):
        """Two sequences decoded in ONE shared step each produce the
        same tokens as the unbatched oracle (pad rows and the second
        sequence cannot perturb the first)."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=4,
                             prefill_buckets=(8,))
        rng = np.random.default_rng(4)
        pa = rng.integers(0, VOCAB, 4).astype(np.int32)
        pb = rng.integers(0, VOCAB, 7).astype(np.int32)
        sa, la = sess.admit(pa)
        sb, lb = sess.admit(pb)
        oa, ob = [int(np.argmax(la))], [int(np.argmax(lb))]
        for _ in range(5):
            lg = sess.decode([sa, sb],
                             np.asarray([oa[-1], ob[-1]], np.int32))
            oa.append(int(np.argmax(lg[0])))
            ob.append(int(np.argmax(lg[1])))
        assert oa == _flax_greedy(model, params, pa, 6)
        assert ob == _flax_greedy(model, params, pb, 6)


class TestCompileCounter:
    def test_steady_state_zero_recompiles(self, tiny_lm):
        """After one admit/decode/evict cycle has touched a (prefill
        bucket, decode bucket) pair, further traffic through the same
        buckets — different prompts, lengths, page assignments, admit
        order — compiles NOTHING new."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=2,
                             prefill_buckets=(8,))
        rng = np.random.default_rng(5)

        def cycle():
            a, la = sess.admit(rng.integers(0, VOCAB, 3).astype(np.int32))
            ta = int(np.argmax(la))
            lg = sess.decode([a], np.asarray([ta], np.int32))
            b, lb = sess.admit(rng.integers(0, VOCAB, 6).astype(np.int32))
            tb = int(np.argmax(lb))
            for _ in range(6):  # crosses the window-8 boundary
                lg = sess.decode([a, b], np.asarray([ta, tb], np.int32))
                ta, tb = int(np.argmax(lg[0])), int(np.argmax(lg[1]))
            sess.release(a)
            lg = sess.decode([b], np.asarray([tb], np.int32))
            sess.release(b)

        cycle()  # warm: compiles prefill x1, decode buckets 1 and 2
        warm = dict(sess.compiles)
        assert warm == {"prefill": 1, "decode": 2}
        for _ in range(3):
            cycle()
        assert sess.compiles == warm, (
            f"steady-state decode recompiled: {warm} -> {sess.compiles}")


# ---------------------------------------------------------------------------
# scheduler.py — continuous batching
# ---------------------------------------------------------------------------


class TestContinuousBatcher:
    def test_mid_stream_admit_shares_step_and_stays_correct(self, tiny_lm):
        """Stream B submitted while A is mid-generation: at least one
        decode step batches BOTH (iteration-level sharing), and both
        streams stay token-identical to the uncached oracle."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=4,
                             prefill_buckets=(8,))
        batcher = ContinuousBatcher(sess, DecodePolicy(max_pending=8),
                                    replica=0).start()
        try:
            rng = np.random.default_rng(6)
            pa = rng.integers(0, VOCAB, 4).astype(np.int32)
            pb = rng.integers(0, VOCAB, 6).astype(np.int32)
            results = {}

            def run(name, prompt, n):
                results[name] = batcher.generate(prompt, n)

            ta = threading.Thread(target=run, args=("a", pa, 24))
            tb = threading.Thread(target=run, args=("b", pb, 12))
            ta.start()
            tb.start()  # lands while A is in flight
            ta.join(60)
            tb.join(60)
            assert results["a"] == _flax_greedy(model, params, pa, 24)
            assert results["b"] == _flax_greedy(model, params, pb, 12)
            st = batcher.stats()
            assert st["shared_steps"] >= 1, st
            assert st["evicted"] == 2 and st["active"] == 0
            assert sess.pool.free_pages == sess.cfg.n_pages
        finally:
            batcher.stop()

    def test_admission_overload_is_typed_and_o1(self, tiny_lm):
        """A full pending queue rejects with the SAME typed Overloaded
        the eval path uses — immediately, without waiting on the
        scheduler."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=2,
                             prefill_buckets=(8,))
        # NOT started: pending can only grow, so the bound is exact
        batcher = ContinuousBatcher(sess, DecodePolicy(max_pending=1),
                                    replica=0)
        errs = []

        def bg():
            try:
                batcher.generate(np.asarray([1, 2, 3], np.int32), 4)
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=bg)
        t.start()
        deadline = 50
        while batcher.stats()["pending"] < 1 and deadline:
            deadline -= 1
            threading.Event().wait(0.02)
        with pytest.raises(Overloaded):
            batcher.generate(np.asarray([1, 2, 3], np.int32), 4)
        batcher.stop()  # fails the queued request with Overloaded
        t.join(10)
        assert len(errs) == 1 and isinstance(errs[0], Overloaded)

    def test_decode_step_fault_restarts_from_export(self, tiny_lm,
                                                    tmp_path):
        """An injected decode_step fault fails THAT step's streams,
        then the replica restarts from a fresh export load of THE
        VERSION IT SERVES on a zeroed page pool (same budgeted
        supervision as eval replicas) and serves the next stream
        correctly.  A newer INCOMPATIBLE publish sitting in the dir
        must not ride in through the restart — that would be a side
        door past the reload watcher's IncompatibleExport refusal."""
        from theanompi_tpu.decode import DecodeReplica
        from theanompi_tpu.resilience import faults

        model, params, _ = tiny_lm
        export_dir = str(tmp_path / "export")
        export_model(model, export_dir, version=0)
        # newer, incompatible (weight dtype) publish: newest-verified,
        # but NOT what this replica serves
        export_model(model, export_dir, version=1, weight_dtype="int8")
        loaded = load_export(export_dir, version=0)
        rep = DecodeReplica(0, export_dir, model, loaded,
                            DecodePolicy(max_pending=4),
                            max_restarts=1, page_size=4,
                            pages_per_seq=8, max_seqs=4,
                            prefill_buckets=(8,))
        rep.batcher.start()
        faults.install([{"site": "decode_step", "replica": 0,
                         "step": 2}])
        try:
            rng = np.random.default_rng(9)
            prompt = rng.integers(0, VOCAB, 5).astype(np.int32)
            with pytest.raises(faults.FaultInjected):
                rep.generate(prompt, 8)
            assert rep.restarts == 1 and rep.alive
            # restarted on the SERVED version, not the newer publish
            assert rep.session.version == 0
            # the restarted replica serves, token-identically
            out = rep.generate(prompt, 6)
            assert out == _flax_greedy(model, params, prompt, 6)
            assert rep.session.pool.free_pages == \
                rep.session.cfg.n_pages
        finally:
            faults.clear()
            rep.batcher.stop()

    def test_request_validation(self, tiny_lm):
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=2,
                             prefill_buckets=(8,))
        # max_new_cap above max_len so the positional-table check is
        # reachable (the cap otherwise clamps the request first)
        batcher = ContinuousBatcher(
            sess, DecodePolicy(max_new_cap=sess.max_len + 8,
                               submit_timeout_s=5.0), replica=0)
        with pytest.raises(ValueError):
            batcher.generate(np.zeros((0,), np.int32), 4)
        with pytest.raises(ValueError):  # prompt > largest bucket
            batcher.generate(np.zeros((9,), np.int32), 4)
        with pytest.raises(ValueError):  # past the positional table
            batcher.generate(np.asarray([1], np.int32),
                             sess.max_len + 1)
        batcher.stop()


# ---------------------------------------------------------------------------
# Quantized exports
# ---------------------------------------------------------------------------


class TestQuantizedExports:
    def test_bf16_round_trip_error_bound(self, tiny_lm):
        _, params, _ = tiny_lm
        deq = dequantize_tree(quantize_tree(params, "bf16"),
                              upcast_bf16=True)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(deq)):
            a, b = np.asarray(a), np.asarray(b)
            assert b.dtype == np.float32
            # bf16 keeps 8 significant bits: elementwise relative
            # error bounded by 2^-8 (plus an absolute floor near 0)
            assert np.all(np.abs(a - b)
                          <= np.abs(a) * 2.0 ** -8 + 1e-12)

    def test_int8_round_trip_error_bound(self, tiny_lm):
        _, params, _ = tiny_lm
        q = quantize_tree(params, "int8")
        deq = dequantize_tree(q, upcast_bf16=True)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(deq)):
            a, b = np.asarray(a), np.asarray(b)
            if a.ndim < 2:
                np.testing.assert_array_equal(a, b)  # kept f32
                continue
            # symmetric per-output-channel scale: |err| <= scale/2
            amax = np.max(np.abs(a), axis=tuple(range(a.ndim - 1)),
                          keepdims=True)
            bound = np.where(amax > 0, amax, 1.0) / 127.0 / 2.0
            assert np.all(np.abs(a - b) <= bound + 1e-7)

    def test_quantized_export_load_and_meta(self, tiny_lm, tmp_path):
        model, params, _ = tiny_lm
        for wd in ("bf16", "int8"):
            d = str(tmp_path / f"export_{wd}")
            export_model(model, d, version=0, weight_dtype=wd)
            loaded = load_export(d)  # dequantize-on-load default
            assert loaded.meta["weight_dtype"] == wd
            assert loaded.meta["decode"] is True
            assert loaded.meta["net"]["vocab"] == VOCAB
            for leaf in jax.tree.leaves(loaded.params):
                assert np.asarray(leaf).dtype == np.float32
            raw = load_export(d, dequantize=False)
            kinds = {np.asarray(leaf).dtype.name
                     for leaf in jax.tree.leaves(raw.params)}
            assert ("int8" in kinds) if wd == "int8" \
                else ("bfloat16" in kinds)

    def test_on_the_fly_matches_dequantize_on_load(self, tiny_lm,
                                                   tmp_path):
        """int8 weights kept quantized on device (dequantize_tree runs
        inside the jitted step) decode the same tokens as the
        collapsed-at-load tree — the two dequant paths are one
        arithmetic."""
        model, params, _ = tiny_lm
        d = str(tmp_path / "export_fly")
        export_model(model, d, version=0, weight_dtype="int8")
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, VOCAB, 5).astype(np.int32)
        outs = []
        for dequantize in (True, False):
            loaded = load_export(d, dequantize=dequantize)
            sess = DecodeSession(model, params=loaded.params,
                                 page_size=4, pages_per_seq=8,
                                 max_seqs=2, prefill_buckets=(8,))
            outs.append(_session_greedy(sess, prompt, 8))
        assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# Hot-reload refusal + the GENERATE wire op
# ---------------------------------------------------------------------------


class TestDecodeServing:
    @pytest.fixture()
    def decode_server(self, tiny_lm):
        model, params, export_dir = tiny_lm
        key_before = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
        server = InferenceServer(
            export_dir, replicas=1, reload_poll_s=0, model=model,
            decode=True,
            decode_opts=dict(page_size=4, pages_per_seq=8, max_seqs=4,
                             prefill_buckets=(8,))).start()
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(
            target=serve, args=(server, "127.0.0.1", port, ready, stop),
            daemon=True)
        t.start()
        assert ready.wait(30)
        addr = f"127.0.0.1:{port}"
        clients: list[InferenceClient] = []

        def make_client() -> InferenceClient:
            c = InferenceClient(addr)
            clients.append(c)
            return c

        yield make_client, server
        try:
            InferenceClient(addr).shutdown()
        except Exception:
            stop.set()
        for c in clients:
            c.close()
        t.join(timeout=5)
        server.stop()
        if key_before is None:
            os.environ.pop("THEANOMPI_TPU_SERVICE_KEY", None)
        else:
            os.environ["THEANOMPI_TPU_SERVICE_KEY"] = key_before

    def test_generate_over_wire_two_streams(self, tiny_lm,
                                            decode_server):
        model, params, _ = tiny_lm
        make_client, server = decode_server
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, VOCAB, 5).astype(np.int32),
                   rng.integers(0, VOCAB, 7).astype(np.int32)]
        outs = [None, None]
        cs = [make_client(), make_client()]

        def run(i):
            outs[i] = cs[i].generate(prompts[i], 10)

        ths = [threading.Thread(target=run, args=(i,))
               for i in range(2)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(60)
        for p, o in zip(prompts, outs):
            assert o is not None and o.dtype == np.int32
            assert list(o) == _flax_greedy(model, params, p, 10)
        st = cs[0].stats()
        assert st["decode"] is True
        assert st["shared_steps"] >= 1, st
        assert st["tokens"] >= 20

    def test_infer_op_rejected_in_decode_mode(self, decode_server):
        make_client, _ = decode_server
        with pytest.raises(ServiceError, match="generate"):
            make_client().infer(np.zeros((1, 16), np.int32))

    def test_reload_refuses_incompatible_then_accepts(
            self, tiny_lm, decode_server):
        """Publish v1 with a DIFFERENT weight dtype: the watcher must
        refuse with the typed IncompatibleExport, keep serving v0, and
        skip the bad version until v2 (compatible) supersedes it."""
        model, params, export_dir = tiny_lm
        make_client, server = decode_server
        c = make_client()
        export_model(model, export_dir, version=1, weight_dtype="int8")
        with pytest.raises(IncompatibleExport, match="weight_dtype"):
            c.reload()
        assert server.version == 0
        # the refusal is remembered (no re-LOAD) but EVERY reload of
        # the refused version re-raises the typed error from memory —
        # a client polling after the background watcher saw the
        # publish first still observes the refusal, not a silent
        # old-version return
        with pytest.raises(IncompatibleExport, match="weight_dtype"):
            c.reload()
        # the server still serves
        out = c.generate(np.asarray([1, 2, 3], np.int32), 4)
        assert len(out) == 4
        # a compatible v2 goes through and supersedes the skip
        export_model(model, export_dir, version=2)
        assert c.reload() == 2
        assert server.version == 2

    def test_export_incompatibility_covers_net_dims(self):
        """A resized transformer (same class, same sample_shape, same
        dtype) must be refused: its arrays cannot adopt into sessions
        built around the live module's dims."""
        from theanompi_tpu.serving import export_incompatibility

        live = {"modelfile": "m", "modelclass": "C",
                "sample_shape": [16], "weight_dtype": "f32",
                "decode": True,
                "net": {"vocab": 32, "d_model": 16, "n_layers": 2}}
        assert export_incompatibility(live, dict(live)) is None
        resized = dict(live,
                       net={"vocab": 32, "d_model": 32, "n_layers": 2})
        assert "net dims" in export_incompatibility(live, resized)

    def test_decode_mode_requires_capable_export(self, tmp_path):
        from tests._tiny_models import TinyCifar

        model = TinyCifar(config=ModelConfig(batch_size=8, n_epochs=1,
                                             print_freq=0),
                          verbose=False)
        d = str(tmp_path / "cnn_export")
        export_model(model, d, version=0)
        with pytest.raises(ValueError, match="decode-capable"):
            InferenceServer(d, replicas=1, reload_poll_s=0,
                            model=model, decode=True)
