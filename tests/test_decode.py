"""decode/: paged KV-cache autoregressive serving (ISSUE 10).

The acceptance pins:

* greedy decode through the paged cache is TOKEN-IDENTICAL to the
  uncached full-forward argmax oracle — per prefill bucket, across a
  ring-eviction boundary (oracle = the same model under a
  sliding-window mask), and after a mid-stream admit;
* steady-state decode triggers ZERO recompiles (trace counters);
* a sequence admitted mid-stream shares a decode step with an
  in-flight one (iteration-level batching, `shared_steps`);
* bf16/int8 quantized exports hold their error bounds, and the
  hot-reload watcher REFUSES an incompatible export with the typed
  `IncompatibleExport` instead of swapping or crashing;
* the GENERATE wire op serves concurrent streams over a real socket.

ISSUE 12 adds the two token-throughput multipliers' pins:

* speculative decoding is byte-identical to the non-speculative
  oracle across every accept/reject boundary (self-draft = full
  accepts, a random small draft = rejects at every depth) and across
  ring eviction, with zero steady-state recompiles (accept counts are
  data, not shapes);
* copy-on-write page sharing: a prefix-cache hit aliases pages and
  stays token-identical, the first wrapping write diverges via COW, a
  shared page outlives its first owner (refcounted eviction), and
  allocation pressure evicts LRU cache entries;
* the draft hot-reload refusal matrix (wrong vocab / resized net ->
  typed `IncompatibleExport`, remembered, server keeps serving).
"""

from __future__ import annotations

import os
import socket
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.decode import (
    CacheConfig,
    ContinuousBatcher,
    DecodePolicy,
    DecodeSession,
    PagePool,
    full_forward,
)
from theanompi_tpu.decode import kvcache
from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.models.transformer import TransformerLM
from theanompi_tpu.serving import (
    IncompatibleExport,
    InferenceClient,
    InferenceServer,
    Overloaded,
    dequantize_tree,
    export_model,
    load_export,
    quantize_tree,
    serve,
)
from theanompi_tpu.serving.server import ServiceError

N_LAYERS, N_HEADS, D_MODEL, VOCAB = 2, 2, 16, 32


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def tiny_lm(tmp_path_factory):
    """One untrained tiny TransformerLM + its f32 export (v0): the
    (model, host params, export_dir) triple the module builds on."""
    cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                      compute_dtype="float32", optimizer="adamw",
                      learning_rate=1e-3, weight_decay=0.0,
                      lr_schedule="constant")
    model = TransformerLM(config=cfg, vocab=VOCAB, seq_len=16,
                          n_layers=N_LAYERS, d_model=D_MODEL,
                          n_heads=N_HEADS, verbose=False)
    params = jax.device_get(model.state.params)
    export_dir = str(tmp_path_factory.mktemp("decode") / "export")
    export_model(model, export_dir, version=0)
    return model, params, export_dir


def _flax_greedy(model, params, prompt, n: int) -> list[int]:
    """The independent oracle: iterative FULL forward through the
    training module (no cache anywhere), argmax of the last position."""
    cur = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        logits = np.asarray(model.module.apply(
            {"params": params}, jnp.asarray([cur], jnp.int32),
            train=False, seq_axis=None))
        tok = int(np.argmax(logits[0, -1]))
        out.append(tok)
        cur.append(tok)
    return out


def _windowed_greedy(params, prompt, n: int, window: int) -> list[int]:
    """Eviction oracle: iterative full forward under the sliding-
    window mask — what the ring cache semantically IS."""
    cur = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        logits, _, _ = full_forward(params, jnp.asarray([cur], jnp.int32),
                                    N_LAYERS, N_HEADS, jnp.float32,
                                    window=window)
        tok = int(np.argmax(np.asarray(logits)[0, -1]))
        out.append(tok)
        cur.append(tok)
    return out


def _hot(compiles: dict) -> dict:
    """The nonzero program families — new families default to 0, so
    equality pins stay exact without enumerating every key."""
    return {k: v for k, v in compiles.items() if v}


def _spec_greedy(sess, draft, prompt, n: int, k: int = 3) -> list[int]:
    """Speculative greedy through a (target, draft) session pair:
    propose -> verify -> commit rounds until n tokens, trimmed to n
    (the emission-trim the scheduler applies)."""
    seq, logits = sess.admit(np.asarray(prompt, np.int32))
    dseq, _ = draft.admit(np.asarray(prompt, np.int32))
    out = [int(np.argmax(logits))]
    while len(out) < n:
        pending = np.asarray([out[-1]], np.int32)
        drafts = draft.propose([dseq], pending, k)
        y, counts = sess.verify([seq], pending, drafts)
        draft.commit([dseq], counts)
        out.extend(int(t) for t in y[0, :counts[0]])
    sess.release(seq)
    draft.release(dseq)
    return out[:n]


def _session_greedy(sess, prompt, n: int) -> list[int]:
    seq, logits = sess.admit(np.asarray(prompt, np.int32))
    out = [int(np.argmax(logits))]
    for _ in range(n - 1):
        lg = sess.decode([seq], np.asarray([out[-1]], np.int32))
        out.append(int(np.argmax(lg[0])))
    sess.release(seq)
    return out


# ---------------------------------------------------------------------------
# kvcache.py — ring math and the page pool
# ---------------------------------------------------------------------------


class TestKVCache:
    def test_stored_positions_and_mask(self):
        w = 4
        # length 0: nothing stored, nothing attendable
        pos = np.asarray(kvcache.stored_positions(jnp.asarray([0]), w))
        assert (pos < 0).all()
        assert not np.asarray(kvcache.cache_mask(jnp.asarray([0]), w)).any()
        # length 3 < window: slots 0..2 hold 0..2, slot 3 unwritten
        pos = np.asarray(kvcache.stored_positions(jnp.asarray([3]), w))[0]
        assert pos.tolist() == [0, 1, 2, -1]
        mask = np.asarray(kvcache.cache_mask(jnp.asarray([3]), w))[0]
        assert mask.tolist() == [True, True, True, False]
        # length 6 > window: ring wrapped — slots hold 4, 5, 2, 3; the
        # next token (position 6) may attend 3, 4, 5 only (window 4
        # including itself), so slot holding 2 (== 6-4) is masked
        pos = np.asarray(kvcache.stored_positions(jnp.asarray([6]), w))[0]
        assert pos.tolist() == [4, 5, 2, 3]
        mask = np.asarray(kvcache.cache_mask(jnp.asarray([6]), w))[0]
        assert mask.tolist() == [True, True, False, True]

    def test_ring_from_prompt_wraps_and_drops_pad(self):
        w = 4
        kv = jnp.arange(6, dtype=jnp.float32).reshape(6, 1, 1) + 1.0
        # length 6 through a window of 4: positions 2..5 survive in
        # slots 2,3,0,1; the padded tail (rows >= length) is dropped
        ring = np.asarray(kvcache.ring_from_prompt(kv, 6, w))[:, 0, 0]
        assert ring.tolist() == [5.0, 6.0, 3.0, 4.0]
        # length 2: slots 0,1 filled, rest stay zero
        ring = np.asarray(kvcache.ring_from_prompt(kv, 2, w))[:, 0, 0]
        assert ring.tolist() == [1.0, 2.0, 0.0, 0.0]

    def test_page_pool_alloc_free(self):
        cfg = CacheConfig(n_layers=1, n_heads=1, d_head=4, page_size=2,
                          pages_per_seq=2, max_seqs=2)
        pool = PagePool(cfg)
        assert pool.free_pages == 4
        a = pool.alloc_seq()
        b = pool.alloc_seq()
        assert pool.alloc_seq() is None and pool.free_pages == 0
        assert pool.used_fraction == 1.0
        pool.free_seq(a)
        assert pool.free_pages == 2
        with pytest.raises(ValueError):
            pool.free_seq(a)  # double free
        pool.free_seq(b)
        assert sorted(np.concatenate([a, b]).tolist()) == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# model.py — the shared-weights applier vs the training module
# ---------------------------------------------------------------------------


class TestFullForward:
    def test_matches_training_module(self, tiny_lm):
        model, params, _ = tiny_lm
        toks = np.random.default_rng(0).integers(
            0, VOCAB, (2, 10)).astype(np.int32)
        want = np.asarray(model.module.apply(
            {"params": params}, jnp.asarray(toks), train=False,
            seq_axis=None))
        got, ks, vs = full_forward(params, jnp.asarray(toks), N_LAYERS,
                                   N_HEADS, jnp.float32)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)
        assert len(ks) == N_LAYERS
        assert ks[0].shape == (2, 10, N_HEADS, D_MODEL // N_HEADS)

    def test_window_geq_len_is_plain_causal(self, tiny_lm):
        _, params, _ = tiny_lm
        toks = np.random.default_rng(1).integers(
            0, VOCAB, (1, 6)).astype(np.int32)
        a, _, _ = full_forward(params, jnp.asarray(toks), N_LAYERS,
                               N_HEADS, jnp.float32, window=None)
        b, _, _ = full_forward(params, jnp.asarray(toks), N_LAYERS,
                               N_HEADS, jnp.float32, window=6)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6)


# ---------------------------------------------------------------------------
# session.py — greedy token identity + the compile-counter pin
# ---------------------------------------------------------------------------


class TestGreedyIdentity:
    def test_token_identical_per_prefill_bucket(self, tiny_lm):
        """Prompts landing in DIFFERENT prefill buckets (8 and 16)
        decode token-identically to the uncached flax oracle."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=2,
                             prefill_buckets=(8, 16))
        rng = np.random.default_rng(2)
        for plen in (5, 12):  # buckets 8 and 16
            prompt = rng.integers(0, VOCAB, plen).astype(np.int32)
            got = _session_greedy(sess, prompt, 6)
            assert got == _flax_greedy(model, params, prompt, 6)
        assert _hot(sess.compiles) == {"prefill": 2, "decode": 1}

    def test_token_identical_across_eviction_boundary(self, tiny_lm):
        """window = 8 (page_size 4 x 2 pages); 5-token prompt + 10
        generated crosses the ring boundary at position 8 — identical
        to the sliding-window full-forward oracle, including a prompt
        that ALONE overflows the window (prefill-side eviction)."""
        model, params, _ = tiny_lm
        rng = np.random.default_rng(3)
        for plen in (5, 12):
            sess = DecodeSession(model, params=params, page_size=4,
                                 pages_per_seq=2, max_seqs=2,
                                 prefill_buckets=(8, 16))
            assert sess.window == 8
            prompt = rng.integers(0, VOCAB, plen).astype(np.int32)
            got = _session_greedy(sess, prompt, 10)
            assert got == _windowed_greedy(params, prompt, 10, 8)

    def test_batched_decode_matches_sequential(self, tiny_lm):
        """Two sequences decoded in ONE shared step each produce the
        same tokens as the unbatched oracle (pad rows and the second
        sequence cannot perturb the first)."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=4,
                             prefill_buckets=(8,))
        rng = np.random.default_rng(4)
        pa = rng.integers(0, VOCAB, 4).astype(np.int32)
        pb = rng.integers(0, VOCAB, 7).astype(np.int32)
        sa, la = sess.admit(pa)
        sb, lb = sess.admit(pb)
        oa, ob = [int(np.argmax(la))], [int(np.argmax(lb))]
        for _ in range(5):
            lg = sess.decode([sa, sb],
                             np.asarray([oa[-1], ob[-1]], np.int32))
            oa.append(int(np.argmax(lg[0])))
            ob.append(int(np.argmax(lg[1])))
        assert oa == _flax_greedy(model, params, pa, 6)
        assert ob == _flax_greedy(model, params, pb, 6)


class TestCompileCounter:
    def test_steady_state_zero_recompiles(self, tiny_lm):
        """After one admit/decode/evict cycle has touched a (prefill
        bucket, decode bucket) pair, further traffic through the same
        buckets — different prompts, lengths, page assignments, admit
        order — compiles NOTHING new."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=2,
                             prefill_buckets=(8,))
        rng = np.random.default_rng(5)

        def cycle():
            a, la = sess.admit(rng.integers(0, VOCAB, 3).astype(np.int32))
            ta = int(np.argmax(la))
            lg = sess.decode([a], np.asarray([ta], np.int32))
            b, lb = sess.admit(rng.integers(0, VOCAB, 6).astype(np.int32))
            tb = int(np.argmax(lb))
            for _ in range(6):  # crosses the window-8 boundary
                lg = sess.decode([a, b], np.asarray([ta, tb], np.int32))
                ta, tb = int(np.argmax(lg[0])), int(np.argmax(lg[1]))
            sess.release(a)
            lg = sess.decode([b], np.asarray([tb], np.int32))
            sess.release(b)

        cycle()  # warm: compiles prefill x1, decode buckets 1 and 2
        warm = dict(sess.compiles)
        assert _hot(warm) == {"prefill": 1, "decode": 2}
        for _ in range(3):
            cycle()
        assert sess.compiles == warm, (
            f"steady-state decode recompiled: {warm} -> {sess.compiles}")


# ---------------------------------------------------------------------------
# scheduler.py — continuous batching
# ---------------------------------------------------------------------------


class TestContinuousBatcher:
    def test_mid_stream_admit_shares_step_and_stays_correct(self, tiny_lm):
        """Stream B submitted while A is mid-generation: at least one
        decode step batches BOTH (iteration-level sharing), and both
        streams stay token-identical to the uncached oracle."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=4,
                             prefill_buckets=(8,))
        batcher = ContinuousBatcher(sess, DecodePolicy(max_pending=8),
                                    replica=0).start()
        try:
            rng = np.random.default_rng(6)
            pa = rng.integers(0, VOCAB, 4).astype(np.int32)
            pb = rng.integers(0, VOCAB, 6).astype(np.int32)
            results = {}

            def run(name, prompt, n):
                results[name] = batcher.generate(prompt, n)

            ta = threading.Thread(target=run, args=("a", pa, 24))
            tb = threading.Thread(target=run, args=("b", pb, 12))
            ta.start()
            tb.start()  # lands while A is in flight
            ta.join(60)
            tb.join(60)
            assert results["a"] == _flax_greedy(model, params, pa, 24)
            assert results["b"] == _flax_greedy(model, params, pb, 12)
            st = batcher.stats()
            assert st["shared_steps"] >= 1, st
            assert st["evicted"] == 2 and st["active"] == 0
            # every page is either free or retained by the prefix
            # cache for the NEXT stream — none leaked to dead seqs
            assert sess.pool.free_pages \
                + sess.prefix_cache.cached_pages == sess.cfg.n_pages
        finally:
            batcher.stop()

    def test_admission_overload_is_typed_and_o1(self, tiny_lm):
        """A full pending queue rejects with the SAME typed Overloaded
        the eval path uses — immediately, without waiting on the
        scheduler."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=2,
                             prefill_buckets=(8,))
        # NOT started: pending can only grow, so the bound is exact
        batcher = ContinuousBatcher(sess, DecodePolicy(max_pending=1),
                                    replica=0)
        errs = []

        def bg():
            try:
                batcher.generate(np.asarray([1, 2, 3], np.int32), 4)
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=bg)
        t.start()
        deadline = 50
        while batcher.stats()["pending"] < 1 and deadline:
            deadline -= 1
            threading.Event().wait(0.02)
        with pytest.raises(Overloaded):
            batcher.generate(np.asarray([1, 2, 3], np.int32), 4)
        batcher.stop()  # fails the queued request with Overloaded
        t.join(10)
        assert len(errs) == 1 and isinstance(errs[0], Overloaded)

    def test_decode_step_fault_restarts_from_export(self, tiny_lm,
                                                    tmp_path):
        """An injected decode_step fault fails THAT step's streams,
        then the replica restarts from a fresh export load of THE
        VERSION IT SERVES on a zeroed page pool (same budgeted
        supervision as eval replicas) and serves the next stream
        correctly.  A newer INCOMPATIBLE publish sitting in the dir
        must not ride in through the restart — that would be a side
        door past the reload watcher's IncompatibleExport refusal."""
        from theanompi_tpu.decode import DecodeReplica
        from theanompi_tpu.resilience import faults

        model, params, _ = tiny_lm
        export_dir = str(tmp_path / "export")
        export_model(model, export_dir, version=0)
        # newer, incompatible (weight dtype) publish: newest-verified,
        # but NOT what this replica serves
        export_model(model, export_dir, version=1, weight_dtype="int8")
        loaded = load_export(export_dir, version=0)
        rep = DecodeReplica(0, export_dir, model, loaded,
                            DecodePolicy(max_pending=4),
                            max_restarts=1, page_size=4,
                            pages_per_seq=8, max_seqs=4,
                            prefill_buckets=(8,))
        rep.batcher.start()
        faults.install([{"site": "decode_step", "replica": 0,
                         "step": 2}])
        try:
            rng = np.random.default_rng(9)
            prompt = rng.integers(0, VOCAB, 5).astype(np.int32)
            with pytest.raises(faults.FaultInjected):
                rep.generate(prompt, 8)
            assert rep.restarts == 1 and rep.alive
            # restarted on the SERVED version, not the newer publish
            assert rep.session.version == 0
            # the restarted replica serves, token-identically
            out = rep.generate(prompt, 6)
            assert out == _flax_greedy(model, params, prompt, 6)
            assert rep.session.pool.free_pages \
                + rep.session.prefix_cache.cached_pages == \
                rep.session.cfg.n_pages
        finally:
            faults.clear()
            rep.batcher.stop()

    def test_request_validation(self, tiny_lm):
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=2,
                             prefill_buckets=(8,))
        # max_new_cap above max_len so the positional-table check is
        # reachable (the cap otherwise clamps the request first)
        batcher = ContinuousBatcher(
            sess, DecodePolicy(max_new_cap=sess.max_len + 8,
                               submit_timeout_s=5.0), replica=0)
        with pytest.raises(ValueError):
            batcher.generate(np.zeros((0,), np.int32), 4)
        with pytest.raises(ValueError):  # prompt > largest bucket
            batcher.generate(np.zeros((9,), np.int32), 4)
        with pytest.raises(ValueError):  # past the positional table
            batcher.generate(np.asarray([1], np.int32),
                             sess.max_len + 1)
        batcher.stop()


# ---------------------------------------------------------------------------
# Quantized exports
# ---------------------------------------------------------------------------


class TestQuantizedExports:
    def test_bf16_round_trip_error_bound(self, tiny_lm):
        _, params, _ = tiny_lm
        deq = dequantize_tree(quantize_tree(params, "bf16"),
                              upcast_bf16=True)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(deq)):
            a, b = np.asarray(a), np.asarray(b)
            assert b.dtype == np.float32
            # bf16 keeps 8 significant bits: elementwise relative
            # error bounded by 2^-8 (plus an absolute floor near 0)
            assert np.all(np.abs(a - b)
                          <= np.abs(a) * 2.0 ** -8 + 1e-12)

    def test_int8_round_trip_error_bound(self, tiny_lm):
        _, params, _ = tiny_lm
        q = quantize_tree(params, "int8")
        deq = dequantize_tree(q, upcast_bf16=True)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(deq)):
            a, b = np.asarray(a), np.asarray(b)
            if a.ndim < 2:
                np.testing.assert_array_equal(a, b)  # kept f32
                continue
            # symmetric per-output-channel scale: |err| <= scale/2
            amax = np.max(np.abs(a), axis=tuple(range(a.ndim - 1)),
                          keepdims=True)
            bound = np.where(amax > 0, amax, 1.0) / 127.0 / 2.0
            assert np.all(np.abs(a - b) <= bound + 1e-7)

    def test_quantized_export_load_and_meta(self, tiny_lm, tmp_path):
        model, params, _ = tiny_lm
        for wd in ("bf16", "int8"):
            d = str(tmp_path / f"export_{wd}")
            export_model(model, d, version=0, weight_dtype=wd)
            loaded = load_export(d)  # dequantize-on-load default
            assert loaded.meta["weight_dtype"] == wd
            assert loaded.meta["decode"] is True
            assert loaded.meta["net"]["vocab"] == VOCAB
            for leaf in jax.tree.leaves(loaded.params):
                assert np.asarray(leaf).dtype == np.float32
            raw = load_export(d, dequantize=False)
            kinds = {np.asarray(leaf).dtype.name
                     for leaf in jax.tree.leaves(raw.params)}
            assert ("int8" in kinds) if wd == "int8" \
                else ("bfloat16" in kinds)

    def test_on_the_fly_matches_dequantize_on_load(self, tiny_lm,
                                                   tmp_path):
        """int8 weights kept quantized on device (dequantize_tree runs
        inside the jitted step) decode the same tokens as the
        collapsed-at-load tree — the two dequant paths are one
        arithmetic."""
        model, params, _ = tiny_lm
        d = str(tmp_path / "export_fly")
        export_model(model, d, version=0, weight_dtype="int8")
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, VOCAB, 5).astype(np.int32)
        outs = []
        for dequantize in (True, False):
            loaded = load_export(d, dequantize=dequantize)
            sess = DecodeSession(model, params=loaded.params,
                                 page_size=4, pages_per_seq=8,
                                 max_seqs=2, prefill_buckets=(8,))
            outs.append(_session_greedy(sess, prompt, 8))
        assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# Hot-reload refusal + the GENERATE wire op
# ---------------------------------------------------------------------------


class TestDecodeServing:
    @pytest.fixture()
    def decode_server(self, tiny_lm):
        model, params, export_dir = tiny_lm
        key_before = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
        server = InferenceServer(
            export_dir, replicas=1, reload_poll_s=0, model=model,
            decode=True,
            decode_opts=dict(page_size=4, pages_per_seq=8, max_seqs=4,
                             prefill_buckets=(8,))).start()
        port = _free_port()
        ready, stop = threading.Event(), threading.Event()
        t = threading.Thread(
            target=serve, args=(server, "127.0.0.1", port, ready, stop),
            daemon=True)
        t.start()
        assert ready.wait(30)
        addr = f"127.0.0.1:{port}"
        clients: list[InferenceClient] = []

        def make_client() -> InferenceClient:
            c = InferenceClient(addr)
            clients.append(c)
            return c

        yield make_client, server
        try:
            InferenceClient(addr).shutdown()
        except Exception:
            stop.set()
        for c in clients:
            c.close()
        t.join(timeout=5)
        server.stop()
        if key_before is None:
            os.environ.pop("THEANOMPI_TPU_SERVICE_KEY", None)
        else:
            os.environ["THEANOMPI_TPU_SERVICE_KEY"] = key_before

    def test_generate_over_wire_two_streams(self, tiny_lm,
                                            decode_server):
        model, params, _ = tiny_lm
        make_client, server = decode_server
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, VOCAB, 5).astype(np.int32),
                   rng.integers(0, VOCAB, 7).astype(np.int32)]
        outs = [None, None]
        cs = [make_client(), make_client()]

        def run(i):
            outs[i] = cs[i].generate(prompts[i], 10)

        ths = [threading.Thread(target=run, args=(i,))
               for i in range(2)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(60)
        for p, o in zip(prompts, outs):
            assert o is not None and o.dtype == np.int32
            assert list(o) == _flax_greedy(model, params, p, 10)
        st = cs[0].stats()
        assert st["decode"] is True
        assert st["shared_steps"] >= 1, st
        assert st["tokens"] >= 20

    def test_infer_op_rejected_in_decode_mode(self, decode_server):
        make_client, _ = decode_server
        with pytest.raises(ServiceError, match="generate"):
            make_client().infer(np.zeros((1, 16), np.int32))

    def test_reload_refuses_incompatible_then_accepts(
            self, tiny_lm, decode_server):
        """Publish v1 with a DIFFERENT weight dtype: the watcher must
        refuse with the typed IncompatibleExport, keep serving v0, and
        skip the bad version until v2 (compatible) supersedes it."""
        model, params, export_dir = tiny_lm
        make_client, server = decode_server
        c = make_client()
        export_model(model, export_dir, version=1, weight_dtype="int8")
        with pytest.raises(IncompatibleExport, match="weight_dtype"):
            c.reload()
        assert server.version == 0
        # the refusal is remembered (no re-LOAD) but EVERY reload of
        # the refused version re-raises the typed error from memory —
        # a client polling after the background watcher saw the
        # publish first still observes the refusal, not a silent
        # old-version return
        with pytest.raises(IncompatibleExport, match="weight_dtype"):
            c.reload()
        # the server still serves
        out = c.generate(np.asarray([1, 2, 3], np.int32), 4)
        assert len(out) == 4
        # a compatible v2 goes through and supersedes the skip
        export_model(model, export_dir, version=2)
        assert c.reload() == 2
        assert server.version == 2

    def test_export_incompatibility_covers_net_dims(self):
        """A resized transformer (same class, same sample_shape, same
        dtype) must be refused: its arrays cannot adopt into sessions
        built around the live module's dims."""
        from theanompi_tpu.serving import export_incompatibility

        live = {"modelfile": "m", "modelclass": "C",
                "sample_shape": [16], "weight_dtype": "f32",
                "decode": True,
                "net": {"vocab": 32, "d_model": 16, "n_layers": 2}}
        assert export_incompatibility(live, dict(live)) is None
        resized = dict(live,
                       net={"vocab": 32, "d_model": 32, "n_layers": 2})
        assert "net dims" in export_incompatibility(live, resized)

    def test_decode_mode_requires_capable_export(self, tmp_path):
        from tests._tiny_models import TinyCifar

        model = TinyCifar(config=ModelConfig(batch_size=8, n_epochs=1,
                                             print_freq=0),
                          verbose=False)
        d = str(tmp_path / "cnn_export")
        export_model(model, d, version=0)
        with pytest.raises(ValueError, match="decode-capable"):
            InferenceServer(d, replicas=1, reload_poll_s=0,
                            model=model, decode=True)


# ---------------------------------------------------------------------------
# Refcounted pool + cross-request prefix cache (ISSUE 12)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_draft():
    """A genuinely smaller net over the SAME vocab — random weights,
    so its proposals force real accept/reject boundaries."""
    cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                      compute_dtype="float32", optimizer="adamw",
                      learning_rate=1e-3, weight_decay=0.0,
                      lr_schedule="constant")
    model = TransformerLM(config=cfg, vocab=VOCAB, seq_len=16,
                          n_layers=1, d_model=8, n_heads=1,
                          verbose=False)
    return model, jax.device_get(model.state.params)


class TestRefcountedPagePool:
    def test_incref_decref_and_free_list(self):
        cfg = CacheConfig(n_layers=1, n_heads=1, d_head=4, page_size=2,
                          pages_per_seq=2, max_seqs=2)
        pool = PagePool(cfg)
        row = pool.alloc_seq()
        pool.incref(row)                      # a second owner
        assert all(pool.refcount(int(p)) == 2 for p in row)
        pool.free_seq(row)                    # first owner gone
        assert pool.free_pages == 2           # still held
        assert pool.decref(row) == 2          # last ref frees
        assert pool.free_pages == 4
        with pytest.raises(ValueError):
            pool.decref(row)                  # double free
        with pytest.raises(ValueError):
            pool.incref([int(row[0])])        # incref of a free page
        with pytest.raises(ValueError):
            pool.incref([cfg.n_pages + 1])    # foreign id

    def test_prefix_cache_longest_match_and_lru(self, tiny_lm):
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=2,
                             prefill_buckets=(8,))
        pc = sess.prefix_cache
        rng = np.random.default_rng(11)
        p = rng.integers(0, VOCAB, 8).astype(np.int32)
        seq, _ = sess.admit(p)                # registers 4-token entry
        assert len(pc) == 1 and pc.misses == 1
        # longest-match: same first page hits; a different page misses
        hit = pc.lookup(np.concatenate([p[:4], p[:1]]))
        assert hit is not None and hit.n_tokens == 4
        assert pc.lookup(rng.integers(0, VOCAB, 8).astype(np.int32)) \
            is None
        # prompts longer than the window are never matched or cached
        assert pc.lookup(np.tile(p, 2)) is None
        sess.release(seq)
        # eviction returns the cache's refs; pool drains to fully free
        assert pc.evict_lru() >= 1
        assert sess.pool.free_pages == sess.cfg.n_pages


class TestPrefixSharing:
    def test_hit_aliases_pages_and_stays_token_identical(self, tiny_lm):
        """Stream B starting with A's page-aligned prefix prefills
        only its suffix against A's shared pages — and still decodes
        token-identically to the uncached oracle."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=4,
                             prefill_buckets=(8, 16))
        rng = np.random.default_rng(12)
        base = rng.integers(0, VOCAB, 4).astype(np.int32)
        pa = np.concatenate([base,
                             rng.integers(0, VOCAB, 2).astype(np.int32)])
        pb = np.concatenate([base,
                             rng.integers(0, VOCAB, 3).astype(np.int32)])
        sa, la = sess.admit(pa)
        sb, lb = sess.admit(pb)
        assert sess.prefix_cache.hits == 1
        assert int(sa.page_row[0]) == int(sb.page_row[0])  # aliased
        oa, ob = [int(np.argmax(la))], [int(np.argmax(lb))]
        for _ in range(7):
            lg = sess.decode([sa, sb],
                             np.asarray([oa[-1], ob[-1]], np.int32))
            oa.append(int(np.argmax(lg[0])))
            ob.append(int(np.argmax(lg[1])))
        assert oa == _flax_greedy(model, params, pa, 8)
        assert ob == _flax_greedy(model, params, pb, 8)

    def test_cow_divergence_across_ring_wrap(self, tiny_lm):
        """window=8: decoding past the window writes into the shared
        prefix page -> host copy-on-write gives each stream a private
        copy; both stay identical to the sliding-window oracle and
        their tables diverge."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=4,
                             prefill_buckets=(8,))
        rng = np.random.default_rng(13)
        base = rng.integers(0, VOCAB, 5).astype(np.int32)
        pa = base
        pb = np.concatenate([base[:4],
                             rng.integers(0, VOCAB, 2).astype(np.int32)])
        sa, la = sess.admit(pa)
        sb, lb = sess.admit(pb)
        assert int(sa.page_row[0]) == int(sb.page_row[0])
        oa, ob = [int(np.argmax(la))], [int(np.argmax(lb))]
        for _ in range(11):   # crosses the window-8 boundary
            lg = sess.decode([sa, sb],
                             np.asarray([oa[-1], ob[-1]], np.int32))
            oa.append(int(np.argmax(lg[0])))
            ob.append(int(np.argmax(lg[1])))
        assert oa == _windowed_greedy(params, pa, 12, 8)
        assert ob == _windowed_greedy(params, pb, 12, 8)
        assert sess.cow_copies >= 2
        assert int(sa.page_row[0]) != int(sb.page_row[0])  # diverged

    def test_shared_page_outlives_first_owner(self, tiny_lm):
        """Refcounted eviction: the prefilling stream releases, a
        later stream still hits its cached prefix and decodes
        correctly; pages only truly free once cache AND users let go."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=2,
                             prefill_buckets=(8, 16))
        rng = np.random.default_rng(14)
        base = rng.integers(0, VOCAB, 4).astype(np.int32)
        pa = np.concatenate([base,
                             rng.integers(0, VOCAB, 1).astype(np.int32)])
        sa, _ = sess.admit(pa)
        sess.release(sa)      # owner gone; the cache keeps the page
        assert sess.pool.free_pages < sess.cfg.n_pages
        pb = np.concatenate([base,
                             rng.integers(0, VOCAB, 2).astype(np.int32)])
        sb, lb = sess.admit(pb)             # hits the orphaned prefix
        assert sess.prefix_cache.hits == 1
        out = [int(np.argmax(lb))]
        for _ in range(5):
            lg = sess.decode([sb], np.asarray([out[-1]], np.int32))
            out.append(int(np.argmax(lg[0])))
        assert out == _flax_greedy(model, params, pb, 6)
        sess.release(sb)
        sess.prefix_cache.evict_all()
        assert sess.pool.free_pages == sess.cfg.n_pages

    def test_allocation_pressure_evicts_lru_entries(self, tiny_lm):
        """Each released stream leaves one cached prefix page behind;
        once orphaned pages fill the pool, the next admission evicts
        LRU entries (the free-list discipline extended to shared
        pages) instead of rejecting."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=4,
                             prefill_buckets=(8,))
        rng = np.random.default_rng(15)
        for _ in range(12):   # > n_pages=8 one-page entries
            p = rng.integers(0, VOCAB, 6).astype(np.int32)
            s, _ = sess.admit(p)
            sess.release(s)
            assert sess.can_admit()
            # nothing leaks: every page is free or cache-held
            assert sess.pool.free_pages \
                + sess.prefix_cache.cached_pages == sess.cfg.n_pages
        assert sess.prefix_cache.evictions >= 1

    def test_zero_recompiles_with_sharing(self, tiny_lm):
        """Hit/miss/COW cycles through warmed buckets compile nothing
        new: extend + cow_copy are program families like any other."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=4,
                             prefill_buckets=(8,))
        rng = np.random.default_rng(16)

        def cycle():
            base = rng.integers(0, VOCAB, 5).astype(np.int32)
            pb = np.concatenate(
                [base[:4], rng.integers(0, VOCAB, 2).astype(np.int32)])
            sa, la = sess.admit(base)
            sb, lb = sess.admit(pb)
            ta, tb = int(np.argmax(la)), int(np.argmax(lb))
            for _ in range(10):  # wraps window 8 -> COW
                lg = sess.decode([sa, sb],
                                 np.asarray([ta, tb], np.int32))
                ta, tb = (int(np.argmax(lg[0])),
                          int(np.argmax(lg[1])))
            sess.release(sa)
            sess.release(sb)

        cycle()
        warm = dict(sess.compiles)
        assert warm["extend"] == 1 and warm["cow_copy"] == 1
        for _ in range(2):
            cycle()
        assert sess.compiles == warm, (
            f"sharing recompiled: {warm} -> {sess.compiles}")


# ---------------------------------------------------------------------------
# Speculative decoding (ISSUE 12)
# ---------------------------------------------------------------------------


class TestSpeculative:
    def test_full_accept_token_identity(self, tiny_lm):
        """Draft == target (self-speculation): every draft accepted,
        output still byte-identical to the uncached oracle, and the
        bonus token makes rounds emit k+1 tokens."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=2,
                             prefill_buckets=(8,))
        draft = DecodeSession(model, params=params, page_size=4,
                              pages_per_seq=8, max_seqs=2,
                              prefill_buckets=(8,))
        rng = np.random.default_rng(17)
        prompt = rng.integers(0, VOCAB, 5).astype(np.int32)
        out = _spec_greedy(sess, draft, prompt, 12, k=3)
        assert out == _flax_greedy(model, params, prompt, 12)

    def test_accept_reject_boundaries_token_identity(self, tiny_lm,
                                                     tiny_draft):
        """A random SMALL draft proposes mostly-wrong tokens: rounds
        reject at every possible boundary and the output is STILL
        byte-identical to the oracle — rejected drafts were never
        written (count-masked scatter), so no rollback can corrupt."""
        model, params, _ = tiny_lm
        dmodel, dparams = tiny_draft
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=2,
                             prefill_buckets=(8,))
        draft = DecodeSession(dmodel, params=dparams, page_size=4,
                              pages_per_seq=8, max_seqs=2,
                              prefill_buckets=(8,))
        rng = np.random.default_rng(18)
        for plen in (3, 7):
            prompt = rng.integers(0, VOCAB, plen).astype(np.int32)
            out = _spec_greedy(sess, draft, prompt, 10, k=3)
            assert out == _flax_greedy(model, params, prompt, 10)

    def test_identity_across_eviction_boundary(self, tiny_lm):
        """Speculative rounds crossing the ring-wrap boundary match
        the sliding-window oracle (count-masked writes + the chunk
        mask agree with the ring's eviction semantics)."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=2,
                             prefill_buckets=(8,))
        draft = DecodeSession(model, params=params, page_size=4,
                              pages_per_seq=2, max_seqs=2,
                              prefill_buckets=(8,))
        rng = np.random.default_rng(19)
        prompt = rng.integers(0, VOCAB, 5).astype(np.int32)
        out = _spec_greedy(sess, draft, prompt, 14, k=3)
        assert out == _windowed_greedy(params, prompt, 14, 8)

    def test_zero_recompiles_across_accept_reject(self, tiny_lm,
                                                  tiny_draft):
        """Accept counts are DATA: rounds with full accepts, partial
        accepts and total rejects all run the same three programs."""
        model, params, _ = tiny_lm
        dmodel, dparams = tiny_draft
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=2,
                             prefill_buckets=(8,))
        draft = DecodeSession(dmodel, params=dparams, page_size=4,
                              pages_per_seq=8, max_seqs=2,
                              prefill_buckets=(8,))
        rng = np.random.default_rng(20)
        prompt = rng.integers(0, VOCAB, 5).astype(np.int32)
        _spec_greedy(sess, draft, prompt, 8, k=3)
        warm_t, warm_d = dict(sess.compiles), dict(draft.compiles)
        assert warm_t["verify"] == 1
        assert warm_d["propose"] == 1 and warm_d["commit"] == 1
        for seed in (21, 22):
            p = np.random.default_rng(seed).integers(
                0, VOCAB, 6).astype(np.int32)
            _spec_greedy(sess, draft, p, 8, k=3)
        assert sess.compiles == warm_t
        assert draft.compiles == warm_d

    def test_batcher_speculates_with_shared_prefix(self, tiny_lm):
        """End to end through the ContinuousBatcher: two concurrent
        streams sharing a prefix, speculation on — both match the
        oracle, at least one step batches both, accept rate lands in
        stats with the shared token-accounting shape."""
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=8, max_seqs=4,
                             prefill_buckets=(8,))
        draft = DecodeSession(model, params=params, page_size=4,
                              pages_per_seq=8, max_seqs=4,
                              prefill_buckets=(8,))
        batcher = ContinuousBatcher(
            sess, DecodePolicy(max_pending=8, speculate_k=3),
            replica=0, draft_session=draft).start()
        try:
            rng = np.random.default_rng(23)
            base = rng.integers(0, VOCAB, 4).astype(np.int32)
            pa = np.concatenate(
                [base, rng.integers(0, VOCAB, 1).astype(np.int32)])
            pb = np.concatenate(
                [base, rng.integers(0, VOCAB, 2).astype(np.int32)])
            results = {}

            def run(name, prompt, n):
                results[name] = batcher.generate(prompt, n)

            ta = threading.Thread(target=run, args=("a", pa, 17))
            tb = threading.Thread(target=run, args=("b", pb, 9))
            ta.start()
            tb.start()
            ta.join(60)
            tb.join(60)
            assert results["a"] == _flax_greedy(model, params, pa, 17)
            assert results["b"] == _flax_greedy(model, params, pb, 9)
            st = batcher.stats()
            assert st["shared_steps"] >= 1
            spec = st["speculation"]
            assert spec["draft_tokens"] > 0
            assert spec["accept_rate"] is not None \
                and spec["accept_rate"] > 0
            assert st["prefix_cache"]["hits"] >= 1
            # emitted tokens, NOT drafted, are the throughput axis:
            # exactly max_new per stream despite multi-token rounds
            # (the emission trim), far fewer steps than tokens
            assert st["tokens"] == 17 + 9
            assert st["steps"] < st["tokens"]
            assert st["evicted"] == 2 and st["active"] == 0
            assert sess.pool.free_pages \
                + sess.prefix_cache.cached_pages == sess.cfg.n_pages
        finally:
            batcher.stop()

    def test_speculate_k_validation(self, tiny_lm):
        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, page_size=4,
                             pages_per_seq=2, max_seqs=2,
                             prefill_buckets=(8,))
        draft = DecodeSession(model, params=params, page_size=4,
                              pages_per_seq=2, max_seqs=2,
                              prefill_buckets=(8,))
        with pytest.raises(ValueError, match="speculate_k"):
            ContinuousBatcher(sess, DecodePolicy(speculate_k=8),
                              replica=0, draft_session=draft)

    def test_speculative_accounting_shape(self):
        from theanompi_tpu.utils.token_accounting import (
            speculative_accounting,
        )

        none_yet = speculative_accounting(0, 0, 0)
        assert none_yet["accept_rate"] is None
        rec = speculative_accounting(26, 18, 12)
        assert rec == {"emitted_tokens": 26, "draft_tokens": 18,
                       "accepted_draft_tokens": 12,
                       "accept_rate": 12 / 18}


class TestDraftServing:
    def test_draft_incompatibility_matrix(self):
        from theanompi_tpu.serving import draft_incompatibility

        target = {"decode": True,
                  "net": {"vocab": 32, "seq_len": 16, "d_model": 16,
                          "n_layers": 2, "n_heads": 2}}
        ok = {"decode": True,
              "net": {"vocab": 32, "seq_len": 16, "d_model": 8,
                      "n_layers": 1, "n_heads": 1}}
        assert draft_incompatibility(target, ok) is None
        assert "decode-capable" in draft_incompatibility(
            target, dict(ok, decode=False))
        assert "vocab" in draft_incompatibility(
            target, dict(ok, net=dict(ok["net"], vocab=16)))
        big = dict(target, net=dict(target["net"], seq_len=4096))
        assert "positional" in draft_incompatibility(big, ok)

    def test_draft_reload_refusal_matrix_over_wire(self, tiny_lm,
                                                   tmp_path):
        """The PR-10 refusal matrix extended to the draft poll: a
        published draft with the wrong vocab (target anchor) or
        resized net (draft-session anchor) raises the typed
        IncompatibleExport, is REMEMBERED (no reload churn), the
        server keeps serving AND speculating; a compatible newer
        draft supersedes the skip."""
        model, params, _ = tiny_lm
        export_dir = str(tmp_path / "export")
        draft_dir = str(tmp_path / "draft")
        export_model(model, export_dir, version=0)
        export_model(model, draft_dir, version=0, weight_dtype="bf16")
        server = InferenceServer(
            export_dir, replicas=1, reload_poll_s=0, model=model,
            decode=True,
            decode_opts=dict(page_size=4, pages_per_seq=8, max_seqs=4,
                             prefill_buckets=(8,),
                             draft_export_dir=draft_dir,
                             speculate_k=3)).start()
        try:
            rng = np.random.default_rng(24)
            prompt = rng.integers(0, VOCAB, 5).astype(np.int32)
            oracle = _flax_greedy(model, params, prompt, 6)
            assert server.generate(prompt, 6).tolist() == oracle
            cfg = model.config
            wrong_vocab = TransformerLM(
                config=cfg, vocab=16, seq_len=16, n_layers=1,
                d_model=8, n_heads=1, verbose=False)
            export_model(wrong_vocab, draft_dir, version=1)
            with pytest.raises(IncompatibleExport, match="vocab"):
                server.check_draft_reload()
            resized = TransformerLM(
                config=cfg, vocab=VOCAB, seq_len=16, n_layers=1,
                d_model=8, n_heads=1, verbose=False)
            export_model(resized, draft_dir, version=2)
            with pytest.raises(IncompatibleExport, match="net dims"):
                server.check_draft_reload()
            # remembered: re-raises from memory, still serving v0
            with pytest.raises(IncompatibleExport):
                server.check_draft_reload()
            assert server.draft_version == 0
            assert server.generate(prompt, 4).tolist() == oracle[:4]
            # a compatible newer draft goes through
            export_model(model, draft_dir, version=3,
                         weight_dtype="bf16")
            assert server.check_draft_reload() == 3
            assert server.generate(prompt, 6).tolist() == oracle
            st = server.stats()
            assert st["draft_version"] == 3
            assert st["accept_rate"] is not None
        finally:
            server.stop()

    def test_incompatible_draft_refused_at_construction(self, tiny_lm,
                                                        tmp_path):
        model, params, _ = tiny_lm
        export_dir = str(tmp_path / "export")
        draft_dir = str(tmp_path / "draft")
        export_model(model, export_dir, version=0)
        wrong = TransformerLM(config=model.config, vocab=16,
                              seq_len=16, n_layers=1, d_model=8,
                              n_heads=1, verbose=False)
        export_model(wrong, draft_dir, version=0)
        with pytest.raises(IncompatibleExport, match="vocab"):
            InferenceServer(
                export_dir, replicas=1, reload_poll_s=0, model=model,
                decode=True,
                decode_opts=dict(page_size=4, pages_per_seq=8,
                                 max_seqs=4, prefill_buckets=(8,),
                                 draft_export_dir=draft_dir))


# ---------------------------------------------------------------------------
# Batched prefill (ISSUE 18)
# ---------------------------------------------------------------------------


class TestBatchedPrefill:
    def _session(self, tiny_lm, **over):
        model, params, _ = tiny_lm
        opts = dict(page_size=4, pages_per_seq=4, max_seqs=4,
                    prefill_buckets=(4, 8))
        opts.update(over)
        return model, params, DecodeSession(model, params=params,
                                            **opts)

    def test_batch_identity_across_buckets_zero_recompiles(
            self, tiny_lm):
        """Every (n_seqs, token) bucket pair: a batched admission's
        rows decode token-identically to the uncached oracle (= the
        serial admit path's own identity anchor), and after
        ``warmup_prefill_batch`` no batch shape compiles anything."""
        model, params, sess = self._session(tiny_lm,
                                            prefix_cache=False)
        sess.warmup()
        sess.warmup_prefill_batch()
        warm = dict(sess.compiles)
        rng = np.random.default_rng(30)
        # n straddles the n_seqs buckets (1, 2, 4); lengths straddle
        # the token buckets (4, 8) inside one batch
        for lens in ((3,), (4, 5), (3, 4, 8), (2, 4, 5, 8)):
            prompts = [rng.integers(0, VOCAB, t).astype(np.int32)
                       for t in lens]
            admitted = sess.admit_batch(prompts)
            seqs = [s for s, _ in admitted]
            outs = [[int(np.argmax(lg))] for _, lg in admitted]
            for _ in range(3):
                lg = sess.decode(seqs, np.asarray(
                    [o[-1] for o in outs], np.int32))
                for i, o in enumerate(outs):
                    o.append(int(np.argmax(lg[i])))
            for p, o in zip(prompts, outs):
                assert o == _flax_greedy(model, params, p, 4)
            for s in seqs:
                sess.release(s)
        # the decode calls above touch their own (unwarmed) n-seq
        # buckets; the batched-prefill pin is the prefill families
        for fam in ("prefill", "prefill_batch", "extend"):
            assert sess.compiles[fam] == warm[fam], (
                f"{fam} recompiled: {warm} -> {sess.compiles}")

    def test_mixed_cold_and_hit_rows_share_pages(self, tiny_lm):
        """One batch carries a prefix-cache HIT row (extend from a
        start offset) and a COLD row (start 0): the hit aliases the
        cached page, the cold row fills fresh pages, both rows decode
        token-identically."""
        model, params, sess = self._session(tiny_lm)
        rng = np.random.default_rng(31)
        base = rng.integers(0, VOCAB, 4).astype(np.int32)
        seed, _ = sess.admit(np.concatenate(
            [base, rng.integers(0, VOCAB, 1).astype(np.int32)]))
        ph = np.concatenate(
            [base, rng.integers(0, VOCAB, 2).astype(np.int32)])
        pcold = rng.integers(0, VOCAB, 6).astype(np.int32)
        hits0 = sess.prefix_cache.hits
        (sh, lh), (sc, lc) = sess.admit_batch([ph, pcold])
        assert sess.prefix_cache.hits == hits0 + 1
        assert int(sh.page_row[0]) == int(seed.page_row[0])  # aliased
        assert int(sc.page_row[0]) != int(seed.page_row[0])
        oh, oc = [int(np.argmax(lh))], [int(np.argmax(lc))]
        for _ in range(5):
            lg = sess.decode([sh, sc],
                             np.asarray([oh[-1], oc[-1]], np.int32))
            oh.append(int(np.argmax(lg[0])))
            oc.append(int(np.argmax(lg[1])))
        assert oh == _flax_greedy(model, params, ph, 6)
        assert oc == _flax_greedy(model, params, pcold, 6)

    def test_cow_when_two_batch_rows_share_a_page(self, tiny_lm):
        """Two rows of ONE batch alias the same cached prefix page;
        decoding past the ring window writes into it -> COW un-shares
        each row privately, both match the sliding-window oracle."""
        model, params, sess = self._session(tiny_lm, pages_per_seq=2,
                                            prefill_buckets=(8,))
        rng = np.random.default_rng(32)
        base = rng.integers(0, VOCAB, 5).astype(np.int32)
        seed, _ = sess.admit(base)        # registers base[:4]
        sess.release(seed)
        pa = np.concatenate(
            [base[:4], rng.integers(0, VOCAB, 1).astype(np.int32)])
        pb = np.concatenate(
            [base[:4], rng.integers(0, VOCAB, 2).astype(np.int32)])
        (sa, la), (sb, lb) = sess.admit_batch([pa, pb])
        shared = int(sa.page_row[0])
        assert shared == int(sb.page_row[0])
        assert sess.pool.refcount(shared) == 3   # cache + both rows
        oa, ob = [int(np.argmax(la))], [int(np.argmax(lb))]
        for _ in range(11):               # crosses the window-8 wrap
            lg = sess.decode([sa, sb],
                             np.asarray([oa[-1], ob[-1]], np.int32))
            oa.append(int(np.argmax(lg[0])))
            ob.append(int(np.argmax(lg[1])))
        assert oa == _windowed_greedy(params, pa, 12, 8)
        assert ob == _windowed_greedy(params, pb, 12, 8)
        assert sess.cow_copies >= 2
        assert int(sa.page_row[0]) != int(sb.page_row[0])  # diverged

    def test_allocation_pressure_evicts_mid_batch(self, tiny_lm):
        """A batch whose rows outnumber the free pages evicts LRU
        prefix entries row by row instead of failing — and the
        admitted rows still decode correctly."""
        model, params, sess = self._session(tiny_lm, pages_per_seq=2,
                                            prefill_buckets=(8,))
        rng = np.random.default_rng(33)
        for _ in range(4):                # 4 one-page orphan entries
            s, _ = sess.admit(rng.integers(0, VOCAB, 5)
                              .astype(np.int32))
            sess.release(s)
        assert len(sess.prefix_cache) == 4
        assert sess.pool.free_pages == 4  # of n_pages=8
        prompts = [rng.integers(0, VOCAB, 5).astype(np.int32)
                   for _ in range(3)]
        admitted = sess.admit_batch(prompts)    # needs 6 pages
        assert sess.prefix_cache.evictions >= 1
        seqs = [s for s, _ in admitted]
        outs = [[int(np.argmax(lg))] for _, lg in admitted]
        for _ in range(2):                # stays inside window 8
            lg = sess.decode(seqs, np.asarray(
                [o[-1] for o in outs], np.int32))
            for i, o in enumerate(outs):
                o.append(int(np.argmax(lg[i])))
        for p, o in zip(prompts, outs):
            assert o == _flax_greedy(model, params, p, 3)
        # nothing leaked: once the rows release and the cache drops
        # its refs, every page is free again
        for s in seqs:
            sess.release(s)
        sess.prefix_cache.evict_all()
        assert sess.pool.free_pages == sess.cfg.n_pages

    def test_failed_batch_leaks_no_pages(self, tiny_lm):
        """A batch refused mid-validation (one over-long prompt)
        unwinds every already-taken page reference."""
        model, params, sess = self._session(tiny_lm)
        rng = np.random.default_rng(35)
        free0 = sess.pool.free_pages
        good = rng.integers(0, VOCAB, 5).astype(np.int32)
        bad = rng.integers(0, VOCAB, 9).astype(np.int32)  # > bucket 8
        with pytest.raises(ValueError, match="prompt length"):
            sess.admit_batch([good, bad])
        assert sess.pool.free_pages == free0


class TestDrainMigration:
    def test_drained_stream_resumes_byte_identical(self, tiny_lm):
        """Scale-down drain: a mid-flight stream leaves the batcher as
        a MigratedStream (emitted tokens + resume manifest + pages); a
        survivor batcher adopts it and the stitched output is
        byte-identical to one uninterrupted stream.  The draining
        batcher refuses new work with the typed Overloaded."""
        from theanompi_tpu.decode.scheduler import MigratedStream

        model, params, _ = tiny_lm

        def mk():
            return DecodeSession(model, params=params, page_size=4,
                                 pages_per_seq=4, max_seqs=2,
                                 prefill_buckets=(8,))

        rng = np.random.default_rng(34)
        prompt = rng.integers(0, VOCAB, 3).astype(np.int32)
        ref = _flax_greedy(model, params, prompt, 13)

        # no scheduler thread: pump by hand so the drain lands at a
        # deterministic point (4 emitted, the stream mid-flight)
        b = ContinuousBatcher(mk(), DecodePolicy(max_pending=4,
                                                 prefill_delay_ms=0.0))
        res = {}
        t = threading.Thread(
            target=lambda: res.setdefault("out",
                                          b.generate(prompt, 13)))
        t.start()
        import time
        for _ in range(2000):
            if b._pending:
                break
            time.sleep(0.002)
        b._admit()
        for _ in range(3):
            b._step()
        b._draining = True
        b._migrate_out()
        t.join(30)
        out = res["out"]
        assert isinstance(out, MigratedStream)
        # the pending (un-resumed) token rides the manifest, not the
        # emitted list
        assert out.tokens == ref[:3]
        assert out.manifest["first_token"] == ref[3]
        with pytest.raises(Overloaded, match="draining"):
            b.generate(prompt, 2)
        st = b.stats()
        assert st["drain_migrated"] == 1 and st["draining"]

        survivor = ContinuousBatcher(
            mk(), DecodePolicy(max_pending=4)).start()
        try:
            rest = survivor.generate_adopted(
                out.manifest, out.k, out.v, 13 - len(out.tokens))
            assert out.tokens + [int(x) for x in rest] == ref
        finally:
            survivor.stop()
