import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.ops import lrn


def _lrn_ref(x, n, k, alpha, beta, scaled=True):
    """Straightforward numpy LRN for cross-checking."""
    N, H, W, C = x.shape
    out = np.zeros_like(x)
    a = alpha / n if scaled else alpha
    for c in range(C):
        lo = max(0, c - (n - 1) // 2)
        hi = min(C, c + (n - 1 - (n - 1) // 2) + 1)
        s = (x[..., lo:hi] ** 2).sum(axis=-1)
        out[..., c] = x[..., c] / (k + a * s) ** beta
    return out


def test_lrn_matches_reference_formula():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 4, 8).astype(np.float32)
    got = np.asarray(lrn(jnp.asarray(x), n=5, k=2.0, alpha=1e-4, beta=0.75))
    want = _lrn_ref(x, 5, 2.0, 1e-4, 0.75, scaled=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_lrn_unscaled_variant():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 2, 6).astype(np.float32)
    got = np.asarray(lrn(jnp.asarray(x), n=3, k=1.0, alpha=1e-3, beta=0.5,
                         alpha_scaled_by_n=False))
    want = _lrn_ref(x, 3, 1.0, 1e-3, 0.5, scaled=False)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_lrn_differentiable():
    x = jnp.ones((1, 2, 2, 4))
    g = jax.grad(lambda y: lrn(y).sum())(x)
    assert np.isfinite(np.asarray(g)).all()


class TestLRNPallas:
    """lrn_pallas runs in interpret mode off-TPU (conftest pins cpu),
    so numerics and the analytic VJP are testable on the CPU mesh."""

    def test_matches_xla_impl(self):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 3, 5, 96).astype(np.float32)
        got = np.asarray(lrn(jnp.asarray(x), impl="pallas"))
        want = np.asarray(lrn(jnp.asarray(x), impl="xla"))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_analytic_vjp_matches_autodiff(self):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(2, 2, 3, 16).astype(np.float32))
        ct = jnp.asarray(rng.randn(2, 2, 3, 16).astype(np.float32))
        g_pallas = jax.grad(lambda v: (lrn(v, impl="pallas") * ct).sum())(x)
        g_xla = jax.grad(lambda v: (lrn(v, impl="xla") * ct).sum())(x)
        np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_xla),
                                   rtol=1e-4, atol=1e-5)

    def test_non_tile_aligned_rows(self, monkeypatch):
        # force a genuinely ragged grid: TILE_M=8 with m=N*H*W=10 →
        # 2 blocks, last one masked; results must still be exact
        from theanompi_tpu.ops import lrn_pallas as lp
        monkeypatch.setattr(lp, "TILE_M", 8)
        rng = np.random.RandomState(4)
        x = rng.randn(1, 2, 5, 8).astype(np.float32)
        got = np.asarray(lrn(jnp.asarray(x), impl="pallas"))
        want = np.asarray(lrn(jnp.asarray(x), impl="xla"))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_even_window_gradient(self):
        # even n: the window is asymmetric, so the VJP must use the
        # adjoint window — compare against autodiff of the XLA form
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(1, 2, 3, 12).astype(np.float32))
        ct = jnp.asarray(rng.randn(1, 2, 3, 12).astype(np.float32))
        g_pallas = jax.grad(
            lambda v: (lrn(v, n=4, impl="pallas") * ct).sum())(x)
        g_xla = jax.grad(
            lambda v: (lrn(v, n=4, impl="xla") * ct).sum())(x)
        np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_xla),
                                   rtol=1e-4, atol=1e-5)

    def test_bad_impl_rejected(self):
        import pytest
        with pytest.raises(ValueError):
            lrn(jnp.ones((1, 1, 1, 4)), impl="cuda")


class TestFusedAttention:
    """ops/attention.py Pallas kernel (interpret mode on CPU) vs the
    parallel/sequence.py oracle."""

    def _rand(self, b=2, tq=16, tk=16, h=2, d=8, seed=0):
        import jax

        ks = jax.random.split(jax.random.key(seed), 3)
        q = jax.random.normal(ks[0], (b, tq, h, d))
        k = jax.random.normal(ks[1], (b, tk, h, d))
        v = jax.random.normal(ks[2], (b, tk, h, d))
        return q, k, v

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_oracle(self, causal):
        from theanompi_tpu.ops.attention import fused_attention
        from theanompi_tpu.parallel.sequence import attention_reference

        q, k, v = self._rand()
        got = fused_attention(q, k, v, causal=causal, impl="pallas")
        want = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_global_positions_match_oracle(self):
        import jax.numpy as jnp

        from theanompi_tpu.ops.attention import fused_attention
        from theanompi_tpu.parallel.sequence import _attention_positions

        q, k, v = self._rand(tq=8, tk=24)
        q_pos = 16 + jnp.arange(8)       # a later shard attends back
        k_pos = jnp.arange(24)
        got = fused_attention(q, k, v, q_pos=q_pos, k_pos=k_pos,
                              causal=True, impl="pallas")
        want = _attention_positions(q, k, v, q_pos, k_pos,
                                    q.shape[-1] ** -0.5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_match_oracle(self):
        import jax

        from theanompi_tpu.ops.attention import fused_attention
        from theanompi_tpu.parallel.sequence import attention_reference

        q, k, v = self._rand(tq=12, tk=12)

        def loss(fn, q, k, v):
            return (fn(q, k, v) ** 2).sum()

        g_got = jax.grad(lambda *a: loss(
            lambda q, k, v: fused_attention(q, k, v, causal=True,
                                            impl="pallas"), *a),
            argnums=(0, 1, 2))(q, k, v)
        g_want = jax.grad(lambda *a: loss(
            lambda q, k, v: attention_reference(q, k, v, causal=True),
            *a), argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=5e-5, atol=5e-5)

    def test_q_blocking_non_divisible(self, monkeypatch):
        import theanompi_tpu.ops.attention as A

        monkeypatch.setattr(A, "_Q_BLOCK", 8)
        q, k, v = self._rand(tq=20, tk=20)   # 20 = 2 full blocks + 4
        got = A.fused_attention(q, k, v, causal=True, impl="pallas")
        from theanompi_tpu.parallel.sequence import attention_reference

        want = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_auto_falls_back_off_tpu_and_on_oversize(self):
        import jax.numpy as jnp

        import theanompi_tpu.ops.attention as A

        q, k, v = self._rand(tq=4, tk=4)
        assert A._resolve_impl("auto", q, k) == "xla"  # cpu backend
        # oversize K/V: auto must refuse pallas even on TPU
        big = jnp.zeros((1, 200_000, 1, 64))
        assert A._resolve_impl("auto", big, big) == "xla"
        with pytest.raises(ValueError, match="unknown attention impl"):
            A._resolve_impl("flash", q, k)

    def test_auto_routes_ragged_tq_to_xla_on_tpu(self, monkeypatch):
        """Ragged q-tails in the Pallas FORWARD rely on out-of-range
        block padding only ever exercised in interpret mode (ADVICE
        r2) — on real silicon 'auto' must route them to XLA exactly
        like the backward already does; impl='pallas' still forces
        the kernel so interpret-mode tests keep their coverage."""
        import jax.numpy as jnp

        import theanompi_tpu.ops.attention as A

        monkeypatch.setattr(A.jax, "default_backend", lambda: "tpu")
        ragged = jnp.zeros((1, A._Q_BLOCK + 4, 2, 16))
        assert A._resolve_impl("auto", ragged, ragged) == "xla"
        exact = jnp.zeros((1, 2 * A._Q_BLOCK, 2, 16))
        assert A._resolve_impl("auto", exact, exact) == "pallas"
        small = jnp.zeros((1, 20, 2, 16))  # tq < _Q_BLOCK: one block
        assert A._resolve_impl("auto", small, small) == "pallas"

    def test_bf16_inputs(self):
        import jax.numpy as jnp

        from theanompi_tpu.ops.attention import fused_attention
        from theanompi_tpu.parallel.sequence import attention_reference

        q, k, v = self._rand()
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        got = fused_attention(qb, kb, vb, causal=True, impl="pallas")
        assert got.dtype == jnp.bfloat16
        want = attention_reference(qb, kb, vb, causal=True)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=2e-2, atol=2e-2)

    def test_fused_bwd_kernel_matches_xla_bwd(self):
        """The flash-style Pallas bwd (recompute-from-lse, fp32
        accumulation) == the composed-XLA VJP, incl. global positions."""
        import jax.numpy as jnp

        import theanompi_tpu.ops.attention as A

        q, k, v = self._rand(tq=16, tk=48)
        q_pos = 32 + jnp.arange(16)
        k_pos = jnp.arange(48)
        g = jax.random.normal(jax.random.key(9), q.shape)
        scale = q.shape[-1] ** -0.5
        _, lse = A._pallas_attention(q, k, v, q_pos, k_pos, scale,
                                     True, interpret=True)
        got = A._pallas_attention_bwd(q, k, v, q_pos, k_pos, lse, g,
                                      scale, True, interpret=True)
        want = A._xla_bwd(q, k, v, q_pos, k_pos, scale, True, g)
        for a, b in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-5, atol=5e-5)

    def test_fused_bwd_ragged_falls_back(self, monkeypatch):
        """tq not divisible by the q-block -> the VJP routes to the
        XLA bwd and grads still match the oracle."""
        import theanompi_tpu.ops.attention as A
        from theanompi_tpu.parallel.sequence import attention_reference

        monkeypatch.setattr(A, "_Q_BLOCK", 8)
        q, k, v = self._rand(tq=20, tk=20)  # 20 % 8 != 0

        g_got = jax.grad(lambda q: (A.fused_attention(
            q, k, v, causal=True, impl="pallas") ** 2).sum())(q)
        g_want = jax.grad(lambda q: (attention_reference(
            q, k, v, causal=True) ** 2).sum())(q)
        np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want),
                                   rtol=5e-5, atol=5e-5)

    def test_fused_bwd_multiblock_accumulation(self, monkeypatch):
        """Several q-blocks per (b*h): dk/dv accumulate across the
        fori_loop correctly."""
        import theanompi_tpu.ops.attention as A
        from theanompi_tpu.parallel.sequence import attention_reference

        monkeypatch.setattr(A, "_Q_BLOCK", 8)
        q, k, v = self._rand(tq=24, tk=24)  # 3 blocks of 8

        def loss(fn, *a):
            return (fn(*a) ** 2).sum()

        g_got = jax.grad(lambda q, k, v: loss(
            lambda q, k, v: A.fused_attention(q, k, v, causal=True,
                                              impl="pallas"), q, k, v),
            argnums=(0, 1, 2))(q, k, v)
        g_want = jax.grad(lambda q, k, v: loss(
            lambda q, k, v: attention_reference(q, k, v, causal=True),
            q, k, v), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-5, atol=5e-5)

    def test_fused_bwd_fully_masked_rows(self):
        """A q row preceding every k position (fully masked): lse
        saturates in fp32, and the bwd's re-normalization must still
        reproduce the XLA VJP's uniform-row gradients."""
        import jax.numpy as jnp

        import theanompi_tpu.ops.attention as A

        q, k, v = self._rand(tq=8, tk=16)
        q_pos = jnp.arange(8)          # rows 0.. precede k_pos 8..
        k_pos = 8 + jnp.arange(16)     # -> ALL rows fully masked
        g = jax.random.normal(jax.random.key(3), q.shape)
        scale = q.shape[-1] ** -0.5
        _, lse = A._pallas_attention(q, k, v, q_pos, k_pos, scale,
                                     True, interpret=True)
        got = A._pallas_attention_bwd(q, k, v, q_pos, k_pos, lse, g,
                                      scale, True, interpret=True)
        want = A._xla_bwd(q, k, v, q_pos, k_pos, scale, True, g)
        for a, b in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-5, atol=5e-5)


def test_attention_env_knobs(monkeypatch):
    """THEANOMPI_TPU_ATTN_QBLOCK / _VMEM_MB let on-chip sweeps tune the
    kernel without code edits; bad values fail at import, not in a
    kernel launch."""
    import importlib

    import theanompi_tpu.ops.attention as A

    try:
        monkeypatch.setenv("THEANOMPI_TPU_ATTN_QBLOCK", "128")
        monkeypatch.setenv("THEANOMPI_TPU_ATTN_VMEM_MB", "8")
        importlib.reload(A)
        assert A._Q_BLOCK == 128
        assert A._VMEM_BUDGET_BYTES == 8 * 1024 * 1024
        monkeypatch.setenv("THEANOMPI_TPU_ATTN_QBLOCK", "100")  # not /8
        with pytest.raises(ValueError, match="multiple of 8"):
            importlib.reload(A)
        monkeypatch.setenv("THEANOMPI_TPU_ATTN_QBLOCK", "256")
        monkeypatch.setenv("THEANOMPI_TPU_ATTN_VMEM_MB", "0")
        with pytest.raises(ValueError, match="must be positive"):
            importlib.reload(A)
    finally:
        # monkeypatch restores env at teardown, but NOT the reloaded
        # module globals — restore them even if an assert above failed
        monkeypatch.delenv("THEANOMPI_TPU_ATTN_QBLOCK", raising=False)
        monkeypatch.delenv("THEANOMPI_TPU_ATTN_VMEM_MB", raising=False)
        importlib.reload(A)
    assert A._Q_BLOCK == 256
