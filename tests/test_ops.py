import jax
import jax.numpy as jnp
import numpy as np

from theanompi_tpu.ops import lrn


def _lrn_ref(x, n, k, alpha, beta, scaled=True):
    """Straightforward numpy LRN for cross-checking."""
    N, H, W, C = x.shape
    out = np.zeros_like(x)
    a = alpha / n if scaled else alpha
    for c in range(C):
        lo = max(0, c - (n - 1) // 2)
        hi = min(C, c + (n - 1 - (n - 1) // 2) + 1)
        s = (x[..., lo:hi] ** 2).sum(axis=-1)
        out[..., c] = x[..., c] / (k + a * s) ** beta
    return out


def test_lrn_matches_reference_formula():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 4, 8).astype(np.float32)
    got = np.asarray(lrn(jnp.asarray(x), n=5, k=2.0, alpha=1e-4, beta=0.75))
    want = _lrn_ref(x, 5, 2.0, 1e-4, 0.75, scaled=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_lrn_unscaled_variant():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 2, 6).astype(np.float32)
    got = np.asarray(lrn(jnp.asarray(x), n=3, k=1.0, alpha=1e-3, beta=0.5,
                         alpha_scaled_by_n=False))
    want = _lrn_ref(x, 3, 1.0, 1e-3, 0.5, scaled=False)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_lrn_differentiable():
    x = jnp.ones((1, 2, 2, 4))
    g = jax.grad(lambda y: lrn(y).sum())(x)
    assert np.isfinite(np.asarray(g)).all()


class TestLRNPallas:
    """lrn_pallas runs in interpret mode off-TPU (conftest pins cpu),
    so numerics and the analytic VJP are testable on the CPU mesh."""

    def test_matches_xla_impl(self):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 3, 5, 96).astype(np.float32)
        got = np.asarray(lrn(jnp.asarray(x), impl="pallas"))
        want = np.asarray(lrn(jnp.asarray(x), impl="xla"))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_analytic_vjp_matches_autodiff(self):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(2, 2, 3, 16).astype(np.float32))
        ct = jnp.asarray(rng.randn(2, 2, 3, 16).astype(np.float32))
        g_pallas = jax.grad(lambda v: (lrn(v, impl="pallas") * ct).sum())(x)
        g_xla = jax.grad(lambda v: (lrn(v, impl="xla") * ct).sum())(x)
        np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_xla),
                                   rtol=1e-4, atol=1e-5)

    def test_non_tile_aligned_rows(self, monkeypatch):
        # force a genuinely ragged grid: TILE_M=8 with m=N*H*W=10 →
        # 2 blocks, last one masked; results must still be exact
        from theanompi_tpu.ops import lrn_pallas as lp
        monkeypatch.setattr(lp, "TILE_M", 8)
        rng = np.random.RandomState(4)
        x = rng.randn(1, 2, 5, 8).astype(np.float32)
        got = np.asarray(lrn(jnp.asarray(x), impl="pallas"))
        want = np.asarray(lrn(jnp.asarray(x), impl="xla"))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_even_window_gradient(self):
        # even n: the window is asymmetric, so the VJP must use the
        # adjoint window — compare against autodiff of the XLA form
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(1, 2, 3, 12).astype(np.float32))
        ct = jnp.asarray(rng.randn(1, 2, 3, 12).astype(np.float32))
        g_pallas = jax.grad(
            lambda v: (lrn(v, n=4, impl="pallas") * ct).sum())(x)
        g_xla = jax.grad(
            lambda v: (lrn(v, n=4, impl="xla") * ct).sum())(x)
        np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_xla),
                                   rtol=1e-4, atol=1e-5)

    def test_bad_impl_rejected(self):
        import pytest
        with pytest.raises(ValueError):
            lrn(jnp.ones((1, 1, 1, 4)), impl="cuda")
