import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from theanompi_tpu.parallel import TrainState
from theanompi_tpu.utils import (
    divide_batches,
    get_learning_rate,
    load_params_npz,
    save_params_npz,
    scale_lr,
    set_learning_rate,
    tree_to_vector,
    vector_to_tree,
)


def test_divide_and_scale():
    assert divide_batches(1000, 128) == 7
    assert divide_batches(1000, 128, drop_remainder=False) == 8
    assert scale_lr(0.01, 8) == pytest.approx(0.08)
    assert scale_lr(0.01, 4, "sqrt") == pytest.approx(0.02)


def test_set_learning_rate_pure_and_structure_preserving():
    params = {"w": jnp.ones(3)}
    tx = optax.chain(
        optax.clip(1.0), optax.inject_hyperparams(optax.sgd)(learning_rate=0.1)
    )
    st = tx.init(params)
    st2 = set_learning_rate(st, 0.5)
    # structure preserved -> no retrace when fed back into a jitted step
    assert jax.tree.structure(st) == jax.tree.structure(st2)
    assert get_learning_rate(st2) == pytest.approx(0.5)
    # pure: the original state is untouched
    assert get_learning_rate(st) == pytest.approx(0.1)


def test_set_learning_rate_requires_injected():
    st = optax.sgd(0.1).init({"w": jnp.ones(2)})
    with pytest.raises(ValueError):
        set_learning_rate(st, 0.5)


def test_tree_vector_roundtrip_mixed_dtypes():
    tree = {
        "w": np.random.RandomState(0).randn(3, 2).astype(np.float32),
        "h": np.arange(4, dtype=np.dtype(jnp.bfloat16)),
        "n": np.array([2**60], dtype=np.int64),
    }
    vec, meta = tree_to_vector(tree)
    assert vec.dtype == np.uint8
    assert vec.nbytes == 3 * 2 * 4 + 4 * 2 + 8  # byte-exact, no upcast
    out = vector_to_tree(vec, meta)
    for k in tree:
        assert out[k].dtype == tree[k].dtype
        np.testing.assert_array_equal(out[k], tree[k])


def test_npz_roundtrip_with_struct_dataclass():
    # attribute-style pytree nodes (flax.struct dataclass) must round-trip
    tx = optax.inject_hyperparams(optax.sgd)(learning_rate=0.1, momentum=0.9)
    state = TrainState.create({"layer": {"w": jnp.ones((2, 2)), "b": jnp.zeros(2)}}, tx)
    path = "/tmp/test_params_roundtrip.npz"
    save_params_npz(path, state.params)
    restored = load_params_npz(path, jax.tree.map(jnp.zeros_like, state.params))
    np.testing.assert_array_equal(np.asarray(restored["layer"]["w"]), 1.0)
    # full state (nested dataclass + namedtuple opt state) also works
    save_params_npz(path, {"state": state})
    back = load_params_npz(path, {"state": jax.tree.map(jnp.zeros_like, state)})
    np.testing.assert_array_equal(np.asarray(back["state"].params["layer"]["b"]), 0.0)


def test_data_mesh_overrequest_raises(devices8):
    from theanompi_tpu.parallel import data_mesh

    with pytest.raises(ValueError):
        data_mesh(1024)


def test_recorder_load_restores_all_time(tmp_path):
    # a resumed run must report honest LIFETIME section totals: load()
    # reconstructs all_time from the saved per-epoch time dicts
    from theanompi_tpu.utils.recorder import Recorder

    r = Recorder(rank=0, size=1, print_freq=0)
    for epoch in range(2):
        r.start()
        r._t0 -= 1.5  # pretend 1.5s of calc
        r.end("calc")
        r.start()
        r._t0 -= 0.25
        r.end("wait")
        r.train_metrics(1.0, 0.5, 8)
        r.epoch_summary(epoch)
    r.save(str(tmp_path))
    expect_calc = r.all_time["calc"]

    fresh = Recorder(rank=0, size=1, print_freq=0)
    fresh.load(str(tmp_path))
    assert fresh.epoch == 2
    assert fresh.all_time["calc"] == pytest.approx(expect_calc, abs=0.01)
    assert fresh.all_time["wait"] == pytest.approx(0.5, abs=0.01)
    # and keeps accumulating on top of the restored totals
    fresh.start()
    fresh._t0 -= 2.0
    fresh.end("calc")
    assert fresh.all_time["calc"] == pytest.approx(expect_calc + 2.0,
                                                   abs=0.01)


def test_recorder_reports_tflops_when_model_declares_flops():
    from theanompi_tpu.utils.recorder import Recorder

    r = Recorder(rank=1, size=4, print_freq=0, flops_per_sample=12.3e9)
    r.train_metrics(1.0, 0.5, 4000)
    r._epoch_start -= 10.0  # pretend 10s of wall
    rec = r.epoch_summary(0)
    # 4000 img / 10 s / 4 shards * 12.3 GF = 1.23 TF/s per shard
    assert rec["tflops_per_shard"] == 1.23
    # column omitted when the model declares nothing
    r2 = Recorder(rank=1, size=4, print_freq=0)
    r2.train_metrics(1.0, 0.5, 4000)
    assert r2.epoch_summary(0)["tflops_per_shard"] is None
