"""DCN transport for the async rules (parallel/service.py).

The reference's async parameter traffic rode MPI p2p between ranks on
different machines (SURVEY.md §2.5/§3.3/§5.8); here the equivalent is a
TCP parameter service.  These tests prove the wire path end to end:
the protocol round-trips pytrees, the remote stores keep their
arithmetic, and — the acceptance bar (VERDICT round 1, next-round #4)
— an EASGD session whose server lives in a SEPARATE OS PROCESS
converges on the CPU mesh.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from theanompi_tpu.parallel.service import (
    RemoteASGD,
    RemoteEASGD,
    RemoteGossipHub,
    ServiceClient,
    serve,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(params=["v1", "v2"])
def local_service(request, monkeypatch, rpc_loop):
    """serve() on a background thread (same process, real sockets).

    Parametrized over both wire protocols (ISSUE 5) AND both RPC
    substrates (ISSUE 11, ``rpc_loop`` in conftest): every store test
    below runs over v1 pickle and v2 framed transport on the threaded
    loop and the selector event plane — same arithmetic, same restored
    trees, both directions, both loops."""
    monkeypatch.setenv("THEANOMPI_TPU_WIRE_PROTOCOL", request.param)
    key_before = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
    port = _free_port()
    ready, stop = threading.Event(), threading.Event()
    t = threading.Thread(target=serve,
                         args=("127.0.0.1", port, ready, stop), daemon=True)
    t.start()
    assert ready.wait(10)
    yield f"127.0.0.1:{port}"
    stop.set()
    try:
        ServiceClient(f"127.0.0.1:{port}").call("shutdown")
    except Exception:
        pass
    t.join(timeout=5)
    # serve() exports a generated key when none was set — don't let it
    # leak into later tests that assume the unset-key path
    if key_before is None:
        os.environ.pop("THEANOMPI_TPU_SERVICE_KEY", None)
    else:
        os.environ["THEANOMPI_TPU_SERVICE_KEY"] = key_before


def test_transport_round_trips_trees_byte_exact(local_service):
    """ISSUE 5 satellite: both transports restore pytrees BYTE-exactly
    in the default f32/none mode — mixed dtypes, 0-size leaves, nested
    containers — and the connection actually negotiated the protocol
    the fixture asked for (a v2 run silently degraded to v1 would be
    testing the wrong wire)."""
    tree = {"f32": np.arange(12, dtype=np.float32).reshape(3, 4) * 0.37,
            "f64": np.linspace(0.0, 1.0, 7),
            "i32": np.arange(-5, 5, dtype=np.int32),
            "u8": np.arange(64, dtype=np.uint8).reshape(8, 8),
            "empty": np.zeros((0, 3), np.float32),
            "nested": [np.full((2, 2), 9.5, np.float16),
                       {"deep": np.array([True, False])}]}
    srv = RemoteEASGD(local_service, tree, alpha=0.5, session_id="bytes")
    assert srv.wire_protocol == os.environ["THEANOMPI_TPU_WIRE_PROTOCOL"]
    back = srv.get_center()
    flat, treedef = jax.tree.flatten(tree)
    flat_back, treedef_back = jax.tree.flatten(back)
    assert treedef == treedef_back
    for a, b in zip(flat, flat_back):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()
    srv.close()


def test_v2_bf16_wire_dtype_end_to_end(local_service, monkeypatch):
    """The per-payload bf16 wire dtype: f32 leaves travel as bfloat16
    (half the bytes) and come back f32 within bf16's 8-bit-mantissa
    tolerance; non-f32 leaves are untouched.  v1 ignores the knob —
    pickle has no dtype option — so the tree stays exact there."""
    monkeypatch.setenv("THEANOMPI_TPU_WIRE_DTYPE", "bf16")
    tree = {"w": np.linspace(-3.0, 3.0, 257, dtype=np.float32),
            "step": np.arange(4, dtype=np.int32)}
    srv = RemoteEASGD(local_service, tree, alpha=0.5, session_id="bf16")
    back = srv.get_center()
    if srv.wire_protocol == "v2":
        np.testing.assert_allclose(back["w"], tree["w"], rtol=2 ** -8)
    else:
        assert np.asarray(back["w"]).tobytes() == tree["w"].tobytes()
    assert np.asarray(back["step"]).dtype == np.int32
    np.testing.assert_array_equal(back["step"], tree["step"])
    srv.close()


def test_remote_easgd_matches_closed_form(local_service):
    params = {"w": np.ones((4, 3), np.float32), "b": np.zeros(3, np.float32)}
    alpha = 0.5
    srv = RemoteEASGD(local_service, params, alpha=alpha)

    worker = {"w": np.full((4, 3), 3.0, np.float32),
              "b": np.full(3, 2.0, np.float32)}
    new_w = srv.exchange(worker)
    # worker <- worker - a(worker - center): 3 - .5(3-1) = 2 ; 2 - .5*2 = 1
    np.testing.assert_allclose(new_w["w"], 2.0)
    np.testing.assert_allclose(new_w["b"], 1.0)
    center = srv.get_center()
    # center <- center + a(worker - center): 1 + .5(3-1) = 2 ; 0 + 1 = 1
    np.testing.assert_allclose(center["w"], 2.0)
    np.testing.assert_allclose(center["b"], 1.0)
    assert srv.n_exchanges == 1
    srv.close()


def test_remote_asgd_applies_sgd(local_service):
    params = {"w": np.zeros(5, np.float32)}
    srv = RemoteASGD(local_service, params,
                     {"learning_rate": 0.1, "momentum": 0.0,
                      "nesterov": False, "weight_decay": 0.0})
    fresh = srv.push_pull({"w": np.ones(5, np.float32)})
    np.testing.assert_allclose(fresh["w"], -0.1, rtol=1e-6)
    srv.set_lr(0.5)
    fresh = srv.push_pull({"w": np.ones(5, np.float32)})
    np.testing.assert_allclose(fresh["w"], -0.6, rtol=1e-6)
    assert srv.n_updates == 2
    srv.close()


def test_remote_gossip_hub_roundtrip(local_service):
    hub_a = RemoteGossipHub(local_service, n_workers=4, rank_offset=0)
    hub_b = RemoteGossipHub(local_service, n_workers=4, rank_offset=2)
    # worker 1 (host a) pushes to global rank 3 (= host b local rank 1)
    assert hub_a.push(3, {"w": np.ones(2, np.float32)}, 0.125)
    got = hub_b.drain(1)
    assert len(got) == 1
    np.testing.assert_allclose(got[0][0]["w"], 1.0)
    assert got[0][1] == 0.125
    assert hub_b.drain(1) == []  # drained
    hub_b.deactivate(1)
    assert not hub_a.push(3, {"w": np.ones(2, np.float32)}, 0.125)
    hub_a.close()
    hub_b.close()


def test_bad_authkey_rejected(local_service):
    old = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
    os.environ["THEANOMPI_TPU_SERVICE_KEY"] = "wrong-key"
    try:
        with pytest.raises(Exception):
            ServiceClient(local_service).call("ping")
    finally:
        if old is None:
            os.environ.pop("THEANOMPI_TPU_SERVICE_KEY")
        else:
            os.environ["THEANOMPI_TPU_SERVICE_KEY"] = old
    # service survives the failed handshake
    c = ServiceClient(local_service)
    assert c.call("ping") == "pong"
    c.close()


@pytest.mark.slow
def test_easgd_with_server_in_separate_process(tmp_path, monkeypatch):
    """EASGD converges with its center-param server in another OS
    process — the reference's server-as-own-rank topology over DCN."""
    from theanompi_tpu import EASGD
    from theanompi_tpu.models.base import ModelConfig

    # both processes must share the key — an unset key would make the
    # child service mint its own random one and auth would fail
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_KEY", "test-dcn-key")
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "theanompi_tpu.parallel.service",
         "--host", "127.0.0.1", "--port", str(port), "--platform", "cpu"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 30
        while True:
            try:
                c = ServiceClient(f"127.0.0.1:{port}")
                assert c.call("ping") == "pong"
                c.close()
                break
            except (ConnectionRefusedError, OSError):
                assert time.monotonic() < deadline, "service never came up"
                assert proc.poll() is None, (
                    f"service died:\n{proc.stdout.read().decode()[-2000:]}")
                time.sleep(0.3)

        rule = EASGD()
        rule.init(devices=4, modelfile="theanompi_tpu.models.cifar10",
                  modelclass="Cifar10_model",
                  config=ModelConfig(batch_size=8, n_epochs=2,
                                     learning_rate=0.01,
                                     snapshot_dir=str(tmp_path),
                                     print_freq=0),
                  tau=5, alpha=0.5, checkpoint=False,
                  server_addr=f"127.0.0.1:{port}")
        res = rule.wait()
        assert res["n_exchanges"] > 0
        assert res["val"]["error"] < 0.85  # learned something
        assert all(np.isfinite(np.asarray(v)).all()
                   for v in jax.tree.leaves(res["center"]))
    finally:
        proc.kill()
        proc.wait()


def test_unset_key_client_refuses(monkeypatch):
    """No hard-coded key fallback (VERDICT r2 #6): a client without
    THEANOMPI_TPU_SERVICE_KEY must refuse before touching the network —
    the transport is pickle, so a well-known default key would be
    remote code execution for anyone who can reach the port."""
    monkeypatch.delenv("THEANOMPI_TPU_SERVICE_KEY", raising=False)
    with pytest.raises(RuntimeError, match="THEANOMPI_TPU_SERVICE_KEY"):
        ServiceClient("127.0.0.1:1")


def test_unset_key_server_generates_and_exports(monkeypatch):
    """A server with no key mints a random one and exports it so
    same-process clients still connect; nothing uses a public default."""
    monkeypatch.delenv("THEANOMPI_TPU_SERVICE_KEY", raising=False)
    port = _free_port()
    ready, stop = threading.Event(), threading.Event()
    t = threading.Thread(target=serve,
                         args=("127.0.0.1", port, ready, stop), daemon=True)
    t.start()
    try:
        assert ready.wait(10)
        generated = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
        assert generated and generated != "theanompi-tpu"
        c = ServiceClient(f"127.0.0.1:{port}")
        assert c.call("ping") == "pong"
        c.call("shutdown")
        c.close()
        t.join(timeout=5)
    finally:
        # serve() exported the generated key outside monkeypatch's
        # bookkeeping; scrub it so later tests see the unset state
        os.environ.pop("THEANOMPI_TPU_SERVICE_KEY", None)


def test_session_scoping_and_displacement(local_service):
    """A new session id replaces the store; the displaced session's ops
    fail FAST instead of silently hitting the new store; same-session
    workers join without re-shipping params."""
    p = {"w": np.zeros(2, np.float32)}
    s1 = RemoteEASGD(local_service, p, alpha=0.5, session_id="a")
    worker = RemoteEASGD(local_service, None, alpha=0.5, session_id="a")
    out = worker.exchange({"w": np.ones(2, np.float32)})
    np.testing.assert_allclose(out["w"], 0.5)

    s2 = RemoteEASGD(local_service, p, alpha=0.5, session_id="b")
    with pytest.raises(RuntimeError, match="displaced"):
        s1.exchange({"w": np.ones(2, np.float32)})
    with pytest.raises(RuntimeError, match="not active"):
        RemoteEASGD(local_service, None, alpha=0.5, session_id="zzz")
    s2.exchange({"w": np.ones(2, np.float32)})  # live session still works
    for c in (s1, worker, s2):
        c.close()


def test_v1_corrupt_pickle_typeerror_gets_diagnostic(local_service):
    """A v1 request whose unpickle raises TypeError (e.g. a hostile
    __reduce__ with bad args) must get the typed 'err' diagnostic and
    leave the connection usable — not be mistaken for the
    shutdown-closed-handle TypeError and silently dropped
    (code-review regression guard for the conns close-sweep)."""
    from multiprocessing.connection import Client as RawClient

    host, _, port = local_service.rpartition(":")
    key = os.environ["THEANOMPI_TPU_SERVICE_KEY"].encode()
    conn = RawClient((host, int(port)), authkey=key)
    try:
        # pickle of int('a', 'b') — REDUCE raises TypeError at load
        conn.send_bytes(b"cbuiltins\nint\n(S'a'\nS'b'\ntR.")
        status, payload = conn.recv()
        assert status == "err" and "TypeError" in payload
        # connection survived the poison frame
        conn.send(("ping",))
        assert conn.recv() == ("ok", "pong")
    finally:
        conn.close()


def test_malformed_requests_fail_cleanly():
    """Unknown ops and old-protocol requests (no session id) must get
    purposeful errors, not unpacking crashes or a params-tree-as-
    session-id misdiagnosis."""
    from theanompi_tpu.parallel.service import ParamService

    svc = ParamService()
    with pytest.raises(ValueError, match="unknown op"):
        svc.handle("bogus_op")
    with pytest.raises(ValueError, match="unknown op"):
        svc.handle("bogus_op", "sid", 1, 2)
    # known store op with no args at all
    with pytest.raises(ValueError, match="session id"):
        svc.handle("easgd_exchange")
    # old-protocol client: first arg is the params tree, not a str id
    with pytest.raises(ValueError, match="session"):
        svc.handle("easgd_exchange", {"w": np.ones(2)})
