"""Expert parallelism (parallel/expert.py + TransformerLM_MoE): the
all_to_all dispatch must reproduce the single-shard MoE exactly,
expert params must physically shard, and the model trains through the
rule spine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.parallel.mesh import MeshSpec, make_training_mesh


def lm_cfg(**kw):
    base = dict(batch_size=4, n_epochs=1, learning_rate=0.1,
                momentum=0.9, weight_decay=0.0, lr_schedule="constant",
                print_freq=0)
    base.update(kw)
    return ModelConfig(**base)


NET = dict(vocab=32, seq_len=16, n_layers=1, d_model=32, n_heads=4,
           n_experts=8)


def make_moe(mesh, cfg=None, **kw):
    from theanompi_tpu.models.transformer import TransformerLM_MoE

    net = dict(NET)
    net.update(kw)
    return TransformerLM_MoE(config=cfg or lm_cfg(), mesh=mesh,
                             verbose=False, **net)


class TestMoeFfnPrimitive:
    def _setup(self, e=4, n=16, d=8, ff=16, seed=0):
        rng = np.random.default_rng(seed)
        x = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
        router = jnp.asarray(rng.standard_normal((d, e)).astype(np.float32))
        params = {
            "k1": jnp.asarray(rng.standard_normal((e, d, ff)).astype(np.float32)),
            "k2": jnp.asarray(rng.standard_normal((e, ff, d)).astype(np.float32)),
        }

        def apply_expert(p, tok):
            return jnp.maximum(tok @ p["k1"], 0.0) @ p["k2"]

        return x, router, params, apply_expert

    def test_ep_matches_single_shard(self, devices8):
        """moe_ffn over expert=4 shards, each with ITS OWN tokens, must
        equal four independent single-shard MoE applications: outputs
        per token group, per-group losses, and expert grads summed over
        groups (the all_to_all round trip + its transpose are exact)."""
        from theanompi_tpu.parallel.expert import moe_ffn

        _, router, params, apply_expert = self._setup()
        rng = np.random.default_rng(3)
        x_all = jnp.asarray(rng.standard_normal((64, 8)).astype(np.float32))
        mesh = make_training_mesh(MeshSpec(data=1, expert=4), devices8[:4])

        def sharded_fn(params, x):   # x: this shard's 16 tokens
            out, aux = moe_ffn(x, router, params, apply_expert,
                               axis_name="expert")
            return out.sum() + aux, out

        def run_shard(params, x):
            (loss, out), grads = jax.value_and_grad(
                sharded_fn, has_aux=True)(params, x)
            return loss[None], out, grads

        run = jax.jit(jax.shard_map(
            run_shard, mesh=mesh, in_specs=(P("expert"), P("expert")),
            out_specs=(P("expert"), P("expert"), P("expert")),
            check_vma=False))
        losses, out, grads = run(params, x_all)

        # reference: each 16-token group through an unsharded MoE with
        # the full expert set; expert grads accumulate over groups
        ref_losses, ref_outs = [], []
        ref_grads = jax.tree.map(jnp.zeros_like, params)
        for g in range(4):
            xg = x_all[g * 16:(g + 1) * 16]
            (lg, og), gg = jax.value_and_grad(
                lambda p: (lambda o, a: (o.sum() + a, o))(
                    *moe_ffn(xg, router, p, apply_expert, axis_name=None)),
                has_aux=True)(params)
            ref_losses.append(float(lg))
            ref_outs.append(og)
            ref_grads = jax.tree.map(jnp.add, ref_grads, gg)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(jnp.concatenate(ref_outs)),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(losses), ref_losses,
                                   rtol=1e-5)
        for k in params:
            np.testing.assert_allclose(np.asarray(grads[k]),
                                       np.asarray(ref_grads[k]),
                                       rtol=1e-4, atol=1e-4)

    def test_capacity_drops_overflow(self):
        """With capacity_factor tiny, overflowing tokens contribute
        zero output (dropped, not mis-routed)."""
        from theanompi_tpu.parallel.expert import moe_ffn

        x, router, params, apply_expert = self._setup(n=16)
        out_full, _ = moe_ffn(x, router, params, apply_expert,
                              capacity_factor=4.0, axis_name=None)
        out_tight, _ = moe_ffn(x, router, params, apply_expert,
                               capacity_factor=0.25, axis_name=None)
        # tight capacity zeroes some tokens that full capacity serves
        dropped = np.all(np.asarray(out_tight) == 0.0, axis=-1)
        served = np.all(np.asarray(out_full) == 0.0, axis=-1)
        assert dropped.sum() > served.sum()


class TestModel:
    def test_expert_params_physically_sharded(self, devices8):
        mesh = make_training_mesh(MeshSpec(data=2, expert=4), devices8)
        m = make_moe(mesh)
        up = m.state.params["experts"][0]["up_kernel"]
        assert up.shape == (8, 32, 128)
        # 2 experts per shard, replicated over data
        assert {s.data.shape for s in up.addressable_shards} == {(2, 32, 128)}
        # router stays replicated
        assert m.param_specs["router"][0] == P()

    def test_moe_trains_and_balances(self, devices8, tmp_path):
        from theanompi_tpu.rules.bsp import run_bsp_session

        mesh = make_training_mesh(MeshSpec(data=2, expert=4), devices8)
        m = make_moe(mesh)
        res = run_bsp_session(m, checkpoint=False)
        assert np.isfinite(res["val"]["loss"])
        assert res["records"][-1]["train_loss"] < 3.0  # below ~uniform

    def test_ep_step_matches_single_shard(self, devices8, tmp_path):
        """One training step on the SAME global batch over
        (data=2, expert=4) vs (data=2, expert=1) must produce the same
        updated params.  Capacity is DROP-FREE and aux weight 0 so
        token grouping cannot perturb the math — what remains is
        exactly the all_to_all dispatch path vs the local one.  (Full
        trajectories diverge slightly by design: capacity truncation
        and the aux loss are computed per routing group.)

        capacity_factor=8.0 (= n_experts), NOT a looser 4.0: capacity
        is ``int(cf * group_tokens / E)``, so only cf >= E guarantees
        capacity >= the whole routing group.  At init the router is
        heavily imbalanced (LN'd activations are correlated across
        tokens, so most argmax to one expert — measured 52 of 64
        tokens on one expert here), and at cf=4.0 ~73 of 512 tokens
        were silently dropped — DIFFERENT tokens per grouping (64-token
        groups under ep=4 vs 256 under ep=1), a 0.13% loss split that
        failed this test from the seed onward.  Per-group truncation
        is real serving-time behavior; the oracle must simply not sit
        on top of it."""
        from theanompi_tpu.parallel.mesh import shard_batch

        results = {}
        for ep, devs, bs in ((4, devices8, 4), (1, devices8[:2], 16)):
            mesh = make_training_mesh(MeshSpec(data=2, expert=ep), devs)
            m = make_moe(mesh, cfg=lm_cfg(batch_size=bs),
                         capacity_factor=8.0, aux_weight=0.0)
            assert m.global_batch == 32  # equalized across meshes
            m.compile_iter_fns("avg")
            batch = next(m.data.train_batches(0, 32))
            sb = shard_batch(batch, mesh, spec=m.batch_partition)
            st, metrics = m.train_step(m.state, sb, jax.random.key(0))
            results[ep] = (
                np.asarray(st.params["router"][0]),
                np.asarray(st.params["experts"][0]["up_kernel"]),
                float(metrics["loss"]),
            )
        np.testing.assert_allclose(results[4][2], results[1][2], rtol=1e-5)
        np.testing.assert_allclose(results[4][0], results[1][0],
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(results[4][1], results[1][1],
                                   rtol=1e-4, atol=1e-6)

    def test_indivisible_experts_rejected(self, devices8):
        mesh = make_training_mesh(MeshSpec(data=2, expert=4), devices8)
        with pytest.raises(ValueError, match="divisible"):
            make_moe(mesh, n_experts=6)
