"""Wasserstein GAN: the two-network fused SPMD round on the 8-device
CPU mesh (reference ``wasserstein_gan.py``, SURVEY.md §2.8)."""

import numpy as np
import pytest

from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.utils.recorder import Recorder


@pytest.fixture
def wgan(mesh8):
    from theanompi_tpu.models.wasserstein_gan import (
        Wasserstein_GAN,
        WGANCifar_data,
    )

    class TinyWGAN(Wasserstein_GAN):
        def build_data(self):
            return WGANCifar_data(synthetic_n=512, seed=self.config.seed)

    cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=100,
                      learning_rate=5e-5, lr_schedule="constant")
    return TinyWGAN(config=cfg, mesh=mesh8, width=8)


class TestWGAN:
    def test_round_updates_both_networks_and_clips(self, wgan):
        import jax

        wgan.compile_iter_fns()
        rec = Recorder(rank=1, size=8, print_freq=100)
        gp_before = jax.tree.map(np.asarray, wgan.state.gen_params)
        cp_before = jax.tree.map(np.asarray, wgan.state.critic_params)
        wgan.begin_epoch(0)
        for i in range(2):
            wgan.train_iter(i, rec)
        wgan._flush_metrics(rec)
        assert np.isfinite(wgan.current_info["loss"])
        gp_after = jax.tree.map(np.asarray, wgan.state.gen_params)
        cp_after = jax.tree.map(np.asarray, wgan.state.critic_params)
        assert any(not np.allclose(a, b) for a, b in
                   zip(jax.tree.leaves(gp_after), jax.tree.leaves(gp_before)))
        assert any(not np.allclose(a, b) for a, b in
                   zip(jax.tree.leaves(cp_after), jax.tree.leaves(cp_before)))
        # Lipschitz clip held on every critic weight
        for leaf in jax.tree.leaves(cp_after):
            assert np.all(np.abs(leaf) <= wgan.clip_c + 1e-8)
        wgan.cleanup()

    def test_val_and_generate(self, wgan):
        wgan.compile_iter_fns()
        rec = Recorder(rank=1, size=8, print_freq=100)
        val = wgan.val_epoch(rec)
        assert np.isfinite(val["loss"])
        imgs = wgan.generate(4, seed=1)
        assert imgs.shape == (4, 32, 32, 3)
        assert np.all(imgs >= -1.0) and np.all(imgs <= 1.0)

    def test_save_load_roundtrip(self, wgan, tmp_path):
        import jax

        p = wgan.save(str(tmp_path / "wgan.npz"))
        before = jax.tree.map(np.asarray, wgan.params)
        # perturb, then load back
        wgan.state = wgan.state.replace(
            gen_params=jax.tree.map(lambda x: x + 1.0, wgan.state.gen_params))
        wgan.load(p)
        after = jax.tree.map(np.asarray, wgan.params)
        for a, b in zip(jax.tree.leaves(after), jax.tree.leaves(before)):
            np.testing.assert_allclose(a, b, rtol=1e-6)

    @pytest.mark.slow
    def test_bsp_session_drives_wgan(self, mesh8, tmp_path):
        from theanompi_tpu.models.wasserstein_gan import (
            Wasserstein_GAN,
            WGANCifar_data,
        )
        from theanompi_tpu.rules.bsp import run_bsp_session

        class TinyWGAN(Wasserstein_GAN):
            def build_data(self):
                return WGANCifar_data(synthetic_n=256, seed=0)

        cfg = ModelConfig(batch_size=2, n_epochs=1, print_freq=100,
                          learning_rate=5e-5, lr_schedule="constant",
                          snapshot_dir=str(tmp_path))
        m = TinyWGAN(config=cfg, mesh=mesh8, width=8)
        out = run_bsp_session(m, max_epochs=1, checkpoint=True)
        assert out["epochs_run"] == 1
        assert np.isfinite(out["val"]["loss"])
