"""Pipeline parallelism (parallel/pipeline.py + TransformerLM_PP):
the GPipe schedule over ``ppermute``+``scan`` must reproduce the
unpipelined forward/backward exactly, stage params must physically
shard, and the model must train through the rule spine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.parallel.mesh import MeshSpec, make_training_mesh


def lm_cfg(**kw):
    base = dict(batch_size=8, n_epochs=1, learning_rate=0.1,
                momentum=0.9, weight_decay=0.0, lr_schedule="constant",
                print_freq=0)
    base.update(kw)
    return ModelConfig(**base)


NET = dict(vocab=32, seq_len=16, n_layers=4, d_model=32, n_heads=4,
           n_microbatches=2)


def make_pp(mesh, **kw):
    from theanompi_tpu.models.transformer import TransformerLM_PP

    net = dict(NET)
    net.update(kw)
    return TransformerLM_PP(config=lm_cfg(), mesh=mesh, verbose=False, **net)


class TestPipelinePrimitive:
    def test_pipeline_matches_sequential(self, devices8):
        """pipeline_apply over 4 stages == applying the 4 stage fns in
        order, for values AND gradients (the scan+ppermute schedule is
        transposed by jax for the backward).  Uses the masked-loss
        convention: outputs/loss are real on the last stage only, and
        the loss is psum-ed over 'pipe' AFTER the grad computation."""
        import jax.lax as lax

        from theanompi_tpu.parallel.pipeline import pipeline_apply

        mesh = make_training_mesh(MeshSpec(data=1, pipe=4), devices8[:4])
        rng = np.random.default_rng(0)
        # stage params: one (4,4) matrix per stage, stacked
        w = jnp.asarray(rng.standard_normal((4, 4, 4)).astype(np.float32))
        x = jnp.asarray(rng.standard_normal((6, 2, 4)).astype(np.float32))

        def stage_fn(wi, h):  # wi: (1, 4, 4) — this stage's slice
            return jnp.tanh(h @ wi[0])

        def pipelined(w, x):
            out = pipeline_apply(stage_fn, w, x, axis_name="pipe")
            return out.sum(), out  # zero off the last stage

        def run_shard(w, x):
            (loss, out), grads = jax.value_and_grad(
                pipelined, has_aux=True)(w, x)
            return lax.psum(loss, "pipe"), lax.psum(out, "pipe"), grads

        run = jax.jit(jax.shard_map(
            run_shard, mesh=mesh, in_specs=(P("pipe"), P()),
            out_specs=(P(), P(), P("pipe")), check_vma=False))
        loss, out, grads = run(w, x)

        ref = x
        for i in range(4):
            ref = jnp.tanh(ref @ w[i])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

        ref_loss, ref_grads = jax.value_and_grad(
            lambda w: jnp.tanh(jnp.tanh(jnp.tanh(jnp.tanh(
                x @ w[0]) @ w[1]) @ w[2]) @ w[3]).sum())(w)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_grads),
                                   rtol=1e-4, atol=1e-6)


class TestModel:
    def test_stage_params_physically_sharded(self, devices8):
        mesh = make_training_mesh(MeshSpec(data=2, pipe=4), devices8)
        m = make_pp(mesh)
        blk = m.state.params["blocks"]["q_proj"]["kernel"]
        assert blk.shape == (4, 32, 32)  # 4 stacked layers
        # one layer per stage on each pipe shard
        assert {s.data.shape for s in blk.addressable_shards} == {(1, 32, 32)}
        assert m.param_specs["blocks"]["q_proj"]["kernel"] == P("pipe")
        assert m.param_specs["embed"]["embedding"] == P()

    @pytest.mark.slow
    def test_pp_trajectory_matches_single_stage(self, devices8, tmp_path):
        """Same seed/config on (data=2, pipe=4) vs (data=2, pipe=1):
        identical init, so the 4-stage pipeline schedule must reproduce
        the unpipelined trajectory to fp tolerance."""
        from theanompi_tpu.rules.bsp import run_bsp_session

        res = {}
        for pipe, devs in ((4, devices8), (1, devices8[:2])):
            mesh = make_training_mesh(MeshSpec(data=2, pipe=pipe), devs)
            m = make_pp(mesh)
            res[pipe] = run_bsp_session(m, checkpoint=False)
        np.testing.assert_allclose(res[4]["val"]["loss"],
                                   res[1]["val"]["loss"], rtol=1e-3)
        np.testing.assert_allclose(
            res[4]["records"][-1]["train_loss"],
            res[1]["records"][-1]["train_loss"], rtol=1e-3)
        assert np.isfinite(res[4]["val"]["loss"])

    def test_bad_divisibility_rejected(self, devices8):
        mesh = make_training_mesh(MeshSpec(data=2, pipe=4), devices8)
        with pytest.raises(ValueError, match="divisible"):
            make_pp(mesh, n_layers=6)  # 6 layers over 4 stages
        with pytest.raises(ValueError, match="microbatch"):
            make_pp(mesh, n_microbatches=3)  # local batch 4 not /3
