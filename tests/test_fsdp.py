"""FSDP / ZeRO-3-class parameter sharding (parallel/fsdp.py): params
and optimizer state live 1/N per device, the step is plain global math
under GSPMD, and the trajectory equals the unsharded oracle exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.bsp import (
    TrainState,
    apply_update,
    grad_and_metrics,
    make_bsp_train_step,
)
from theanompi_tpu.parallel.fsdp import (
    fsdp_specs,
    init_fsdp_state,
    make_bsp_fsdp_step,
)
from theanompi_tpu.parallel.mesh import shard_batch
from theanompi_tpu.utils.helper_funcs import build_optimizer


def _loss(params, model_state, batch, rng):
    x, y = batch
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    pred = h @ params["w2"] + params["b_odd"]
    loss = jnp.mean((pred - y) ** 2)
    return loss, (model_state, {"loss": loss, "error": loss})


def _params():
    k1, k2 = jax.random.split(jax.random.key(0))
    # w1/w2/b1 have an 8-divisible dim (sharded); b_odd (3,) does not
    # (stays replicated) — both placement classes exercised
    return {"w1": jax.random.normal(k1, (5, 16)),
            "w2": jax.random.normal(k2, (16, 3)),
            "b1": jnp.zeros((16,)),
            "b_odd": jnp.zeros((3,))}


def _batch(n=32, seed=1):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, 5)).astype(np.float32),
            rng.standard_normal((n, 3)).astype(np.float32))


def test_specs_pick_largest_divisible_dim(mesh8):
    specs = fsdp_specs(_params(), mesh8)
    assert specs["w1"] == P(None, "data")
    assert specs["w2"] == P("data")      # 16 > 3: dim 0
    assert specs["b1"] == P("data")
    assert specs["b_odd"] == P()


@pytest.mark.parametrize("opt", ["sgd", "adamw"])
def test_fsdp_step_equals_unsharded_oracle(mesh8, opt):
    """The FSDP step is the SAME global trace as a single-device step
    on the full batch — the oracle is exact, not statistical."""
    tx = build_optimizer(0.05, optimizer=opt, momentum=0.9,
                         weight_decay=1e-4)
    params = _params()
    rng = jax.random.key(2)
    x, y = _batch()

    def oracle_step(state, batch, r):
        grads, ms, metrics = grad_and_metrics(
            _loss, state.params, state.model_state, batch, r)
        return apply_update(tx, state, grads, ms), metrics

    s_o = TrainState.create(params, tx)
    specs = fsdp_specs(params, mesh8)
    s_f = init_fsdp_state(params, tx, {}, mesh8, specs)
    fstep = make_bsp_fsdp_step(_loss, tx, mesh8, params, donate=False)

    batch = shard_batch((x, y), mesh8)
    for _ in range(3):
        s_o, m_o = jax.jit(oracle_step)(s_o, (jnp.asarray(x),
                                              jnp.asarray(y)), rng)
        s_f, m_f = fstep(s_f, batch, rng)
    for a, b in zip(jax.tree.leaves(s_o.params),
                    jax.tree.leaves(s_f.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert float(m_f["loss"]) == pytest.approx(float(m_o["loss"]),
                                               rel=1e-5)


def test_fsdp_step_equals_plain_bsp(mesh8):
    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9)
    params = _params()
    rng = jax.random.key(3)
    x, y = _batch()

    plain = make_bsp_train_step(_loss, tx, mesh8, donate=False)
    s_p = TrainState.create(params, tx)
    fstep = make_bsp_fsdp_step(_loss, tx, mesh8, params, donate=False)
    s_f = init_fsdp_state(params, tx, {}, mesh8, fsdp_specs(params, mesh8))

    batch = shard_batch((x, y), mesh8)
    for _ in range(3):
        s_p, m_p = plain(s_p, batch, rng)
        s_f, m_f = fstep(s_f, batch, rng)
    for a, b in zip(jax.tree.leaves(s_p.params),
                    jax.tree.leaves(s_f.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    assert float(m_f["loss"]) == pytest.approx(float(m_p["loss"]),
                                               rel=1e-5)


def test_params_and_momentum_physically_sharded(mesh8):
    tx = build_optimizer(0.1, optimizer="sgd", momentum=0.9)
    params = _params()
    specs = fsdp_specs(params, mesh8)
    state = init_fsdp_state(params, tx, {}, mesh8, specs)
    fstep = make_bsp_fsdp_step(_loss, tx, mesh8, params, donate=False)
    batch = shard_batch(_batch(), mesh8)
    state, _ = fstep(state, batch, jax.random.key(0))  # stays sharded

    def shard_shapes(leaf):
        return {s.data.shape for s in leaf.addressable_shards}

    for tree in (state.params,):
        assert shard_shapes(tree["w1"]) == {(5, 2)}
        assert shard_shapes(tree["w2"]) == {(2, 3)}
        assert shard_shapes(tree["b1"]) == {(2,)}
        assert shard_shapes(tree["b_odd"]) == {(3,)}  # replicated
    # momentum buffers follow their params (out_shardings pin)
    mom = [l for l in jax.tree.leaves(state.opt_state)
           if getattr(l, "shape", None) == (5, 16)]
    assert mom and shard_shapes(mom[0]) == {(5, 2)}


def test_fsdp_multi_equals_separate_calls(mesh8):
    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9)
    params = _params()
    rng = jax.random.key(4)
    specs = fsdp_specs(params, mesh8)

    single = make_bsp_fsdp_step(_loss, tx, mesh8, params, donate=False)
    multi = make_bsp_fsdp_step(_loss, tx, mesh8, params, donate=False,
                               multi=True)
    k = 4
    batches = [_batch(seed=10 + i) for i in range(k)]
    stacked = tuple(np.stack([b[j] for b in batches]) for j in range(2))

    s_a = init_fsdp_state(params, tx, {}, mesh8, specs)
    for i, b in enumerate(batches):
        s_a, m_a = single(s_a, shard_batch(b, mesh8),
                          jax.random.fold_in(rng, i))

    s_b = init_fsdp_state(params, tx, {}, mesh8, specs)
    s_b, m_b = multi(s_b, shard_batch(stacked, mesh8,
                                      spec=P(None, "data")), rng)
    for a, b in zip(jax.tree.leaves(s_a.params),
                    jax.tree.leaves(s_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    # stacked metrics: one row per sub-step, last row == last call
    assert np.asarray(m_b["loss"]).shape == (k,)
    assert float(np.asarray(m_b["loss"])[-1]) == pytest.approx(
        float(m_a["loss"]), rel=1e-6)


def test_fsdp_accum_equals_big_batch(mesh8):
    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9)
    params = _params()
    rng = jax.random.key(5)
    specs = fsdp_specs(params, mesh8)

    accum = make_bsp_fsdp_step(_loss, tx, mesh8, params, donate=False,
                               accum=True)
    single = make_bsp_fsdp_step(_loss, tx, mesh8, params, donate=False)

    x, y = _batch(n=64, seed=6)
    a = 2
    stacked = (x.reshape(a, 32, 5), y.reshape(a, 32, 3))

    s_a = init_fsdp_state(params, tx, {}, mesh8, specs)
    s_a, m_a = accum(s_a, shard_batch(stacked, mesh8,
                                      spec=P(None, "data")), rng)

    # oracle: ONE update from the mean of the microbatch grads — the
    # accum cadence's defining contract (grad of mean over both
    # microbatches, each with its fold_in rng; _loss ignores rng so
    # the fold detail is invisible here)
    def two_mb_oracle(state):
        g0, ms, _ = grad_and_metrics(_loss, state.params,
                                     state.model_state,
                                     (x[:32], y[:32]), rng)
        g1, ms, _ = grad_and_metrics(_loss, state.params, ms,
                                     (x[32:], y[32:]), rng)
        g = jax.tree.map(lambda p, q: (p + q) / 2.0, g0, g1)
        return apply_update(tx, state, g, ms)

    s_o = jax.jit(two_mb_oracle)(TrainState.create(params, tx))
    for a_, b_ in zip(jax.tree.leaves(s_o.params),
                      jax.tree.leaves(s_a.params)):
        np.testing.assert_allclose(np.asarray(a_), np.asarray(b_),
                                   rtol=2e-5, atol=1e-6)


def test_fsdp_cdd_sum_semantics(mesh8):
    """'cdd' (sum) trajectory == shard_map BSP with sum exchange."""
    from theanompi_tpu.parallel.exchanger import BSP_Exchanger

    tx = build_optimizer(0.01, optimizer="sgd", momentum=0.9)
    params = _params()
    rng = jax.random.key(7)
    batch = shard_batch(_batch(), mesh8)

    plain = make_bsp_train_step(
        _loss, tx, mesh8, BSP_Exchanger(avg=False), donate=False)
    s_p = TrainState.create(params, tx)
    fstep = make_bsp_fsdp_step(_loss, tx, mesh8, params, donate=False,
                               avg=False)
    s_f = init_fsdp_state(params, tx, {}, mesh8, fsdp_specs(params, mesh8))

    s_p, _ = plain(s_p, batch, rng)
    s_f, _ = fstep(s_f, batch, rng)
    for a, b in zip(jax.tree.leaves(s_p.params),
                    jax.tree.leaves(s_f.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)


def test_model_trains_with_fsdp_and_resume(mesh8, tmp_path):
    """Model-layer integration: ModelConfig.fsdp_sharding through
    compile_iter_fns/train_iter, lr schedule feedback, npz save/load
    re-placing params per param_specs."""
    from tests._tiny_models import TinyCifar128
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.utils.recorder import Recorder

    cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                      print_freq=0, fsdp_sharding=True,
                      lr_schedule="step", lr_decay_epochs=(1,),
                      snapshot_dir=str(tmp_path))
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    assert m.param_specs is not None
    # at least one leaf is physically sharded at rest
    sharded = [l for l in jax.tree.leaves(m.state.params)
               if len({s.data.shape for s in l.addressable_shards}) == 1
               and next(iter(l.addressable_shards)).data.shape != l.shape]
    assert sharded, "no param leaf is sharded"
    m.compile_iter_fns("avg")
    rec = Recorder(rank=0, size=8, print_freq=0)
    n = m.begin_epoch(0)  # 128 samples @ global 32 = 4 iters/epoch
    assert n == 4
    losses = []
    for i in range(4):
        m.train_iter(i, rec)
        m._flush_metrics(rec)
        losses.append(rec.train_losses[-1])
    assert np.isfinite(losses).all()
    assert m.adjust_hyperp(1) == pytest.approx(0.002)
    m.begin_epoch(1)
    m.train_iter(0, rec)
    m._flush_metrics(rec)

    # save -> load keeps the FSDP placement (load uses param_specs)
    path = m.save()
    m.load(path)
    for leaf, spec in zip(jax.tree.leaves(m.state.params),
                          jax.tree.leaves(m.param_specs,
                                          is_leaf=lambda x:
                                          isinstance(x, P))):
        assert leaf.sharding.spec == spec
    m.train_iter(1, rec)
    m._flush_metrics(rec)
    assert np.isfinite(rec.train_losses).all()
    m.cleanup()


def test_model_fsdp_steps_per_call(mesh8, tmp_path):
    """FSDP x steps_per_call: the scanned cadence consumes k iters per
    dispatch and stays finite."""
    from tests._tiny_models import TinyCifar128
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.utils.recorder import Recorder

    cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                      print_freq=0, fsdp_sharding=True, steps_per_call=2,
                      snapshot_dir=str(tmp_path))
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    rec = Recorder(rank=0, size=8, print_freq=0)
    n = m.begin_epoch(0)
    assert n >= 2
    consumed = m.train_iter(0, rec)
    assert consumed == 2
    m._flush_metrics(rec)
    assert np.isfinite(rec.train_losses).all()
    m.cleanup()


def test_fsdp_rejects_zero_and_bf16_exchange(mesh8):
    from tests._tiny_models import TinyCifar128
    from theanompi_tpu.models.base import ModelConfig

    with pytest.raises(ValueError, match="meaningless"):
        TinyCifar128(config=ModelConfig(batch_size=4, fsdp_sharding=True,
                                        zero_sharding=True),
                     mesh=mesh8, verbose=False)
    with pytest.raises(ValueError, match="bf16-compressed"):
        TinyCifar128(config=ModelConfig(batch_size=4, fsdp_sharding=True,
                                        exchange_strategy="nccl16"),
                     mesh=mesh8, verbose=False)
    # the modern spelling is rejected too: GSPMD inserts the gradient
    # collectives itself — there is no quantization seam under FSDP
    with pytest.raises(ValueError, match="exchange_dtype"):
        TinyCifar128(config=ModelConfig(batch_size=4, fsdp_sharding=True,
                                        exchange_dtype="bf16"),
                     mesh=mesh8, verbose=False)
    from theanompi_tpu.parallel.fsdp import make_bsp_fsdp_step

    with pytest.raises(ValueError, match="no seam"):
        make_bsp_fsdp_step(_loss, build_optimizer(0.1), mesh8, _params(),
                           exchange_dtype="bf16")


def test_fsdp_lars_equals_unsharded_oracle(mesh8):
    """LARS under FSDP: the layerwise trust-ratio norms run over
    SHARDED params, so GSPMD inserts the norm collectives — the reason
    fsdp_sharding has no elementwise-optimizer restriction (ZeRO-1's
    flat shard cannot see layer boundaries; the README claim is backed
    here)."""
    tx = build_optimizer(0.1, optimizer="lars", momentum=0.9,
                         weight_decay=1e-4, lars_trust_coefficient=0.01)
    params = _params()
    rng = jax.random.key(9)
    x, y = _batch()

    def oracle_step(state, batch, r):
        grads, ms, metrics = grad_and_metrics(
            _loss, state.params, state.model_state, batch, r)
        return apply_update(tx, state, grads, ms), metrics

    s_o = TrainState.create(params, tx)
    s_f = init_fsdp_state(params, tx, {}, mesh8, fsdp_specs(params, mesh8))
    fstep = make_bsp_fsdp_step(_loss, tx, mesh8, params, donate=False)

    batch = shard_batch((x, y), mesh8)
    for _ in range(3):
        s_o, m_o = jax.jit(oracle_step)(s_o, (jnp.asarray(x),
                                              jnp.asarray(y)), rng)
        s_f, m_f = fstep(s_f, batch, rng)
    for a, b in zip(jax.tree.leaves(s_o.params),
                    jax.tree.leaves(s_f.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert float(m_f["loss"]) == pytest.approx(float(m_o["loss"]),
                                               rel=1e-5)


# ---------------------------------------------------------------------------
# Bucketed exchange (ISSUE 13): per-bucket optimization_barrier fences
# in the backward — GSPMD owns the collectives, the fences pin their
# per-bucket grouping.  Identity numerics, pinned bit-equal.
# ---------------------------------------------------------------------------


def _run_fsdp_bucketed(mesh8, B, steps=3):
    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9,
                         weight_decay=1e-4)
    params = _params()
    specs = fsdp_specs(params, mesh8)
    s = init_fsdp_state(params, tx, {}, mesh8, specs)
    step = make_bsp_fsdp_step(_loss, tx, mesh8, params, donate=False,
                              specs=specs, exchange_buckets=B)
    batch = shard_batch(_batch(), mesh8)
    rng = jax.random.key(2)
    traj = []
    for _ in range(steps):
        s, m = step(s, batch, rng)
        traj.append(jax.tree.map(np.asarray, s.params))
    return s, m, traj


def test_fsdp_bucketed_bit_identical_to_b1(mesh8):
    """The acceptance pin on the FSDP plane: the barrier tags are the
    identity — B>1 equals B=1 bit-for-bit at every step."""
    _, m1, traj1 = _run_fsdp_bucketed(mesh8, 1)
    for B in (2, 4, 8):
        _, mB, trajB = _run_fsdp_bucketed(mesh8, B)
        for t1, tB in zip(traj1, trajB):
            for a, b in zip(jax.tree.leaves(t1), jax.tree.leaves(tB)):
                np.testing.assert_array_equal(a, b, err_msg=f"B={B}")
        assert float(m1["loss"]) == float(mB["loss"])


def test_fsdp_bucket_barriers_in_lowering(mesh8):
    """Structural pin: the bucketed program carries one
    optimization_barrier per bucket in the backward; the unbucketed
    one carries none."""
    from theanompi_tpu.parallel.exchanger import (
        _leaf_nbytes,
        bucket_ranges,
    )

    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9)
    params = _params()
    specs = fsdp_specs(params, mesh8)
    s = init_fsdp_state(params, tx, {}, mesh8, specs)
    batch = shard_batch(_batch(), mesh8)

    def barriers(B):
        step = make_bsp_fsdp_step(_loss, tx, mesh8, params,
                                  donate=False, specs=specs,
                                  exchange_buckets=B)
        txt = step.lower(s, batch, jax.random.key(0)).as_text()
        return txt.count("stablehlo.optimization_barrier")

    assert barriers(1) == 0
    leaves = jax.tree.leaves(params)
    for B in (2, 4):
        n_buckets = len(bucket_ranges(
            [_leaf_nbytes(l) for l in leaves], B))
        assert barriers(B) == n_buckets, (B, n_buckets)


def test_fsdp_bucketed_model_glue_and_validation(mesh8):
    """ModelConfig.exchange_buckets reaches the FSDP stack; bad bucket
    counts are refused at the builder."""
    from theanompi_tpu.models.base import ModelConfig
    from tests._tiny_models import TinyCifar128

    with pytest.raises(ValueError, match="exchange_buckets"):
        make_bsp_fsdp_step(_loss, build_optimizer(0.05), mesh8,
                           _params(), exchange_buckets=0)
    cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                      fsdp_sharding=True, exchange_buckets=4)
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    from theanompi_tpu.utils.recorder import Recorder

    rec = Recorder(rank=0, size=8, print_freq=0)
    m.begin_epoch(0)
    m.train_iter(0, rec)
    m._flush_metrics(rec)
    assert np.isfinite(rec.train_losses).all()
    m.cleanup()
