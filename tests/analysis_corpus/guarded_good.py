"""TM101 known-good twin: the checker must stay silent here.

Exercises every escape the convention defines: with-blocks on the lock
AND on its Condition alias, the ``requires_lock`` method annotation,
constructor exemption, inline suppression, and undeclared attributes.
"""

import threading


class TidyCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._count = 0       # guarded_by: self._lock
        self._pending = []    # guarded_by: self._cond
        self.public = 0
        self._count = self.public  # constructor access is exempt

    def locked_inc(self):
        with self._lock:
            self._count += 1
            return self._count

    def cond_wait(self):
        with self._cond:
            while not self._pending:
                self._cond.wait(0.1)
            return self._pending.pop()

    def alias_ok(self):
        # the Condition wraps the same lock, so either name guards both
        with self._cond:
            self._count += len(self._pending)

    def helper(self):  # requires_lock: self._lock
        return self._count

    def suppressed(self):
        return self._count  # lint: ok TM101

    def unguarded_public(self):
        return self.public  # undeclared attr: not checked
