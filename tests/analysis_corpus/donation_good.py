"""TM201 known-good twin: donation followed by legal access only."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def update(state, grads):
    return jax.tree.map(lambda s, g: s - g, state, grads)


def rebind_same_name(state, grads):
    # x = f(x): the call consumes the old binding, the store installs
    # the result — nothing dangling
    state = update(state, grads)
    return state["w"]


def rebind_attribute(model, grads):
    model.state = model.state.replace(
        params=update(model.state.params, grads))
    return model.state.params


def read_before_donate(state, grads):
    norm = state["w"].sum()
    new = update(state, grads)
    return new, norm


def donate_expression_arg(state, grads):
    # the donated position holds an expression, not a simple path —
    # nothing to track, nothing to flag
    new = update(dict(state), grads)
    return new, state


def suppressed(state, grads):
    new = update(state, grads)
    return new, state  # lint: ok TM201


def _plain_step(state):
    return state


#: the idiomatic explicit NO-donate spec — must not register as
#: donating argument 0
keep_step = jax.jit(_plain_step, donate_argnums=())


def explicit_empty_donate(state):
    new = keep_step(state)
    return new, state


def exclusive_branches(state, grads, flag):
    # a donation in one branch must not poison the OTHER branch's
    # reads — the zoo's k>1/a>1/else step dispatch is exactly this
    if flag:
        out = update(state, grads)
    else:
        out = state["w"] + 1
    return out
