"""TM401/TM403 seeded-bad corpus (paired with coverage_docs.md).

The docs twin documents site ``alpha`` and metric ``corpus/a_total``
(in sync), plus site ``beta`` and metric ``corpus/ghost_total`` that
this module never produces (TM402/TM404 fire on the DOCS lines); this
module additionally fires ``undocumented_site`` and emits
``corpus/b_ms`` that the docs lack (TM401/TM403 fire here).
"""

from theanompi_tpu import monitor
from theanompi_tpu.resilience import faults


def documented_pair(x):
    faults.fire("alpha", worker=1)
    monitor.inc("corpus/a_total", op="x")
    return x


def undocumented_pair(x):
    faults.fire("undocumented_site", step=2)  # SEED: TM401
    monitor.observe("corpus/b_ms", 1.0)       # SEED: TM403
    return x
