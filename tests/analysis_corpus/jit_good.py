"""TM301/TM302 known-good twin."""

import pickle

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def clean_step(x):
    # shape-derived scalars are static under tracing: not host syncs
    rows = int(x.shape[0])
    scale = float(len(x.shape))
    return jnp.sum(x) / (rows * scale)


def host_helper(x):
    # host-side code may sync freely: this function is NOT reachable
    # from any traced root
    return float(np.asarray(x).item())


def gated_decode(buf, opts):
    # the wire-v2 pattern: the pickle escape is reachable only behind
    # an explicit allow_pickle opt-in that raises when off
    if not opts.allow_pickle:
        raise ValueError("frame carries pickle but allow_pickle=False")
    return pickle.loads(buf)


def safe_numpy_load(path):
    return np.load(path)  # allow_pickle defaults to False
