"""TM201 seeded-bad corpus: uses-after-donate the checker must flag."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0,))
def update(state, grads):
    return jax.tree.map(lambda s, g: s - g, state, grads)


def build_step(donate: bool = True):
    def step(state, batch):
        return state

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def simple_use_after_donate(state, grads):
    new = update(state, grads)
    return new, jnp.sum(state["w"])  # SEED: TM201 (state donated above)


def attr_use_after_donate(model, grads):
    out = update(model.state.params, grads)
    norm = model.state.params["w"].sum()  # SEED: TM201
    return out, norm


def factory_use_after_donate(state, batch):
    step = build_step()
    new = step(state, batch)
    return new, state  # SEED: TM201 (factory-built step donates arg 0)


def _dyn_spec(donate, donate_batch):
    return (0, 1) if donate_batch else (0,)


def build_staged_step():
    def step(state, batch):
        return state

    # dynamic donate spec (the bsp/zero/fsdp builder shape): the lint
    # must assume the state+staged-batch (0, 1) donation
    return jax.jit(step, donate_argnums=_dyn_spec(True, True))


def staged_batch_use_after_donate(state, batch):
    step = build_staged_step()
    new = step(state, batch)
    return new, batch  # SEED: TM201 (batch donated at position 1)


def post_branch_use_after_donate(state, grads, flag):
    if flag:
        new = update(state, grads)
    else:
        new = state
    return new, state  # SEED: TM201 (donated in one branch -> dead after)
