"""TM101 seeded-bad corpus: every marked line must be flagged.

A ``SEED:`` comment with a check ID marks the exact line the checker
must report (tests/test_analysis.py asserts line numbers match).
"""

import threading


class LeakyCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._count = 0       # guarded_by: self._lock
        self._pending = []    # guarded_by: self._cond
        self.public = 0       # undeclared: never checked

    def locked_inc(self):
        with self._lock:
            self._count += 1

    def cond_push(self, item):
        with self._cond:
            self._pending.append(item)
            self._cond.notify_all()

    def bare_read(self):
        return self._count  # SEED: TM101

    def bare_write(self):
        self._pending = []  # SEED: TM101

    def half_locked(self):
        with self._lock:
            n = self._count
        return n + self._count  # SEED: TM101 (second read is outside)
