"""TM301/TM302 seeded-bad corpus."""

import pickle
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_sync(x):
    return x * x.item()  # SEED: TM301 (.item in a jitted fn)


@partial(jax.jit, donate_argnums=(0,))
def partial_decorated_sync(x):
    return jnp.asarray(np.asarray(x))  # SEED: TM301 (np.asarray)


def helper(x):
    return float(x) * 2.0  # SEED: TM301 (scalar coercion, reachable)


def traced(x):
    return helper(x) + 1


traced_step = jax.jit(traced)


def decode_frame(buf):
    return pickle.loads(buf)  # SEED: TM302 (no allow_pickle guard)


def load_numpy(path):
    return np.load(path, allow_pickle=True)  # SEED: TM302
