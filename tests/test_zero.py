"""ZeRO-1 optimizer-state sharding (parallel/zero.py +
ModelConfig.zero_sharding): reduce_scatter/update-shard/all_gather,
step-equal to plain BSP, state physically sharded."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.parallel.bsp import TrainState, make_bsp_train_step
from theanompi_tpu.parallel.mesh import AXIS_DATA, data_mesh, shard_batch
from theanompi_tpu.parallel.zero import (
    init_zero_opt_state,
    make_bsp_zero_step,
)
from theanompi_tpu.utils.helper_funcs import (
    build_optimizer,
    get_learning_rate,
    set_learning_rate,
)
from theanompi_tpu.utils.recorder import Recorder


def _loss(params, model_state, batch, rng):
    x, y = batch
    pred = jnp.tanh(x @ params["w1"]) @ params["w2"] + params["b"]
    loss = jnp.mean((pred - y) ** 2)
    return loss, (model_state, {"loss": loss, "error": loss})


def _params():
    k = jax.random.key(0)
    k1, k2 = jax.random.split(k)
    # deliberately not divisible by 8 so the pad path is exercised
    return {"w1": jax.random.normal(k1, (5, 7)),
            "w2": jax.random.normal(k2, (7, 3)),
            "b": jnp.zeros((3,))}


@pytest.mark.parametrize("opt", ["sgd", "adamw"])
def test_zero_step_equals_plain_bsp(mesh8, opt):
    """N steps of ZeRO == N steps of plain BSP (elementwise update is
    sharding-transparent), while opt state lives 1/8 per device."""
    tx = build_optimizer(0.05, optimizer=opt, momentum=0.9,
                         weight_decay=1e-4)
    params = _params()
    rng_np = np.random.default_rng(1)
    x = rng_np.standard_normal((32, 5)).astype(np.float32)
    y = rng_np.standard_normal((32, 3)).astype(np.float32)
    rng = jax.random.key(2)

    plain = make_bsp_train_step(_loss, tx, mesh8, donate=False)
    s_p = TrainState.create(params, tx)

    zero = make_bsp_zero_step(_loss, tx, mesh8, params, donate=False)
    opt0, specs = init_zero_opt_state(tx, params, mesh8)
    s_z = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                     opt_state=opt0, model_state={})

    batch = shard_batch((x, y), mesh8)
    for _ in range(3):
        s_p, m_p = plain(s_p, batch, rng)
        s_z, m_z = zero(s_z, batch, rng)
    for a, b in zip(jax.tree.leaves(s_p.params),
                    jax.tree.leaves(s_z.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    assert float(m_z["loss"]) == pytest.approx(float(m_p["loss"]),
                                               rel=1e-5)


def test_opt_state_physically_sharded(mesh8):
    tx = build_optimizer(0.1, optimizer="sgd", momentum=0.9)
    params = _params()
    opt0, specs = init_zero_opt_state(tx, params, mesh8)
    vec_leaves = [l for l in jax.tree.leaves(opt0)
                  if getattr(l, "ndim", 0) == 1 and l.size >= 8]
    assert vec_leaves, "expected momentum vector slots"
    for leaf in vec_leaves:
        # each device holds 1/8 of the padded flat vector
        shard_shapes = {s.data.shape for s in leaf.addressable_shards}
        assert shard_shapes == {(leaf.shape[0] // 8,)}, leaf.sharding
    # lr stays mutable through the sharded state (adjust_hyperp path)
    opt1 = set_learning_rate(opt0, 0.01)
    assert get_learning_rate(opt1) == pytest.approx(0.01)


def test_model_trains_with_zero_and_lr_schedule(mesh8, tmp_path):
    from tests._tiny_models import TinyCifar128

    cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                      print_freq=0, zero_sharding=True,
                      lr_schedule="step", lr_decay_epochs=(1,),
                      snapshot_dir=str(tmp_path))
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    rec = Recorder(rank=0, size=8, print_freq=0)
    m.begin_epoch(0)
    for i in range(3):
        m.train_iter(i, rec)
    m._flush_metrics(rec)
    assert np.isfinite(rec.train_losses).all()
    assert m.adjust_hyperp(1) == pytest.approx(0.002)
    # the schedule's new lr feeds back through the sharded state
    m.train_iter(3, rec)
    m._flush_metrics(rec)
    assert np.isfinite(rec.train_losses).all()
    m.cleanup()


def test_zero_rejects_unsupported(mesh8):
    from tests._tiny_models import TinyCifar

    for bad, msg in [
        (dict(optimizer="lars"), "ELEMENTWISE"),
        (dict(exchange_what="params"), "IS the gradient exchange"),
    ]:
        cfg = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                          **bad)
        with pytest.raises(ValueError, match=msg):
            TinyCifar(config=cfg, mesh=mesh8, verbose=False)
    # the two stacked cadences never nest (same rule as plain BSP)
    cfg = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                      steps_per_call=2, grad_accum_steps=2)
    m = TinyCifar(config=cfg, mesh=mesh8, verbose=False)
    with pytest.raises(ValueError, match="stacked-batch cadences"):
        m.compile_iter_fns("avg")


def test_zero_multi_step_equals_singles(mesh8):
    """ZeRO x steps_per_call (round-3 completion of the cadence
    matrix): the scanned multi-step runs the FULL sharded step —
    reduce_scatter + shard update + all_gather — per sub-step, so its
    trajectory equals k single zero steps with rngs fold_in(rng, i)."""
    from jax.sharding import PartitionSpec as P

    tx = build_optimizer(0.05, optimizer="adamw", momentum=0.9,
                         weight_decay=1e-4)
    params = _params()
    rng = jax.random.key(7)
    k = 3
    rng_np = np.random.default_rng(3)
    xs = rng_np.standard_normal((k, 32, 5)).astype(np.float32)
    ys = rng_np.standard_normal((k, 32, 3)).astype(np.float32)

    multi = make_bsp_zero_step(_loss, tx, mesh8, params, donate=False,
                               multi=True)
    opt0, _ = init_zero_opt_state(tx, params, mesh8)
    s_m = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                     opt_state=opt0, model_state={})
    stacked = shard_batch((xs, ys), mesh8, spec=P(None, AXIS_DATA))
    s_m, metrics = multi(s_m, stacked, rng)
    assert np.asarray(metrics["loss"]).shape == (k,)

    single = make_bsp_zero_step(_loss, tx, mesh8, params, donate=False)
    opt0b, _ = init_zero_opt_state(tx, params, mesh8)
    s_s = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                     opt_state=opt0b, model_state={})
    losses = []
    for i in range(k):
        batch = shard_batch((xs[i], ys[i]), mesh8)
        s_s, m = single(s_s, batch, jax.random.fold_in(rng, i))
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(np.asarray(metrics["loss"]), losses,
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s_m.params),
                    jax.tree.leaves(s_s.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    assert int(s_m.step) == k


def test_zero_stacked_cadence_donates_staged_batch(mesh8):
    """ISSUE 3 copy-done fix reaches the ZeRO cadences too: the
    multi-step lowering donates the two batch leaves on top of the
    state, and donate_batch=False withholds exactly those two."""
    from jax.sharding import PartitionSpec as P

    from tests.test_multi_step import _donated_inputs

    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9)
    params = _params()
    rng_np = np.random.default_rng(9)
    x = rng_np.standard_normal((2, 16, 5)).astype(np.float32)
    y = rng_np.standard_normal((2, 16, 3)).astype(np.float32)

    def donors(**kw):
        zm = make_bsp_zero_step(_loss, tx, mesh8, params, multi=True,
                                **kw)
        opt0, _ = init_zero_opt_state(tx, params, mesh8)
        s = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt0, model_state={})
        stacked = shard_batch((x, y), mesh8, spec=P(None, AXIS_DATA))
        return _donated_inputs(
            zm.lower(s, stacked, jax.random.key(0)).as_text())

    assert donors() == donors(donate_batch=False) + 2
    assert donors(donate=False) == 0


def test_zero_steps_per_call_model_glue(mesh8):
    """The model path (stacked host batches -> train_step_multi) works
    with a SHARDED optimizer state."""
    from tests._tiny_models import TinyCifar128
    from theanompi_tpu.utils.recorder import Recorder

    cfg = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                      steps_per_call=2, n_epochs=1)
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    rec = Recorder(rank=0, size=8, print_freq=0)
    n = m.begin_epoch(0)
    it = 0
    while it < n:
        it += m.train_iter(it, rec)
    m._flush_metrics(rec)
    assert it == n
    assert len(rec.train_losses) == n  # every sub-step recorded
    assert np.isfinite(rec.train_losses).all()
    m.cleanup()


def test_zero_rejects_bf16_strategy_and_variant_models(mesh8):
    from tests._tiny_models import TinyCifar
    from theanompi_tpu.models.transformer import TransformerLM_TP
    from theanompi_tpu.parallel.mesh import MeshSpec, make_training_mesh

    cfg = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                      exchange_strategy="nccl16")
    with pytest.raises(ValueError, match="exchange_dtype"):
        TinyCifar(config=cfg, mesh=mesh8, verbose=False)
    # ... and the modern spelling IS accepted: the reduce_scatter has a
    # quantization seam (see test_zero_bf16_* for the numerics)
    cfg_ok = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                         exchange_dtype="bf16")
    TinyCifar(config=cfg_ok, mesh=mesh8, verbose=False)

    mesh = make_training_mesh(MeshSpec(data=2, model=4),
                              jax.devices()[:8])
    cfg = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                      weight_decay=0.0)
    m = TransformerLM_TP(config=cfg, mesh=mesh, verbose=False,
                         n_layers=1, d_model=32, n_heads=4, seq_len=16)
    with pytest.raises(ValueError, match="zero_sharding is not"):
        m.compile_iter_fns("avg")


def _zero_state(params, tx, mesh, residual=None):
    opt0, _ = init_zero_opt_state(tx, params, mesh)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt0, model_state={},
                      exchange_residual=residual)


def test_zero_bf16_step_close_to_f32(mesh8):
    """ISSUE 5 equivalence pin, ZeRO flavor: the bf16-wire
    reduce-scatter (all_to_all of the quantized flat vector + local
    f32 accumulation) lands within bf16 tolerance of the f32 ZeRO
    step, for both the plain and the error-feedback variant."""
    from theanompi_tpu.parallel.zero import init_zero_exchange_residual

    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9)
    params = _params()
    rng_np = np.random.default_rng(11)
    x = rng_np.standard_normal((32, 5)).astype(np.float32)
    y = rng_np.standard_normal((32, 3)).astype(np.float32)
    batch = shard_batch((x, y), mesh8)
    rng = jax.random.key(3)

    def run(state, **kw):
        step = make_bsp_zero_step(_loss, tx, mesh8, params,
                                  donate=False, **kw)
        for _ in range(3):
            state, m = step(state, batch, rng)
        return state, m

    s_f, m_f = run(_zero_state(params, tx, mesh8))
    s_b, m_b = run(_zero_state(params, tx, mesh8),
                   exchange_dtype="bf16")
    s_e, _ = run(_zero_state(params, tx, mesh8,
                             init_zero_exchange_residual(params, mesh8)),
                 exchange_dtype="bf16", error_feedback=True)
    for name, s_q in (("bf16", s_b), ("bf16+ef", s_e)):
        for a, b in zip(jax.tree.leaves(s_f.params),
                        jax.tree.leaves(s_q.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0.02, atol=2e-3,
                                       err_msg=name)
    assert float(m_b["loss"]) == pytest.approx(float(m_f["loss"]),
                                               rel=0.02)
    # EF residual: per-data-shard rows of the padded flat vector, live
    res = s_e.exchange_residual
    assert res.shape[0] == 8 and np.abs(np.asarray(res)).max() > 0


def test_zero_bf16_validation(mesh8):
    tx = build_optimizer(0.05)
    with pytest.raises(ValueError, match="exchange_dtype"):
        make_bsp_zero_step(_loss, tx, mesh8, _params(),
                           exchange_dtype="f16")
    with pytest.raises(ValueError, match="bf16"):
        make_bsp_zero_step(_loss, tx, mesh8, _params(),
                           error_feedback=True)


def test_zero_bf16_model_glue(mesh8):
    """ModelConfig threading: zero_sharding + exchange_dtype='bf16' +
    error feedback builds, creates the sharded flat residual in
    TrainState, and trains finite."""
    from tests._tiny_models import TinyCifar128

    cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                      print_freq=0, zero_sharding=True,
                      exchange_dtype="bf16",
                      exchange_error_feedback=True)
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    res = m.state.exchange_residual
    assert res is not None and res.ndim == 2 and res.shape[0] == 8
    from theanompi_tpu.utils.recorder import Recorder

    rec = Recorder(rank=0, size=8, print_freq=0)
    m.begin_epoch(0)
    for i in range(2):
        m.train_iter(i, rec)
    m._flush_metrics(rec)
    assert np.isfinite(rec.train_losses).all()
    # the residual is trained state now — it must have moved
    assert np.abs(np.asarray(m.state.exchange_residual)).max() > 0
    m.cleanup()


def test_zero_composes_with_sequence_parallel():
    """ZeRO over (data x seq): extra axes psum plainly, the data axis
    reduce_scatters — one step equals the plain SP step, with the
    optimizer state sharded over 'data' only."""
    from theanompi_tpu.models.transformer import TransformerLM
    from theanompi_tpu.parallel.mesh import MeshSpec, make_training_mesh
    from theanompi_tpu.utils.recorder import Recorder

    mesh = make_training_mesh(MeshSpec(data=2, seq=4), jax.devices()[:8])

    def make(zero):
        cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.05,
                          print_freq=0, weight_decay=0.0, seed=7,
                          zero_sharding=zero)
        return TransformerLM(config=cfg, mesh=mesh, verbose=False,
                             n_layers=1, d_model=32, n_heads=4,
                             seq_len=32)

    losses = {}
    for zero in (False, True):
        m = make(zero)
        m.compile_iter_fns("avg")
        rec = Recorder(rank=0, size=8, print_freq=0)
        m.begin_epoch(0)
        for i in range(2):
            m.train_iter(i, rec)
        m._flush_metrics(rec)
        losses[zero] = list(np.asarray(rec.train_losses))
        if zero:
            vec = [l for l in jax.tree.leaves(m.state.opt_state)
                   if getattr(l, "ndim", 0) == 1 and l.size >= 8]
            assert vec, "momentum vector slots expected"
            # sharded over 'data' (2-way), replicated over 'seq'
            assert {s.data.shape for s in vec[0].addressable_shards} \
                == {(vec[0].shape[0] // 2,)}
        m.cleanup()
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-5,
                               atol=1e-6)


def test_zero_composes_with_grad_accum(mesh8, tmp_path):
    """ZeRO x grad-accum: a microbatches, one sharded update — equals
    the plain grad-accum step (which itself equals the big batch)."""
    from jax.sharding import PartitionSpec as P

    from theanompi_tpu.parallel.bsp import make_bsp_accum_step

    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9)
    params = _params()
    rng_np = np.random.default_rng(5)
    x = rng_np.standard_normal((64, 5)).astype(np.float32)
    y = rng_np.standard_normal((64, 3)).astype(np.float32)
    rng = jax.random.key(1)
    stacked = shard_batch((x.reshape(4, 16, 5), y.reshape(4, 16, 3)),
                          mesh8, spec=P(None, AXIS_DATA))

    plain = make_bsp_accum_step(_loss, tx, mesh8, donate=False)
    s_p, m_p = plain(TrainState.create(params, tx), stacked, rng)

    za = make_bsp_zero_step(_loss, tx, mesh8, params, donate=False,
                            accum=True)
    opt0, _ = init_zero_opt_state(tx, params, mesh8)
    s_z = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                     opt_state=opt0, model_state={})
    s_z, m_z = za(s_z, stacked, rng)

    for a, b in zip(jax.tree.leaves(s_p.params),
                    jax.tree.leaves(s_z.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    assert float(m_z["loss"]) == pytest.approx(float(m_p["loss"]),
                                               rel=1e-5)
    assert int(s_z.step) == 1

    # model plumbing: both knobs on -> accum dispatches, counts hold
    from tests._tiny_models import TinyCifar128
    from theanompi_tpu.utils.recorder import Recorder

    cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                      print_freq=0, zero_sharding=True,
                      grad_accum_steps=4, snapshot_dir=str(tmp_path))
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    rec = Recorder(rank=0, size=8, print_freq=0)
    n_iters = m.begin_epoch(0)
    it = 0
    while it < n_iters:
        assert m.train_iter(it, rec) == 4
        it += 4
    m._flush_metrics(rec)
    assert int(m.state.step) == n_iters // 4
    assert np.isfinite(rec.train_losses).all()
    m.cleanup()
