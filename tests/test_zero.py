"""ZeRO-1 optimizer-state sharding (parallel/zero.py +
ModelConfig.zero_sharding): reduce_scatter/update-shard/all_gather,
step-equal to plain BSP, state physically sharded."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.parallel.bsp import TrainState, make_bsp_train_step
from theanompi_tpu.parallel.mesh import AXIS_DATA, data_mesh, shard_batch
from theanompi_tpu.parallel.zero import (
    init_zero_opt_state,
    make_bsp_zero_step,
)
from theanompi_tpu.utils.helper_funcs import (
    build_optimizer,
    get_learning_rate,
    set_learning_rate,
)
from theanompi_tpu.utils.recorder import Recorder


def _loss(params, model_state, batch, rng):
    x, y = batch
    pred = jnp.tanh(x @ params["w1"]) @ params["w2"] + params["b"]
    loss = jnp.mean((pred - y) ** 2)
    return loss, (model_state, {"loss": loss, "error": loss})


def _params():
    k = jax.random.key(0)
    k1, k2 = jax.random.split(k)
    # deliberately not divisible by 8 so the pad path is exercised
    return {"w1": jax.random.normal(k1, (5, 7)),
            "w2": jax.random.normal(k2, (7, 3)),
            "b": jnp.zeros((3,))}


@pytest.mark.parametrize("opt", ["sgd", "adamw"])
def test_zero_step_equals_plain_bsp(mesh8, opt):
    """N steps of ZeRO == N steps of plain BSP (elementwise update is
    sharding-transparent), while opt state lives 1/8 per device."""
    tx = build_optimizer(0.05, optimizer=opt, momentum=0.9,
                         weight_decay=1e-4)
    params = _params()
    rng_np = np.random.default_rng(1)
    x = rng_np.standard_normal((32, 5)).astype(np.float32)
    y = rng_np.standard_normal((32, 3)).astype(np.float32)
    rng = jax.random.key(2)

    plain = make_bsp_train_step(_loss, tx, mesh8, donate=False)
    s_p = TrainState.create(params, tx)

    zero = make_bsp_zero_step(_loss, tx, mesh8, params, donate=False)
    opt0, specs = init_zero_opt_state(tx, params, mesh8)
    s_z = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                     opt_state=opt0, model_state={})

    batch = shard_batch((x, y), mesh8)
    for _ in range(3):
        s_p, m_p = plain(s_p, batch, rng)
        s_z, m_z = zero(s_z, batch, rng)
    for a, b in zip(jax.tree.leaves(s_p.params),
                    jax.tree.leaves(s_z.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    assert float(m_z["loss"]) == pytest.approx(float(m_p["loss"]),
                                               rel=1e-5)


def test_opt_state_physically_sharded(mesh8):
    tx = build_optimizer(0.1, optimizer="sgd", momentum=0.9)
    params = _params()
    opt0, specs = init_zero_opt_state(tx, params, mesh8)
    vec_leaves = [l for l in jax.tree.leaves(opt0)
                  if getattr(l, "ndim", 0) == 1 and l.size >= 8]
    assert vec_leaves, "expected momentum vector slots"
    for leaf in vec_leaves:
        # each device holds 1/8 of the padded flat vector
        shard_shapes = {s.data.shape for s in leaf.addressable_shards}
        assert shard_shapes == {(leaf.shape[0] // 8,)}, leaf.sharding
    # lr stays mutable through the sharded state (adjust_hyperp path)
    opt1 = set_learning_rate(opt0, 0.01)
    assert get_learning_rate(opt1) == pytest.approx(0.01)


def test_model_trains_with_zero_and_lr_schedule(mesh8, tmp_path):
    from tests._tiny_models import TinyCifar128

    cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                      print_freq=0, zero_sharding=True,
                      lr_schedule="step", lr_decay_epochs=(1,),
                      snapshot_dir=str(tmp_path))
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    rec = Recorder(rank=0, size=8, print_freq=0)
    m.begin_epoch(0)
    for i in range(3):
        m.train_iter(i, rec)
    m._flush_metrics(rec)
    assert np.isfinite(rec.train_losses).all()
    assert m.adjust_hyperp(1) == pytest.approx(0.002)
    # the schedule's new lr feeds back through the sharded state
    m.train_iter(3, rec)
    m._flush_metrics(rec)
    assert np.isfinite(rec.train_losses).all()
    m.cleanup()


def test_zero_rejects_unsupported(mesh8):
    from tests._tiny_models import TinyCifar

    for bad, msg in [
        (dict(optimizer="lars"), "ELEMENTWISE"),
        (dict(exchange_what="params"), "IS the gradient exchange"),
    ]:
        cfg = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                          **bad)
        with pytest.raises(ValueError, match=msg):
            TinyCifar(config=cfg, mesh=mesh8, verbose=False)
    # the two stacked cadences never nest (same rule as plain BSP)
    cfg = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                      steps_per_call=2, grad_accum_steps=2)
    m = TinyCifar(config=cfg, mesh=mesh8, verbose=False)
    with pytest.raises(ValueError, match="stacked-batch cadences"):
        m.compile_iter_fns("avg")


def test_zero_multi_step_equals_singles(mesh8):
    """ZeRO x steps_per_call (round-3 completion of the cadence
    matrix): the scanned multi-step runs the FULL sharded step —
    reduce_scatter + shard update + all_gather — per sub-step, so its
    trajectory equals k single zero steps with rngs fold_in(rng, i)."""
    from jax.sharding import PartitionSpec as P

    tx = build_optimizer(0.05, optimizer="adamw", momentum=0.9,
                         weight_decay=1e-4)
    params = _params()
    rng = jax.random.key(7)
    k = 3
    rng_np = np.random.default_rng(3)
    xs = rng_np.standard_normal((k, 32, 5)).astype(np.float32)
    ys = rng_np.standard_normal((k, 32, 3)).astype(np.float32)

    multi = make_bsp_zero_step(_loss, tx, mesh8, params, donate=False,
                               multi=True)
    opt0, _ = init_zero_opt_state(tx, params, mesh8)
    s_m = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                     opt_state=opt0, model_state={})
    stacked = shard_batch((xs, ys), mesh8, spec=P(None, AXIS_DATA))
    s_m, metrics = multi(s_m, stacked, rng)
    assert np.asarray(metrics["loss"]).shape == (k,)

    single = make_bsp_zero_step(_loss, tx, mesh8, params, donate=False)
    opt0b, _ = init_zero_opt_state(tx, params, mesh8)
    s_s = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                     opt_state=opt0b, model_state={})
    losses = []
    for i in range(k):
        batch = shard_batch((xs[i], ys[i]), mesh8)
        s_s, m = single(s_s, batch, jax.random.fold_in(rng, i))
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(np.asarray(metrics["loss"]), losses,
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s_m.params),
                    jax.tree.leaves(s_s.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    assert int(s_m.step) == k


def test_zero_stacked_cadence_donates_staged_batch(mesh8):
    """ISSUE 3 copy-done fix reaches the ZeRO cadences too: the
    multi-step lowering donates the two batch leaves on top of the
    state, and donate_batch=False withholds exactly those two."""
    from jax.sharding import PartitionSpec as P

    from tests.test_multi_step import _donated_inputs

    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9)
    params = _params()
    rng_np = np.random.default_rng(9)
    x = rng_np.standard_normal((2, 16, 5)).astype(np.float32)
    y = rng_np.standard_normal((2, 16, 3)).astype(np.float32)

    def donors(**kw):
        zm = make_bsp_zero_step(_loss, tx, mesh8, params, multi=True,
                                **kw)
        opt0, _ = init_zero_opt_state(tx, params, mesh8)
        s = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt0, model_state={})
        stacked = shard_batch((x, y), mesh8, spec=P(None, AXIS_DATA))
        return _donated_inputs(
            zm.lower(s, stacked, jax.random.key(0)).as_text())

    assert donors() == donors(donate_batch=False) + 2
    assert donors(donate=False) == 0


def test_zero_steps_per_call_model_glue(mesh8):
    """The model path (stacked host batches -> train_step_multi) works
    with a SHARDED optimizer state."""
    from tests._tiny_models import TinyCifar128
    from theanompi_tpu.utils.recorder import Recorder

    cfg = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                      steps_per_call=2, n_epochs=1)
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    rec = Recorder(rank=0, size=8, print_freq=0)
    n = m.begin_epoch(0)
    it = 0
    while it < n:
        it += m.train_iter(it, rec)
    m._flush_metrics(rec)
    assert it == n
    assert len(rec.train_losses) == n  # every sub-step recorded
    assert np.isfinite(rec.train_losses).all()
    m.cleanup()


def test_zero_rejects_bf16_strategy_and_variant_models(mesh8):
    from tests._tiny_models import TinyCifar
    from theanompi_tpu.models.transformer import TransformerLM_TP
    from theanompi_tpu.parallel.mesh import MeshSpec, make_training_mesh

    cfg = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                      exchange_strategy="nccl16")
    with pytest.raises(ValueError, match="exchange_dtype"):
        TinyCifar(config=cfg, mesh=mesh8, verbose=False)
    # ... and the modern spelling IS accepted: the reduce_scatter has a
    # quantization seam (see test_zero_bf16_* for the numerics)
    cfg_ok = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                         exchange_dtype="bf16")
    TinyCifar(config=cfg_ok, mesh=mesh8, verbose=False)

    mesh = make_training_mesh(MeshSpec(data=2, model=4),
                              jax.devices()[:8])
    cfg = ModelConfig(batch_size=4, print_freq=0, zero_sharding=True,
                      weight_decay=0.0)
    m = TransformerLM_TP(config=cfg, mesh=mesh, verbose=False,
                         n_layers=1, d_model=32, n_heads=4, seq_len=16)
    with pytest.raises(ValueError, match="zero_sharding is not"):
        m.compile_iter_fns("avg")


def _zero_state(params, tx, mesh, residual=None):
    opt0, _ = init_zero_opt_state(tx, params, mesh)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt0, model_state={},
                      exchange_residual=residual)


def test_zero_bf16_step_close_to_f32(mesh8):
    """ISSUE 5 equivalence pin, ZeRO flavor: the bf16-wire
    reduce-scatter (all_to_all of the quantized flat vector + local
    f32 accumulation) lands within bf16 tolerance of the f32 ZeRO
    step, for both the plain and the error-feedback variant."""
    from theanompi_tpu.parallel.zero import init_zero_exchange_residual

    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9)
    params = _params()
    rng_np = np.random.default_rng(11)
    x = rng_np.standard_normal((32, 5)).astype(np.float32)
    y = rng_np.standard_normal((32, 3)).astype(np.float32)
    batch = shard_batch((x, y), mesh8)
    rng = jax.random.key(3)

    def run(state, **kw):
        step = make_bsp_zero_step(_loss, tx, mesh8, params,
                                  donate=False, **kw)
        for _ in range(3):
            state, m = step(state, batch, rng)
        return state, m

    s_f, m_f = run(_zero_state(params, tx, mesh8))
    s_b, m_b = run(_zero_state(params, tx, mesh8),
                   exchange_dtype="bf16")
    s_e, _ = run(_zero_state(params, tx, mesh8,
                             init_zero_exchange_residual(params, mesh8)),
                 exchange_dtype="bf16", error_feedback=True)
    for name, s_q in (("bf16", s_b), ("bf16+ef", s_e)):
        for a, b in zip(jax.tree.leaves(s_f.params),
                        jax.tree.leaves(s_q.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0.02, atol=2e-3,
                                       err_msg=name)
    assert float(m_b["loss"]) == pytest.approx(float(m_f["loss"]),
                                               rel=0.02)
    # EF residual: per-data-shard rows of the padded flat vector, live
    res = s_e.exchange_residual
    assert res.shape[0] == 8 and np.abs(np.asarray(res)).max() > 0


def test_zero_bf16_validation(mesh8):
    tx = build_optimizer(0.05)
    with pytest.raises(ValueError, match="exchange_dtype"):
        make_bsp_zero_step(_loss, tx, mesh8, _params(),
                           exchange_dtype="f16")
    with pytest.raises(ValueError, match="bf16"):
        make_bsp_zero_step(_loss, tx, mesh8, _params(),
                           error_feedback=True)


def test_zero_bf16_model_glue(mesh8):
    """ModelConfig threading: zero_sharding + exchange_dtype='bf16' +
    error feedback builds, creates the sharded flat residual in
    TrainState, and trains finite."""
    from tests._tiny_models import TinyCifar128

    cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                      print_freq=0, zero_sharding=True,
                      exchange_dtype="bf16",
                      exchange_error_feedback=True)
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    res = m.state.exchange_residual
    assert res is not None and res.ndim == 2 and res.shape[0] == 8
    from theanompi_tpu.utils.recorder import Recorder

    rec = Recorder(rank=0, size=8, print_freq=0)
    m.begin_epoch(0)
    for i in range(2):
        m.train_iter(i, rec)
    m._flush_metrics(rec)
    assert np.isfinite(rec.train_losses).all()
    # the residual is trained state now — it must have moved
    assert np.abs(np.asarray(m.state.exchange_residual)).max() > 0
    m.cleanup()


def test_zero_composes_with_sequence_parallel():
    """ZeRO over (data x seq): extra axes psum plainly, the data axis
    reduce_scatters — one step equals the plain SP step, with the
    optimizer state sharded over 'data' only."""
    from theanompi_tpu.models.transformer import TransformerLM
    from theanompi_tpu.parallel.mesh import MeshSpec, make_training_mesh
    from theanompi_tpu.utils.recorder import Recorder

    mesh = make_training_mesh(MeshSpec(data=2, seq=4), jax.devices()[:8])

    def make(zero):
        cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.05,
                          print_freq=0, weight_decay=0.0, seed=7,
                          zero_sharding=zero)
        return TransformerLM(config=cfg, mesh=mesh, verbose=False,
                             n_layers=1, d_model=32, n_heads=4,
                             seq_len=32)

    losses = {}
    for zero in (False, True):
        m = make(zero)
        m.compile_iter_fns("avg")
        rec = Recorder(rank=0, size=8, print_freq=0)
        m.begin_epoch(0)
        for i in range(2):
            m.train_iter(i, rec)
        m._flush_metrics(rec)
        losses[zero] = list(np.asarray(rec.train_losses))
        if zero:
            vec = [l for l in jax.tree.leaves(m.state.opt_state)
                   if getattr(l, "ndim", 0) == 1 and l.size >= 8]
            assert vec, "momentum vector slots expected"
            # sharded over 'data' (2-way), replicated over 'seq'
            assert {s.data.shape for s in vec[0].addressable_shards} \
                == {(vec[0].shape[0] // 2,)}
        m.cleanup()
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-5,
                               atol=1e-6)


def test_zero_composes_with_grad_accum(mesh8, tmp_path):
    """ZeRO x grad-accum: a microbatches, one sharded update — equals
    the plain grad-accum step (which itself equals the big batch)."""
    from jax.sharding import PartitionSpec as P

    from theanompi_tpu.parallel.bsp import make_bsp_accum_step

    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9)
    params = _params()
    rng_np = np.random.default_rng(5)
    x = rng_np.standard_normal((64, 5)).astype(np.float32)
    y = rng_np.standard_normal((64, 3)).astype(np.float32)
    rng = jax.random.key(1)
    stacked = shard_batch((x.reshape(4, 16, 5), y.reshape(4, 16, 3)),
                          mesh8, spec=P(None, AXIS_DATA))

    plain = make_bsp_accum_step(_loss, tx, mesh8, donate=False)
    s_p, m_p = plain(TrainState.create(params, tx), stacked, rng)

    za = make_bsp_zero_step(_loss, tx, mesh8, params, donate=False,
                            accum=True)
    opt0, _ = init_zero_opt_state(tx, params, mesh8)
    s_z = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                     opt_state=opt0, model_state={})
    s_z, m_z = za(s_z, stacked, rng)

    for a, b in zip(jax.tree.leaves(s_p.params),
                    jax.tree.leaves(s_z.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)
    assert float(m_z["loss"]) == pytest.approx(float(m_p["loss"]),
                                               rel=1e-5)
    assert int(s_z.step) == 1

    # model plumbing: both knobs on -> accum dispatches, counts hold
    from tests._tiny_models import TinyCifar128
    from theanompi_tpu.utils.recorder import Recorder

    cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                      print_freq=0, zero_sharding=True,
                      grad_accum_steps=4, snapshot_dir=str(tmp_path))
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    rec = Recorder(rank=0, size=8, print_freq=0)
    n_iters = m.begin_epoch(0)
    it = 0
    while it < n_iters:
        assert m.train_iter(it, rec) == 4
        it += 4
    m._flush_metrics(rec)
    assert int(m.state.step) == n_iters // 4
    assert np.isfinite(rec.train_losses).all()
    m.cleanup()


# ---------------------------------------------------------------------------
# Bucketed exchange (ISSUE 13): per-bucket reduce_scatter/all_to_all,
# embedded in the backward on the single/multi step, layout contract.
# ---------------------------------------------------------------------------


def _zero_bucket_state(tx, params, mesh8, B, ef=False):
    from theanompi_tpu.parallel.zero import init_zero_exchange_residual

    opt0, _ = init_zero_opt_state(tx, params, mesh8, exchange_buckets=B)
    res = (init_zero_exchange_residual(params, mesh8, exchange_buckets=B)
           if ef else None)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt0, model_state={},
                      exchange_residual=res)


def _run_zero_bucketed(mesh8, B, dtype="f32", ef=False, cadence=None,
                       steps=3):
    from jax.sharding import PartitionSpec as P

    from theanompi_tpu.parallel.mesh import shard_batch
    from theanompi_tpu.utils.helper_funcs import build_optimizer

    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9,
                         weight_decay=1e-4)
    params = _params()
    s = _zero_bucket_state(tx, params, mesh8, B, ef)
    kw = dict(exchange_dtype=dtype, error_feedback=ef,
              exchange_buckets=B, donate=False)
    if cadence:
        kw[cadence] = True
    step = make_bsp_zero_step(_loss, tx, mesh8, params, **kw)
    rng_np = np.random.default_rng(1)
    if cadence:
        xs = rng_np.standard_normal((2, 32, 5)).astype(np.float32)
        ys = rng_np.standard_normal((2, 32, 3)).astype(np.float32)
        batch = shard_batch((xs, ys), mesh8, spec=P(None, "data"))
        steps = 1
    else:
        x = rng_np.standard_normal((32, 5)).astype(np.float32)
        y = rng_np.standard_normal((32, 3)).astype(np.float32)
        batch = shard_batch((x, y), mesh8)
    rng = jax.random.key(2)
    traj = []
    for _ in range(steps):
        s, m = step(s, batch, rng)
        traj.append(jax.tree.map(np.asarray, s.params))
    return s, m, traj


@pytest.mark.parametrize("dtype,ef", [("f32", False), ("bf16", False),
                                      ("bf16", True)])
def test_zero_bucketed_identical_to_b1(mesh8, dtype, ef):
    """The acceptance pin on the ZeRO plane: B>1 equals B=1 at every
    step.  f32 is bit-identical; the bf16 variants sit within one f32
    ulp (the per-segment all_to_all programs fuse the quantize/sum
    chain differently from the whole-vector one — reassociation noise,
    not drift; pinned tight so real drift still fails)."""
    exact = dtype == "f32"
    _, m1, traj1 = _run_zero_bucketed(mesh8, 1, dtype, ef)
    for B in (2, 4, 8):
        _, mB, trajB = _run_zero_bucketed(mesh8, B, dtype, ef)
        for t1, tB in zip(traj1, trajB):
            for a, b in zip(jax.tree.leaves(t1), jax.tree.leaves(tB)):
                if exact:
                    np.testing.assert_array_equal(a, b, err_msg=f"B={B}")
                else:
                    np.testing.assert_allclose(a, b, rtol=2e-6,
                                               atol=1e-8,
                                               err_msg=f"B={B}")
        close = (float(m1["loss"]) == float(mB["loss"]) if exact else
                 float(m1["loss"]) == pytest.approx(float(mB["loss"]),
                                                    rel=1e-6))
        assert close


@pytest.mark.parametrize("cadence", ["multi", "accum"])
def test_zero_bucketed_cadences_identical(mesh8, cadence):
    """multi scans the tagged backward-embedded step; accum keeps ONE
    post-accumulation exchange split per bucket — both must equal
    their B=1 twins."""
    _, _, traj1 = _run_zero_bucketed(mesh8, 1, cadence=cadence)
    _, _, traj4 = _run_zero_bucketed(mesh8, 4, cadence=cadence)
    for a, b in zip(jax.tree.leaves(traj1[-1]),
                    jax.tree.leaves(traj4[-1])):
        np.testing.assert_array_equal(a, b, err_msg=cadence)


def test_zero_bucket_layout_properties(mesh8):
    """The layout is a pure function of (leaf shapes, N, B): segments
    are N-divisible, offsets consistent, and B=1 degenerates to the
    historical global flat layout exactly."""
    from theanompi_tpu.parallel.zero import _flat_info, _zero_layout

    params = _params()
    total, pad, per_shard = _flat_info(params, 8)
    l1 = _zero_layout(params, 8, 1)
    assert l1.per_shard == per_shard and l1.total_flat == total + pad
    # the layout-contract enforcement: per-shard length is strictly
    # increasing in the (clamped) bucket count, so resuming a
    # checkpoint under a different exchange_buckets ALWAYS fails on
    # shape — natural pads alone can coincide across bucket counts
    lengths = [_zero_layout(params, 8, B).per_shard
               for B in (1, 2, 3)]  # 3 leaves: clamp caps at 3
    assert lengths == sorted(set(lengths)), lengths
    many = {f"l{i}": np.zeros((8, 4)) for i in range(16)}  # all pads 0
    many_lengths = [_zero_layout(many, 8, B).per_shard
                    for B in (1, 2, 4, 8, 16)]
    assert many_lengths == sorted(set(many_lengths)), many_lengths
    for B in (2, 3):
        lB = _zero_layout(params, 8, B)
        assert lB == _zero_layout(params, 8, B)  # pure
        assert all(s % 8 == 0 for s in lB.seg)
        assert sum(lB.m) == total
        assert lB.per_shard == sum(lB.pb)
        assert lB.total_flat == sum(lB.seg)
        # opt-state shard length is a LAYOUT property: resuming a
        # checkpoint under a different B must fail on shape, not
        # silently misalign (the docstring's layout contract)
        opt0, _ = init_zero_opt_state(
            optax_sgd_momentum(), params, mesh8, exchange_buckets=B)
        vec = [l for l in jax.tree.leaves(opt0)
               if getattr(l, "ndim", 0) == 1 and l.size >= 8]
        assert vec and all(v.shape[0] == 8 * lB.per_shard for v in vec)


def optax_sgd_momentum():
    from theanompi_tpu.utils.helper_funcs import build_optimizer

    return build_optimizer(0.05, optimizer="sgd", momentum=0.9,
                           weight_decay=1e-4)


def test_zero_bucketed_collectives_in_lowering(mesh8):
    """Structural pin: the f32 bucketed step lowers to exactly B
    reduce-scatters (one per bucket), interleaved with backward
    compute — not one whole-vector scatter after the full backward."""
    from theanompi_tpu.utils.helper_funcs import build_optimizer

    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9,
                         weight_decay=1e-4)
    params = _params()

    def lowered(B):
        s = _zero_bucket_state(tx, params, mesh8, B)
        step = make_bsp_zero_step(_loss, tx, mesh8, params,
                                  exchange_buckets=B, donate=False)
        rng_np = np.random.default_rng(1)
        batch = shard_batch(
            (rng_np.standard_normal((32, 5)).astype(np.float32),
             rng_np.standard_normal((32, 3)).astype(np.float32)), mesh8)
        return step.lower(s, batch, jax.random.key(0)).as_text()

    def layout(txt):
        lines = txt.splitlines()
        rs = [i for i, l in enumerate(lines)
              if "stablehlo.reduce_scatter" in l]
        dots = [i for i, l in enumerate(lines)
                if "stablehlo.dot_general" in l]
        return rs, dots

    rs1, dots1 = layout(lowered(1))
    assert len(rs1) == 1
    assert not [d for d in dots1 if d > rs1[0]], \
        "B=1 has backward compute after the scatter"
    # _params() has 3 leaves, so B=4 clamps to 3 per-leaf buckets —
    # assert against the plan's own bucket count
    from theanompi_tpu.parallel.zero import _zero_layout

    for B in (2, 4):
        n_buckets = len(_zero_layout(params, 8, B).ranges)
        rsB, dotsB = layout(lowered(B))
        assert len(rsB) == n_buckets, (B, n_buckets, len(rsB))
        assert [d for d in dotsB if d > rsB[0]], \
            f"B={B}: no backward compute after the first scatter"


def test_zero_bucketed_donation_unchanged(mesh8):
    """Bucketing must not change what the stacked cadence donates
    (aliasing/buffer-donor count identical to B=1)."""
    from jax.sharding import PartitionSpec as P

    from theanompi_tpu.utils.helper_funcs import build_optimizer

    tx = build_optimizer(0.05, optimizer="sgd", momentum=0.9)
    params = _params()

    def donors(B):
        s = _zero_bucket_state(tx, params, mesh8, B)
        step = make_bsp_zero_step(_loss, tx, mesh8, params, multi=True,
                                  exchange_buckets=B)
        rng_np = np.random.default_rng(1)
        xs = rng_np.standard_normal((2, 32, 5)).astype(np.float32)
        ys = rng_np.standard_normal((2, 32, 3)).astype(np.float32)
        stacked = shard_batch((xs, ys), mesh8, spec=P(None, "data"))
        txt = step.lower(s, stacked, jax.random.key(0)).as_text()
        return (txt.count("tf.aliasing_output")
                + txt.count("jax.buffer_donor"))

    assert donors(4) == donors(1) > 0


def test_zero_bucketed_model_glue(mesh8):
    """ModelConfig.exchange_buckets reaches the ZeRO stack end to end:
    the sharded opt state and the residual are built on the SAME
    layout the step uses, and a few iterations train finite."""
    from tests._tiny_models import TinyCifar128

    from theanompi_tpu.utils.recorder import Recorder

    cfg = ModelConfig(batch_size=4, n_epochs=1, learning_rate=0.02,
                      print_freq=0, zero_sharding=True,
                      exchange_buckets=4, exchange_dtype="bf16",
                      exchange_error_feedback=True)
    m = TinyCifar128(config=cfg, mesh=mesh8, verbose=False)
    m.compile_iter_fns("avg")
    rec = Recorder(rank=0, size=8, print_freq=0)
    m.begin_epoch(0)
    for i in range(2):
        m.train_iter(i, rec)
    m._flush_metrics(rec)
    assert np.isfinite(rec.train_losses).all()
    # the residual rides the bucketed layout
    from theanompi_tpu.parallel.zero import _zero_layout

    layout = _zero_layout(m.state.params, 8, 4)
    res = m.state.exchange_residual
    assert res is not None and res.shape == (8, layout.total_flat)
    m.cleanup()
