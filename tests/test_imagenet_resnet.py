"""ImageNet pipeline + ResNet-50 + driver entry points (tiny shapes,
8-device CPU mesh — the harness the reference never had, SURVEY.md §4)."""

import numpy as np
import pytest

from theanompi_tpu.data.imagenet import (
    ImageNet_data,
    prepare_imagenet_shards,
    readahead,
)


def tiny_imagenet(**kw):
    kw.setdefault("crop", 16)
    kw.setdefault("synthetic_n", 256)
    kw.setdefault("synthetic_pool", 8)
    kw.setdefault("synthetic_store", 20)
    return ImageNet_data(**kw)


class TestImageNetSynthetic:
    def test_shapes_and_determinism(self):
        d = tiny_imagenet()
        assert d.synthetic and d.sample_shape == (16, 16, 3)
        b1 = list(d.train_batches(0, 32))
        b2 = list(d.train_batches(0, 32))
        assert len(b1) == d.n_train // 32
        x, y = b1[0]
        assert x.shape == (32, 16, 16, 3) and x.dtype == np.float32
        assert y.shape == (32,) and y.dtype == np.int32
        # epoch order is a pure function of (seed, epoch)
        np.testing.assert_array_equal(b1[0][0], b2[0][0])
        # different epochs differ
        b3 = next(iter(d.train_batches(1, 32)))
        assert not np.array_equal(b1[0][0], b3[0])

    def test_val_deterministic_center_crop(self):
        d = tiny_imagenet()
        v1 = [y for _, y in d.val_batches(32)]
        v2 = [y for _, y in d.val_batches(32)]
        for a, b in zip(v1, v2):
            np.testing.assert_array_equal(a, b)

    def test_async_shard_split(self):
        d = tiny_imagenet()
        n_full = len(list(d.train_batches(0, 16)))
        n_half = len(list(d.train_batches(0, 16, rank=0, size=2)))
        assert n_half == n_full // 2


class TestImageNetFiles:
    def test_shard_roundtrip(self, tmp_path):
        rng = np.random.default_rng(0)
        x = rng.integers(0, 255, (100, 20, 20, 3), dtype=np.uint8)
        y = rng.integers(0, 10, 100).astype(np.int32)
        prepare_imagenet_shards(x, y, str(tmp_path), "train", shard_size=32)
        prepare_imagenet_shards(x[:40], y[:40], str(tmp_path), "val",
                                shard_size=32)
        d = ImageNet_data(data_dir=str(tmp_path), crop=16)
        assert not d.synthetic
        assert d.n_train == 100 and d.n_val == 40
        batches = list(d.train_batches(0, 16))
        # tail samples carry across files: floor(100/16) full batches
        assert len(batches) == 6
        xb, yb = batches[0]
        assert xb.shape == (16, 16, 16, 3)
        # every label yielded must come from the source label set
        assert set(np.concatenate([b[1] for b in batches])) <= set(y.tolist())
        vb = list(d.val_batches(20))
        assert len(vb) == 2

    def test_unequal_shard_iteration_count(self, tmp_path):
        # 3 files x 32 over 2 ranks -> one rank gets 2 files, the other
        # 1; n_train_batches_for must match what each rank yields
        rng = np.random.default_rng(0)
        x = rng.integers(0, 255, (96, 20, 20, 3), dtype=np.uint8)
        y = (np.arange(96) % 10).astype(np.int32)
        prepare_imagenet_shards(x, y, str(tmp_path), "train", shard_size=32)
        d = ImageNet_data(data_dir=str(tmp_path), crop=16)
        for epoch in (0, 1):
            for rank in (0, 1):
                want = d.n_train_batches_for(epoch, 8, rank, 2)
                got = len(list(d.train_batches(epoch, 8, rank, 2)))
                assert want == got
            counts = [d.n_train_batches_for(epoch, 8, r, 2) for r in (0, 1)]
            assert sorted(counts) == [4, 8]

    def test_manifest_written_and_used(self, tmp_path):
        import json
        rng = np.random.default_rng(0)
        x = rng.integers(0, 255, (50, 20, 20, 3), dtype=np.uint8)
        y = (np.arange(50) % 10).astype(np.int32)
        prepare_imagenet_shards(x, y, str(tmp_path), "train", shard_size=32)
        mpath = tmp_path / "manifest.json"
        assert mpath.exists()
        m = json.loads(mpath.read_text())
        assert m == {"train_0000.x.npy": 32, "train_0001.x.npy": 18}
        d = ImageNet_data(data_dir=str(tmp_path), crop=16)
        assert d.n_train == 50

    def test_rank_file_sharding(self, tmp_path):
        rng = np.random.default_rng(0)
        x = rng.integers(0, 255, (64, 20, 20, 3), dtype=np.uint8)
        y = np.arange(64).astype(np.int32) % 10
        prepare_imagenet_shards(x, y, str(tmp_path), "train", shard_size=16)
        d = ImageNet_data(data_dir=str(tmp_path), crop=16)
        got0 = [b[1] for b in d.train_batches(0, 8, rank=0, size=2)]
        got1 = [b[1] for b in d.train_batches(0, 8, rank=1, size=2)]
        assert len(got0) == len(got1) == 4  # 2 files x 16 / batch 8


def test_readahead_order_and_errors():
    out = list(readahead([1, 2, 3], lambda v: v * 2))
    assert out == [2, 4, 6]
    with pytest.raises(ValueError):
        def bad(v):
            raise ValueError("boom")
        list(readahead([1], bad))


class TestResNet50:
    def make(self, mesh8):
        import jax.numpy as jnp
        from theanompi_tpu.models.base import ModelConfig
        from theanompi_tpu.models.resnet50 import ResNet, ResNet50

        class TinyRN(ResNet50):
            def build_data(self):
                return tiny_imagenet(synthetic_n=512)

            def build_module(self):
                return ResNet(stage_sizes=(1, 1, 1, 1), width=8,
                              n_classes=self.data.n_classes,
                              dtype=jnp.float32)

        cfg = ModelConfig(batch_size=2, n_epochs=1, compute_dtype="float32",
                          print_freq=4, track_top5=True)
        return TinyRN(config=cfg, mesh=mesh8)

    @pytest.mark.slow
    def test_train_and_val(self, mesh8):
        from theanompi_tpu.utils.recorder import Recorder

        m = self.make(mesh8)
        assert m.global_batch == 16
        m.compile_iter_fns("avg")
        rec = Recorder(rank=1, size=8, print_freq=4)
        m.begin_epoch(0)
        losses = []
        for i in range(6):
            m.train_iter(i, rec)
        m._flush_metrics(rec)
        assert np.isfinite(m.current_info["loss"])
        v = m.val_epoch(rec)
        assert "top5_error" in v and 0.0 <= v["error"] <= 1.0
        m.cleanup()

    @pytest.mark.slow  # fast-set coverage: the BN-movement assert in
    # test_device_augment.py's e2e (same contract, one compile)
    def test_bn_state_updates(self, mesh8):
        from theanompi_tpu.utils.recorder import Recorder
        import jax

        m = self.make(mesh8)
        m.compile_iter_fns("avg")
        before = jax.tree.map(np.asarray, m.state.model_state)
        rec = Recorder(rank=1, size=8, print_freq=100)
        m.begin_epoch(0)
        m.train_iter(0, rec)
        m._flush_metrics(rec)
        after = jax.tree.map(np.asarray, m.state.model_state)
        leaves_b = jax.tree.leaves(before)
        leaves_a = jax.tree.leaves(after)
        assert leaves_b and any(
            not np.allclose(a, b) for a, b in zip(leaves_a, leaves_b))
        m.cleanup()


class TestSyncBN:
    """ModelConfig.sync_bn — cross-replica BN (round-4: per-shard
    stats from a 4-image shard were too noisy to serve eval, observed
    as chance val error at converged train loss in the jpeg e2e)."""

    def test_small_shard_batch_warns_without_sync_bn(self, mesh8):
        """A BN model compiled with a small per-shard batch and
        sync_bn=False must warn (the silent-recurrence guard the
        round-4 verdict demanded, weak #4); sync_bn=True and a big
        batch must both stay silent."""
        import dataclasses
        import warnings

        import jax.numpy as jnp
        from theanompi_tpu.models.base import ModelConfig
        from theanompi_tpu.models.resnet50 import ResNet, ResNet50

        class TinyRN(ResNet50):
            def build_data(self):
                return tiny_imagenet(synthetic_n=512)

            def build_module(self):
                return ResNet(stage_sizes=(1,), width=8,
                              n_classes=self.data.n_classes,
                              dtype=jnp.float32,
                              bn_axis=self._bn_axis())

        cfg = ModelConfig(batch_size=2, n_epochs=1,
                          compute_dtype="float32", print_freq=10**9)
        with pytest.warns(UserWarning, match="sync_bn"):
            m = TinyRN(config=cfg, mesh=mesh8)
            m.compile_iter_fns("avg")
        m.cleanup()

        with warnings.catch_warnings():
            # escalate only the guarded warning: a blanket 'error'
            # would make this test fail on unrelated library
            # deprecations inside the jit trace
            warnings.filterwarnings("error", message=".*sync_bn.*")
            m = TinyRN(config=dataclasses.replace(cfg, sync_bn=True),
                       mesh=mesh8)
            m.compile_iter_fns("avg")
            m.cleanup()
            m = TinyRN(config=dataclasses.replace(cfg, batch_size=16),
                       mesh=mesh8)
            m.compile_iter_fns("avg")
            m.cleanup()

    def test_sync_bn_equals_whole_batch_stats(self, mesh8):
        """The defining invariant: train-mode forward with sync BN over
        8 shards == plain BN over the full batch on one device — both
        the logits and the updated running stats."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from theanompi_tpu.models.resnet50 import ResNet

        kw = dict(stage_sizes=(1,), width=8, n_classes=4,
                  dtype=jnp.float32)
        plain = ResNet(**kw)
        sync = ResNet(**kw, bn_axis="data")
        x = jax.random.normal(jax.random.key(0), (32, 32, 32, 3))
        variables = plain.init({"params": jax.random.key(1)}, x[:2],
                               train=True)

        logits_ref, upd_ref = plain.apply(
            variables, x, train=True, mutable=["batch_stats"])

        def shard_fwd(variables, xs):
            logits, upd = sync.apply(variables, xs, train=True,
                                     mutable=["batch_stats"])
            return logits, upd

        sharded = jax.jit(jax.shard_map(
            shard_fwd, mesh=mesh8,
            in_specs=(P(), P("data")), out_specs=(P("data"), P()),
            check_vma=False))
        logits_sync, upd_sync = sharded(variables, x)

        np.testing.assert_allclose(np.asarray(logits_sync),
                                   np.asarray(logits_ref),
                                   rtol=2e-4, atol=2e-4)
        for a, b in zip(jax.tree.leaves(upd_sync),
                        jax.tree.leaves(upd_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_per_shard_bn_differs_from_whole_batch(self, mesh8):
        """Control for the test above: WITHOUT sync_bn, per-shard
        stats genuinely differ from whole-batch stats (otherwise the
        equality test would be vacuous)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from theanompi_tpu.models.resnet50 import ResNet

        plain = ResNet(stage_sizes=(1,), width=8, n_classes=4,
                       dtype=jnp.float32)
        x = jax.random.normal(jax.random.key(0), (32, 32, 32, 3))
        variables = plain.init({"params": jax.random.key(1)}, x[:2],
                               train=True)
        _, upd_ref = plain.apply(variables, x, train=True,
                                 mutable=["batch_stats"])

        def shard_fwd(variables, xs):
            _, upd = plain.apply(variables, xs, train=True,
                                 mutable=["batch_stats"])
            # per-shard stats diverge across devices; pmean them like
            # the BSP step does before comparing
            return jax.tree.map(lambda v: jax.lax.pmean(v, "data"), upd)

        sharded = jax.jit(jax.shard_map(
            shard_fwd, mesh=mesh8, in_specs=(P(), P("data")),
            out_specs=P(), check_vma=False))
        upd_shard = sharded(variables, x)
        diffs = [float(np.abs(np.asarray(a) - np.asarray(b)).max())
                 for a, b in zip(jax.tree.leaves(upd_shard),
                                 jax.tree.leaves(upd_ref))]
        assert max(diffs) > 1e-4, diffs

    def test_sync_bn_rejected_with_fsdp(self, mesh8):
        import dataclasses

        from tests._tiny_models import TinyRecipeResNet

        cfg = dataclasses.replace(
            TinyRecipeResNet.default_config(), batch_size=2,
            sync_bn=True, fsdp_sharding=True, print_freq=0)
        m = TinyRecipeResNet(config=cfg, mesh=mesh8, verbose=False)
        with pytest.raises(ValueError, match="sync_bn"):
            m.compile_iter_fns("avg")

    def test_sync_bn_trains_through_bsp_step(self, mesh8):
        """One real train_iter with sync_bn on — the axis name resolves
        inside the BSP shard_map step and stats move."""
        import dataclasses

        import jax
        from tests._tiny_models import TinyRecipeResNet
        from theanompi_tpu.utils.recorder import Recorder

        cfg = dataclasses.replace(
            TinyRecipeResNet.default_config(), batch_size=2, n_epochs=1,
            sync_bn=True, print_freq=0)
        m = TinyRecipeResNet(config=cfg, mesh=mesh8, verbose=False)
        m.compile_iter_fns("avg")
        before = jax.tree.map(np.asarray, m.state.model_state)
        rec = Recorder(rank=0, size=8, print_freq=100)
        try:
            m.begin_epoch(0)
            m.train_iter(0, rec)
            m._flush_metrics(rec)
        finally:
            m.cleanup()
        after = jax.tree.map(np.asarray, m.state.model_state)
        assert any(not np.allclose(a, b)
                   for a, b in zip(jax.tree.leaves(after),
                                   jax.tree.leaves(before)))


@pytest.mark.slow
def test_graft_entry_dryrun():
    # conftest already pinned cpu + 8 virtual devices, so the dryrun's
    # own forcing is a no-op and 8 devices are available.
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


class TestS2dStem:
    def test_space_to_depth_layout(self):
        import jax.numpy as jnp

        from theanompi_tpu.models.resnet50 import space_to_depth

        x = jnp.arange(2 * 4 * 4 * 3).reshape(2, 4, 4, 3)
        y = space_to_depth(x, 2)
        assert y.shape == (2, 2, 2, 12)
        # block (0,0) channels = pixels (0,0),(0,1),(1,0),(1,1) in
        # (row-offset, col-offset, channel) order
        np.testing.assert_array_equal(
            np.asarray(y[0, 0, 0]),
            np.concatenate([np.asarray(x[0, 0, 0]), np.asarray(x[0, 0, 1]),
                            np.asarray(x[0, 1, 0]), np.asarray(x[0, 1, 1])]))

    def test_s2d_stem_exactly_matches_conv7(self):
        """The s2d stem is a re-parameterization, not an approximation:
        transplanting a trained 7x7 kernel through
        s2d_stem_kernel_from_conv7 reproduces the conv7 network's
        output on random input."""
        import jax
        import jax.numpy as jnp

        from theanompi_tpu.models.resnet50 import (
            ResNet,
            s2d_stem_kernel_from_conv7,
        )

        kw = dict(stage_sizes=(1,), width=8, n_classes=4)
        m7 = ResNet(stem="conv7", **kw)
        ms = ResNet(stem="s2d", **kw)
        x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
        v7 = m7.init(jax.random.key(1), x, train=True)
        vs = jax.tree.map(jnp.copy, v7)
        vs["params"]["stem_conv"]["Conv_0"]["kernel"] = (
            s2d_stem_kernel_from_conv7(
                v7["params"]["stem_conv"]["Conv_0"]["kernel"]))
        out7 = m7.apply(v7, x, train=False)
        outs = ms.apply(vs, x, train=False)
        np.testing.assert_allclose(np.asarray(outs), np.asarray(out7),
                                   rtol=1e-5, atol=1e-5)


def test_stem_pool_relu_swap_is_exact():
    """relu(max_pool(x)) must equal max_pool(relu(x)) bit-for-bit —
    values AND gradients — including window padding and all-negative
    windows (the round-5 stem reorder that moves the relu onto the 4x
    smaller pooled tensor rides on this identity)."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    x = jax.random.normal(jax.random.key(0), (2, 12, 12, 5)) * 3.0
    # force some all-negative pool windows
    x = x.at[:, :4, :4, :].set(-jnp.abs(x[:, :4, :4, :]))

    def pool_then_relu(x):
        return nn.relu(nn.max_pool(x, (3, 3), (2, 2),
                                   padding=[(1, 1), (1, 1)]))

    def relu_then_pool(x):
        return nn.max_pool(nn.relu(x), (3, 3), (2, 2),
                           padding=[(1, 1), (1, 1)])

    a, b = pool_then_relu(x), relu_then_pool(x)
    assert (a == b).all()

    ga = jax.grad(lambda x: (pool_then_relu(x) ** 2).sum())(x)
    gb = jax.grad(lambda x: (relu_then_pool(x) ** 2).sum())(x)
    assert (ga == gb).all()
