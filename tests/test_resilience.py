"""Resilience subsystem (theanompi_tpu/resilience): retry-policy math,
fault-plan matching, supervisor restart/quorum semantics, checkpoint
integrity + corrupt-latest fallback, ServiceClient reconnect through a
server restart, and the fault-matrix e2e (EASGD worker killed mid-run
recovers from center) — plus the strict faults-disabled no-op
contract, the same discipline test_monitor.py pins for telemetry."""

from __future__ import annotations

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from theanompi_tpu import monitor
from theanompi_tpu.resilience import faults, recovery
from theanompi_tpu.resilience.faults import FaultInjected, FaultPlan
from theanompi_tpu.resilience.retry import RetryPolicy
from theanompi_tpu.resilience.supervisor import WorkerSupervisor


@pytest.fixture(autouse=True)
def fresh_resilience():
    faults.clear()
    monitor.reset_for_tests()
    yield
    faults.clear()
    monitor.reset_for_tests()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_delay_growth_and_cap(self):
        p = RetryPolicy(base_delay=0.1, max_delay=1.0, multiplier=2.0,
                        jitter=0.0)
        assert p.delay(0) == pytest.approx(0.1)
        assert p.delay(1) == pytest.approx(0.2)
        assert p.delay(2) == pytest.approx(0.4)
        assert p.delay(10) == pytest.approx(1.0)  # capped

    def test_jitter_bounds(self):
        p = RetryPolicy(base_delay=1.0, max_delay=1.0, jitter=0.5)
        for _ in range(100):
            assert 0.5 <= p.delay(0) <= 1.0

    def test_call_retries_transient_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionResetError("transient")
            return "ok"

        p = RetryPolicy(max_attempts=5, base_delay=0.001, jitter=0.0)
        assert p.call(flaky) == "ok"
        assert len(calls) == 3

    def test_call_does_not_retry_unretryable(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("logic bug")

        p = RetryPolicy(max_attempts=5, base_delay=0.001)
        with pytest.raises(ValueError):
            p.call(bad)
        assert len(calls) == 1

    def test_call_exhausts_attempts(self):
        calls = []

        def down():
            calls.append(1)
            raise ConnectionRefusedError("down")

        p = RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0)
        with pytest.raises(ConnectionRefusedError):
            p.call(down)
        assert len(calls) == 3

    def test_deadline_stops_early(self):
        def down():
            raise ConnectionRefusedError("down")

        p = RetryPolicy(max_attempts=100, base_delay=0.2, jitter=0.0,
                        deadline_s=0.05)
        t0 = time.monotonic()
        with pytest.raises(ConnectionRefusedError):
            p.call(down)
        assert time.monotonic() - t0 < 1.0

    def test_classifier_wins_over_types(self):
        p = RetryPolicy(max_attempts=3, base_delay=0.001,
                        classify=lambda e: "retry me" in str(e))
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise ValueError("please retry me")
            return 7

        assert p.call(flaky) == 7
        assert len(calls) == 2


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_disabled_is_strict_noop(self):
        """The acceptance contract (same pattern as the monitor's
        zero-write guarantee): with no plan installed, every fire()
        site returns None after one is-None check, no wrapper objects
        exist, and the registry sees ZERO writes."""
        assert faults.enabled() is False
        assert faults._plan is None  # no lurking plan object
        for _ in range(100):
            assert faults.fire("worker_step", rule="easgd", worker=0,
                               step=1) is None
            assert faults.fire("service_call", op="easgd_exchange") is None
            assert faults.fire("checkpoint", epoch=0) is None
            assert faults.fire("exchange", kind="gosgd") is None
        assert monitor.registry().write_count == 0
        assert monitor.registry().series_names() == set()

    def test_raise_action_with_coordinates(self):
        faults.install([{"site": "worker_step", "worker": 1, "step": 3}])
        # wrong worker / wrong step: no fire
        assert faults.fire("worker_step", worker=0, step=3) is None
        assert faults.fire("worker_step", worker=1, step=2) is None
        with pytest.raises(FaultInjected, match="worker_step"):
            faults.fire("worker_step", worker=1, step=3)
        # times=1 default: consumed
        assert faults.fire("worker_step", worker=1, step=3) is None

    def test_int_vs_str_coordinates_equal(self):
        faults.install([{"site": "worker_step", "worker": "1"}])
        with pytest.raises(FaultInjected):
            faults.fire("worker_step", worker=1, step=0)

    def test_nth_and_times(self):
        faults.install([{"site": "service_call", "op": "x",
                         "action": "drop", "nth": 2, "times": 2}])
        assert faults.fire("service_call", op="x") is None      # 1st
        assert faults.fire("service_call", op="x") == "drop"    # 2nd
        assert faults.fire("service_call", op="x") == "drop"    # 3rd
        assert faults.fire("service_call", op="x") is None      # 4th

    def test_times_minus_one_fires_forever(self):
        faults.install([{"site": "exchange", "action": "drop",
                         "times": -1}])
        for _ in range(10):
            assert faults.fire("exchange", kind="easgd") == "drop"

    def test_delay_action_sleeps(self):
        faults.install([{"site": "service_call", "action": "delay",
                         "delay_s": 0.05}])
        t0 = time.monotonic()
        assert faults.fire("service_call", op="y") == "delay"
        assert time.monotonic() - t0 >= 0.04

    def test_load_inline_and_file(self, tmp_path):
        plan = faults.load('[{"site": "a"}]')
        assert isinstance(plan, FaultPlan) and len(plan) == 1
        path = tmp_path / "plan.json"
        path.write_text(json.dumps(
            [{"site": "b"}, {"site": "c", "action": "drop"}]))
        assert len(faults.load(str(path))) == 2

    def test_env_install(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, '[{"site": "z"}]')
        faults.install_from_env()
        assert faults.enabled()
        with pytest.raises(FaultInjected):
            faults.fire("z")
        monkeypatch.delenv(faults.ENV_VAR)
        faults.install_from_env()
        assert not faults.enabled()

    def test_bad_spec_rejected(self):
        with pytest.raises(ValueError, match="site"):
            FaultPlan([{"action": "raise"}])
        with pytest.raises(ValueError, match="nth"):
            FaultPlan([{"site": "a", "nth": 0}])


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------


class TestWorkerSupervisor:
    def test_restart_within_budget_completes(self):
        died = {"n": 0}
        restarted = []

        def worker(abort):
            if died["n"] < 2:
                died["n"] += 1
                raise FaultInjected("boom")

        sup = WorkerSupervisor(n_workers=1, max_restarts=2,
                               restart_from=restarted.append)
        sup.run([worker])
        assert restarted == [0, 0]
        assert sup.restart_counts() == {0: 2}
        assert sup.lost_workers() == []

    def test_budget_exhausted_quorum_lost_aborts(self):
        def worker(abort):
            raise FaultInjected("always dies")

        sup = WorkerSupervisor(n_workers=1, max_restarts=1,
                               restart_from=lambda r: None)
        with pytest.raises(FaultInjected):
            sup.run([worker])
        assert sup.lost_workers() == [0]

    def test_lost_worker_with_quorum_continues(self):
        lost_hook = []
        finished = []

        def dying(abort):
            raise FaultInjected("dead on arrival")

        def healthy(abort):
            finished.append(True)

        sup = WorkerSupervisor(n_workers=2, max_restarts=1,
                               min_workers=1, restart_from=None,
                               on_lost=lost_hook.append)
        sup.run([dying, healthy])  # must NOT raise
        assert lost_hook == [0]
        assert finished == [True]
        assert sup.lost_workers() == [0]

    def test_quorum_loss_aborts_peers(self):
        def dying(abort):
            raise FaultInjected("dead")

        def patient(abort):
            # cooperative loop: exits promptly on abort
            for _ in range(500):
                if abort.is_set():
                    return
                time.sleep(0.01)

        sup = WorkerSupervisor(n_workers=2, max_restarts=0,
                               min_workers=2, restart_from=None)
        t0 = time.monotonic()
        with pytest.raises(FaultInjected):
            sup.run([dying, patient])
        assert time.monotonic() - t0 < 4.0  # peers aborted, not run out

    def test_base_exception_is_fatal_despite_budget(self):
        def worker(abort):
            raise KeyboardInterrupt()

        sup = WorkerSupervisor(n_workers=1, max_restarts=5,
                               restart_from=lambda r: None)
        with pytest.raises(KeyboardInterrupt):
            sup.run([worker])
        assert sup.restart_counts() == {}

    def test_failing_restart_hook_aborts(self):
        def worker(abort):
            raise FaultInjected("boom")

        def bad_restart(rank):
            raise ConnectionError("center unreachable")

        sup = WorkerSupervisor(n_workers=1, max_restarts=3,
                               restart_from=bad_restart)
        with pytest.raises(ConnectionError):
            sup.run([worker])

    def test_extra_target_failure_aborts(self):
        def worker(abort):
            for _ in range(500):
                if abort.is_set():
                    return
                time.sleep(0.01)

        def orchestrator(abort):
            raise RuntimeError("validation exploded")

        sup = WorkerSupervisor(n_workers=1, max_restarts=2,
                               restart_from=lambda r: None)
        with pytest.raises(RuntimeError, match="validation exploded"):
            sup.run([worker], extra=[orchestrator])

    def test_restart_resumes_worker_closure_state(self):
        """The rules' restart pattern (code-review finding): worker
        closures carry a mutable ``progress`` dict OUTSIDE the target
        fn, so a supervised re-invocation resumes at the epoch the
        worker died in — NOT at the start epoch (which would retrain
        redundantly and, for ASGD rank 0, re-push the early-schedule
        LR to the server)."""
        seen = []
        progress = {"epoch": 0}

        def worker(abort):
            for epoch in range(progress["epoch"], 3):
                progress["epoch"] = epoch
                seen.append(epoch)
                if epoch == 1 and seen.count(1) == 1:
                    raise FaultInjected("die mid-epoch 1")

        sup = WorkerSupervisor(n_workers=1, max_restarts=1,
                               restart_from=lambda r: None)
        sup.run([worker])
        assert seen == [0, 1, 1, 2]  # epoch 0 NOT re-run

    def test_note_straggler_edges(self, tmp_path):
        sup = WorkerSupervisor(n_workers=2, max_restarts=1,
                               restart_from=lambda r: None)
        with monitor.session(run_dir=str(tmp_path)):
            sup.note_straggler(1, True)
            sup.note_straggler(1, True)   # no double count
            assert sup.stragglers() == [1]
            sup.note_straggler(1, False)  # recovery clears
            assert sup.stragglers() == []
            sup.note_straggler(1, True)
            assert monitor.registry().value(
                "resilience/straggler_handoffs_total", worker="1") == 2


# ---------------------------------------------------------------------------
# checkpoint integrity + recovery
# ---------------------------------------------------------------------------


def _payload(v: float):
    return {"state": {"w": np.full((4, 3), v, np.float32)}, "epoch": 0}


class TestCheckpointIntegrity:
    def test_manifest_written_and_verifies(self, tmp_path):
        from theanompi_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path), async_save=False)
        ckpt.save(0, _payload(1.0))
        ckpt.close()
        assert os.path.exists(recovery.manifest_path(str(tmp_path), 0))
        ok, detail = recovery.verify_checkpoint(str(tmp_path), 0)
        assert ok is True, detail

    def test_truncation_detected(self, tmp_path):
        from theanompi_tpu.utils.checkpoint import Checkpointer
        from theanompi_tpu.utils.checkpoint import _truncate_largest_file

        ckpt = Checkpointer(str(tmp_path), async_save=False)
        ckpt.save(0, _payload(1.0))
        ckpt.close()
        _truncate_largest_file(recovery.find_step_dir(str(tmp_path), 0))
        ok, detail = recovery.verify_checkpoint(str(tmp_path), 0)
        assert ok is False
        assert "mismatch" in detail or "missing" in detail

    def test_corrupt_latest_falls_back_to_previous(self, tmp_path):
        """The acceptance-criteria case: truncated-latest restore
        falls back to the previous kept epoch.  The corrupt step dir
        is QUARANTINED so the resumed run's save of that epoch really
        writes (orbax silently skips saves to an existing step) and
        no later manifest pass re-blesses the corrupt files
        (code-review finding)."""
        from theanompi_tpu.utils.checkpoint import Checkpointer
        from theanompi_tpu.utils.checkpoint import _truncate_largest_file

        ckpt = Checkpointer(str(tmp_path), async_save=False)
        ckpt.save(0, _payload(1.0))
        ckpt.save(1, _payload(2.0))
        ckpt.close()
        _truncate_largest_file(recovery.find_step_dir(str(tmp_path), 1))

        ckpt2 = Checkpointer(str(tmp_path), async_save=False)
        epoch, payload = ckpt2.restore_latest_verified(like=_payload(0.0))
        assert epoch == 0
        np.testing.assert_allclose(payload["state"]["w"], 1.0)
        # corrupt epoch 1 was quarantined: step dir gone, manifest
        # gone, corpse preserved for forensics
        assert recovery.find_step_dir(str(tmp_path), 1) is None
        assert not os.path.exists(recovery.manifest_path(str(tmp_path), 1))
        assert os.path.isdir(tmp_path / "quarantine" / "1")
        # ...so re-saving epoch 1 actually persists and verifies
        ckpt2.save(1, _payload(5.0))
        ckpt2.close()
        ok, detail = recovery.verify_checkpoint(str(tmp_path), 1)
        assert ok is True, detail
        ckpt3 = Checkpointer(str(tmp_path))
        epoch, payload = ckpt3.restore_latest_verified(like=_payload(0.0))
        ckpt3.close()
        assert epoch == 1
        np.testing.assert_allclose(payload["state"]["w"], 5.0)

    def test_intact_latest_restores_latest(self, tmp_path):
        from theanompi_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path), async_save=False)
        ckpt.save(0, _payload(1.0))
        ckpt.save(1, _payload(2.0))
        epoch, payload = ckpt.restore_latest_verified(like=_payload(0.0))
        ckpt.close()
        assert epoch == 1
        np.testing.assert_allclose(payload["state"]["w"], 2.0)

    def test_empty_dir_returns_none(self, tmp_path):
        from theanompi_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path))
        epoch, payload = ckpt.restore_latest_verified()
        ckpt.close()
        assert epoch is None and payload is None

    def test_legacy_checkpoint_without_manifest_still_restores(
            self, tmp_path):
        from theanompi_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path), async_save=False,
                            integrity=False)  # pre-resilience writer
        ckpt.save(0, _payload(3.0))
        ckpt.close()
        assert not os.path.exists(recovery.manifest_path(str(tmp_path), 0))
        ckpt2 = Checkpointer(str(tmp_path))
        epoch, payload = ckpt2.restore_latest_verified(like=_payload(0.0))
        ckpt2.close()
        assert epoch == 0
        np.testing.assert_allclose(payload["state"]["w"], 3.0)

    def test_manifests_pruned_with_max_to_keep(self, tmp_path):
        from theanompi_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path), max_to_keep=2,
                            async_save=False)
        for e in range(4):
            ckpt.save(e, _payload(float(e)))
        ckpt.close()
        manifests = sorted(p for p in os.listdir(tmp_path)
                           if p.startswith("manifest_"))
        assert manifests == ["manifest_2.json", "manifest_3.json"]

    def test_fault_plan_truncate_action(self, tmp_path):
        """The 'checkpoint write landed corrupt' fault: the plan
        truncates epoch 1 AFTER its manifest is written, so the next
        verified restore falls back to epoch 0."""
        from theanompi_tpu.utils.checkpoint import Checkpointer

        faults.install([{"site": "checkpoint", "epoch": 1,
                         "action": "truncate"}])
        ckpt = Checkpointer(str(tmp_path), async_save=False)
        ckpt.save(0, _payload(1.0))
        ckpt.save(1, _payload(2.0))
        ckpt.close()
        faults.clear()
        ckpt2 = Checkpointer(str(tmp_path))
        epoch, payload = ckpt2.restore_latest_verified(like=_payload(0.0))
        ckpt2.close()
        assert epoch == 0
        np.testing.assert_allclose(payload["state"]["w"], 1.0)


# ---------------------------------------------------------------------------
# service: reconnect through faults and a full server restart
# ---------------------------------------------------------------------------


def _start_service(port):
    from theanompi_tpu.parallel.service import serve

    ready, stop = threading.Event(), threading.Event()
    t = threading.Thread(target=serve,
                         args=("127.0.0.1", port, ready, stop),
                         daemon=True)
    t.start()
    assert ready.wait(10)
    return t, stop


@pytest.fixture()
def service_env(monkeypatch):
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_KEY", "resilience-test")
    # fast client retry so failure paths stay test-speed
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_RETRIES", "6")
    monkeypatch.setenv("THEANOMPI_TPU_SERVICE_RETRY_DEADLINE_S", "20")


class TestServiceResilience:
    def test_call_survives_injected_drop(self, service_env):
        from theanompi_tpu.parallel.service import RemoteEASGD

        port = _free_port()
        t, stop = _start_service(port)
        try:
            faults.install([{"site": "service_call",
                             "op": "easgd_exchange", "action": "drop"}])
            params = {"w": np.ones((3,), np.float32)}
            srv = RemoteEASGD(f"127.0.0.1:{port}", params, alpha=0.5,
                              session_id="drop-test")
            # the dropped RPC reconnects, rejoins, re-sends — the
            # caller never sees the transport failure
            out = srv.exchange({"w": np.full((3,), 3.0, np.float32)})
            np.testing.assert_allclose(out["w"], 2.0)  # 3 - 0.5*(3-1)
            srv.close()
        finally:
            stop.set()
            _shutdown_service(port)
            t.join(timeout=5)

    def test_client_survives_server_restart(self, service_env, rpc_loop):
        """Acceptance-criteria case: a ServiceClient reconnects
        through a full parameter-service restart (new process-worth of
        state: the store is GONE) without losing session state — the
        rejoin rebuilds the center from the client's last good
        params."""
        from theanompi_tpu.parallel.service import RemoteEASGD

        port = _free_port()
        t1, stop1 = _start_service(port)
        params = {"w": np.zeros((3,), np.float32)}
        srv = RemoteEASGD(f"127.0.0.1:{port}", params, alpha=0.5,
                          session_id="restart-test")
        out1 = srv.exchange({"w": np.full((3,), 2.0, np.float32)})
        np.testing.assert_allclose(out1["w"], 1.0)  # 2 - 0.5*(2-0)

        # hard server restart on the same port: all stores lost
        stop1.set()
        _shutdown_service(port)
        t1.join(timeout=5)
        t2, stop2 = _start_service(port)
        try:
            # next exchange: transport error -> reconnect -> rejoin
            # rebuilds the center from the last exchange result (1.0)
            out2 = srv.exchange({"w": np.full((3,), 5.0, np.float32)})
            np.testing.assert_allclose(out2["w"], 3.0)  # 5 - 0.5*(5-1)
            srv.close()
        finally:
            stop2.set()
            _shutdown_service(port)
            t2.join(timeout=5)

    def test_joiner_rejoins_once_peer_rebuilds(self, service_env):
        """A join-only client (no rebuild payload) must keep RETRYING
        its rejoin across attempts until a payload-bearing peer has
        rebuilt the store — not die on the first op the restarted
        server rejects (code-review finding)."""
        from theanompi_tpu.parallel.service import RemoteEASGD

        port = _free_port()
        t1, stop1 = _start_service(port)
        params = {"w": np.zeros((2,), np.float32)}
        creator = RemoteEASGD(f"127.0.0.1:{port}", params, alpha=0.5,
                              session_id="joiner-test")
        creator.exchange({"w": np.full((2,), 2.0, np.float32)})
        joiner = RemoteEASGD(f"127.0.0.1:{port}", None, alpha=0.5,
                             session_id="joiner-test")
        # joiner has NO payload yet (never exchanged) when the service
        # restarts
        stop1.set()
        _shutdown_service(port)
        t1.join(timeout=5)
        t2, stop2 = _start_service(port)
        try:
            # the creator rebuilds the store shortly AFTER the joiner
            # starts retrying
            def rebuild_later():
                time.sleep(0.8)
                creator.exchange({"w": np.full((2,), 3.0, np.float32)})

            helper = threading.Thread(target=rebuild_later, daemon=True)
            helper.start()
            out = joiner.exchange({"w": np.full((2,), 5.0, np.float32)})
            helper.join(timeout=10)
            assert np.all(np.isfinite(out["w"]))
            creator.close()
            joiner.close()
        finally:
            stop2.set()
            _shutdown_service(port)
            t2.join(timeout=5)

    def test_lost_reply_retries_idempotent_tolerant_op(self, service_env, rpc_loop):
        """easgd_exchange tolerates at-least-once: a reply lost after
        the server applied it is re-sent (one extra elastic pull)."""
        from theanompi_tpu.parallel.service import RemoteEASGD

        port = _free_port()
        t, stop = _start_service(port)
        try:
            srv = RemoteEASGD(f"127.0.0.1:{port}",
                              {"w": np.zeros(2, np.float32)}, alpha=0.5,
                              session_id="alo-test")
            # stub BOTH read primitives: v1 pickle replies arrive via
            # conn.recv(), v2 framed replies via conn.recv_bytes()
            # (wire.recv_msg) — the negotiated protocol decides which
            # one the lost-reply simulation must intercept
            real_recv = srv._conn.recv
            real_recv_bytes = srv._conn.recv_bytes
            calls = {"n": 0}

            def _flaky(real):
                def flaky(*a, **kw):
                    if calls["n"] == 0:
                        calls["n"] += 1
                        raise ConnectionResetError("reply lost")
                    return real(*a, **kw)
                return flaky

            srv._conn.recv = _flaky(real_recv)
            srv._conn.recv_bytes = _flaky(real_recv_bytes)
            out = srv.exchange({"w": np.full(2, 2.0, np.float32)})
            assert np.all(np.isfinite(out["w"]))
            srv.close()
        finally:
            stop.set()
            _shutdown_service(port)
            t.join(timeout=5)

    def test_lost_reply_does_not_resend_gossip_ops(self, service_env, rpc_loop):
        """AT-MOST-ONCE for gossip push/drain (code-review finding):
        once the request is on the wire, a lost reply must RAISE, not
        re-send — a re-applied push double-delivers gossip weight and
        a re-sent drain silently discards the popped payload."""
        from theanompi_tpu.parallel.service import RemoteGossipHub

        port = _free_port()
        t, stop = _start_service(port)
        try:
            hub = RemoteGossipHub(f"127.0.0.1:{port}", 2,
                                  session_id="amo-test")

            def dead_recv(*a, **kw):
                raise ConnectionResetError("reply lost after send")

            # kill both read primitives — see the at-least-once test
            # above for why v1 and v2 read through different ones
            hub._conn.recv = dead_recv
            hub._conn.recv_bytes = dead_recv
            with pytest.raises(ConnectionError, match="not\\s+re-sending"):
                hub.push(1, {"w": np.ones(2, np.float32)}, 0.25)
            # no reconnect happened (the client raised instead of
            # retrying), so the patched connection is still in place
            with pytest.raises(ConnectionError, match="not\\s+re-sending"):
                hub.drain(0)
        finally:
            stop.set()
            _shutdown_service(port)
            t.join(timeout=5)

    def test_displaced_session_rejoin_refused(self, service_env):
        from theanompi_tpu.parallel.service import (
            RemoteEASGD,
            ServiceError,
        )

        port = _free_port()
        t, stop = _start_service(port)
        try:
            params = {"w": np.zeros((2,), np.float32)}
            old = RemoteEASGD(f"127.0.0.1:{port}", params, alpha=0.5,
                              session_id="old")
            old.exchange({"w": np.ones((2,), np.float32)})
            RemoteEASGD(f"127.0.0.1:{port}", params, alpha=0.5,
                        session_id="new")  # displaces 'old'
            with pytest.raises(ServiceError, match="displaced"):
                old._rejoin()
            old.close()
        finally:
            stop.set()
            _shutdown_service(port)
            t.join(timeout=5)


def _shutdown_service(port):
    from theanompi_tpu.parallel.service import ServiceClient

    try:
        ServiceClient(f"127.0.0.1:{port}").call("shutdown")
    except Exception:
        pass


# ---------------------------------------------------------------------------
# fault matrix e2e: the rules under injected faults
# ---------------------------------------------------------------------------


def tiny_cfg(tmp_path, **kw):
    from theanompi_tpu.models.base import ModelConfig

    base = dict(batch_size=8, n_epochs=1, learning_rate=0.01,
                snapshot_dir=str(tmp_path), print_freq=0)
    base.update(kw)
    return ModelConfig(**base)


def test_easgd_worker_killed_recovers(tmp_path):
    """Acceptance-criteria case: an EASGD worker killed mid-run is
    restarted from center params and the session completes."""
    from theanompi_tpu import EASGD

    faults.install([{"site": "worker_step", "rule": "easgd",
                     "worker": 1, "step": 3}])
    rule = EASGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=tiny_cfg(tmp_path),
              tau=4, alpha=0.5, checkpoint=False, max_restarts=1)
    res = rule.wait()
    assert res["restarts"] == {1: 1}
    assert res["lost_workers"] == []
    assert res["n_exchanges"] > 0
    assert np.isfinite(res["val"]["loss"])


def test_easgd_fault_without_supervision_still_fails_fast(tmp_path):
    """Control: max_restarts=0 (the default) keeps the reference's
    fail-fast semantics even with a fault plan installed."""
    from theanompi_tpu import EASGD

    faults.install([{"site": "worker_step", "rule": "easgd",
                     "worker": 1, "step": 3}])
    rule = EASGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=tiny_cfg(tmp_path),
              tau=4, alpha=0.5, checkpoint=False)
    with pytest.raises(FaultInjected):
        rule.wait()


@pytest.mark.slow
def test_easgd_killed_matches_no_fault_run(tmp_path):
    """Tolerance leg of the acceptance criteria: the recovered run's
    final loss matches a no-fault run within tolerance (the restarted
    worker re-seeds from center, so both trainings see ~the same
    trajectory length on a converged tiny problem)."""
    from theanompi_tpu import EASGD

    def run(fault: bool, sub: str):
        faults.clear()
        if fault:
            faults.install([{"site": "worker_step", "rule": "easgd",
                             "worker": 1, "step": 5}])
        rule = EASGD()
        rule.init(devices=2, modelfile="tests._tiny_models",
                  modelclass="TinyCifar",
                  config=tiny_cfg(tmp_path / sub, n_epochs=2),
                  tau=4, alpha=0.5, checkpoint=False,
                  max_restarts=1)
        return rule.wait()

    base = run(False, "nofault")
    faulted = run(True, "fault")
    assert faulted["restarts"] == {1: 1}
    assert abs(faulted["val"]["loss"] - base["val"]["loss"]) < 0.35, \
        (faulted["val"], base["val"])


@pytest.mark.slow
def test_gosgd_lost_worker_deactivates_and_completes(tmp_path):
    """GOSGD fallback path: no center to restart from — the killed
    worker is deactivated (peers stop pushing at it) and the session
    completes on the surviving quorum."""
    from theanompi_tpu import GOSGD

    faults.install([{"site": "worker_step", "rule": "gosgd",
                     "worker": 1, "step": 2}])
    rule = GOSGD()
    rule.init(devices=3, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=tiny_cfg(tmp_path),
              p_push=0.3, checkpoint=False, max_restarts=1)
    res = rule.wait()
    assert res["lost_workers"] == [1]
    assert np.isfinite(res["val"]["loss"])


def test_rule_resume_falls_back_past_corrupt_latest(tmp_path):
    """End-to-end recovery wiring: an EASGD run checkpoints per epoch;
    the LATEST checkpoint is then truncated; a resumed session must
    fall back to the previous epoch instead of dying."""
    from theanompi_tpu import EASGD
    from theanompi_tpu.models.base import ModelConfig

    cfg = tiny_cfg(tmp_path, n_epochs=2)
    rule = EASGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar", config=cfg, tau=4,
              checkpoint=True)
    rule.wait()

    ckpt_dir = os.path.join(str(tmp_path), rule.model.name)
    epochs = sorted(int(n) for n in os.listdir(ckpt_dir) if n.isdigit())
    assert len(epochs) >= 2, epochs
    from theanompi_tpu.utils.checkpoint import _truncate_largest_file

    _truncate_largest_file(recovery.find_step_dir(ckpt_dir, epochs[-1]))

    cfg2 = tiny_cfg(tmp_path, n_epochs=3)
    rule2 = EASGD()
    rule2.init(devices=2, modelfile="tests._tiny_models",
               modelclass="TinyCifar", config=cfg2, tau=4,
               checkpoint=True, resume=True)
    res = rule2.wait()
    assert np.isfinite(res["val"]["loss"])
    # the corrupt epoch was quarantined at resume and RE-SAVED by the
    # resumed run — on disk again and verifying (code-review finding:
    # without quarantine orbax silently skips the re-save and the
    # corrupt files get re-blessed)
    ok, detail = recovery.verify_checkpoint(ckpt_dir, epochs[-1])
    assert ok is True, detail


def test_crash_marker_written_with_monitoring(tmp_path, monkeypatch):
    """rules/base.py postmortem hook: a crashed session leaves a
    machine-readable resilience crash marker in the monitor dir."""
    from theanompi_tpu import EASGD

    mondir = tmp_path / "mon"
    monkeypatch.setenv(monitor.ENV_VAR, str(mondir))
    faults.install([{"site": "worker_step", "rule": "easgd",
                     "worker": 0, "step": 1}])
    rule = EASGD()
    rule.init(devices=2, modelfile="tests._tiny_models",
              modelclass="TinyCifar",
              config=tiny_cfg(tmp_path / "snap"),
              tau=4, checkpoint=False)
    with pytest.raises(FaultInjected):
        rule.wait()
    markers = [p for p in os.listdir(mondir)
               if p.startswith("resilience_crash_")]
    assert markers, os.listdir(mondir)
    marker = json.load(open(mondir / markers[0]))
    assert marker["rule"] == "EASGD"
    assert "FaultInjected" in marker["error"]
