"""Test harness: simulate an 8-device TPU-like mesh on CPU.

The reference could only test distributed behavior on a real cluster
(SURVEY.md §4).  JAX lets us do better:
``--xla_force_host_platform_device_count=8`` gives 8 virtual CPU
devices, so collectives, shardings and all four rules' merge arithmetic
get real unit tests without hardware.

NOTE: this environment pre-registers an experimental TPU PJRT plugin
via sitecustomize and sets JAX_PLATFORMS=axon, so we must both set the
XLA flag *and* force the cpu platform before any backend is created.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run tests marked slow (full e2e rule sessions, multi-host "
             "subprocess tests; several extra minutes)")


def pytest_collection_modifyitems(config, items):
    """Default `pytest tests/` stays under ~5 min on this 1-core box:
    slow e2e tests need --runslow (or RUNSLOW=1).  The fast set keeps a
    short representative of each contract path (BSP rule e2e, one async
    rule e2e incl. resume, merge arithmetic, service wire protocol);
    the slow set runs every rule at full length plus the multi-host and
    separate-process sessions (VERDICT r1, next-round #7)."""
    if config.getoption("--runslow") or os.environ.get("RUNSLOW"):
        return
    skip = pytest.mark.skip(reason="slow: needs --runslow (or RUNSLOW=1)")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture(scope="session")
def mesh8(devices8):
    from theanompi_tpu.parallel import data_mesh

    return data_mesh(8, devices8)
