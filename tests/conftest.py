"""Test harness: simulate an 8-device TPU-like mesh on CPU.

The reference could only test distributed behavior on a real cluster
(SURVEY.md §4).  JAX lets us do better:
``--xla_force_host_platform_device_count=8`` gives 8 virtual CPU
devices, so collectives, shardings and all four rules' merge arithmetic
get real unit tests without hardware.

NOTE: this environment pre-registers an experimental TPU PJRT plugin
via sitecustomize and sets JAX_PLATFORMS=axon, so we must both set the
XLA flag *and* force the cpu platform before any backend is created.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
# lock-order detection (analysis/lockgraph.py): tier-1 always runs the
# threaded host plane (_ExchangePipe, DynamicBatcher, WorkerSupervisor,
# InferenceServer) on TrackedLock, so an AB/BA inversion introduced by
# any PR raises LockOrderError in the test that exercises it instead of
# deadlocking until the CI timeout (docs/ANALYSIS.md)
os.environ.setdefault("THEANOMPI_TPU_LOCKCHECK", "1")

import threading  # noqa: E402
import time  # noqa: E402

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run tests marked slow (full e2e rule sessions, multi-host "
             "subprocess tests; several extra minutes)")


def pytest_collection_modifyitems(config, items):
    """Default `pytest tests/` stays under ~5 min on this 1-core box:
    slow e2e tests need --runslow (or RUNSLOW=1).  The fast set keeps a
    short representative of each contract path (BSP rule e2e, one async
    rule e2e incl. resume, merge arithmetic, service wire protocol);
    the slow set runs every rule at full length plus the multi-host and
    separate-process sessions (VERDICT r1, next-round #7)."""
    if config.getoption("--runslow") or os.environ.get("RUNSLOW"):
        return
    skip = pytest.mark.skip(reason="slow: needs --runslow (or RUNSLOW=1)")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


#: repo thread families that hold closures over models/clients — a
#: test that leaks one pins device buffers and sockets for the rest of
#: the session, so these fail the leak guard even though they are
#: daemonic (daemon= only means the INTERPRETER may exit; the suite
#: keeps running)
_REPO_THREAD_NAMES = ("-exchange-", "serving-batcher-",
                      "serving-reload-watcher", "monitor-heartbeat-",
                      "monitor-export", "collector-watcher",
                      "ingest-", "decode-", "rpc-", "frontdoor-")
#: library pools that are non-daemon BY DESIGN and process-lived
#: (concurrent.futures executors inside jax/orbax) — not leaks
_POOL_THREAD_PREFIXES = ("ThreadPoolExecutor", "asyncio_", "grpc",
                         "orbax")


def leaked_threads(before: set, grace_s: float = 2.0) -> list:
    """Threads started since ``before`` that are still alive after the
    grace window and are either non-daemon (excluding known library
    pools) or members of a repo thread family.  Exposed as a plain
    function so tests/test_analysis.py can pin the detection itself."""
    deadline = time.monotonic() + grace_s
    while True:
        fresh = [t for t in threading.enumerate()
                 if t not in before and t.is_alive()]
        leaked = [
            t for t in fresh
            if (not t.daemon
                and not t.name.startswith(_POOL_THREAD_PREFIXES))
            or any(p in t.name for p in _REPO_THREAD_NAMES)
        ]
        if not leaked or time.monotonic() > deadline:
            return leaked
        time.sleep(0.05)


@pytest.fixture(autouse=True)
def thread_leak_guard():
    """Tier-1 leak fence: every test must stop what it starts — a
    leaked `_ExchangePipe`/batcher/watcher/heartbeat thread fails the
    leaking test by name, not some later test by mystery."""
    before = set(threading.enumerate())
    yield
    leaked = leaked_threads(before)
    if leaked:
        names = ", ".join(f"{t.name}(daemon={t.daemon})"
                          for t in leaked)
        pytest.fail(f"test leaked {len(leaked)} thread(s): {names} — "
                    "close/stop the owning object (pipe.close(), "
                    "batcher.stop(), server.stop(), monitor session "
                    "exit) before returning")


@pytest.fixture(autouse=True)
def shm_segment_leak_guard():
    """Shared-memory twin of the thread fence: every test must decref
    what it leases — a leaked ``tmshm_*`` segment pins /dev/shm pages
    for the rest of the session.  Segments owned by shard/worker
    subprocesses a test spawned are swept by the dead-pid orphan probe
    before we judge."""
    from theanompi_tpu.parallel import shm

    before = set(shm.segment_names())
    yield
    shm.release_all()
    shm.sweep_orphans()
    deadline = time.monotonic() + 2.0
    while True:
        leaked = [n for n in shm.segment_names() if n not in before]
        if not leaked or time.monotonic() > deadline:
            break
        time.sleep(0.05)
        shm.sweep_orphans()
    if leaked:
        for n in leaked:  # unpin the suite before failing the test
            try:
                os.unlink(os.path.join("/dev/shm", n))
            except OSError:
                pass
        pytest.fail(
            f"test leaked {len(leaked)} shm segment(s): "
            f"{', '.join(sorted(leaked))} — close the owning channel "
            "(client.close(), server stop) or decref the lease before "
            "returning")


@pytest.fixture(params=["threaded", "selector"])
def rpc_loop(request, monkeypatch):
    """Both RPC substrates (parallel/rpc.py, ISSUE 11): tests naming
    this fixture run once per loop, so every byte-identity / fence /
    failover pin that opts in covers the legacy thread-per-connection
    loop AND the selector event plane during the migration window."""
    monkeypatch.setenv("THEANOMPI_TPU_RPC_LOOP", request.param)
    return request.param


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture(scope="session")
def mesh8(devices8):
    from theanompi_tpu.parallel import data_mesh

    return data_mesh(8, devices8)
