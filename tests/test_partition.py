"""Property tests for the shared range-partition walk
(``parallel/partition.py``) on DEGENERATE inputs — single-leaf trees,
zero-byte leaves, and plans wider than the leaf count — exercised
through BOTH consumers: the shard plane (``partition_ranges``, refuses
k > n) and the bucket plane (``bucket_ranges``, clamps).  The walk is
the one algorithm every rank must derive identically, so the
properties (cover, contiguous, non-empty, deterministic) are asserted
over a brute-force sweep rather than a few samples.
"""

from __future__ import annotations

import numpy as np
import pytest

from theanompi_tpu.parallel.exchanger import bucket_ranges
from theanompi_tpu.parallel.partition import balanced_ranges
from theanompi_tpu.parallel.shards import partition_ranges


def assert_valid_plan(ranges, n, k):
    assert len(ranges) == k
    assert ranges[0][0] == 0 and ranges[-1][1] == n
    for (_, b), (c, _) in zip(ranges, ranges[1:]):
        assert b == c                       # contiguous
    assert all(hi > lo for lo, hi in ranges)  # never empty


class TestBalancedRangesProperties:
    def test_property_sweep_random_sizes(self):
        """Brute-force property sweep: every (sizes, k) plan covers,
        is contiguous, non-empty, and deterministic — including sizes
        drawn with many zeros (zero-byte leaves are legal: empty
        buffers still need an owner)."""
        rng = np.random.default_rng(11)
        for trial in range(60):
            n = int(rng.integers(1, 40))
            # ~1/3 zero-byte leaves on average
            sizes = [int(s) if rng.random() > 0.33 else 0
                     for s in rng.integers(1, 10_000, n)]
            for k in {kk for kk in (1, 2, n // 2 or 1, n) if kk <= n}:
                plan = balanced_ranges(sizes, k)
                assert_valid_plan(plan, n, k)
                assert plan == balanced_ranges(list(sizes), k)

    def test_single_leaf(self):
        assert balanced_ranges([123], 1) == [(0, 1)]
        assert partition_ranges([123], 1) == [(0, 1)]
        assert bucket_ranges([123], 1) == [(0, 1)]

    def test_all_zero_byte_leaves(self):
        """A tree of empty buffers still partitions: every range owns
        >= 1 leaf and the cover holds (total bytes 0 makes every
        quantile target 0 — the walk must not divide by it or stall)."""
        for n in (1, 2, 3, 7):
            for k in range(1, n + 1):
                plan = balanced_ranges([0] * n, k)
                assert_valid_plan(plan, n, k)

    def test_zero_byte_leaves_between_giants(self):
        sizes = [0, 10**9, 0, 0, 10**9, 0]
        for k in (1, 2, 3, 6):
            plan = balanced_ranges(sizes, k)
            assert_valid_plan(plan, len(sizes), k)
        # the two giants must not share a range when k >= 2
        by_range = [sum(sizes[lo:hi]) for lo, hi in
                    balanced_ranges(sizes, 2)]
        assert by_range == [10**9, 10**9]

    def test_k_above_leaf_count_raises(self):
        with pytest.raises(ValueError, match="never split"):
            balanced_ranges([1, 2, 3], 4)

    def test_k_below_one_raises(self):
        with pytest.raises(ValueError, match=">= 1"):
            balanced_ranges([1], 0)

    def test_empty_sequence_raises(self):
        with pytest.raises(ValueError, match="empty"):
            balanced_ranges([], 1)


class TestConsumerPlanes:
    """The two consumers must keep their DOCUMENTED degenerate-input
    contracts: shards refuse a plan wider than the tree (a shard with
    no leaves has nothing to serve), buckets clamp (a bucket plan is a
    scheduling hint, not an ownership contract)."""

    def test_shard_plane_refuses_k_above_leaves(self):
        with pytest.raises(ValueError, match="lower --shards"):
            partition_ranges([8, 8], 3)

    def test_shard_plane_refuses_empty_tree(self):
        with pytest.raises(ValueError, match="empty"):
            partition_ranges([], 1)

    def test_bucket_plane_clamps_to_per_leaf(self):
        plan = bucket_ranges([4, 4, 4], 100)
        assert plan == [(0, 1), (1, 2), (2, 3)]

    def test_bucket_plane_single_leaf_any_count(self):
        for b in (1, 2, 17):
            assert bucket_ranges([64], b) == [(0, 1)]

    def test_planes_agree_when_both_legal(self):
        """One walk, two wrappers: wherever both consumers accept
        (k <= n), their plans are identical — the shared-algorithm
        guarantee the module docstring promises."""
        rng = np.random.default_rng(13)
        for _ in range(20):
            n = int(rng.integers(1, 30))
            sizes = [int(s) for s in rng.integers(0, 5_000, n)]
            for k in range(1, n + 1):
                assert partition_ranges(sizes, k) \
                    == bucket_ranges(sizes, k)
