"""frontdoor/: disaggregated prefill/decode serving (ISSUE 17).

The acceptance pins:

* **byte identity** — a stream routed prefill → (pages over the wire)
  → decode is token-identical to the single-role decode server and to
  the uncached full-forward oracle; the migrated page BYTES round-trip
  the wire exactly (raw frames, no re-encode);
* **typed refusals** — a geometry-mismatched adopt is refused with the
  typed ``IncompatiblePages`` over the wire and the CONNECTION (and
  the replica) keep serving; the whole manifest/pages refusal matrix
  is covered in-process;
* **failover** — a decode backend lost mid-stream makes the router
  re-prefill from the prompt and adopt onto a survivor; the retried
  stream is byte-identical (the adopt RPC returns whole streams, so
  nothing was delivered before the loss);
* **load shedding** — admission bounds anywhere (router, prefill
  fleet, decode fleet) surface as the typed ``Overloaded`` end to end,
  never a destructive retry;
* **scale events drop nothing** — adding a backend admits new traffic
  with zero dropped streams; removing one DRAINS (no new routes,
  in-flight streams finish, closed only at zero streams);
* **autoscaler units** — hysteresis/hold/cooldown against an injected
  clock; the signal fold (queue depth, occupancy, p99 vs SLO,
  overload-delta saturation); scale-down drains before release.

The real-subprocess fleet (``DisaggregatedFleet``) is exercised in the
slow set and by ``tools/preflight.sh``; everything above runs
in-process over real sockets, the ``tests/test_decode.py`` pattern.
"""

from __future__ import annotations

import os
import socket
import threading

import jax
import numpy as np
import pytest

from theanompi_tpu.decode.migrate import (
    GEOMETRY_FIELDS,
    IncompatiblePages,
    manifest_incompatibility,
    page_manifest,
    pages_incompatibility,
)
from theanompi_tpu.frontdoor import (
    Autoscaler,
    HysteresisController,
    PrefillClient,
    PrefillServer,
    Router,
    RouterClient,
)
from theanompi_tpu.frontdoor import prefill as prefill_mod
from theanompi_tpu.frontdoor import router as router_mod
from theanompi_tpu.models.base import ModelConfig
from theanompi_tpu.models.transformer import TransformerLM
from theanompi_tpu.serving import (
    InferenceClient,
    InferenceServer,
    Overloaded,
    export_model,
    serve,
)

N_LAYERS, N_HEADS, D_MODEL, VOCAB = 2, 2, 16, 32
GEO = dict(page_size=4, pages_per_seq=8, max_seqs=4,
           prefill_buckets=(8,))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def tiny_lm(tmp_path_factory):
    cfg = ModelConfig(batch_size=4, n_epochs=1, print_freq=0,
                      compute_dtype="float32", optimizer="adamw",
                      learning_rate=1e-3, weight_decay=0.0,
                      lr_schedule="constant")
    model = TransformerLM(config=cfg, vocab=VOCAB, seq_len=16,
                          n_layers=N_LAYERS, d_model=D_MODEL,
                          n_heads=N_HEADS, verbose=False)
    params = jax.device_get(model.state.params)
    export_dir = str(tmp_path_factory.mktemp("frontdoor") / "export")
    export_model(model, export_dir, version=0)
    return model, params, export_dir


def _flax_greedy(model, params, prompt, n: int) -> list[int]:
    import jax.numpy as jnp

    cur = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        logits = np.asarray(model.module.apply(
            {"params": params}, jnp.asarray([cur], jnp.int32),
            train=False, seq_axis=None))
        tok = int(np.argmax(logits[0, -1]))
        out.append(tok)
        cur.append(tok)
    return out


def _serve_thread(target_serve, obj, port):
    """Start ``target_serve(obj, ...)`` on 127.0.0.1:port in a daemon
    thread; returns (addr, stop_event, thread)."""
    ready, stop = threading.Event(), threading.Event()
    t = threading.Thread(target=target_serve,
                         args=(obj, "127.0.0.1", port, ready, stop),
                         daemon=True)
    t.start()
    assert ready.wait(30)
    return f"127.0.0.1:{port}", stop, t


@pytest.fixture(scope="module")
def servers(tiny_lm):
    """The expensive half of the stack, built once per module: one
    PrefillServer session, two geometry-matched decode servers (A, B)
    and one geometry-MISMATCHED one (C, page_size 2 vs 4) — batchers
    running, NO sockets (the wire is function-scoped so each test's
    RPC worker threads die with the test)."""
    model, params, export_dir = tiny_lm
    key_before = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
    pre = PrefillServer(export_dir, model=model, max_pending=8, **GEO)

    def decode_server(**over):
        opts = dict(GEO)
        opts.update(over)
        return InferenceServer(export_dir, replicas=1, reload_poll_s=0,
                               model=model, decode=True,
                               decode_opts=opts).start()

    srv_a = decode_server()
    srv_b = decode_server()
    srv_c = decode_server(page_size=2)  # window still 16 >= bucket 8
    yield dict(model=model, params=params, export_dir=export_dir,
               prefill_server=pre, srv_a=srv_a, srv_b=srv_b,
               srv_c=srv_c)
    for srv in (srv_a, srv_b, srv_c):
        srv.stop()
    if key_before is None:
        os.environ.pop("THEANOMPI_TPU_SERVICE_KEY", None)
    else:
        os.environ["THEANOMPI_TPU_SERVICE_KEY"] = key_before


@pytest.fixture()
def stack(servers):
    """Function-scoped wire over the module-scoped servers: serve
    loops (and their spawn-on-demand RPC pools) start and stop inside
    each test, so the thread-leak fence stays exact."""
    stops, threads = [], []

    def up(target_serve, obj):
        addr, stop, t = _serve_thread(target_serve, obj, _free_port())
        stops.append(stop)
        threads.append(t)
        return addr

    yield dict(servers,
               prefill=up(prefill_mod.serve,
                          servers["prefill_server"]),
               decode_a=up(serve, servers["srv_a"]),
               decode_b=up(serve, servers["srv_b"]),
               mismatch=up(serve, servers["srv_c"]))
    for stop in stops:
        stop.set()
    for t in threads:
        t.join(timeout=5)


class _served_router:
    """Context manager: serve ``router`` on a free port, yield a
    :class:`RouterClient` factory, tear down router + clients."""

    def __init__(self, router: Router):
        self.router = router
        self.clients: list[RouterClient] = []

    def __enter__(self):
        self.addr, self._stop, self._t = _serve_thread(
            router_mod.serve, self.router, _free_port())
        return self

    def client(self) -> RouterClient:
        c = RouterClient(self.addr)
        self.clients.append(c)
        return c

    def __exit__(self, *exc):
        for c in self.clients:
            c.close()
        self._stop.set()
        self._t.join(timeout=5)
        self.router.close()


# ---------------------------------------------------------------------------
# migrate.py — the manifest/pages refusal matrix (in-process)
# ---------------------------------------------------------------------------


class TestRefusalMatrix:
    def _cfg_and_pages(self, stack):
        sess = stack["prefill_server"].session
        prompt = np.arange(1, 6, dtype=np.int32)
        with stack["prefill_server"]._lock:
            seq, logits = sess.admit(prompt)
            k, v = sess.export_pages(seq)
            man = page_manifest(sess.cfg, prompt, seq.length,
                                int(np.argmax(logits)))
            sess.release(seq)
        return sess.cfg, man, k, v

    def test_compatible_passes(self, stack):
        cfg, man, k, v = self._cfg_and_pages(stack)
        assert manifest_incompatibility(man, cfg) is None
        assert pages_incompatibility(man, k, v, cfg) is None

    def test_every_geometry_field_refused(self, stack):
        cfg, man, k, v = self._cfg_and_pages(stack)
        for f in GEOMETRY_FIELDS:
            bad = dict(man)
            bad[f] = "float64" if f == "dtype" else int(man[f]) + 1
            reason = manifest_incompatibility(bad, cfg)
            assert reason is not None and f in reason, (f, reason)

    def test_missing_fields_and_lies_refused(self, stack):
        cfg, man, k, v = self._cfg_and_pages(stack)
        for f in (*GEOMETRY_FIELDS, "length", "prompt", "first_token"):
            bad = {x: y for x, y in man.items() if x != f}
            assert f in (manifest_incompatibility(bad, cfg) or "")
        bad = dict(man, length=0)
        assert "length" in manifest_incompatibility(bad, cfg)
        bad = dict(man, prompt=man["prompt"] + [1])
        assert "prompt" in manifest_incompatibility(bad, cfg)
        # the manifest can lie about the arrays: shape and dtype
        assert "shaped" in pages_incompatibility(man, k[:, :1], v, cfg)
        assert "dtype" in pages_incompatibility(
            man, k, v.astype(np.float64), cfg)

    def test_mismatch_refused_over_wire_connection_survives(
            self, stack, tiny_lm):
        """Ship geometry-correct pages to the page_size-2 server: the
        typed ``IncompatiblePages`` rides the wire and the SAME client
        connection (and the replica) keep serving."""
        model, params, _ = tiny_lm
        cfg, man, k, v = self._cfg_and_pages(stack)
        c = InferenceClient(stack["mismatch"])
        try:
            with pytest.raises(IncompatiblePages,
                               match="page geometry mismatch"):
                c.adopt(man, k, v, 4)
            # same connection, same replica: native streams unaffected
            out = c.generate(np.asarray(man["prompt"], np.int32), 4)
            assert list(out) == _flax_greedy(model, params,
                                             man["prompt"], 4)
            assert sum(r.get("adopt_refused", 0)
                       for r in c.stats()["replicas"]) >= 1
        finally:
            c.close()


# ---------------------------------------------------------------------------
# prefill.py — page export byte identity + shedding
# ---------------------------------------------------------------------------


class TestPrefill:
    def test_pages_byte_identical_over_wire(self, stack):
        """The raw-frame transport pin: the page bytes the CLIENT
        receives are exactly the bytes the server handler returned —
        no bf16 re-dtype, no lossy step anywhere on the wire.  Spies
        on the served object, so prefill numerics (prefix-cache hits
        take the extend program) can't blur the comparison."""
        server = stack["prefill_server"]
        sent = {}
        orig = server.prefill

        def spy(prompt):
            man, raw = orig(prompt)
            sent["k"], sent["v"] = raw  # RawArrays IS a tuple
            return man, raw

        server.prefill = spy
        prompt = np.asarray([3, 1, 4, 1, 5], np.int32)
        c = PrefillClient(stack["prefill"])
        try:
            man, k, v = c.prefill(prompt)
        finally:
            c.close()
            del server.prefill  # un-shadow the method
        assert man["prompt"] == [int(t) for t in prompt]
        assert man["length"] == len(prompt)
        assert k.dtype == sent["k"].dtype
        assert v.dtype == sent["v"].dtype
        assert k.tobytes() == sent["k"].tobytes()
        assert v.tobytes() == sent["v"].tobytes()

    def test_admission_shed_is_typed(self, tiny_lm, stack):
        model, _, export_dir = tiny_lm
        server = PrefillServer(export_dir, model=model, max_pending=0,
                               warmup=False, **GEO)
        with pytest.raises(Overloaded, match="max_pending"):
            server.prefill(np.asarray([1, 2, 3], np.int32))
        assert server.stats()["overloaded"] == 1


# ---------------------------------------------------------------------------
# router.py — byte identity, failover, shedding, drain (real sockets)
# ---------------------------------------------------------------------------


class TestRouter:
    def test_stream_byte_identical_to_single_role(self, stack):
        """The headline pin: router(prefill → migrate → adopt) equals
        the single-role decode server equals the uncached oracle."""
        model, params = stack["model"], stack["params"]
        router = Router(prefill=[stack["prefill"]],
                        decode=[stack["decode_a"]])
        with _served_router(router) as sr:
            rng = np.random.default_rng(17)
            prompts = [rng.integers(0, VOCAB, n).astype(np.int32)
                       for n in (5, 7, 8)]
            single = InferenceClient(stack["decode_b"])
            try:
                for p in prompts:
                    got = sr.client().generate(p, 10)
                    assert list(got) == list(single.generate(p, 10))
                    assert list(got) == _flax_greedy(model, params,
                                                     p, 10)
            finally:
                single.close()
            st = sr.client().stats()
            assert st["streams"] == len(prompts)
            assert st["shed"] == 0 and st["failovers"] == 0

    def test_concurrent_streams_all_correct(self, stack):
        model, params = stack["model"], stack["params"]
        router = Router(prefill=[stack["prefill"]],
                        decode=[stack["decode_a"], stack["decode_b"]])
        with _served_router(router) as sr:
            rng = np.random.default_rng(23)
            prompts = [rng.integers(0, VOCAB, 5 + i % 4)
                          .astype(np.int32) for i in range(6)]
            outs = [None] * len(prompts)

            def run(i, c):
                outs[i] = c.generate(prompts[i], 8)

            ths = [threading.Thread(target=run,
                                    args=(i, sr.client()))
                   for i in range(len(prompts))]
            for t in ths:
                t.start()
            for t in ths:
                t.join(60)
            for p, o in zip(prompts, outs):
                assert o is not None
                assert list(o) == _flax_greedy(model, params, p, 8)

    def test_dead_decode_backend_fails_over_byte_identical(
            self, stack):
        """A decode backend lost on the token leg: the router
        re-prefills from the prompt and adopts onto the survivor —
        stream output byte-identical, failover counted."""
        model, params = stack["model"], stack["params"]
        dead = f"127.0.0.1:{_free_port()}"  # nobody listening
        router = Router(prefill=[stack["prefill"]],
                        decode=[dead, stack["decode_a"]])
        # pin round-robin so the DEAD backend is tried first
        router._rr["decode"] = 0
        prompt = np.asarray([2, 7, 1, 8], np.int32)
        out = router.generate(prompt, 8)
        assert list(out) == _flax_greedy(model, params, prompt, 8)
        st = router.stats()
        assert st["failovers"] == 1
        assert st["shed"] == 0
        router.close()

    def test_failover_budget_exhausts_to_connection_error(self, stack):
        dead = f"127.0.0.1:{_free_port()}"
        router = Router(prefill=[stack["prefill"]], decode=[dead],
                        failover_attempts=1)
        with pytest.raises(ConnectionError):
            router.generate(np.asarray([1, 2, 3], np.int32), 4)
        assert router.stats()["failovers"] == 1
        router.close()

    def test_overload_sheds_typed_end_to_end(self, stack):
        """Admission bounds surface as typed ``Overloaded`` over the
        wire — router admission and an empty decode role both."""
        router = Router(prefill=[stack["prefill"]],
                        decode=[stack["decode_a"]], max_streams=0)
        with _served_router(router) as sr:
            with pytest.raises(Overloaded, match="max_streams"):
                sr.client().generate(np.asarray([1, 2], np.int32), 4)
        router = Router(prefill=[stack["prefill"]], decode=[])
        with _served_router(router) as sr:
            c = sr.client()
            with pytest.raises(Overloaded, match="decode"):
                c.generate(np.asarray([1, 2], np.int32), 4)
            # typed shed: the connection survives
            assert c.stats()["shed"] >= 1

    def test_incompatible_backend_propagates_typed(self, stack):
        """A geometry-mismatched decode fleet is a deployment error:
        the typed refusal reaches the client, the router keeps
        serving."""
        router = Router(prefill=[stack["prefill"]],
                        decode=[stack["mismatch"]])
        with _served_router(router) as sr:
            c = sr.client()
            with pytest.raises(IncompatiblePages,
                               match="page geometry mismatch"):
                c.generate(np.asarray([1, 2, 3], np.int32), 4)
            assert c.stats()["active_streams"] == 0

    def test_scale_up_admits_with_zero_dropped_streams(self, stack):
        """Adding a backend mid-traffic: every stream before, during
        and after the add completes; the new backend takes work."""
        model, params = stack["model"], stack["params"]
        router = Router(prefill=[stack["prefill"]],
                        decode=[stack["decode_a"]])

        def adopted_on_b() -> int:
            c = InferenceClient(stack["decode_b"])
            try:
                return sum(r.get("adopted", 0)
                           for r in c.stats()["replicas"])
            finally:
                c.close()

        adopted_b0 = adopted_on_b()
        with _served_router(router) as sr:
            prompt = np.asarray([4, 4, 2], np.int32)
            want = _flax_greedy(model, params, prompt, 6)
            assert list(sr.client().generate(prompt, 6)) == want
            router.add_backend("decode", stack["decode_b"])
            outs = [None] * 4

            def run(i, c):
                outs[i] = c.generate(prompt, 6)

            ths = [threading.Thread(target=run, args=(i, sr.client()))
                   for i in range(4)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(60)
            assert all(o is not None and list(o) == want for o in outs)
            st = sr.client().stats()
            assert st["shed"] == 0
        # the added backend took streams: zero dropped, real traffic
        assert adopted_on_b() > adopted_b0

    def test_scale_down_drains_before_close(self, stack):
        """The drain protocol: a removed backend takes no NEW streams,
        reports its in-flight count until the last stream releases,
        and only then leaves the router."""
        model, params = stack["model"], stack["params"]
        router = Router(prefill=[stack["prefill"]],
                        decode=[stack["decode_a"], stack["decode_b"]])
        with router._lock:
            b = next(x for x in router._backends["decode"]
                     if x.addr == stack["decode_b"])
        inflight = b.acquire()  # one stream parked on B
        router.remove_backend("decode", stack["decode_b"])
        assert router.backend_streams("decode", stack["decode_b"]) == 1
        # no new streams route to the draining backend
        assert all(x.addr != stack["decode_b"]
                   for x in router._candidates("decode"))
        prompt = np.asarray([6, 1, 6], np.int32)
        assert list(router.generate(prompt, 5)) == \
            _flax_greedy(model, params, prompt, 5)
        # last stream out closes the backend
        assert b.release(inflight, ok=True) is True
        router._drop_if_drained(b)
        assert router.backend_streams("decode", stack["decode_b"]) == 0
        assert all(s["addr"] != stack["decode_b"]
                   for s in router.stats()["backends"]["decode"])
        router.close()


# ---------------------------------------------------------------------------
# autoscale.py — controller units + the scaler loop (no subprocesses)
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


class TestHysteresis:
    def test_validation(self):
        with pytest.raises(ValueError, match="down < up"):
            HysteresisController(up=0.2, down=0.8)
        with pytest.raises(ValueError, match="min_size"):
            HysteresisController(min_size=3, max_size=2)

    def test_hold_then_up_then_cooldown(self):
        clk = _Clock()
        c = HysteresisController(up=0.8, down=0.2, hold=2,
                                 cooldown_s=10.0, max_size=4,
                                 clock=clk)
        assert c.decide(0.9, 1) == 0   # first breach holds
        assert c.decide(0.9, 1) == 1   # second scales
        assert c.decide(0.9, 2) == 0   # cooldown gates
        assert c.decide(0.9, 2) == 0
        clk.t = 11.0
        assert c.decide(0.9, 2) == 1   # breaches counted through it

    def test_dead_band_resets_breaches(self):
        c = HysteresisController(up=0.8, down=0.2, hold=2,
                                 cooldown_s=0.0, clock=_Clock())
        assert c.decide(0.9, 1) == 0
        assert c.decide(0.5, 1) == 0   # dead band: counter resets
        assert c.decide(0.9, 1) == 0   # back to one breach
        assert c.decide(0.9, 1) == 1

    def test_down_and_size_clamps(self):
        clk = _Clock()
        c = HysteresisController(up=0.8, down=0.2, hold=2,
                                 cooldown_s=0.0, min_size=1,
                                 max_size=2, clock=clk)
        assert c.decide(0.1, 2) == 0
        assert c.decide(0.1, 2) == -1
        assert c.decide(0.1, 1) == 0   # hold restarts after event
        assert c.decide(0.1, 1) == 0   # min_size clamps
        assert c.decide(0.9, 2) == 0
        assert c.decide(0.9, 2) == 0   # max_size clamps


class _FakeGroup:
    def __init__(self, addrs):
        self._addrs = list(addrs)
        self.grown = 0
        self.released: list[str] = []

    def addresses(self):
        return list(self._addrs)

    def __len__(self):
        return len(self._addrs)

    def grow(self):
        self.grown += 1
        addr = f"127.0.0.1:{9000 + self.grown}"
        self._addrs.append(addr)
        return addr

    def release(self, addr):
        self._addrs.remove(addr)
        self.released.append(addr)


class _FakeRouter:
    def __init__(self):
        self.log: list[tuple] = []
        self.streams: dict[str, int] = {}

    def add_backend(self, role, addr):
        self.log.append(("add", role, addr))

    def remove_backend(self, role, addr):
        self.log.append(("remove", role, addr))

    def backend_streams(self, role, addr):
        return self.streams.get(addr, 0)


class TestAutoscaler:
    def _scaler(self, stats_map, **ctl):
        group = _FakeGroup(list(stats_map))
        router = _FakeRouter()
        ctl.setdefault("hold", 1)
        ctl.setdefault("cooldown_s", 0.0)
        ctl.setdefault("clock", _Clock())
        scaler = Autoscaler(router, {"decode": group},
                            {"decode": HysteresisController(**ctl)},
                            drain_timeout_s=0.2)
        scaler._stats = lambda addr: stats_map.get(addr)
        return scaler, group, router

    def test_replica_load_fold(self):
        scaler, _, _ = self._scaler({})
        scaler.slo_p99_ms = 10.0
        # prefill: queue depth
        assert scaler._replica_load("a", {
            "role": "prefill", "inflight": 4, "max_pending": 8,
            "overloaded": 0}) == pytest.approx(0.5)
        # decode: max over pending depth / occupancy / p99-vs-SLO
        load = scaler._replica_load("b", {
            "overloaded": 0,
            "replicas": [{"pending": 2, "active": 3, "free_pages": 8,
                          "intertoken_ms": {"p99": 25.0}}]})
        assert load == pytest.approx(2.5)  # p99 dominates: 25/10
        # an overload DELTA saturates the signal to 1.0 — but the
        # first observation only primes the baseline
        assert scaler._replica_load("c", {
            "role": "prefill", "inflight": 0, "max_pending": 8,
            "overloaded": 5}) == 0.0
        assert scaler._replica_load("c", {
            "role": "prefill", "inflight": 0, "max_pending": 8,
            "overloaded": 6}) == 1.0

    def test_tick_scales_up_on_load(self):
        stats_map = {"127.0.0.1:8001": {
            "role": "prefill", "inflight": 8, "max_pending": 8,
            "overloaded": 0}}
        scaler, group, router = self._scaler(stats_map)
        scaler.tick()
        assert group.grown == 1
        assert router.log == [("add", "decode", "127.0.0.1:9001")]
        assert scaler.events == [("decode", "up", "127.0.0.1:9001")]

    def test_tick_drains_then_releases_on_idle(self):
        stats_map = {
            "127.0.0.1:8001": {"role": "prefill", "inflight": 0,
                               "max_pending": 8, "overloaded": 0},
            "127.0.0.1:8002": {"role": "prefill", "inflight": 0,
                               "max_pending": 8, "overloaded": 0},
        }
        scaler, group, router = self._scaler(stats_map)
        scaler.tick()
        # newest replica drained: router removal BEFORE process release
        assert router.log == [("remove", "decode", "127.0.0.1:8002")]
        assert group.released == ["127.0.0.1:8002"]
        assert scaler.events == [("decode", "down", "127.0.0.1:8002")]
        # at min_size the controller stops shrinking
        scaler.tick()
        assert group.released == ["127.0.0.1:8002"]

    def test_dead_replica_does_not_kill_the_loop(self):
        stats_map = {"127.0.0.1:8001": None}  # stats unreachable
        scaler, group, router = self._scaler(stats_map)
        scaler.tick()  # load 0.0 from nothing; size 1 = min: no event
        assert router.log == [] and group.released == []


# ---------------------------------------------------------------------------
# the real-subprocess fleet (slow set; tools/preflight.sh drives it too)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_disaggregated_fleet_subprocess_roundtrip(tiny_lm):
    """DisaggregatedFleet end to end: real prefill + decode children,
    the in-process router, one client stream oracle-equal."""
    from theanompi_tpu.frontdoor.fleet import DisaggregatedFleet

    model, params, export_dir = tiny_lm
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with DisaggregatedFleet(export_dir, prefill=1, decode=1,
                            page_size=4, pages_per_seq=8, max_seqs=4,
                            prefill_buckets=(8,)) as fleet:
        c = RouterClient(fleet.router_addr)
        try:
            prompt = np.asarray([3, 1, 4, 1, 5], np.int32)
            out = c.generate(prompt, 8)
            assert list(out) == _flax_greedy(model, params, prompt, 8)
            st = c.stats()
            assert st["streams"] == 1 and st["shed"] == 0
        finally:
            c.close()


# ---------------------------------------------------------------------------
# fleet prefix cache (ISSUE 18): authority ops + lease refusal matrix
# ---------------------------------------------------------------------------


class TestFleetCache:
    def test_register_then_lookup_ships_identical_bytes(self, stack,
                                                        tiny_lm):
        """A peer registers a page-aligned prefix over the wire; the
        next lookup ships back byte-identical pages under a lease."""
        from theanompi_tpu.decode import DecodeSession, fleetcache

        model, params, _ = tiny_lm
        sess = DecodeSession(model, params=params, **GEO)
        c = fleetcache.FleetCacheClient(stack["prefill"])
        try:
            rng = np.random.default_rng(41)
            prompt = rng.integers(0, VOCAB, 8).astype(np.int32)
            assert c.lookup(prompt) is None          # cold fleet
            seq, _ = sess.admit(prompt)
            k, v = sess.export_page_ids([int(seq.page_row[0])])
            man = fleetcache.prefix_manifest(sess.cfg, prompt[:4])
            assert c.register_prefix(man, k, v)["added"] is True
            got = c.lookup(prompt)
            assert got is not None
            m2, k2, v2, lease = got
            assert m2["n_tokens"] == 4
            assert m2["prefix"] == [int(t) for t in prompt[:4]]
            np.testing.assert_array_equal(k2, k)
            np.testing.assert_array_equal(v2, v)
            c.decref(lease)
            sess.release(seq)
        finally:
            c.close()

    def test_lease_refusal_matrix_over_wire(self, stack):
        """Foreign lease and double decref raise the typed LeaseError;
        a geometry-lying register raises IncompatiblePages; the same
        client connection (and the authority) keep serving."""
        from theanompi_tpu.decode import fleetcache

        pre = stack["prefill_server"]
        c = fleetcache.FleetCacheClient(stack["prefill"])
        try:
            with pytest.raises(fleetcache.LeaseError, match="lease"):
                c.decref("lease-0-999999")           # foreign
            rng = np.random.default_rng(42)
            prompt = rng.integers(0, VOCAB, 8).astype(np.int32)
            pre.prefill(prompt)       # cold prefill seeds the cache
            man, k, v, lease = c.lookup(prompt)
            c.decref(lease)
            with pytest.raises(fleetcache.LeaseError, match="lease"):
                c.decref(lease)                      # double decref
            bad = dict(man, page_size=8)
            with pytest.raises(IncompatiblePages, match="page_size"):
                c.register_prefix(bad, np.asarray(k), np.asarray(v))
            # same connection: the authority still answers
            got = c.lookup(prompt)
            assert got is not None
            c.decref(got[3])
        finally:
            c.close()

    def test_evict_while_leased_pages_survive(self, stack):
        """Remote eviction can never free a shipped page mid-flight:
        the lease's reference keeps it allocated until decref."""
        pre = stack["prefill_server"]
        sess = pre.session
        rng = np.random.default_rng(43)
        prompt = rng.integers(0, VOCAB, 8).astype(np.int32)
        pre.prefill(prompt)
        got = pre.cache_lookup(prompt)
        assert got is not None
        _, _, lease = got
        page_ids = list(pre._leases[lease])
        with pre._lock:
            sess.prefix_cache.evict_all()    # cache refs dropped
        assert all(sess.pool.refcount(p) >= 1 for p in page_ids)
        pre.cache_decref(lease)
        assert all(sess.pool.refcount(p) == 0 for p in page_ids)

    def test_cross_replica_fleet_hit_end_to_end(self, stack, tiny_lm):
        """A session that attaches the authority as its fleet cache
        turns a local miss into an adopted local hit (and registers
        its own cold prefixes back): both directions, with the decoded
        stream token-identical to the oracle and no leaked lease."""
        from theanompi_tpu.decode import DecodeSession, fleetcache

        model, params, _ = tiny_lm
        pre = stack["prefill_server"]
        rng = np.random.default_rng(44)
        prompt = rng.integers(0, VOCAB, 8).astype(np.int32)
        pre.prefill(prompt)          # authority caches prompt[:4]
        sess = DecodeSession(model, params=params, **GEO)
        sess.fleet = fleetcache.FleetCacheClient(stack["prefill"])
        try:
            leases0 = len(pre._leases)
            seq, lg = sess.admit(prompt)   # miss -> fetch -> local hit
            assert sess.prefix_cache.hits == 1
            assert len(pre._leases) == leases0     # fetch decrefs
            out = [int(np.argmax(lg))]
            for _ in range(5):
                l2 = sess.decode([seq],
                                 np.asarray([out[-1]], np.int32))
                out.append(int(np.argmax(l2[0])))
            assert out == _flax_greedy(model, params, prompt, 6)
            # reverse direction: a cold admit registers its prefix
            p2 = rng.integers(0, VOCAB, 8).astype(np.int32)
            sess.admit(p2)
            got = pre.cache_lookup(p2)
            assert got is not None and got[0]["n_tokens"] == 4
            pre.cache_decref(got[2])
        finally:
            sess.fleet.close()


class TestPrefillCoalescing:
    def test_concurrent_prefills_coalesce_into_one_batch(self,
                                                         tiny_lm):
        """4 concurrent prefill() calls ride ONE batched program (the
        leader waits out the oldest prompt's deadline) and each caller
        gets pages/manifest byte-identical to the serial cap-1 path."""
        model, params, export_dir = tiny_lm
        pre = PrefillServer(export_dir, model=model, max_pending=8,
                            warmup=False, prefill_delay_ms=250.0,
                            **GEO)
        rng = np.random.default_rng(45)
        prompts = [rng.integers(0, VOCAB, 6 + i % 3).astype(np.int32)
                   for i in range(4)]
        results = [None] * 4

        def run(i):
            results[i] = pre.prefill(prompts[i])

        ts = [threading.Thread(target=run, args=(i,))
              for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
        assert pre.n_batches == 1 and pre.n_prefills == 4
        serial = PrefillServer(export_dir, model=model, max_pending=8,
                               warmup=False, prefill_batch=1, **GEO)
        for p, (man, pages) in zip(prompts, results):
            rman, rpages = serial.prefill(p)
            assert man == rman
            np.testing.assert_array_equal(pages[0], rpages[0])
            np.testing.assert_array_equal(pages[1], rpages[1])
