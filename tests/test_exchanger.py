"""Exchange semantics: psum-of-grads equals sum of per-shard grads,
avg flag, bf16 strategy, async merge arithmetic closed forms."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel import (
    AXIS_DATA,
    BSP_Exchanger,
    asgd_apply_grads,
    easgd_both_updates,
    easgd_center_update,
    easgd_worker_update,
    gosgd_merge,
)


def _run_exchange(mesh, exchanger, tree):
    f = jax.shard_map(
        exchanger.exchange,
        mesh=mesh,
        in_specs=P(AXIS_DATA),
        out_specs=P(AXIS_DATA),
        check_vma=False,
    )
    return f(tree)


def test_psum_sum_of_shards(mesh8):
    x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    ex = BSP_Exchanger(strategy="ar", avg=False)
    out = np.asarray(_run_exchange(mesh8, ex, x))
    expected = np.tile(x.sum(axis=0), (8, 1))
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_psum_avg(mesh8):
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    ex = BSP_Exchanger(strategy="nccl32", avg=True)
    out = np.asarray(_run_exchange(mesh8, ex, x))
    expected = np.tile(x.mean(axis=0), (8, 1))
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_bf16_strategy_close_to_fp32(mesh8):
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    ex16 = BSP_Exchanger(strategy="nccl16", avg=True)
    out = np.asarray(_run_exchange(mesh8, ex16, x))
    expected = np.tile(x.mean(axis=0), (8, 1))
    # bf16 mantissa is 8 bits -> ~1e-2 relative tolerance
    np.testing.assert_allclose(out, expected, rtol=0.05, atol=0.05)
    assert out.dtype == np.float32  # cast back to original dtype


def test_pytree_exchange(mesh8):
    tree = {
        "w": np.ones((8, 2, 2), np.float32),
        "b": np.full((8, 5), 2.0, np.float32),
    }
    ex = BSP_Exchanger(avg=True)
    out = _run_exchange(mesh8, ex, tree)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)
    np.testing.assert_allclose(np.asarray(out["b"]), 2.0)


def test_strategy_aliases():
    for name in ("ar", "asa32", "asa16", "copper", "nccl32", "nccl16"):
        BSP_Exchanger(strategy=name)
    with pytest.raises(ValueError):
        BSP_Exchanger(strategy="bogus")


def test_easgd_closed_form():
    alpha = 0.5
    # note: first args of the update fns are donated — use fresh trees
    new_w = easgd_worker_update({"a": jnp.array([1.0, 2.0])},
                                {"a": jnp.array([0.0, 0.0])}, alpha)
    new_c = easgd_center_update({"a": jnp.array([0.0, 0.0])},
                                {"a": jnp.array([1.0, 2.0])}, alpha)
    np.testing.assert_allclose(np.asarray(new_w["a"]), [0.5, 1.0])
    np.testing.assert_allclose(np.asarray(new_c["a"]), [0.5, 1.0])
    # fused variant matches the two-call form
    w2, c2 = easgd_both_updates({"a": jnp.array([1.0, 2.0])},
                                {"a": jnp.array([0.0, 0.0])}, alpha)
    np.testing.assert_allclose(np.asarray(w2["a"]), [0.5, 1.0])
    np.testing.assert_allclose(np.asarray(c2["a"]), [0.5, 1.0])


def test_asgd_apply():
    c = {"a": jnp.array([1.0])}
    g = {"a": jnp.array([2.0])}
    out = asgd_apply_grads(c, g, 0.1)
    np.testing.assert_allclose(np.asarray(out["a"]), [0.8])


def test_gosgd_merge_weighted_avg():
    own = {"a": jnp.array([0.0])}
    recv = {"a": jnp.array([1.0])}
    merged, w = gosgd_merge(own, 1.0, recv, 3.0)
    np.testing.assert_allclose(np.asarray(merged["a"]), [0.75])
    assert float(w) == 4.0
