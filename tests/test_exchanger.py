"""Exchange semantics: psum-of-grads equals sum of per-shard grads,
avg flag, bf16 strategy, async merge arithmetic closed forms."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel import (
    AXIS_DATA,
    BSP_Exchanger,
    asgd_apply_grads,
    easgd_both_updates,
    easgd_center_update,
    easgd_worker_update,
    gosgd_merge,
)


def _run_exchange(mesh, exchanger, tree):
    f = jax.shard_map(
        exchanger.exchange,
        mesh=mesh,
        in_specs=P(AXIS_DATA),
        out_specs=P(AXIS_DATA),
        check_vma=False,
    )
    return f(tree)


def test_psum_sum_of_shards(mesh8):
    x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    ex = BSP_Exchanger(strategy="ar", avg=False)
    out = np.asarray(_run_exchange(mesh8, ex, x))
    expected = np.tile(x.sum(axis=0), (8, 1))
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_psum_avg(mesh8):
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    ex = BSP_Exchanger(strategy="nccl32", avg=True)
    out = np.asarray(_run_exchange(mesh8, ex, x))
    expected = np.tile(x.mean(axis=0), (8, 1))
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_bf16_strategy_close_to_fp32(mesh8):
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    ex16 = BSP_Exchanger(strategy="nccl16", avg=True)
    out = np.asarray(_run_exchange(mesh8, ex16, x))
    expected = np.tile(x.mean(axis=0), (8, 1))
    # bf16 mantissa is 8 bits -> ~1e-2 relative tolerance
    np.testing.assert_allclose(out, expected, rtol=0.05, atol=0.05)
    assert out.dtype == np.float32  # cast back to original dtype


def test_exchange_dtype_bf16_matches_f32_within_tolerance(mesh8):
    """ISSUE 5 equivalence pin: the modern ``exchange_dtype='bf16'``
    spelling quantizes to bfloat16 for the psum and restores f32 for
    the average — the result must match the f32 exchange within bf16's
    8-bit mantissa (documented tolerance: rel 2^-7 after the
    sum-of-8)."""
    rng = np.random.RandomState(3)
    x = rng.randn(8, 64).astype(np.float32)
    ex_bf = BSP_Exchanger(exchange_dtype="bf16", avg=True)
    ex_f32 = BSP_Exchanger(exchange_dtype="f32", avg=True)
    assert ex_bf.wire_dtype == "bf16" and ex_bf.resolved == "psum_bf16"
    assert ex_f32.wire_dtype == "f32" and ex_f32.resolved == "psum"
    out_bf = np.asarray(_run_exchange(mesh8, ex_bf, x))
    out_f = np.asarray(_run_exchange(mesh8, ex_f32, x))
    assert out_bf.dtype == np.float32  # f32 accumulation downstream
    np.testing.assert_allclose(out_bf, out_f, rtol=2 ** -7, atol=2 ** -7)


def test_exchange_dtype_and_error_feedback_validation():
    with pytest.raises(ValueError, match="exchange_dtype"):
        BSP_Exchanger(exchange_dtype="f16")
    # error feedback compensates bf16 quantization — f32 has none
    with pytest.raises(ValueError, match="bf16"):
        BSP_Exchanger(error_feedback=True)
    with pytest.raises(ValueError, match="params"):
        BSP_Exchanger(exchange_dtype="bf16", error_feedback=True,
                      exchange_what="params")
    # the reference-era strategy spelling counts as the bf16 wire
    BSP_Exchanger(strategy="nccl16", error_feedback=True)
    ex = BSP_Exchanger(exchange_dtype="bf16")
    with pytest.raises(ValueError, match="error_feedback"):
        ex.exchange_with_residual({}, {})


def test_error_feedback_long_run_gradient_sum(mesh8):
    """The ISSUE 5 acceptance pin: with error feedback, the CUMULATIVE
    applied gradient tracks the cumulative true f32 mean to within one
    bf16 quantization step — the error does NOT grow with the number
    of exchanges — while plain bf16 quantization drifts O(K) on
    below-resolution gradient components."""
    from jax.sharding import PartitionSpec

    K = 200
    # per-shard gradient with a component bf16 cannot resolve: 1.0 +
    # eps where eps << 2^-9 never survives Q(1+eps) -> 1.0, so the
    # naive wire silently drops K*eps; the residual must carry it
    eps = np.arange(1, 9, dtype=np.float32)[:, None] * 2e-4
    g = np.ones((8, 16), np.float32) + eps
    true_mean = g.mean(axis=0)

    ex = BSP_Exchanger(exchange_dtype="bf16", error_feedback=True,
                       avg=True)
    step = jax.jit(jax.shard_map(
        ex.exchange_with_residual, mesh=mesh8,
        in_specs=(PartitionSpec(AXIS_DATA), PartitionSpec(AXIS_DATA)),
        out_specs=(PartitionSpec(AXIS_DATA), PartitionSpec(AXIS_DATA)),
        check_vma=False))

    residual = np.zeros_like(g)
    applied = np.zeros((16,), np.float64)
    naive = np.zeros((16,), np.float64)
    for _ in range(K):
        out, residual = step(g, residual)
        applied += np.asarray(out)[0]
        naive += np.asarray(
            jnp.mean(g.astype(jnp.bfloat16).astype(jnp.float32), axis=0))
    target = true_mean.astype(np.float64) * K
    ef_err = np.abs(applied - target).max()
    naive_err = np.abs(naive - target).max()
    # cumulative applied = K*true - mean(r_K) exactly (telescoping sum
    # with f32 accumulation via _bf16_sum), so the error is bounded by
    # ONE bf16 quantization step of the ~1.0 payload (2^-8 ~ 0.004),
    # independent of K (measured 0.0013 at K=200)
    assert ef_err < 4e-3, ef_err
    # the naive wire silently dropped ~K*eps — two orders worse
    assert naive_err > 0.1 and naive_err > 50 * ef_err, (naive_err, ef_err)
    # the residual is live state, not zeros: it holds what the wire
    # hasn't emitted yet
    assert np.abs(np.asarray(residual)).max() > 0


def test_bsp_train_step_bf16_exchange_matches_f32(mesh8):
    """Full BSP train-step equivalence (acceptance criterion): 3 steps
    with the bf16 gradient exchange land within documented tolerance
    of 3 f32 steps, and the error-feedback variant threads its
    residual through ``TrainState.exchange_residual``."""
    import optax

    from theanompi_tpu.parallel.bsp import (
        TrainState,
        init_exchange_residual,
        make_bsp_train_step,
    )

    def loss(params, model_state, batch, rng):
        x, y = batch
        pred = jnp.tanh(x @ params["w1"]) @ params["w2"]
        l = jnp.mean((pred - y) ** 2)
        return l, (model_state, {"loss": l, "error": l})

    k1, k2 = jax.random.split(jax.random.key(0))
    params = {"w1": jax.random.normal(k1, (6, 9)),
              "w2": jax.random.normal(k2, (9, 2))}
    tx = optax.sgd(0.05, momentum=0.9)
    rng_np = np.random.default_rng(5)
    x = rng_np.standard_normal((32, 6)).astype(np.float32)
    y = rng_np.standard_normal((32, 2)).astype(np.float32)
    rng = jax.random.key(1)

    from theanompi_tpu.parallel.mesh import shard_batch
    batch = shard_batch((x, y), mesh8)

    def run(exchanger, residual=None):
        step = make_bsp_train_step(loss, tx, mesh8, exchanger,
                                   donate=False)
        s = TrainState.create(params, tx)
        if residual is not None:
            s = s.replace(exchange_residual=residual)
        for _ in range(3):
            s, m = step(s, batch, rng)
        return s, m

    s_f32, m_f32 = run(BSP_Exchanger(avg=True))
    s_bf16, m_bf16 = run(BSP_Exchanger(exchange_dtype="bf16", avg=True))
    s_ef, _ = run(BSP_Exchanger(exchange_dtype="bf16",
                                error_feedback=True, avg=True),
                  residual=init_exchange_residual(params, 8))
    for name, s_q in (("bf16", s_bf16), ("bf16+ef", s_ef)):
        for a, b in zip(jax.tree.leaves(s_f32.params),
                        jax.tree.leaves(s_q.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0.02, atol=2e-3,
                                       err_msg=name)
    assert float(m_bf16["loss"]) == pytest.approx(float(m_f32["loss"]),
                                                  rel=0.02)
    # the EF run's residual came back per-shard and non-degenerate
    res_leaves = jax.tree.leaves(s_ef.exchange_residual)
    assert res_leaves and all(l.shape[0] == 8 for l in res_leaves)
    # missing residual state fails loudly, not silently uncompensated
    with pytest.raises(ValueError, match="exchange_residual"):
        run(BSP_Exchanger(exchange_dtype="bf16", error_feedback=True,
                          avg=True))


def test_pytree_exchange(mesh8):
    tree = {
        "w": np.ones((8, 2, 2), np.float32),
        "b": np.full((8, 5), 2.0, np.float32),
    }
    ex = BSP_Exchanger(avg=True)
    out = _run_exchange(mesh8, ex, tree)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)
    np.testing.assert_allclose(np.asarray(out["b"]), 2.0)


def test_strategy_aliases():
    for name in ("ar", "asa32", "asa16", "copper", "nccl32", "nccl16"):
        BSP_Exchanger(strategy=name)
    with pytest.raises(ValueError):
        BSP_Exchanger(strategy="bogus")


def test_easgd_closed_form():
    alpha = 0.5
    # note: first args of the update fns are donated — use fresh trees
    new_w = easgd_worker_update({"a": jnp.array([1.0, 2.0])},
                                {"a": jnp.array([0.0, 0.0])}, alpha)
    new_c = easgd_center_update({"a": jnp.array([0.0, 0.0])},
                                {"a": jnp.array([1.0, 2.0])}, alpha)
    np.testing.assert_allclose(np.asarray(new_w["a"]), [0.5, 1.0])
    np.testing.assert_allclose(np.asarray(new_c["a"]), [0.5, 1.0])
    # fused variant matches the two-call form
    w2, c2 = easgd_both_updates({"a": jnp.array([1.0, 2.0])},
                                {"a": jnp.array([0.0, 0.0])}, alpha)
    np.testing.assert_allclose(np.asarray(w2["a"]), [0.5, 1.0])
    np.testing.assert_allclose(np.asarray(c2["a"]), [0.5, 1.0])


def test_asgd_apply():
    c = {"a": jnp.array([1.0])}
    g = {"a": jnp.array([2.0])}
    out = asgd_apply_grads(c, g, 0.1)
    np.testing.assert_allclose(np.asarray(out["a"]), [0.8])


def test_gosgd_merge_weighted_avg():
    own = {"a": jnp.array([0.0])}
    recv = {"a": jnp.array([1.0])}
    merged, w = gosgd_merge(own, 1.0, recv, 3.0)
    np.testing.assert_allclose(np.asarray(merged["a"]), [0.75])
    assert float(w) == 4.0


def _named_leaves(state):
    from jax import tree_util as jtu

    return {jtu.keystr(path): leaf
            for path, leaf in jtu.tree_flatten_with_path(state)[0]}


def _has_field(key: str, name: str) -> bool:
    import re

    return re.search(rf"(?<![A-Za-z_]){name}(?![A-Za-z_])", key) is not None


def test_gosgd_scale_momentum_first_moments_only():
    """Merge-time momentum scaling (the measured stale-momentum
    divergence fix, docs/SCALING.md): FIRST-moment slots (adam mu)
    scale by the receiver's share; second moments (nu), counts and
    hyperparams are kept — shrinking nu with a stale bias-correction
    count would inflate the next preconditioned step."""
    import optax

    from theanompi_tpu.parallel import gosgd_scale_momentum

    params = {"w": jnp.ones(4), "b": jnp.ones(2)}
    tx = optax.adamw(1e-3)
    state = tx.init(params)
    g = jax.tree.map(jnp.ones_like, params)
    _, state = tx.update(g, state, params)

    before = _named_leaves(state)
    after = _named_leaves(gosgd_scale_momentum(state, 0.25))
    assert before.keys() == after.keys()
    n_mu = n_kept = 0
    for key, v in before.items():
        if _has_field(key, "mu"):
            np.testing.assert_allclose(np.asarray(after[key]),
                                       0.25 * np.asarray(v), rtol=1e-6)
            n_mu += 1
        else:  # nu, count
            np.testing.assert_allclose(np.asarray(after[key]),
                                       np.asarray(v))
            n_kept += 1
    assert n_mu >= 2 and n_kept >= 3  # mu x2 leaves; nu x2 + count


def test_gosgd_scale_momentum_through_build_optimizer():
    """The PRODUCTION optimizer shape — inject_hyperparams(chain(...))
    from build_optimizer — must scale its trace/mu and keep nu, count,
    and the injected learning_rate."""
    from theanompi_tpu.parallel import gosgd_scale_momentum
    from theanompi_tpu.utils.helper_funcs import build_optimizer

    params = {"w": jnp.ones(3)}
    for opt, first, kept in [
        ("sgd", "trace", "learning_rate"),
        ("adamw", "mu", "nu"),
    ]:
        tx = build_optimizer(0.1, optimizer=opt, momentum=0.9,
                             weight_decay=1e-4)
        state = tx.init(params)
        _, state = tx.update({"w": jnp.ones(3)}, state, params)
        before = _named_leaves(state)
        after = _named_leaves(gosgd_scale_momentum(state, 0.5))
        f_keys = [k for k in before if _has_field(k, first)]
        k_keys = [k for k in before if _has_field(k, kept)]
        assert f_keys and k_keys, (opt, sorted(before))
        for k in f_keys:
            np.testing.assert_allclose(np.asarray(after[k]),
                                       0.5 * np.asarray(before[k]),
                                       rtol=1e-6)
        for k in k_keys:
            np.testing.assert_allclose(np.asarray(after[k]),
                                       np.asarray(before[k]))


def test_gosgd_dominant_push_resets_momentum():
    """A push whose weight dwarfs the receiver's must effectively reset
    the receiver's momentum (share -> 0), so the next SGD step is a
    plain gradient at the teleported point rather than a stale kick."""
    import optax

    from theanompi_tpu.parallel import gosgd_merge, gosgd_scale_momentum

    tx = optax.sgd(0.1, momentum=0.9)
    params = {"w": jnp.zeros(3)}
    state = tx.init(params)
    _, state = tx.update({"w": jnp.ones(3)}, state, params)

    own_w, recv_w = 1e-6, 0.5
    _, new_w = gosgd_merge(params, own_w, {"w": jnp.ones(3)}, recv_w)
    scaled = gosgd_scale_momentum(state, own_w / float(new_w))
    mom = [v for k, v in _named_leaves(scaled).items()
           if _has_field(k, "trace")]
    assert mom and float(jnp.abs(mom[0]).max()) < 1e-5


# ---------------------------------------------------------------------------
# Bucketed exchange (ISSUE 13): layer-ordered byte-balanced buckets,
# collectives embedded in the backward DAG, B-count equivalence pins.
# ---------------------------------------------------------------------------


class TestBucketPlan:
    def test_plan_pure_balanced_contiguous(self):
        from theanompi_tpu.parallel.exchanger import bucket_ranges

        sizes = [4 * n for n in (7, 7, 3, 64, 64, 64, 64, 1, 4096, 10)]
        for B in (1, 2, 4, 8):
            plan = bucket_ranges(sizes, B)
            # purity: identical on a second derivation (every rank
            # computes its own plan — no plan ever travels on a wire)
            assert plan == bucket_ranges(list(sizes), B)
            # contiguity + full cover, in order (layer order IS
            # flatten order)
            assert plan[0][0] == 0 and plan[-1][1] == len(sizes)
            for (_, hi), (lo2, _) in zip(plan, plan[1:]):
                assert hi == lo2
            assert all(hi > lo for lo, hi in plan)
            # byte balance: the greedy quantile walk never exceeds a
            # quantile target by more than one leaf
            per = [sum(sizes[lo:hi]) for lo, hi in plan]
            assert max(per) <= sum(sizes) / len(plan) + max(sizes)

    def test_plan_clamps_beyond_leaf_count(self):
        from theanompi_tpu.parallel.exchanger import bucket_ranges

        # a bucket plan is a scheduling hint: B > n_leaves degrades to
        # per-leaf buckets instead of raising like the shard plan
        assert bucket_ranges([8, 8, 8], 64) == [(0, 1), (1, 2), (2, 3)]

    def test_plan_shares_the_shard_partition_walk(self):
        from theanompi_tpu.parallel.exchanger import bucket_ranges
        from theanompi_tpu.parallel.shards import partition_ranges

        sizes = [3, 100, 7, 42, 42, 9, 512, 1]
        for k in (1, 2, 4):
            assert bucket_ranges(sizes, k) == partition_ranges(sizes, k)

    def test_exchanger_validates_bucket_count(self):
        for bad in (0, -1, 1.5, True):
            with pytest.raises(ValueError, match="exchange_buckets"):
                BSP_Exchanger(exchange_buckets=bad)


class TestBucketedPostHocExchange:
    """exchange()/exchange_with_residual() with B>1 regroup the
    per-leaf collectives into per-bucket flat ones — elementwise
    identical (no per-element sum moves)."""

    def test_exchange_bit_identical_across_bucket_counts(self, mesh8):
        rng = np.random.RandomState(7)
        tree = {f"l{i:02d}": rng.randn(8, 3 + i).astype(np.float32)
                for i in range(6)}
        for dtype in (None, "bf16"):
            ref = _run_exchange(mesh8,
                                BSP_Exchanger(exchange_dtype=dtype,
                                              avg=True), tree)
            for B in (2, 4, 8):
                out = _run_exchange(
                    mesh8, BSP_Exchanger(exchange_dtype=dtype, avg=True,
                                         exchange_buckets=B), tree)
                for a, b in zip(jax.tree.leaves(ref),
                                jax.tree.leaves(out)):
                    np.testing.assert_array_equal(np.asarray(a),
                                                  np.asarray(b))

    def test_exchange_with_residual_bucketed_identical(self, mesh8):
        from jax.sharding import PartitionSpec

        rng = np.random.RandomState(9)
        tree = {f"l{i}": rng.randn(8, 16).astype(np.float32)
                for i in range(4)}
        res = jax.tree.map(lambda x: np.zeros_like(x), tree)

        def run(B):
            ex = BSP_Exchanger(exchange_dtype="bf16",
                               error_feedback=True, avg=True,
                               exchange_buckets=B)
            f = jax.jit(jax.shard_map(
                ex.exchange_with_residual, mesh=mesh8,
                in_specs=(PartitionSpec(AXIS_DATA),) * 2,
                out_specs=(PartitionSpec(AXIS_DATA),) * 2,
                check_vma=False))
            return f(tree, res)

        out1, res1 = run(1)
        for B in (2, 4):
            outB, resB = run(B)
            for a, b in zip(jax.tree.leaves(out1), jax.tree.leaves(outB)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(res1), jax.tree.leaves(resB)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _bucket_loss(params, model_state, batch, rng):
    x, y = batch
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    pred = h @ params["w2"] + params["b2"]
    loss = jnp.mean((pred - y) ** 2)
    return loss, (model_state, {"loss": loss, "error": loss})


def _bucket_params():
    k = jax.random.split(jax.random.key(3), 2)
    return {"w1": jax.random.normal(k[0], (6, 9)) * 0.3,
            "b1": jnp.zeros(9),
            "w2": jax.random.normal(k[1], (9, 2)) * 0.3,
            "b2": jnp.zeros(2)}


def _bucket_batch(mesh8):
    from theanompi_tpu.parallel.mesh import shard_batch

    rng_np = np.random.default_rng(5)
    x = rng_np.standard_normal((32, 6)).astype(np.float32)
    y = rng_np.standard_normal((32, 2)).astype(np.float32)
    return shard_batch((x, y), mesh8)


class TestBucketedTrainStep:
    """The acceptance pins: B>1 equal to B=1 at EVERY step, plain and
    error-feedback variants, with the collectives embedded in the
    backward (HLO pin below)."""

    def _run(self, mesh8, B, dtype=None, ef=False, steps=3):
        import optax

        from theanompi_tpu.parallel.bsp import (
            TrainState,
            init_exchange_residual,
            make_bsp_train_step,
        )

        params = _bucket_params()
        tx = optax.sgd(0.05, momentum=0.9)
        ex = BSP_Exchanger(exchange_dtype=dtype, error_feedback=ef,
                           exchange_buckets=B, avg=True)
        step = make_bsp_train_step(_bucket_loss, tx, mesh8, ex,
                                   donate=False)
        s = TrainState.create(params, tx)
        if ef:
            s = s.replace(
                exchange_residual=init_exchange_residual(params, 8))
        batch = _bucket_batch(mesh8)
        rng = jax.random.key(1)
        traj = []
        for _ in range(steps):
            s, m = step(s, batch, rng)
            traj.append(jax.tree.map(np.asarray, s.params))
        return s, m, traj

    @pytest.mark.parametrize("dtype,ef", [(None, False), ("bf16", False),
                                          ("bf16", True)])
    def test_bucketed_step_bit_identical_per_step(self, mesh8, dtype, ef):
        s1, m1, traj1 = self._run(mesh8, 1, dtype, ef)
        for B in (2, 4, 8):
            sB, mB, trajB = self._run(mesh8, B, dtype, ef)
            for t1, tB in zip(traj1, trajB):  # EVERY step, not just last
                for a, b in zip(jax.tree.leaves(t1), jax.tree.leaves(tB)):
                    np.testing.assert_array_equal(a, b, err_msg=f"B={B}")
            assert float(m1["loss"]) == float(mB["loss"])
            if ef:
                for a, b in zip(jax.tree.leaves(s1.exchange_residual),
                                jax.tree.leaves(sB.exchange_residual)):
                    np.testing.assert_array_equal(np.asarray(a),
                                                  np.asarray(b))

    def test_bucketed_cadences_bit_identical(self, mesh8):
        import optax
        from jax.sharding import PartitionSpec as P

        from theanompi_tpu.parallel.bsp import (
            TrainState,
            make_bsp_accum_step,
            make_bsp_multi_step,
        )
        from theanompi_tpu.parallel.mesh import shard_batch

        params = _bucket_params()
        tx = optax.sgd(0.05, momentum=0.9)
        rng_np = np.random.default_rng(6)
        xs = rng_np.standard_normal((2, 32, 6)).astype(np.float32)
        ys = rng_np.standard_normal((2, 32, 2)).astype(np.float32)
        stacked = shard_batch((xs, ys), mesh8, spec=P(None, "data"))
        for maker in (make_bsp_multi_step, make_bsp_accum_step):
            outs = {}
            for B in (1, 4):
                ex = BSP_Exchanger(exchange_buckets=B, avg=True)
                step = maker(_bucket_loss, tx, mesh8, ex, donate=False)
                s = TrainState.create(params, tx)
                s, _ = step(s, stacked, jax.random.key(2))
                outs[B] = s
            for a, b in zip(jax.tree.leaves(outs[1].params),
                            jax.tree.leaves(outs[4].params)):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b),
                                              err_msg=maker.__name__)

    def test_backward_exchange_rejects_params_mode(self):
        ex = BSP_Exchanger(exchange_what="params", exchange_buckets=2)
        with pytest.raises(ValueError, match="backward"):
            ex.backward_exchange(_bucket_loss, {}, {}, None, None)

    def test_bucketed_ef_requires_residual_state(self, mesh8):
        import optax

        from theanompi_tpu.parallel.bsp import (
            TrainState,
            make_bsp_train_step,
        )

        ex = BSP_Exchanger(exchange_dtype="bf16", error_feedback=True,
                           exchange_buckets=4, avg=True)
        step = make_bsp_train_step(_bucket_loss, optax.sgd(0.05), mesh8,
                                   ex, donate=False)
        s = TrainState.create(_bucket_params(), optax.sgd(0.05))
        with pytest.raises(ValueError, match="exchange_residual"):
            step(s, _bucket_batch(mesh8), jax.random.key(0))


class TestBucketedHloInterleaving:
    """The structural acceptance pin: the bucketed program carries B
    bucket all-reduces INTERLEAVED with backward compute; the B=1
    program keeps one trailing collective block after every backward
    dot."""

    def _lowered(self, mesh8, B):
        import optax

        from theanompi_tpu.parallel.bsp import (
            TrainState,
            make_bsp_train_step,
        )

        params = _bucket_params()
        tx = optax.sgd(0.05, momentum=0.9)
        ex = BSP_Exchanger(exchange_buckets=B, avg=True)
        step = make_bsp_train_step(_bucket_loss, tx, mesh8, ex,
                                   donate=False)
        s = TrainState.create(params, tx)
        return step.lower(s, _bucket_batch(mesh8),
                          jax.random.key(0)).as_text()

    @staticmethod
    def _layout(txt):
        lines = txt.splitlines()
        ar = [i for i, l in enumerate(lines)
              if "stablehlo.all_reduce" in l]
        dots = [i for i, l in enumerate(lines)
                if "stablehlo.dot_general" in l]
        return ar, dots

    def test_bucket_collective_count_and_interleave(self, mesh8):
        n_leaves = len(jax.tree.leaves(_bucket_params()))
        ar1, dots1 = self._layout(self._lowered(mesh8, 1))
        # B=1: one psum per leaf (+ the metric pmeans) — ALL of them
        # after the last backward dot: one trailing collective block
        metric_ars = len(ar1) - n_leaves
        assert metric_ars >= 0
        assert not [d for d in dots1 if d > ar1[0]], \
            "B=1 lowering has backward compute after a collective"
        for B in (2, 4):
            arB, dotsB = self._layout(self._lowered(mesh8, B))
            # exactly B bucket collectives (each bucket's leaves are
            # flattened into ONE all-reduce) + the metric pmeans
            assert len(arB) == B + metric_ars, (B, len(arB), metric_ars)
            # interleaving: backward dots appear AFTER the first bucket
            # collective — the exchange overlaps the remaining backward
            assert [d for d in dotsB if d > arB[0]], \
                f"B={B} lowering has no backward compute after the " \
                "first bucket collective"

    def test_bucket_gauges_emitted_at_trace_time(self, mesh8, tmp_path):
        import json

        import optax

        from theanompi_tpu import monitor
        from theanompi_tpu.parallel.bsp import (
            TrainState,
            make_bsp_train_step,
        )

        with monitor.session(run_dir=str(tmp_path)):
            ex = BSP_Exchanger(exchange_buckets=4, avg=True)
            step = make_bsp_train_step(_bucket_loss,
                                       optax.sgd(0.05, momentum=0.9),
                                       mesh8, ex, donate=False)
            s = TrainState.create(_bucket_params(),
                                  optax.sgd(0.05, momentum=0.9))
            s, _ = step(s, _bucket_batch(mesh8), jax.random.key(0))
            monitor.flush()
        recs = [json.loads(l) for l in
                open(tmp_path / "metrics_rank0.jsonl")]
        by = {}
        for r in recs:
            by.setdefault(r["name"], []).append(r)
        (bk,) = [r for r in by["bsp/exchange_buckets"]
                 if r["labels"].get("plane") == "bsp"]
        assert bk["value"] == 4
        buckets = {r["labels"]["bucket"]
                   for r in by["bsp/exchange_bucket_bytes"]}
        assert buckets == {"0", "1", "2", "3"}
        total = sum(r["value"] for r in by["bsp/exchange_bucket_bytes"])
        n_param_bytes = sum(l.size * 4 for l in
                            jax.tree.leaves(_bucket_params()))
        assert total == n_param_bytes
