"""Exchange semantics: psum-of-grads equals sum of per-shard grads,
avg flag, bf16 strategy, async merge arithmetic closed forms."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel import (
    AXIS_DATA,
    BSP_Exchanger,
    asgd_apply_grads,
    easgd_both_updates,
    easgd_center_update,
    easgd_worker_update,
    gosgd_merge,
)


def _run_exchange(mesh, exchanger, tree):
    f = jax.shard_map(
        exchanger.exchange,
        mesh=mesh,
        in_specs=P(AXIS_DATA),
        out_specs=P(AXIS_DATA),
        check_vma=False,
    )
    return f(tree)


def test_psum_sum_of_shards(mesh8):
    x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    ex = BSP_Exchanger(strategy="ar", avg=False)
    out = np.asarray(_run_exchange(mesh8, ex, x))
    expected = np.tile(x.sum(axis=0), (8, 1))
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_psum_avg(mesh8):
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    ex = BSP_Exchanger(strategy="nccl32", avg=True)
    out = np.asarray(_run_exchange(mesh8, ex, x))
    expected = np.tile(x.mean(axis=0), (8, 1))
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_bf16_strategy_close_to_fp32(mesh8):
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    ex16 = BSP_Exchanger(strategy="nccl16", avg=True)
    out = np.asarray(_run_exchange(mesh8, ex16, x))
    expected = np.tile(x.mean(axis=0), (8, 1))
    # bf16 mantissa is 8 bits -> ~1e-2 relative tolerance
    np.testing.assert_allclose(out, expected, rtol=0.05, atol=0.05)
    assert out.dtype == np.float32  # cast back to original dtype


def test_exchange_dtype_bf16_matches_f32_within_tolerance(mesh8):
    """ISSUE 5 equivalence pin: the modern ``exchange_dtype='bf16'``
    spelling quantizes to bfloat16 for the psum and restores f32 for
    the average — the result must match the f32 exchange within bf16's
    8-bit mantissa (documented tolerance: rel 2^-7 after the
    sum-of-8)."""
    rng = np.random.RandomState(3)
    x = rng.randn(8, 64).astype(np.float32)
    ex_bf = BSP_Exchanger(exchange_dtype="bf16", avg=True)
    ex_f32 = BSP_Exchanger(exchange_dtype="f32", avg=True)
    assert ex_bf.wire_dtype == "bf16" and ex_bf.resolved == "psum_bf16"
    assert ex_f32.wire_dtype == "f32" and ex_f32.resolved == "psum"
    out_bf = np.asarray(_run_exchange(mesh8, ex_bf, x))
    out_f = np.asarray(_run_exchange(mesh8, ex_f32, x))
    assert out_bf.dtype == np.float32  # f32 accumulation downstream
    np.testing.assert_allclose(out_bf, out_f, rtol=2 ** -7, atol=2 ** -7)


def test_exchange_dtype_and_error_feedback_validation():
    with pytest.raises(ValueError, match="exchange_dtype"):
        BSP_Exchanger(exchange_dtype="f16")
    # error feedback compensates bf16 quantization — f32 has none
    with pytest.raises(ValueError, match="bf16"):
        BSP_Exchanger(error_feedback=True)
    with pytest.raises(ValueError, match="params"):
        BSP_Exchanger(exchange_dtype="bf16", error_feedback=True,
                      exchange_what="params")
    # the reference-era strategy spelling counts as the bf16 wire
    BSP_Exchanger(strategy="nccl16", error_feedback=True)
    ex = BSP_Exchanger(exchange_dtype="bf16")
    with pytest.raises(ValueError, match="error_feedback"):
        ex.exchange_with_residual({}, {})


def test_error_feedback_long_run_gradient_sum(mesh8):
    """The ISSUE 5 acceptance pin: with error feedback, the CUMULATIVE
    applied gradient tracks the cumulative true f32 mean to within one
    bf16 quantization step — the error does NOT grow with the number
    of exchanges — while plain bf16 quantization drifts O(K) on
    below-resolution gradient components."""
    from jax.sharding import PartitionSpec

    K = 200
    # per-shard gradient with a component bf16 cannot resolve: 1.0 +
    # eps where eps << 2^-9 never survives Q(1+eps) -> 1.0, so the
    # naive wire silently drops K*eps; the residual must carry it
    eps = np.arange(1, 9, dtype=np.float32)[:, None] * 2e-4
    g = np.ones((8, 16), np.float32) + eps
    true_mean = g.mean(axis=0)

    ex = BSP_Exchanger(exchange_dtype="bf16", error_feedback=True,
                       avg=True)
    step = jax.jit(jax.shard_map(
        ex.exchange_with_residual, mesh=mesh8,
        in_specs=(PartitionSpec(AXIS_DATA), PartitionSpec(AXIS_DATA)),
        out_specs=(PartitionSpec(AXIS_DATA), PartitionSpec(AXIS_DATA)),
        check_vma=False))

    residual = np.zeros_like(g)
    applied = np.zeros((16,), np.float64)
    naive = np.zeros((16,), np.float64)
    for _ in range(K):
        out, residual = step(g, residual)
        applied += np.asarray(out)[0]
        naive += np.asarray(
            jnp.mean(g.astype(jnp.bfloat16).astype(jnp.float32), axis=0))
    target = true_mean.astype(np.float64) * K
    ef_err = np.abs(applied - target).max()
    naive_err = np.abs(naive - target).max()
    # cumulative applied = K*true - mean(r_K) exactly (telescoping sum
    # with f32 accumulation via _bf16_sum), so the error is bounded by
    # ONE bf16 quantization step of the ~1.0 payload (2^-8 ~ 0.004),
    # independent of K (measured 0.0013 at K=200)
    assert ef_err < 4e-3, ef_err
    # the naive wire silently dropped ~K*eps — two orders worse
    assert naive_err > 0.1 and naive_err > 50 * ef_err, (naive_err, ef_err)
    # the residual is live state, not zeros: it holds what the wire
    # hasn't emitted yet
    assert np.abs(np.asarray(residual)).max() > 0


def test_bsp_train_step_bf16_exchange_matches_f32(mesh8):
    """Full BSP train-step equivalence (acceptance criterion): 3 steps
    with the bf16 gradient exchange land within documented tolerance
    of 3 f32 steps, and the error-feedback variant threads its
    residual through ``TrainState.exchange_residual``."""
    import optax

    from theanompi_tpu.parallel.bsp import (
        TrainState,
        init_exchange_residual,
        make_bsp_train_step,
    )

    def loss(params, model_state, batch, rng):
        x, y = batch
        pred = jnp.tanh(x @ params["w1"]) @ params["w2"]
        l = jnp.mean((pred - y) ** 2)
        return l, (model_state, {"loss": l, "error": l})

    k1, k2 = jax.random.split(jax.random.key(0))
    params = {"w1": jax.random.normal(k1, (6, 9)),
              "w2": jax.random.normal(k2, (9, 2))}
    tx = optax.sgd(0.05, momentum=0.9)
    rng_np = np.random.default_rng(5)
    x = rng_np.standard_normal((32, 6)).astype(np.float32)
    y = rng_np.standard_normal((32, 2)).astype(np.float32)
    rng = jax.random.key(1)

    from theanompi_tpu.parallel.mesh import shard_batch
    batch = shard_batch((x, y), mesh8)

    def run(exchanger, residual=None):
        step = make_bsp_train_step(loss, tx, mesh8, exchanger,
                                   donate=False)
        s = TrainState.create(params, tx)
        if residual is not None:
            s = s.replace(exchange_residual=residual)
        for _ in range(3):
            s, m = step(s, batch, rng)
        return s, m

    s_f32, m_f32 = run(BSP_Exchanger(avg=True))
    s_bf16, m_bf16 = run(BSP_Exchanger(exchange_dtype="bf16", avg=True))
    s_ef, _ = run(BSP_Exchanger(exchange_dtype="bf16",
                                error_feedback=True, avg=True),
                  residual=init_exchange_residual(params, 8))
    for name, s_q in (("bf16", s_bf16), ("bf16+ef", s_ef)):
        for a, b in zip(jax.tree.leaves(s_f32.params),
                        jax.tree.leaves(s_q.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0.02, atol=2e-3,
                                       err_msg=name)
    assert float(m_bf16["loss"]) == pytest.approx(float(m_f32["loss"]),
                                                  rel=0.02)
    # the EF run's residual came back per-shard and non-degenerate
    res_leaves = jax.tree.leaves(s_ef.exchange_residual)
    assert res_leaves and all(l.shape[0] == 8 for l in res_leaves)
    # missing residual state fails loudly, not silently uncompensated
    with pytest.raises(ValueError, match="exchange_residual"):
        run(BSP_Exchanger(exchange_dtype="bf16", error_feedback=True,
                          avg=True))


def test_pytree_exchange(mesh8):
    tree = {
        "w": np.ones((8, 2, 2), np.float32),
        "b": np.full((8, 5), 2.0, np.float32),
    }
    ex = BSP_Exchanger(avg=True)
    out = _run_exchange(mesh8, ex, tree)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)
    np.testing.assert_allclose(np.asarray(out["b"]), 2.0)


def test_strategy_aliases():
    for name in ("ar", "asa32", "asa16", "copper", "nccl32", "nccl16"):
        BSP_Exchanger(strategy=name)
    with pytest.raises(ValueError):
        BSP_Exchanger(strategy="bogus")


def test_easgd_closed_form():
    alpha = 0.5
    # note: first args of the update fns are donated — use fresh trees
    new_w = easgd_worker_update({"a": jnp.array([1.0, 2.0])},
                                {"a": jnp.array([0.0, 0.0])}, alpha)
    new_c = easgd_center_update({"a": jnp.array([0.0, 0.0])},
                                {"a": jnp.array([1.0, 2.0])}, alpha)
    np.testing.assert_allclose(np.asarray(new_w["a"]), [0.5, 1.0])
    np.testing.assert_allclose(np.asarray(new_c["a"]), [0.5, 1.0])
    # fused variant matches the two-call form
    w2, c2 = easgd_both_updates({"a": jnp.array([1.0, 2.0])},
                                {"a": jnp.array([0.0, 0.0])}, alpha)
    np.testing.assert_allclose(np.asarray(w2["a"]), [0.5, 1.0])
    np.testing.assert_allclose(np.asarray(c2["a"]), [0.5, 1.0])


def test_asgd_apply():
    c = {"a": jnp.array([1.0])}
    g = {"a": jnp.array([2.0])}
    out = asgd_apply_grads(c, g, 0.1)
    np.testing.assert_allclose(np.asarray(out["a"]), [0.8])


def test_gosgd_merge_weighted_avg():
    own = {"a": jnp.array([0.0])}
    recv = {"a": jnp.array([1.0])}
    merged, w = gosgd_merge(own, 1.0, recv, 3.0)
    np.testing.assert_allclose(np.asarray(merged["a"]), [0.75])
    assert float(w) == 4.0


def _named_leaves(state):
    from jax import tree_util as jtu

    return {jtu.keystr(path): leaf
            for path, leaf in jtu.tree_flatten_with_path(state)[0]}


def _has_field(key: str, name: str) -> bool:
    import re

    return re.search(rf"(?<![A-Za-z_]){name}(?![A-Za-z_])", key) is not None


def test_gosgd_scale_momentum_first_moments_only():
    """Merge-time momentum scaling (the measured stale-momentum
    divergence fix, docs/SCALING.md): FIRST-moment slots (adam mu)
    scale by the receiver's share; second moments (nu), counts and
    hyperparams are kept — shrinking nu with a stale bias-correction
    count would inflate the next preconditioned step."""
    import optax

    from theanompi_tpu.parallel import gosgd_scale_momentum

    params = {"w": jnp.ones(4), "b": jnp.ones(2)}
    tx = optax.adamw(1e-3)
    state = tx.init(params)
    g = jax.tree.map(jnp.ones_like, params)
    _, state = tx.update(g, state, params)

    before = _named_leaves(state)
    after = _named_leaves(gosgd_scale_momentum(state, 0.25))
    assert before.keys() == after.keys()
    n_mu = n_kept = 0
    for key, v in before.items():
        if _has_field(key, "mu"):
            np.testing.assert_allclose(np.asarray(after[key]),
                                       0.25 * np.asarray(v), rtol=1e-6)
            n_mu += 1
        else:  # nu, count
            np.testing.assert_allclose(np.asarray(after[key]),
                                       np.asarray(v))
            n_kept += 1
    assert n_mu >= 2 and n_kept >= 3  # mu x2 leaves; nu x2 + count


def test_gosgd_scale_momentum_through_build_optimizer():
    """The PRODUCTION optimizer shape — inject_hyperparams(chain(...))
    from build_optimizer — must scale its trace/mu and keep nu, count,
    and the injected learning_rate."""
    from theanompi_tpu.parallel import gosgd_scale_momentum
    from theanompi_tpu.utils.helper_funcs import build_optimizer

    params = {"w": jnp.ones(3)}
    for opt, first, kept in [
        ("sgd", "trace", "learning_rate"),
        ("adamw", "mu", "nu"),
    ]:
        tx = build_optimizer(0.1, optimizer=opt, momentum=0.9,
                             weight_decay=1e-4)
        state = tx.init(params)
        _, state = tx.update({"w": jnp.ones(3)}, state, params)
        before = _named_leaves(state)
        after = _named_leaves(gosgd_scale_momentum(state, 0.5))
        f_keys = [k for k in before if _has_field(k, first)]
        k_keys = [k for k in before if _has_field(k, kept)]
        assert f_keys and k_keys, (opt, sorted(before))
        for k in f_keys:
            np.testing.assert_allclose(np.asarray(after[k]),
                                       0.5 * np.asarray(before[k]),
                                       rtol=1e-6)
        for k in k_keys:
            np.testing.assert_allclose(np.asarray(after[k]),
                                       np.asarray(before[k]))


def test_gosgd_dominant_push_resets_momentum():
    """A push whose weight dwarfs the receiver's must effectively reset
    the receiver's momentum (share -> 0), so the next SGD step is a
    plain gradient at the teleported point rather than a stale kick."""
    import optax

    from theanompi_tpu.parallel import gosgd_merge, gosgd_scale_momentum

    tx = optax.sgd(0.1, momentum=0.9)
    params = {"w": jnp.zeros(3)}
    state = tx.init(params)
    _, state = tx.update({"w": jnp.ones(3)}, state, params)

    own_w, recv_w = 1e-6, 0.5
    _, new_w = gosgd_merge(params, own_w, {"w": jnp.ones(3)}, recv_w)
    scaled = gosgd_scale_momentum(state, own_w / float(new_w))
    mom = [v for k, v in _named_leaves(scaled).items()
           if _has_field(k, "trace")]
    assert mom and float(jnp.abs(mom[0]).max()) < 1e-5
