"""utils/checkpoint.py unit semantics (the rule-level resume paths are
covered in test_async_rules/test_bsp_training/test_multihost)."""

import numpy as np
import pytest

from theanompi_tpu.utils.checkpoint import Checkpointer


def test_async_save_snapshots_before_background_write(tmp_path):
    """save() returns while Orbax writes in the background; the
    payload must be snapshotted so caller mutations after return never
    reach the file."""
    ck = Checkpointer(str(tmp_path), max_to_keep=3)
    buf = np.arange(8.0)
    ck.save(0, {"w": buf, "epoch": 0})
    buf += 100.0  # mutate after the (async) save returned
    ck.save(1, {"w": buf, "epoch": 1})
    assert np.allclose(ck.restore(0)["w"], np.arange(8.0))
    assert np.allclose(ck.restore(1)["w"], np.arange(8.0) + 100.0)
    ck.close()

    # reopen: writes were durable and complete
    ck2 = Checkpointer(str(tmp_path))
    assert ck2.latest_epoch() == 1
    assert ck2.kept_epochs() == {0, 1}
    ck2.close()


def test_sync_mode_still_available(tmp_path):
    ck = Checkpointer(str(tmp_path), async_save=False)
    ck.save(0, {"x": np.ones(3)})
    assert ck.latest_epoch() == 0
    ck.close()


def test_restore_missing_raises(tmp_path):
    ck = Checkpointer(str(tmp_path))
    with pytest.raises(FileNotFoundError):
        ck.restore()
    ck.close()


def test_close_failure_chains_not_masks(tmp_path):
    """If the final write fails during another error's unwind, the
    close error surfaces WITH the original chained (__context__) —
    data-loss is never silent, the real failure never invisible."""
    ck = Checkpointer(str(tmp_path))
    ck.save(0, {"x": np.ones(2)})
    ck._mgr.close()  # sabotage: the wrapper's close will now fail

    class Boom(Exception):
        pass

    try:
        try:
            raise Boom("the real failure")
        finally:
            ck.close()
    except Boom:
        pass  # close() happened to succeed; nothing to chain
    except Exception as e:
        assert isinstance(e.__context__, Boom), e.__context__
