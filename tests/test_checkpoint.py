"""utils/checkpoint.py unit semantics (the rule-level resume paths are
covered in test_async_rules/test_bsp_training/test_multihost)."""

import hashlib
import os

import numpy as np
import pytest

from theanompi_tpu.utils.checkpoint import Checkpointer


def test_async_save_snapshots_before_background_write(tmp_path):
    """save() returns while Orbax writes in the background; the
    payload must be snapshotted so caller mutations after return never
    reach the file."""
    ck = Checkpointer(str(tmp_path), max_to_keep=3)
    buf = np.arange(8.0)
    ck.save(0, {"w": buf, "epoch": 0})
    buf += 100.0  # mutate after the (async) save returned
    ck.save(1, {"w": buf, "epoch": 1})
    assert np.allclose(ck.restore(0)["w"], np.arange(8.0))
    assert np.allclose(ck.restore(1)["w"], np.arange(8.0) + 100.0)
    ck.close()

    # reopen: writes were durable and complete
    ck2 = Checkpointer(str(tmp_path))
    assert ck2.latest_epoch() == 1
    assert ck2.kept_epochs() == {0, 1}
    ck2.close()


def test_sync_mode_still_available(tmp_path):
    ck = Checkpointer(str(tmp_path), async_save=False)
    ck.save(0, {"x": np.ones(3)})
    assert ck.latest_epoch() == 0
    ck.close()


def test_restore_missing_raises(tmp_path):
    ck = Checkpointer(str(tmp_path))
    with pytest.raises(FileNotFoundError):
        ck.restore()
    ck.close()


def test_close_failure_chains_not_masks(tmp_path):
    """If the final write fails during another error's unwind, the
    close error surfaces WITH the original chained (__context__) —
    data-loss is never silent, the real failure never invisible."""
    ck = Checkpointer(str(tmp_path))
    ck.save(0, {"x": np.ones(2)})
    ck._mgr.close()  # sabotage: the wrapper's close will now fail

    class Boom(Exception):
        pass

    try:
        try:
            raise Boom("the real failure")
        finally:
            ck.close()
    except Boom:
        pass  # close() happened to succeed; nothing to chain
    except Exception as e:
        assert isinstance(e.__context__, Boom), e.__context__


# -- read-only mode (the serving-reader contract, docs/SERVING.md) ----------


def _dir_state(root):
    """(files → sha256, set of dirs): the byte-identity oracle."""
    files, dirs = {}, set()
    for r, ds, fs in os.walk(root):
        for d in ds:
            dirs.add(os.path.relpath(os.path.join(r, d), root))
        for name in fs:
            full = os.path.join(r, name)
            with open(full, "rb") as f:
                files[os.path.relpath(full, root)] = (
                    hashlib.sha256(f.read()).hexdigest())
    return files, dirs


def test_read_only_load_leaves_dir_byte_identical(tmp_path):
    """A serving reader's full verified load — fence, manifest
    verification, restore — writes NOTHING: no manifests, no prunes,
    no quarantine, no new files."""
    ck = Checkpointer(str(tmp_path), async_save=False)
    ck.save(0, {"w": np.arange(4.0)})
    ck.save(1, {"w": np.arange(4.0) + 1})
    ck.close()
    before = _dir_state(tmp_path)

    ro = Checkpointer(str(tmp_path), read_only=True)
    assert ro.latest_epoch() == 1
    assert ro.kept_epochs() == {0, 1}
    epoch, payload = ro.restore_latest_verified()
    assert epoch == 1
    np.testing.assert_allclose(payload["w"], np.arange(4.0) + 1)
    ro.close()
    assert _dir_state(tmp_path) == before


def test_read_only_refuses_writes_and_missing_dir(tmp_path):
    ck = Checkpointer(str(tmp_path / "d"))
    ck.save(0, {"x": np.ones(2)})
    ck.close()
    ro = Checkpointer(str(tmp_path / "d"), read_only=True)
    with pytest.raises(RuntimeError, match="read-only"):
        ro.save(1, {"x": np.ones(2)})
    ro.close()
    # a reader must not CREATE the writer's directory either
    with pytest.raises(FileNotFoundError):
        Checkpointer(str(tmp_path / "nope"), read_only=True)


def test_read_only_falls_back_without_quarantine(tmp_path):
    """A corrupt latest epoch: the reader restores the previous kept
    epoch but moves NOTHING — quarantine is the owning writer's
    prerogative (utils/checkpoint.quarantine_epoch read-only no-op)."""
    from theanompi_tpu.resilience.recovery import find_step_dir
    from theanompi_tpu.utils.checkpoint import _truncate_largest_file

    ck = Checkpointer(str(tmp_path), async_save=False)
    ck.save(0, {"w": np.arange(6.0)})
    ck.save(1, {"w": np.arange(6.0) + 1})
    ck.close()
    _truncate_largest_file(find_step_dir(str(tmp_path), 1))
    before = _dir_state(tmp_path)

    ro = Checkpointer(str(tmp_path), read_only=True)
    epoch, payload = ro.restore_latest_verified()
    ro.close()
    assert epoch == 0
    np.testing.assert_allclose(payload["w"], np.arange(6.0))
    assert _dir_state(tmp_path) == before  # corrupt files left in place
    assert not os.path.isdir(tmp_path / "quarantine")
