"""Wire protocol v2 (parallel/wire.py): framed zero-copy pytree
transport — round-trip fidelity, per-payload compression/dtype
options, and the ISSUE 5 hardening bar: truncated / corrupt /
oversized frames raise a TYPED error (never a hang, never a pickle
call for arrays) and a drained frame leaves the connection usable.
"""

from __future__ import annotations

import collections
import json
import struct
import zlib
from multiprocessing import Pipe

import numpy as np
import pytest

from theanompi_tpu.parallel import wire

# importable at module scope — the namedtuple escape resolves classes
# by module/qualname, never by pickle
Point = collections.namedtuple("Point", ["x", "y"])


class Exotic:
    """Module-scope so pickle can reach it — forces the structural
    pickle escape (arrays must never take that path)."""

    def __eq__(self, other):
        return isinstance(other, Exotic)

    def __hash__(self):  # __eq__ without __hash__ would be unhashable
        return 0


def assert_tree_byte_equal(a, b):
    """Exact equality incl. dtype/shape/bytes for array leaves."""
    assert type(a) is type(b) or (
        isinstance(a, (list, tuple)) and isinstance(b, (list, tuple))
    ), (type(a), type(b))
    if isinstance(a, np.ndarray):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()
    elif isinstance(a, dict):
        assert list(a.keys()) == list(b.keys())
        for k in a:
            assert_tree_byte_equal(a[k], b[k])
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert_tree_byte_equal(x, y)
    else:
        assert a == b and type(a) is type(b)


def roundtrip(msg, opts=None, decode_opts=None):
    opts = opts or wire.WireOptions()
    head, bufs, stats = wire.encode_frame(msg, opts)
    # buffers cross the wire as bytes — materialize like send would
    bufs = [b if isinstance(b, bytes) else bytes(b) for b in bufs]
    return wire.decode_frame(head, bufs, decode_opts or opts), stats


MIXED_TREE = {
    "f32": np.arange(12, dtype=np.float32).reshape(3, 4) * 0.37,
    "f64": np.linspace(0, 1, 7),
    "f16": np.ones((2, 2), np.float16) * 0.5,
    "i32": np.arange(-5, 5, dtype=np.int32),
    "u8": np.arange(256, dtype=np.uint8).reshape(16, 16),
    "bool": np.array([True, False, True]),
    "empty": np.zeros((0, 3), np.float32),
    "scalar0d": np.float32(3.25),
    "nested": [1, 2.5, "three", None, True, b"raw-bytes",
               (4, {"deep": np.full((5,), 7, np.int64)})],
    "nt": Point(np.float32(1.5), [np.zeros(2, np.float32)]),
}


class TestRoundTrip:
    def test_mixed_tree_byte_exact(self):
        out, stats = roundtrip(MIXED_TREE)
        assert_tree_byte_equal(out, MIXED_TREE)
        assert stats.n_buffers == 9  # one per ndarray leaf
        # f32/none: what hits the wire is the payload + small framing
        assert stats.post_bytes >= sum(
            v.nbytes for v in MIXED_TREE.values()
            if isinstance(v, np.ndarray))

    def test_non_contiguous_array(self):
        arr = np.arange(24, dtype=np.float32).reshape(4, 6)[::2, ::3]
        out, _ = roundtrip({"strided": arr})
        assert_tree_byte_equal(out["strided"], np.ascontiguousarray(arr))

    def test_int_float_str_subclasses_decode(self):
        """Scalar subclasses (IntEnum config values, ...) must land on
        the plain 'i'/'f'/'s' tags — tagging by subclass NAME would
        produce frames the peer rejects as unknown node types."""
        import enum

        class Color(enum.IntEnum):
            RED = 2

        class Score(float):
            pass

        class Name(str):
            pass

        out, _ = roundtrip({"e": Color.RED, "f": Score(1.5),
                            "s": Name("hi")})
        assert out["e"] == 2 and type(out["e"]) is int
        assert out["f"] == 1.5 and type(out["f"]) is float
        assert out["s"] == "hi" and type(out["s"]) is str

    def test_zlib_lossless_and_kept_only_when_smaller(self):
        opts = wire.WireOptions(compression="zlib")
        compressible = {"z": np.zeros((64, 64), np.float32)}
        out, stats = roundtrip(compressible, opts)
        assert_tree_byte_equal(out, compressible)
        assert stats.post_bytes < stats.pre_bytes  # zeros compress
        rng = np.random.default_rng(0)
        noise = {"n": rng.standard_normal((64, 64)).astype(np.float32)}
        out2, stats2 = roundtrip(noise, opts)
        assert_tree_byte_equal(out2, noise)
        # float noise doesn't shrink: the per-leaf 'none' fallback
        # keeps the raw buffer rather than shipping a bigger one
        assert stats2.post_bytes <= stats2.pre_bytes + 64

    def test_bf16_halves_f32_and_preserves_other_dtypes(self):
        opts = wire.WireOptions(dtype="bf16")
        tree = {"w": np.linspace(-3, 3, 1024).astype(np.float32),
                "step": np.arange(10, dtype=np.int32)}
        out, stats = roundtrip(tree, opts)
        assert out["w"].dtype == np.float32       # restored on receive
        # bf16 keeps 8 mantissa bits: relative error <= 2^-8
        np.testing.assert_allclose(out["w"], tree["w"], rtol=2 ** -8)
        assert_tree_byte_equal(out["step"], tree["step"])  # untouched
        assert stats.post_bytes < tree["w"].nbytes * 0.55 + 100

    def test_optax_namedtuple_state_without_pickle(self):
        import optax

        state = optax.ScaleByAdamState(
            count=np.zeros((), np.int32),
            mu={"w": np.ones(3, np.float32)},
            nu={"w": np.full(3, 2.0, np.float32)})
        head, bufs, _ = wire.encode_frame(("ok", state),
                                          wire.WireOptions())
        # arrays and the optax state must NOT ride the pickle escape
        skel = json.loads(wire.parse_header(head)[2].decode())
        assert b"pkl" not in json.dumps(skel).encode() or \
            '"t":"pkl"' not in json.dumps(skel, separators=(",", ":"))
        status, out = wire.decode_frame(
            head, [bytes(b) for b in bufs], wire.WireOptions())
        assert status == "ok"
        assert isinstance(out, optax.ScaleByAdamState)
        assert_tree_byte_equal(out.mu, state.mu)

    def test_arrays_never_pickled_even_with_exotic_siblings(self):
        msg = {"arr": np.arange(4, dtype=np.float32), "obj": Exotic()}
        head, bufs, _ = wire.encode_frame(msg, wire.WireOptions())
        skel = wire.parse_header(head)[2].decode()
        node = json.loads(skel)
        by_key = dict(zip([k["v"] for k, _ in node["v"]],
                          [v for _, v in node["v"]]))
        assert by_key["arr"]["t"] == "nd"     # raw buffer, not pickle
        assert by_key["obj"]["t"] == "pkl"    # only the exotic leaf
        out = wire.decode_frame(head, [bytes(b) for b in bufs],
                                wire.WireOptions(allow_pickle=True))
        assert_tree_byte_equal(out["arr"], msg["arr"])

    def test_allow_pickle_false_refuses_structural_escape(self):
        head, bufs, _ = wire.encode_frame(Exotic(), wire.WireOptions())
        with pytest.raises(wire.WireDecodeError, match="allow_pickle"):
            wire.decode_frame(head, [bytes(b) for b in bufs],
                              wire.WireOptions(allow_pickle=False))


class TestDecoderHardening:
    def _frame(self, msg=None, opts=None):
        head, bufs, _ = wire.encode_frame(
            msg if msg is not None else MIXED_TREE,
            opts or wire.WireOptions())
        return head, [bytes(b) for b in bufs]

    def test_bad_magic(self):
        head, bufs = self._frame()
        with pytest.raises(wire.WireDecodeError, match="magic"):
            wire.decode_frame(b"XXXX" + head[4:], bufs)

    def test_bad_version(self):
        head, bufs = self._frame()
        with pytest.raises(wire.WireDecodeError, match="version"):
            wire.decode_frame(head[:4] + b"\x09" + head[5:], bufs)

    def test_short_header(self):
        with pytest.raises(wire.WireDecodeError, match="header"):
            wire.parse_header(b"TMW2\x02")

    def test_truncated_skeleton(self):
        head, bufs = self._frame()
        with pytest.raises(wire.WireDecodeError, match="truncated"):
            wire.decode_frame(head[:-3], bufs)

    def test_oversized_buffer_count(self):
        head, bufs = self._frame()
        n = wire.MAX_BUFFERS + 1
        forged = head[:6] + struct.pack(">I", n) + head[10:]
        with pytest.raises(wire.WireDecodeError, match="buffers"):
            wire.parse_header(forged)

    def test_oversized_skeleton_declaration(self):
        head, _ = self._frame()
        forged = head[:10] + struct.pack(
            ">I", wire.MAX_SKELETON_BYTES + 1) + head[14:]
        with pytest.raises(wire.WireDecodeError, match="skeleton"):
            wire.parse_header(forged)

    def test_oversized_array_declaration(self):
        node = {"t": "nd", "i": 0, "dtype": "float32",
                "shape": [2 ** 40], "rawlen": wire.MAX_BUFFER_BYTES + 8,
                "comp": "none"}
        skel = json.dumps(node, separators=(",", ":")).encode()
        head = struct.pack(">4sBBII", wire.MAGIC, wire.WIRE_VERSION, 0,
                           1, len(skel)) + skel
        with pytest.raises(wire.WireDecodeError, match="oversized"):
            wire.decode_frame(head, [b"12345678"])

    def test_corrupt_json_skeleton(self):
        bufs = [b"\x00" * 8]
        skel = b'{"t": "nd", CORRUPT'
        head = struct.pack(">4sBBII", wire.MAGIC, wire.WIRE_VERSION, 0,
                           1, len(skel)) + skel
        with pytest.raises(wire.WireDecodeError, match="skeleton"):
            wire.decode_frame(head, bufs)

    def test_buffer_size_mismatch(self):
        head, bufs = self._frame({"a": np.zeros(8, np.float32)})
        with pytest.raises(wire.WireDecodeError, match="declared"):
            wire.decode_frame(head, [bufs[0][:-4]])

    def test_buffer_index_out_of_range(self):
        head, bufs = self._frame({"a": np.zeros(8, np.float32)})
        with pytest.raises(wire.WireDecodeError, match="buffer"):
            wire.decode_frame(head, [])

    def test_zlib_bomb_is_bounded(self):
        # a buffer claiming rawlen=64 whose zlib stream inflates to 64MB
        bomb = zlib.compress(b"\x00" * (64 << 20), 1)
        node = {"t": "nd", "i": 0, "dtype": "uint8", "shape": [64],
                "rawlen": 64, "comp": "zlib"}
        skel = json.dumps(node, separators=(",", ":")).encode()
        head = struct.pack(">4sBBII", wire.MAGIC, wire.WIRE_VERSION, 0,
                           1, len(skel)) + skel
        with pytest.raises(wire.WireDecodeError, match="declared"):
            wire.decode_frame(head, [bomb])

    def test_corrupt_zlib_buffer(self):
        node = {"t": "nd", "i": 0, "dtype": "uint8", "shape": [64],
                "rawlen": 64, "comp": "zlib"}
        skel = json.dumps(node, separators=(",", ":")).encode()
        head = struct.pack(">4sBBII", wire.MAGIC, wire.WIRE_VERSION, 0,
                           1, len(skel)) + skel
        with pytest.raises(wire.WireDecodeError, match="zlib"):
            wire.decode_frame(head, [b"not zlib at all"])

    def test_namedtuple_escape_refuses_arbitrary_callables(self):
        # a forged 'nt' node must not let a peer call os.system
        node = {"t": "nt", "mod": "os", "qual": "system", "v": []}
        skel = json.dumps(node, separators=(",", ":")).encode()
        head = struct.pack(">4sBBII", wire.MAGIC, wire.WIRE_VERSION, 0,
                           0, len(skel)) + skel
        with pytest.raises(wire.WireDecodeError, match="refusing"):
            wire.decode_frame(head, [])

    def test_unknown_node_type(self):
        skel = json.dumps({"t": "evil"}).encode()
        head = struct.pack(">4sBBII", wire.MAGIC, wire.WIRE_VERSION, 0,
                           0, len(skel)) + skel
        with pytest.raises(wire.WireDecodeError, match="unknown"):
            wire.decode_frame(head, [])

    def test_fuzz_mutations_raise_typed_errors_only(self):
        """Seeded byte-flip fuzz over header+skeleton: every mutation
        either decodes (flip hit a don't-care byte) or raises the
        TYPED WireDecodeError — no hangs, no stray exception types."""
        head, bufs = self._frame(
            {"a": np.arange(6, dtype=np.float32),
             "b": [1, "two", Point(3, 4)]})
        rng = np.random.default_rng(1605)
        for _ in range(300):
            mutated = bytearray(head)
            for _ in range(rng.integers(1, 4)):
                mutated[rng.integers(0, len(mutated))] ^= int(
                    rng.integers(1, 256))
            try:
                wire.decode_frame(bytes(mutated), bufs)
            except wire.WireDecodeError:
                pass  # the typed contract

    def test_truncated_stream_times_out_not_hangs(self):
        """A peer that dies mid-frame: recv_msg raises the typed error
        within the buffer timeout instead of blocking forever."""
        a, b = Pipe()
        try:
            head, bufs, _ = wire.encode_frame(
                {"x": np.zeros(16, np.float32),
                 "y": np.ones(16, np.float32)}, wire.WireOptions())
            a.send_bytes(head)
            a.send_bytes(bytes(bufs[0]))  # ...and never sends buffer 1
            with pytest.raises(wire.WireDecodeError, match="truncated"):
                wire.recv_msg(b, buf_timeout_s=0.2)
        finally:
            a.close()
            b.close()

    def test_connection_survives_drained_corrupt_frame(self):
        """Valid header + all declared buffers but a corrupt skeleton:
        the decoder drains the frame (stream stays aligned), flags
        frame_drained, and the NEXT frame decodes normally."""
        a, b = Pipe()
        try:
            # corrupt frame: well-formed header declaring 1 buffer,
            # skeleton that parses as JSON but is semantically broken
            skel = json.dumps({"t": "nd", "i": 0, "dtype": "float32",
                               "shape": "NOT-A-SHAPE", "rawlen": 8,
                               "comp": "none"}).encode()
            head = struct.pack(">4sBBII", wire.MAGIC, wire.WIRE_VERSION,
                               0, 1, len(skel)) + skel
            a.send_bytes(head)
            a.send_bytes(b"\x00" * 8)
            with pytest.raises(wire.WireDecodeError) as ei:
                wire.recv_msg(b, buf_timeout_s=1.0)
            assert getattr(ei.value, "frame_drained", False) is True
            good = {"ok": np.arange(3, dtype=np.float32)}
            wire.send_msg(a, good, wire.WireOptions())
            out = wire.recv_msg(b, buf_timeout_s=1.0)
            assert_tree_byte_equal(out, good)
        finally:
            a.close()
            b.close()


class TestNegotiation:
    def test_accept_hello_happy_path(self):
        opts, reply, mux = wire.accept_hello(
            {"version": 2, "compression": "zlib", "dtype": "bf16"})
        assert opts.compression == "zlib" and opts.dtype == "bf16"
        assert reply == {"version": 2, "compression": "zlib",
                         "dtype": "bf16"}
        assert mux is False
        # the server decodes peer frames with the pickle escape OFF:
        # an authenticated-but-hostile client must not reach
        # pickle.loads (the security note in docs/DESIGN.md)
        assert opts.allow_pickle is False

    def test_accept_hello_degrades_unknown_options(self):
        opts, _, _ = wire.accept_hello(
            {"version": 2, "compression": "zstd", "dtype": "fp8"})
        assert opts.compression == "none" and opts.dtype == "f32"

    def test_accept_hello_mux_needs_server_grant(self):
        """The mux request key (parallel/rpc.py) is granted only when
        the serving loop can demultiplex, and never granted unasked —
        a legacy client's hello (no key) stays non-mux on every
        server, so the framing after the hello is byte-identical to
        the pre-rpc wire."""
        hello = {"version": 2, "compression": "none", "dtype": "f32",
                 "mux": True}
        opts, reply, mux = wire.accept_hello(hello, allow_mux=True)
        assert mux is True and reply["mux"] is True
        opts, reply, mux = wire.accept_hello(hello, allow_mux=False)
        assert mux is False and "mux" not in reply
        opts, reply, mux = wire.accept_hello(
            {"version": 2}, allow_mux=True)
        assert mux is False and "mux" not in reply

    def test_accept_hello_rejects_other_versions(self):
        with pytest.raises(wire.WireProtocolError):
            wire.accept_hello({"version": 3})
        with pytest.raises(wire.WireProtocolError):
            wire.accept_hello("not-a-dict")

    def test_options_validate(self):
        with pytest.raises(ValueError):
            wire.WireOptions(compression="lz4")
        with pytest.raises(ValueError):
            wire.WireOptions(dtype="f16")


class TestRawArrays:
    """The ingest uint8-batch frame op (ISSUE 9): RawArrays members
    travel as raw zero-copy buffers no matter what the connection
    negotiated — no zlib attempt, no bf16 re-dtype — and decode to a
    plain tuple."""

    def test_roundtrip_plain_tuple(self):
        x = np.arange(2 * 4 * 4 * 3, dtype=np.uint8).reshape(2, 4, 4, 3)
        y = np.array([3, 9], np.int32)
        out, _ = roundtrip(("ok", wire.RawArrays(x, y)))
        status, batch = out
        assert status == "ok" and type(batch) is tuple
        assert_tree_byte_equal(batch, (x, y))

    def test_skips_negotiated_zlib(self):
        # constant image: zlib WOULD shrink it massively, so surviving
        # at raw size proves the compression attempt never ran
        x = np.zeros((4, 16, 16, 3), np.uint8)
        y = np.zeros(4, np.int32)
        opts = wire.WireOptions(compression="zlib", dtype="bf16")
        head, bufs, stats = wire.encode_frame(
            wire.RawArrays(x, y), opts)
        assert stats.post_bytes >= x.nbytes + y.nbytes
        skel = json.loads(head[wire._HEADER.size:])
        assert [n["comp"] for n in skel["v"]] == ["none", "none"]
        assert all("wire" not in n for n in skel["v"])
        out, _ = roundtrip(wire.RawArrays(x, y), opts)
        assert_tree_byte_equal(out, (x, y))

    def test_no_bf16_redtype_for_f32_member(self):
        # an f32 leaf inside RawArrays must stay f32 on the wire even
        # under a bf16-negotiated connection (bit-exactness contract)
        f = np.linspace(0, 1, 7, dtype=np.float32)
        out, stats = roundtrip(wire.RawArrays(f),
                               wire.WireOptions(dtype="bf16"))
        assert stats.post_bytes >= f.nbytes
        assert_tree_byte_equal(out, (f,))

    def test_rejects_non_arrays(self):
        with pytest.raises(TypeError):
            wire.RawArrays(np.zeros(2), "not-an-array")

    def test_malformed_raw_node_is_typed(self):
        head, bufs, _ = wire.encode_frame(
            wire.RawArrays(np.zeros(2, np.uint8)), wire.WireOptions())
        skel = json.loads(head[wire._HEADER.size:])
        skel["v"][0] = {"t": "i", "v": 3}  # not an array node
        new_skel = json.dumps(skel, separators=(",", ":")).encode()
        new_head = wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION, 0,
                                     len(bufs), len(new_skel)) + new_skel
        with pytest.raises(wire.WireDecodeError):
            wire.decode_frame(new_head,
                              [bytes(b) for b in bufs],
                              wire.WireOptions())

    def test_pickles_for_the_v1_path(self):
        # a v1 (pickle) connection ships the whole reply through
        # pickle; a RawArrays that cannot reconstruct would crash the
        # trainer's first pull instead of delivering the batch
        import pickle

        x = np.arange(6, dtype=np.uint8).reshape(2, 3)
        y = np.array([1, 2], np.int32)
        out = pickle.loads(pickle.dumps(("ok", wire.RawArrays(x, y))))
        status, batch = out
        assert status == "ok"
        assert_tree_byte_equal(tuple(batch), (x, y))
