from theanompi_tpu.parallel.exchanger import (
    BSP_Exchanger,
    asgd_apply_grads,
    easgd_both_updates,
    easgd_center_update,
    easgd_worker_update,
    gosgd_merge,
    gosgd_scale_momentum,
)
from theanompi_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_MODEL,
    AXIS_PIPE,
    AXIS_SEQ,
    MeshSpec,
    batch_sharding,
    data_axis_size,
    data_mesh,
    local_batch,
    make_training_mesh,
    replicate,
    replicated,
    shard_batch,
)
from theanompi_tpu.parallel.bsp import (
    TrainState,
    make_bsp_eval_step,
    make_bsp_train_step,
)
from theanompi_tpu.parallel.fsdp import (
    fsdp_specs,
    init_fsdp_state,
    make_bsp_fsdp_step,
)

__all__ = [
    "AXIS_DATA", "AXIS_MODEL", "AXIS_PIPE", "AXIS_SEQ", "AXIS_EXPERT",
    "MeshSpec", "make_training_mesh", "data_mesh", "batch_sharding",
    "replicated", "replicate", "shard_batch", "local_batch", "data_axis_size",
    "BSP_Exchanger", "easgd_worker_update", "easgd_center_update",
    "easgd_both_updates", "asgd_apply_grads", "gosgd_merge",
    "gosgd_scale_momentum",
    "TrainState", "make_bsp_train_step", "make_bsp_eval_step",
    "fsdp_specs", "init_fsdp_state", "make_bsp_fsdp_step",
]
