"""ZeRO-1 data parallelism: optimizer state sharded over ``data``.

Plain BSP replicates the optimizer state (momentum, adam moments) on
every data shard — for a model with P parameters and an optimizer with
m state slots, each chip holds m*P floats it only ever reads 1/N of
usefully.  ZeRO-1 shards that state over the data axis:

    grads  --psum_scatter-->  1/N grad shard        (reduce_scatter)
    update on the 1/N param/opt shard               (compute saved too)
    params --all_gather-->    full replicated tree

Same collective volume as one psum (reduce_scatter + all_gather IS the
ring allreduce, just with the update between the halves), identical
update math for elementwise optimizers (sgd/momentum/adam/adamw/
rmsprop — proven step-equal to plain BSP in tests), and m*P/N
optimizer memory per chip.  LARS is layerwise, not elementwise, so it
is rejected (a flat shard has no layer boundaries) — enforced at the
config layer (models/base.py compile_iter_fns); direct callers of this
module must likewise pass an elementwise optimizer.

The reference has no analogue (its exchanger zoo allreduced grads or
params, SURVEY.md §2.4); this is the TPU-era completion of that zoo —
selected as ``ModelConfig.zero_sharding=True``, BSP only (composes
with the ``seq`` axis — extra reduce axes psum the gradient shard —
and with ``grad_accum_steps`` via the shared cadence scan).  The pattern is the cross-replica
weight-update sharding of arXiv:2004.13336 (retrieved in PAPERS.md) /
ZeRO stage 1.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.bsp import (
    TrainState,
    _donate_argnums,
    _fold_axis_rng,
    _pmean,
    accumulate_microbatch_grads,
    grad_and_metrics,
)
from theanompi_tpu.parallel.bsp import state_partition_spec  # noqa: F401
from theanompi_tpu.parallel.mesh import AXIS_DATA

PyTree = Any


def _flat_info(params: PyTree, n_shards: int) -> tuple[int, int, int]:
    """(total, pad, per_shard) for the flattened param vector."""
    total = sum(int(np.prod(l.shape)) if hasattr(l, "shape") else 1
                for l in jax.tree.leaves(params))
    pad = (-total) % n_shards
    return total, pad, (total + pad) // n_shards


def _opt_specs(tx: optax.GradientTransformation, per_shard: int):
    """Per-leaf PartitionSpecs for the sharded optimizer state, derived
    STRUCTURALLY (ADVICE r2): ``optax.tree_map_params`` knows exactly
    which state leaves mirror the params (momentum/moments — sharded
    over 'data'); everything else (inject_hyperparams' learning_rate,
    counts) replicates.  Shape matching alone would silently mis-shard
    a replicated vector whose length happens to equal per_shard.

    A param-SHAPED leaf that tree_map_params does NOT register (a
    custom transform keeping unregistered per-param state) would be
    replicated yet updated with shard-local values — silent divergence
    under check_vma=False — so it is rejected instead."""
    template = jax.eval_shape(tx.init, jnp.zeros((per_shard,), jnp.float32))
    marked = optax.tree_map_params(tx, lambda _: True, template,
                                   transform_non_params=lambda _: False)
    specs = jax.tree.map(lambda m: P(AXIS_DATA) if m else P(), marked)
    suspect = [
        leaf for m, leaf in zip(jax.tree.leaves(marked),
                                jax.tree.leaves(template))
        if not m and getattr(leaf, "ndim", 0) == 1
        and leaf.shape[0] == per_shard
    ]
    if suspect:
        raise ValueError(
            f"optimizer state holds {len(suspect)} param-shaped leaf/leaves "
            "not registered as params with optax.tree_map_params; ZeRO "
            "cannot tell whether to shard them — use an optimizer whose "
            "per-param state is registered (sgd/adam/adamw/rmsprop are)")
    return template, specs


def init_zero_opt_state(tx: optax.GradientTransformation, params: PyTree,
                        mesh: jax.sharding.Mesh):
    """Build the optimizer state directly SHARDED over 'data' (never
    materializing the full-size state on any device)."""
    n = mesh.shape[AXIS_DATA]
    total, pad, per_shard = _flat_info(params, n)
    _, specs = _opt_specs(tx, per_shard)

    def shard_init(params):
        idx = lax.axis_index(AXIS_DATA)
        pflat, _ = ravel_pytree(params)
        pflat = jnp.pad(pflat.astype(jnp.float32), (0, pad))
        pshard = lax.dynamic_slice(pflat, (idx * per_shard,), (per_shard,))
        return tx.init(pshard)

    sharded = jax.shard_map(shard_init, mesh=mesh, in_specs=(P(),),
                            out_specs=specs, check_vma=False)
    return jax.jit(sharded)(params), specs


def init_zero_exchange_residual(params_template: PyTree,
                                mesh: jax.sharding.Mesh) -> np.ndarray:
    """Zero error-feedback residual for the ZeRO step: the padded flat
    gradient vector per data shard, host-side ``(n_data, total+pad)``
    f32 — the caller places it sharded ``P('data')`` on the leading
    axis (models/base.py ``_create_state``)."""
    n = mesh.shape[AXIS_DATA]
    total, pad, _ = _flat_info(params_template, n)
    return np.zeros((n, total + pad), np.float32)


def make_bsp_zero_step(
    loss_fn,
    tx: optax.GradientTransformation,
    mesh: jax.sharding.Mesh,
    params_template: PyTree,
    avg: bool = True,
    donate: bool = True,
    donate_batch: bool = True,
    batch_partition: P = P(AXIS_DATA),
    reduce_axes: tuple[str, ...] = (AXIS_DATA,),
    accum: bool = False,
    multi: bool = False,
    exchange_dtype: str = "f32",
    error_feedback: bool = False,
):
    """Build the ZeRO-1 training step.

    ``exchange_dtype='bf16'`` quantizes the flat gradient vector to
    bfloat16 before the data-axis ``psum_scatter`` — the ring
    reduce-scatter (and therefore the pod's ICI gradient bytes) moves
    2 bytes/element — and upcasts the received shard to f32 BEFORE the
    extra-axis psum, the average, and the optimizer update, so
    accumulation on the shard stays f32.  ``error_feedback=True``
    additionally carries each shard's f32 quantization error in
    ``state.exchange_residual`` (flat, ``(n_data, total+pad)`` global,
    sharded over 'data') and re-injects it into the next exchange —
    the cumulative applied gradient then tracks the cumulative true
    gradient to one quantization step (same scheme as the unflattened
    path in parallel/bsp.py).

    ``accum=True`` builds the grad-accumulation variant instead:
    ``step(state, stacked_batch, rng)`` with a leading microbatch axis
    — grads accumulate locally as the padded flat vector, then ONE
    sharded exchange/update (ZeRO x grad-accum composition).

    ``multi=True`` builds the ``steps_per_call`` variant (ZeRO x
    multi-step): ``lax.scan`` of the FULL sharded step —
    reduce_scatter + shard update + all_gather per sub-step, so the
    trajectory is identical to k separate calls with rngs
    ``fold_in(rng, i)`` — amortizing the per-dispatch floor k-fold
    exactly like parallel/bsp.py's make_bsp_multi_step.  Mutually
    exclusive with ``accum`` (the two stacked cadences always are).

    ``step(state, batch, rng) -> (state, metrics)`` with ``state.params``
    replicated and ``state.opt_state`` sharded over 'data' (the specs
    come from ``init_zero_opt_state``).  ``reduce_axes`` must include
    'data'; any OTHER reduce axis (e.g. 'seq' for the long-context
    family) is psum-ed plainly before the data-axis reduce_scatter —
    the optimizer shard stays a pure data-axis concept.
    """
    if AXIS_DATA not in reduce_axes:
        raise ValueError(f"zero needs the '{AXIS_DATA}' axis in "
                         f"reduce_axes, got {reduce_axes}")
    if accum and multi:
        raise ValueError("accum and multi are mutually exclusive "
                         "stacked cadences")
    if exchange_dtype not in ("f32", "bf16"):
        raise ValueError(f"exchange_dtype must be 'f32' or 'bf16', "
                         f"got {exchange_dtype!r}")
    if error_feedback and exchange_dtype != "bf16":
        raise ValueError("error_feedback compensates bf16 quantization; "
                         "it needs exchange_dtype='bf16'")
    extra_axes = tuple(a for a in reduce_axes if a != AXIS_DATA)
    n = mesh.shape[AXIS_DATA]
    n_total = n * int(np.prod([mesh.shape[a] for a in extra_axes] or [1]))
    total, pad, per_shard = _flat_info(params_template, n)
    _, opt_specs = _opt_specs(tx, per_shard)
    state_in_specs = TrainState(step=P(), params=P(), opt_state=opt_specs,
                                model_state=P(),
                                exchange_residual=P(AXIS_DATA))

    def exchange_and_update(state, gflat, new_ms):
        """The ZeRO tail, from a local padded fp32 flat gradient:
        reduce_scatter FIRST (the sums commute, and psum-ing only the
        1/N shard over the extra axes moves data-axis-size times less
        traffic than psum-ing the full vector would), update the
        shard, all_gather the params."""
        new_res = state.exchange_residual
        if exchange_dtype == "bf16":
            # quantize before the scatter (2 bytes/element on the
            # wire), accumulate in f32: a bf16 psum_scatter would
            # round every partial sum to 8 mantissa bits and (at N
            # shards) swallow quantization-step-sized corrections —
            # the same failure the exchanger's _bf16_sum documents.
            # all_to_all moves exactly the ring reduce-scatter's
            # (N-1)/N x bytes, but every add happens locally in f32.
            if error_feedback:
                comp = gflat + state.exchange_residual[0]
                q = comp.astype(jnp.bfloat16)
                new_res = (comp - q.astype(jnp.float32))[None]
            else:
                q = gflat.astype(jnp.bfloat16)
            recv = lax.all_to_all(q.reshape(n, -1), AXIS_DATA,
                                  split_axis=0, concat_axis=0,
                                  tiled=True)
            gshard = jnp.sum(recv.astype(jnp.float32), axis=0)
        else:
            gshard = lax.psum_scatter(gflat, AXIS_DATA,
                                      scatter_dimension=0, tiled=True)
        if extra_axes:
            gshard = lax.psum(gshard, extra_axes)
        if avg:
            gshard = gshard / n_total

        idx = lax.axis_index(AXIS_DATA)
        pflat, unravel = ravel_pytree(state.params)
        pdtype = pflat.dtype
        pflat = jnp.pad(pflat.astype(jnp.float32), (0, pad))
        pshard = lax.dynamic_slice(pflat, (idx * per_shard,), (per_shard,))

        updates, new_opt = tx.update(gshard, state.opt_state, pshard)
        new_pshard = optax.apply_updates(pshard, updates)
        new_pflat = lax.all_gather(new_pshard, AXIS_DATA, tiled=True)
        new_params = unravel(new_pflat[:total].astype(pdtype))
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt, model_state=new_ms,
                          exchange_residual=new_res)

    def shard_step(state: TrainState, batch, rng):
        rng = _fold_axis_rng(rng, reduce_axes)
        grads, new_ms, metrics = grad_and_metrics(
            loss_fn, state.params, state.model_state, batch, rng)
        new_ms = _pmean(new_ms, reduce_axes)
        gflat, _ = ravel_pytree(grads)
        gflat = jnp.pad(gflat.astype(jnp.float32), (0, pad))
        new_state = exchange_and_update(state, gflat, new_ms)
        return new_state, _pmean(metrics, reduce_axes)

    def shard_accum(state: TrainState, stacked, rng):
        # a microbatches -> ONE sharded update (ZeRO x grad-accum):
        # grads accumulate locally as the padded flat vector (the
        # shared cadence scan in parallel/bsp.py), then the same tail
        # as the single-batch step
        rng = _fold_axis_rng(rng, reduce_axes)

        def add_flat(gsum, grads):
            gflat, _ = ravel_pytree(grads)
            return gsum + jnp.pad(gflat.astype(jnp.float32), (0, pad))

        gz = jnp.zeros((total + pad,), jnp.float32)
        new_ms, gsum, metrics, a = accumulate_microbatch_grads(
            loss_fn, state.params, state.model_state, stacked, rng,
            gz, add_flat)
        new_ms = _pmean(new_ms, reduce_axes)
        new_state = exchange_and_update(state, gsum / a, new_ms)
        return new_state, _pmean(metrics, reduce_axes)

    def shard_multi(state: TrainState, stacked, rng):
        def body(carry, xs):
            i, batch = xs
            return shard_step(carry, batch, jax.random.fold_in(rng, i))

        k = jax.tree.leaves(stacked)[0].shape[0]
        return lax.scan(body, state, (jnp.arange(k), stacked))

    fn = shard_accum if accum else (shard_multi if multi else shard_step)
    partition = (P(None, *batch_partition) if (accum or multi)
                 else batch_partition)
    sharded = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(state_in_specs, partition, P()),
        out_specs=(state_in_specs, P()),
        check_vma=False,
    )
    # the stacked cadences donate the staged batch like parallel/bsp.py
    # (same copy-done rationale + the same opt-out for batch replayers)
    dn = _donate_argnums(donate, donate_batch and (accum or multi))
    return jax.jit(sharded, donate_argnums=dn)
