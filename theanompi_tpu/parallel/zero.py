"""ZeRO-1 data parallelism: optimizer state sharded over ``data``.

Plain BSP replicates the optimizer state (momentum, adam moments) on
every data shard — for a model with P parameters and an optimizer with
m state slots, each chip holds m*P floats it only ever reads 1/N of
usefully.  ZeRO-1 shards that state over the data axis:

    grads  --psum_scatter-->  1/N grad shard        (reduce_scatter)
    update on the 1/N param/opt shard               (compute saved too)
    params --all_gather-->    full replicated tree

Same collective volume as one psum (reduce_scatter + all_gather IS the
ring allreduce, just with the update between the halves), identical
update math for elementwise optimizers (sgd/momentum/adam/adamw/
rmsprop — proven step-equal to plain BSP in tests), and m*P/N
optimizer memory per chip.  LARS is layerwise, not elementwise, so it
is rejected (a flat shard has no layer boundaries) — enforced at the
config layer (models/base.py compile_iter_fns); direct callers of this
module must likewise pass an elementwise optimizer.

**Bucketed exchange (ISSUE 13).**  ``exchange_buckets=B`` cuts the
flatten-order leaves into B layer-ordered, byte-balanced buckets
(``parallel/exchanger.bucket_ranges`` — the same pure plan every rank
derives) and the flat gradient vector becomes B per-bucket segments,
each padded to a multiple of N and scattered by its OWN collective.
On the single/multi step the segment collectives are embedded in the
backward DAG via custom_vjp boundary tags (each bucket's reduce-
scatter/all_to_all fires as soon as its layers' cotangents are
complete — the backward emits its result through the cotangent of a
dummy ``(segment/N,)`` slot input, the only side channel a custom
backward has for a shape-changing output), so XLA's latency-hiding
scheduler overlaps bucket i's collective with bucket i+1's gradient
compute.  The grad-accum cadence accumulates locally first (one
exchange per update is the whole point of accumulation), then runs
the SAME per-segment collectives post-backward.

Layout contract: with B>1 the per-shard flat vector is the
concatenation of per-bucket shard pieces — same trajectory for every
REAL parameter element (elementwise update; pad elements stay zero),
but the element ORDER inside the shard (and therefore inside the
sharded optimizer state and the flat error-feedback residual) depends
on B.  A checkpoint written under one ``exchange_buckets`` must be
resumed under the same value — ENFORCED by shape: the last bucket
carries an n*B^2-element encoding pad that makes the per-shard length
strictly increasing in the bucket count, so a mismatched resume fails
loudly in the structural restore instead of silently applying
momentum to the wrong parameters (natural per-bucket pads alone can
coincide across bucket counts).

The reference has no analogue (its exchanger zoo allreduced grads or
params, SURVEY.md §2.4); this is the TPU-era completion of that zoo —
selected as ``ModelConfig.zero_sharding=True``, BSP only (composes
with the ``seq`` axis — extra reduce axes psum the gradient shard —
and with ``grad_accum_steps`` via the shared cadence scan).  The pattern is the cross-replica
weight-update sharding of arXiv:2004.13336 (retrieved in PAPERS.md) /
ZeRO stage 1.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.bsp import (
    TrainState,
    _donate_argnums,
    _fold_axis_rng,
    _pmean,
    accumulate_microbatch_grads,
    grad_and_metrics,
)
from theanompi_tpu.parallel.bsp import state_partition_spec  # noqa: F401
from theanompi_tpu.parallel.exchanger import (
    bucket_ranges,
    emit_bucket_gauges,
    validate_bucket_count,
)
from theanompi_tpu.parallel.mesh import AXIS_DATA

PyTree = Any


def _flat_info(params: PyTree, n_shards: int) -> tuple[int, int, int]:
    """(total, pad, per_shard) for the flattened param vector."""
    total = sum(int(np.prod(l.shape)) if hasattr(l, "shape") else 1
                for l in jax.tree.leaves(params))
    pad = (-total) % n_shards
    return total, pad, (total + pad) // n_shards


@dataclasses.dataclass(frozen=True)
class _ZeroLayout:
    """The bucketed flat layout — a pure function of (leaf shapes,
    n_shards, exchange_buckets), derived identically on every rank.
    Bucket b owns leaves ``ranges[b]``, i.e. ``m[b]`` elements padded
    by ``pad[b]`` to segment ``seg[b]`` (a multiple of n_shards);
    its per-shard piece is ``pb[b] = seg[b]//n`` at offset
    ``shard_off[b]`` in the shard vector and ``flat_off[b]`` in the
    bucketed flat vector.  B=1 degenerates to the historical global
    layout exactly."""

    ranges: tuple          # ((lo, hi) leaf index ranges)
    leaf_elems: tuple      # element count per leaf, flatten order
    m: tuple               # real elements per bucket
    pad: tuple             # pad elements per bucket
    seg: tuple             # m + pad (multiple of n)
    pb: tuple              # per-shard piece per bucket
    flat_off: tuple        # bucket offset in the bucketed flat vector
    shard_off: tuple       # bucket offset in the per-shard vector
    per_shard: int         # sum(pb)
    total_flat: int        # sum(seg)


def _zero_layout(params: PyTree, n_shards: int,
                 exchange_buckets: int = 1) -> _ZeroLayout:
    leaves = jax.tree.leaves(params)
    elems = tuple(int(np.prod(l.shape)) if hasattr(l, "shape") else 1
                  for l in leaves)
    ranges = tuple(bucket_ranges(elems, exchange_buckets))
    m = tuple(sum(elems[lo:hi]) for lo, hi in ranges)
    pad = tuple((-mb) % n_shards for mb in m)
    if len(ranges) > 1:
        # B-ENCODING pad: the last bucket carries n*B^2 extra zero
        # elements, which makes per_shard strictly increasing in the
        # bucket count (natural pads sum to < n*B, and n*(B'^2-B^2)
        # exceeds that for every B' > B >= 1) — so resuming a
        # checkpoint under a different exchange_buckets REALLY fails
        # on shape instead of silently misaligning the momentum/
        # residual layout when the natural pads happen to coincide.
        # Pad elements are trajectory-neutral: zero params, zero
        # grads, zero momentum, dropped at the gather.  Cost: B^2*n
        # f32 elements (2 KB at B=8, n=8).
        pad = pad[:-1] + (pad[-1] + n_shards * len(ranges) ** 2,)
    seg = tuple(mb + pb for mb, pb in zip(m, pad))
    pb = tuple(s // n_shards for s in seg)
    flat_off = tuple(int(x) for x in np.cumsum((0,) + seg[:-1]))
    shard_off = tuple(int(x) for x in np.cumsum((0,) + pb[:-1]))
    return _ZeroLayout(ranges=ranges, leaf_elems=elems, m=m, pad=pad,
                       seg=seg, pb=pb, flat_off=flat_off,
                       shard_off=shard_off, per_shard=sum(pb),
                       total_flat=sum(seg))


def _ravel_bucket(leaves, lo: int, hi: int, pad: int):
    """One bucket's leaves as a padded f32 segment (flatten order —
    identical element order to ``ravel_pytree`` over the same
    leaves)."""
    parts = [leaves[i].reshape(-1).astype(jnp.float32)
             for i in range(lo, hi)]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return jnp.pad(flat, (0, pad)) if pad else flat


def _ravel_bucketed(tree: PyTree, layout: _ZeroLayout):
    leaves = jax.tree.leaves(tree)
    segs = [_ravel_bucket(leaves, lo, hi, pad)
            for (lo, hi), pad in zip(layout.ranges, layout.pad)]
    return segs[0] if len(segs) == 1 else jnp.concatenate(segs)


def _unravel_bucketed(flat, tree_template: PyTree, layout: _ZeroLayout):
    """Rebuild the param tree from the bucketed flat vector (inverse
    of ``_ravel_bucketed``; pad elements dropped, per-leaf dtypes
    restored)."""
    t_leaves, treedef = jax.tree.flatten(tree_template)
    out = []
    for (lo, hi), off in zip(layout.ranges, layout.flat_off):
        pos = off
        for i in range(lo, hi):
            n = layout.leaf_elems[i]
            out.append(flat[pos:pos + n]
                       .reshape(t_leaves[i].shape)
                       .astype(t_leaves[i].dtype))
            pos += n
    return jax.tree.unflatten(treedef, out)


def _opt_specs(tx: optax.GradientTransformation, per_shard: int):
    """Per-leaf PartitionSpecs for the sharded optimizer state, derived
    STRUCTURALLY (ADVICE r2): ``optax.tree_map_params`` knows exactly
    which state leaves mirror the params (momentum/moments — sharded
    over 'data'); everything else (inject_hyperparams' learning_rate,
    counts) replicates.  Shape matching alone would silently mis-shard
    a replicated vector whose length happens to equal per_shard.

    A param-SHAPED leaf that tree_map_params does NOT register (a
    custom transform keeping unregistered per-param state) would be
    replicated yet updated with shard-local values — silent divergence
    under check_vma=False — so it is rejected instead."""
    template = jax.eval_shape(tx.init, jnp.zeros((per_shard,), jnp.float32))
    marked = optax.tree_map_params(tx, lambda _: True, template,
                                   transform_non_params=lambda _: False)
    specs = jax.tree.map(lambda m: P(AXIS_DATA) if m else P(), marked)
    suspect = [
        leaf for m, leaf in zip(jax.tree.leaves(marked),
                                jax.tree.leaves(template))
        if not m and getattr(leaf, "ndim", 0) == 1
        and leaf.shape[0] == per_shard
    ]
    if suspect:
        raise ValueError(
            f"optimizer state holds {len(suspect)} param-shaped leaf/leaves "
            "not registered as params with optax.tree_map_params; ZeRO "
            "cannot tell whether to shard them — use an optimizer whose "
            "per-param state is registered (sgd/adam/adamw/rmsprop are)")
    return template, specs


def _shard_slice(pflat, layout: _ZeroLayout, idx):
    """This shard's slice of the bucketed flat vector: the
    concatenation of its per-bucket pieces."""
    pieces = [lax.dynamic_slice(pflat, (off + idx * pb,), (pb,))
              for off, pb in zip(layout.flat_off, layout.pb)]
    return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)


def init_zero_opt_state(tx: optax.GradientTransformation, params: PyTree,
                        mesh: jax.sharding.Mesh,
                        exchange_buckets: int = 1):
    """Build the optimizer state directly SHARDED over 'data' (never
    materializing the full-size state on any device).
    ``exchange_buckets`` must match the step's — it fixes the shard
    layout (see the module docstring's layout contract)."""
    n = mesh.shape[AXIS_DATA]
    layout = _zero_layout(params, n, exchange_buckets)
    _, specs = _opt_specs(tx, layout.per_shard)

    def shard_init(params):
        idx = lax.axis_index(AXIS_DATA)
        pshard = _shard_slice(_ravel_bucketed(params, layout), layout,
                              idx)
        return tx.init(pshard)

    sharded = jax.shard_map(shard_init, mesh=mesh, in_specs=(P(),),
                            out_specs=specs, check_vma=False)
    return jax.jit(sharded)(params), specs


def init_zero_exchange_residual(params_template: PyTree,
                                mesh: jax.sharding.Mesh,
                                exchange_buckets: int = 1) -> np.ndarray:
    """Zero error-feedback residual for the ZeRO step: the bucketed
    flat gradient vector per data shard, host-side
    ``(n_data, total_flat)`` f32 — the caller places it sharded
    ``P('data')`` on the leading axis (models/base.py
    ``_create_state``).  ``exchange_buckets`` fixes the flat layout
    the residual lives in."""
    n = mesh.shape[AXIS_DATA]
    layout = _zero_layout(params_template, n, exchange_buckets)
    return np.zeros((n, layout.total_flat), np.float32)


def make_bsp_zero_step(
    loss_fn,
    tx: optax.GradientTransformation,
    mesh: jax.sharding.Mesh,
    params_template: PyTree,
    avg: bool = True,
    donate: bool = True,
    donate_batch: bool = True,
    batch_partition: P = P(AXIS_DATA),
    reduce_axes: tuple[str, ...] = (AXIS_DATA,),
    accum: bool = False,
    multi: bool = False,
    exchange_dtype: str = "f32",
    error_feedback: bool = False,
    exchange_buckets: int = 1,
):
    """Build the ZeRO-1 training step.

    ``exchange_dtype='bf16'`` quantizes the flat gradient vector to
    bfloat16 before the data-axis ``psum_scatter`` — the ring
    reduce-scatter (and therefore the pod's ICI gradient bytes) moves
    2 bytes/element — and upcasts the received shard to f32 BEFORE the
    extra-axis psum, the average, and the optimizer update, so
    accumulation on the shard stays f32.  ``error_feedback=True``
    additionally carries each shard's f32 quantization error in
    ``state.exchange_residual`` (flat, ``(n_data, total_flat)`` global,
    sharded over 'data') and re-injects it into the next exchange —
    the cumulative applied gradient then tracks the cumulative true
    gradient to one quantization step (same scheme as the unflattened
    path in parallel/bsp.py).

    ``exchange_buckets=B`` splits the flat vector into B layer-ordered
    segments with one collective each; on the single/multi step the
    segment collectives are embedded in the backward DAG (module
    docstring).  ``init_zero_opt_state`` / the residual init must be
    built with the SAME bucket count — the plan fixes the shard
    layout.

    ``accum=True`` builds the grad-accumulation variant instead:
    ``step(state, stacked_batch, rng)`` with a leading microbatch axis
    — grads accumulate locally as the padded flat vector, then ONE
    sharded (per-bucket) exchange/update (ZeRO x grad-accum
    composition).

    ``multi=True`` builds the ``steps_per_call`` variant (ZeRO x
    multi-step): ``lax.scan`` of the FULL sharded step —
    reduce_scatter + shard update + all_gather per sub-step, so the
    trajectory is identical to k separate calls with rngs
    ``fold_in(rng, i)`` — amortizing the per-dispatch floor k-fold
    exactly like parallel/bsp.py's make_bsp_multi_step.  Mutually
    exclusive with ``accum`` (the two stacked cadences always are).

    ``step(state, batch, rng) -> (state, metrics)`` with ``state.params``
    replicated and ``state.opt_state`` sharded over 'data' (the specs
    come from ``init_zero_opt_state``).  ``reduce_axes`` must include
    'data'; any OTHER reduce axis (e.g. 'seq' for the long-context
    family) is psum-ed plainly before the data-axis reduce_scatter —
    the optimizer shard stays a pure data-axis concept.
    """
    if AXIS_DATA not in reduce_axes:
        raise ValueError(f"zero needs the '{AXIS_DATA}' axis in "
                         f"reduce_axes, got {reduce_axes}")
    if accum and multi:
        raise ValueError("accum and multi are mutually exclusive "
                         "stacked cadences")
    if exchange_dtype not in ("f32", "bf16"):
        raise ValueError(f"exchange_dtype must be 'f32' or 'bf16', "
                         f"got {exchange_dtype!r}")
    if error_feedback and exchange_dtype != "bf16":
        raise ValueError("error_feedback compensates bf16 quantization; "
                         "it needs exchange_dtype='bf16'")
    validate_bucket_count(exchange_buckets)
    extra_axes = tuple(a for a in reduce_axes if a != AXIS_DATA)
    n = mesh.shape[AXIS_DATA]
    n_total = n * int(np.prod([mesh.shape[a] for a in extra_axes] or [1]))
    layout = _zero_layout(params_template, n, exchange_buckets)
    n_buckets = len(layout.ranges)
    _, opt_specs = _opt_specs(tx, layout.per_shard)
    state_in_specs = TrainState(step=P(), params=P(), opt_state=opt_specs,
                                model_state=P(),
                                exchange_residual=P(AXIS_DATA))
    wire = "bf16" if exchange_dtype == "bf16" else "f32"

    def scatter_segment(seg, res_seg):
        """One bucket's collective, from its local padded f32 segment:
        reduce_scatter (f32) or quantize + all_to_all + f32 local
        accumulation (bf16, optionally error-fed).  Returns
        (per-shard piece, new residual segment | None).

        Why all_to_all for bf16: a bf16 psum_scatter would round every
        partial sum to 8 mantissa bits and (at N shards) swallow
        quantization-step-sized corrections — the same failure the
        exchanger's _bf16_sum documents.  all_to_all moves exactly the
        ring reduce-scatter's (N-1)/N x bytes, but every add happens
        locally in f32."""
        if exchange_dtype == "bf16":
            if error_feedback:
                comp = seg + res_seg
                q = comp.astype(jnp.bfloat16)
                new_r = comp - q.astype(jnp.float32)
            else:
                q = seg.astype(jnp.bfloat16)
                new_r = None
            recv = lax.all_to_all(q.reshape(n, -1), AXIS_DATA,
                                  split_axis=0, concat_axis=0,
                                  tiled=True)
            return jnp.sum(recv.astype(jnp.float32), axis=0), new_r
        piece = lax.psum_scatter(seg, AXIS_DATA,
                                 scatter_dimension=0, tiled=True)
        return piece, None

    def scatter_flat(gflat, residual_flat):
        """All buckets' collectives from the local bucketed flat
        gradient (the post-backward path: B=1 single step and the
        accum tail).  Returns (gshard, new bucketed residual | None)."""
        pieces, res_segs = [], []
        for b in range(n_buckets):
            off, sg = layout.flat_off[b], layout.seg[b]
            seg = lax.dynamic_slice(gflat, (off,), (sg,))
            res_seg = (lax.dynamic_slice(residual_flat, (off,), (sg,))
                       if error_feedback else None)
            piece, new_r = scatter_segment(seg, res_seg)
            pieces.append(piece)
            res_segs.append(new_r)
        gshard = (pieces[0] if n_buckets == 1
                  else jnp.concatenate(pieces))
        if error_feedback:
            new_res = (res_segs[0] if n_buckets == 1
                       else jnp.concatenate(res_segs))
            return gshard, new_res
        return gshard, None

    def update_and_gather(state, gshard, new_res, new_ms):
        """The ZeRO tail from the per-shard gradient: extra-axis psum
        (the sums commute, and psum-ing only the 1/N shard moves
        data-axis-size times less traffic than the full vector would),
        average, update the shard, gather the params back per
        bucket."""
        if extra_axes:
            gshard = lax.psum(gshard, extra_axes)
        if avg:
            gshard = gshard / n_total

        idx = lax.axis_index(AXIS_DATA)
        pflat = _ravel_bucketed(state.params, layout)
        pshard = _shard_slice(pflat, layout, idx)

        updates, new_opt = tx.update(gshard, state.opt_state, pshard)
        new_pshard = optax.apply_updates(pshard, updates)
        gathered = lax.all_gather(new_pshard, AXIS_DATA)  # (n, per_shard)
        segs = [gathered[:, so:so + pb].reshape(-1)
                for so, pb in zip(layout.shard_off, layout.pb)]
        new_flat = segs[0] if n_buckets == 1 else jnp.concatenate(segs)
        new_params = _unravel_bucketed(new_flat, state.params, layout)
        if new_res is not None:
            new_res = new_res[None]  # leading shard axis back on
        else:
            new_res = state.exchange_residual
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt, model_state=new_ms,
                          exchange_residual=new_res)

    # -- backward-embedded bucketed scatter (exchange_buckets > 1) ------

    def _zero_tag(b: int):
        """Boundary tag for bucket ``b``: identity on its param leaves;
        the backward ravels the bucket's cotangents and fires its
        scatter collective immediately.  The per-shard piece (and the
        new residual segment) leave the backward through the
        cotangents of dummy slot inputs — a custom_vjp backward's only
        outputs are cotangents, and the scatter result's shape
        (1/N of the segment) matches no real input, so a
        ``(seg/N,)``-shaped slot exists to carry it."""
        lo, hi = layout.ranges[b]
        pad = layout.pad[b]

        if error_feedback:
            @jax.custom_vjp
            def tag(leaves, slot, res_seg):
                return leaves

            def fwd(leaves, slot, res_seg):
                return leaves, res_seg

            def bwd(res_seg, cts):
                seg = _ravel_bucket(cts, 0, len(cts), pad)
                piece, new_r = scatter_segment(seg, res_seg)
                zeros = tuple(jnp.zeros_like(c) for c in cts)
                return zeros, piece, new_r
        else:
            @jax.custom_vjp
            def tag(leaves, slot):
                return leaves

            def fwd(leaves, slot):
                return leaves, None

            def bwd(_, cts):
                seg = _ravel_bucket(cts, 0, len(cts), pad)
                piece, _ = scatter_segment(seg, None)
                zeros = tuple(jnp.zeros_like(c) for c in cts)
                return zeros, piece

        tag.defvjp(fwd, bwd)
        return tag

    def backward_scatter(state, batch, rng):
        """Gradient computation with per-bucket scatters embedded in
        the backward (the exchange_buckets>1 sibling of
        exchanger.backward_exchange).  Returns (gshard, new_res | None,
        new_ms, metrics)."""
        leaves0, treedef0 = jax.tree.flatten(state.params)
        emit_bucket_gauges("zero", layout.ranges, leaves0, wire)
        slots = tuple(jnp.zeros((pb,), jnp.float32) for pb in layout.pb)
        if error_feedback:
            res_full = state.exchange_residual[0]
            res_slots = tuple(
                lax.dynamic_slice(res_full, (off,), (sg,))
                for off, sg in zip(layout.flat_off, layout.seg))
            diff_arg = (slots, res_slots)
        else:
            diff_arg = slots

        def tagged_loss(diff_arg, model_state, batch, rng):
            slots_, res_ = (diff_arg if error_feedback
                            else (diff_arg, None))
            new_leaves = []
            for b, (lo, hi) in enumerate(layout.ranges):
                bucket = tuple(leaves0[lo:hi])
                if error_feedback:
                    new_leaves.extend(
                        _zero_tag(b)(bucket, slots_[b], res_[b]))
                else:
                    new_leaves.extend(_zero_tag(b)(bucket, slots_[b]))
            return loss_fn(jax.tree.unflatten(treedef0, new_leaves),
                           model_state, batch, rng)

        grad_fn = jax.value_and_grad(tagged_loss, has_aux=True)
        (loss, (new_ms, metrics)), g = grad_fn(
            diff_arg, state.model_state, batch, rng)
        metrics = dict(metrics)
        metrics.setdefault("loss", loss)
        if error_feedback:
            pieces, res_segs = g
            new_res = (res_segs[0] if n_buckets == 1
                       else jnp.concatenate(res_segs))
        else:
            pieces, new_res = g, None
        gshard = (pieces[0] if n_buckets == 1
                  else jnp.concatenate(pieces))
        return gshard, new_res, new_ms, metrics

    def shard_step(state: TrainState, batch, rng):
        rng = _fold_axis_rng(rng, reduce_axes)
        if n_buckets > 1:
            gshard, new_res, new_ms, metrics = backward_scatter(
                state, batch, rng)
        else:
            grads, new_ms, metrics = grad_and_metrics(
                loss_fn, state.params, state.model_state, batch, rng)
            gflat = _ravel_bucketed(grads, layout)
            res_flat = (state.exchange_residual[0] if error_feedback
                        else None)
            gshard, new_res = scatter_flat(gflat, res_flat)
        new_ms = _pmean(new_ms, reduce_axes)
        new_state = update_and_gather(state, gshard, new_res, new_ms)
        return new_state, _pmean(metrics, reduce_axes)

    def shard_accum(state: TrainState, stacked, rng):
        # a microbatches -> ONE sharded update (ZeRO x grad-accum):
        # grads accumulate locally as the bucketed flat vector (the
        # shared cadence scan in parallel/bsp.py), then the same
        # post-backward per-bucket scatter tail as the B=1 step —
        # accumulation's whole point is ONE exchange per update, so
        # the bucket collectives stay after the (scanned) backward
        rng = _fold_axis_rng(rng, reduce_axes)

        def add_flat(gsum, grads):
            return gsum + _ravel_bucketed(grads, layout)

        gz = jnp.zeros((layout.total_flat,), jnp.float32)
        new_ms, gsum, metrics, a = accumulate_microbatch_grads(
            loss_fn, state.params, state.model_state, stacked, rng,
            gz, add_flat)
        if n_buckets > 1:
            leaves0 = jax.tree.leaves(state.params)
            emit_bucket_gauges("zero", layout.ranges, leaves0, wire)
        new_ms = _pmean(new_ms, reduce_axes)
        res_flat = (state.exchange_residual[0] if error_feedback
                    else None)
        gshard, new_res = scatter_flat(gsum / a, res_flat)
        new_state = update_and_gather(state, gshard, new_res, new_ms)
        return new_state, _pmean(metrics, reduce_axes)

    def shard_multi(state: TrainState, stacked, rng):
        def body(carry, xs):
            i, batch = xs
            return shard_step(carry, batch, jax.random.fold_in(rng, i))

        k = jax.tree.leaves(stacked)[0].shape[0]
        return lax.scan(body, state, (jnp.arange(k), stacked))

    fn = shard_accum if accum else (shard_multi if multi else shard_step)
    partition = (P(None, *batch_partition) if (accum or multi)
                 else batch_partition)
    sharded = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(state_in_specs, partition, P()),
        out_specs=(state_in_specs, P()),
        check_vma=False,
    )
    # the stacked cadences donate the staged batch like parallel/bsp.py
    # (same copy-done rationale + the same opt-out for batch replayers)
    dn = _donate_argnums(donate, donate_batch and (accum or multi))
    return jax.jit(sharded, donate_argnums=dn)
