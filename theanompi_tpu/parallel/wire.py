"""Wire protocol v2 — framed, zero-copy, compressed pytree transport.

The v1 transport (``parallel/service.py``) ships every request as one
pickled tuple over ``multiprocessing.connection``: a 100 MB parameter
tree is serialized by pickle (buffer copies), decoded by pickle
(arbitrary-code execution for anyone holding the key), and there is no
seam to compress or re-dtype the payload.  MPI-characterization work
(arXiv:1810.11112, PAPERS.md) shows exactly this pattern — host
serialization copies on the critical path — dominating data-parallel
scaling before the network does.

v2 splits every message into

* a **fixed header** — magic ``TMW2``, flags, buffer count, skeleton
  length — followed by a **skeleton**: the message's pytree structure
  as JSON with each ndarray replaced by a placeholder describing its
  buffer index, dtype, shape, wire dtype, and compression;
* one **raw buffer per ndarray leaf**, sent straight from the array's
  memory via ``memoryview`` — ndarrays never pass through pickle in
  either direction.

Per-payload options (negotiated at connect time, recorded per leaf so
any frame can deviate):

* ``compression``: ``'none'`` | ``'zlib'`` — zlib level 1 per buffer,
  kept only when it actually shrinks the leaf;
* ``dtype``: ``'f32'`` | ``'bf16'`` — float32 leaves travel as
  bfloat16 (half the bytes; bf16 keeps f32's exponent range) and are
  restored to float32 on receive, so *accumulation at the receiving
  store stays f32* (``parallel/server.py`` centers never see bf16).

Decoder hardening (the v1 pickle transport could neither validate nor
survive a bad frame): every failure mode — bad magic, corrupt
skeleton, buffer-size mismatch, zlib bomb, a peer that stops sending
mid-frame — raises a **typed** :class:`WireDecodeError` instead of
hanging or crashing the server loop; when the header was intact the
decoder drains the frame's declared buffers first so the connection
stays usable.  Structural leaves JSON cannot express (optax
namedtuple states) are rebuilt by validated module/qualname import —
NOT pickle — with a last-resort pickle escape that is disabled by
default on the server side of the v2 path (see ``WireOptions``).

``parallel/service.py`` negotiates v2 at HMAC-handshake time and
falls back to v1 pickle for old peers; ``tools/bench_exchange.py``
measures both protocols over real sockets.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import os
import struct
import zlib
from typing import Any

import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.monitor import trace as _trace
from theanompi_tpu.parallel import shm as _shm

try:  # jax dependency; the bf16 wire dtype needs it as a numpy dtype
    import ml_dtypes

    BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover - ml_dtypes ships with jax
    BF16 = None

MAGIC = b"TMW2"
WIRE_VERSION = 2
#: fixed header: magic(4) version(1) flags(1) n_bufs(4) skeleton_len(4)
_HEADER = struct.Struct(">4sBBII")

#: hard ceilings so a malicious/corrupt header cannot make the decoder
#: allocate unbounded memory (the 'oversized frame' failure mode)
MAX_SKELETON_BYTES = 64 << 20
MAX_BUFFERS = 1 << 16
MAX_BUFFER_BYTES = 1 << 32

#: leaves smaller than this skip zlib (the header would outweigh it)
_MIN_COMPRESS_BYTES = 512

#: how long the decoder waits for each declared buffer message before
#: calling the frame truncated (a peer that died mid-frame must yield
#: a typed error, never a hang)
DEFAULT_BUF_TIMEOUT_S = float(os.environ.get(
    "THEANOMPI_TPU_WIRE_BUF_TIMEOUT_S", "30"))

_FLAG_SKELETON_ZLIB = 1

#: per-leaf options for :class:`RawArrays` members — raw transport no
#: matter what the connection negotiated
_RAW_OPTS = None  # filled in below WireOptions (forward declaration)


class WireError(RuntimeError):
    """Base class for wire-protocol failures."""


class WireDecodeError(WireError, ConnectionError):
    """A frame that cannot be decoded (truncated / corrupt /
    oversized).  Subclasses ``ConnectionError`` so the service
    client's reconnect-with-backoff loop treats a garbled *reply*
    stream like any other transport failure (the at-most-once
    discipline for destructive ops still applies)."""


class WireProtocolError(WireError):
    """Version/negotiation mismatch (not a per-frame problem)."""


class ShmRefusal(WireDecodeError):
    """A shared-memory descriptor or piggybacked ack this peer must
    refuse: stale generation, foreign segment, double decref, expired
    lease, or shm content on a connection that negotiated no lane.
    The message leads with the underlying :mod:`.shm` error's class
    name, so clients classify it the same way they classify
    ``SessionDisplaced`` — and respond by disabling the lane and
    retrying in-band, never by failing the caller."""


@dataclasses.dataclass(frozen=True)
class WireOptions:
    """Per-connection defaults for frame encoding.

    ``allow_pickle`` gates the DECODE side's last-resort pickle escape
    for exotic structural leaves; the encoder only emits that escape
    for objects neither JSON nor the namedtuple path can express.
    Arrays never use it in either direction.
    """

    compression: str = "none"       # 'none' | 'zlib'
    dtype: str = "f32"              # 'f32' | 'bf16'
    allow_pickle: bool = True
    #: the connection's negotiated shared-memory lane (an
    #: ``shm.ShmChannel``), or None for plain in-band v2.  Excluded
    #: from equality: two connections with the same codec options are
    #: codec-equal regardless of their private lanes.
    shm: Any = dataclasses.field(default=None, compare=False)

    def __post_init__(self):
        if self.compression not in ("none", "zlib"):
            raise ValueError(
                f"compression must be 'none' or 'zlib', "
                f"got {self.compression!r}")
        if self.dtype not in ("f32", "bf16"):
            raise ValueError(
                f"wire dtype must be 'f32' or 'bf16', got {self.dtype!r}")
        if self.dtype == "bf16" and BF16 is None:  # pragma: no cover
            raise RuntimeError("bf16 wire dtype needs ml_dtypes")

    @classmethod
    def from_env(cls) -> "WireOptions":
        return cls(
            compression=os.environ.get(
                "THEANOMPI_TPU_WIRE_COMPRESSION", "none"),
            dtype=os.environ.get("THEANOMPI_TPU_WIRE_DTYPE", "f32"),
        )


_RAW_OPTS = WireOptions(compression="none", dtype="f32")


class RawArrays(tuple):
    """Marks a tuple of ndarrays as a **raw batch frame** (the ingest
    uint8-batch op, docs/DESIGN.md "Distributed ingest"): each array
    is sent as its own zero-copy buffer with the per-leaf options
    FORCED to raw — no zlib attempt (level-1 zlib on a 25 MB uint8
    image batch costs real CPU per batch and essentially never
    shrinks photographic content) and no bf16 re-dtype (uint8 pixels
    and int32 labels must arrive bit-exact; the f32→bf16 wire dtype
    only ever applied to f32 anyway, but the batch path must not
    depend on that).  Decodes to a plain tuple of arrays, so the
    consumer sees ``(x, y)`` with no wire-layer type leaking out."""

    __slots__ = ()

    def __new__(cls, *arrays: np.ndarray):
        for a in arrays:
            if not isinstance(a, np.ndarray):
                raise TypeError(
                    f"RawArrays carries ndarrays only, got {type(a)}")
        return super().__new__(cls, arrays)

    def __getnewargs__(self):
        # pickle support: tuple subclasses pickle through __new__, and
        # ours takes *arrays, not one iterable — without this a v1
        # (pickle) connection crashes decoding a batch reply instead
        # of delivering it (pinned by tests/test_wire.py)
        return tuple(self)


@dataclasses.dataclass
class WireStats:
    """Byte accounting for one frame: ``pre`` is the logical payload
    (skeleton + every buffer at its ORIGINAL dtype), ``post`` the
    bytes that actually hit the socket — the pre/post pair is what the
    monitor's compression-ratio gauge is built from."""

    pre_bytes: int = 0
    post_bytes: int = 0
    n_buffers: int = 0

    @property
    def ratio(self) -> float:
        return self.post_bytes / self.pre_bytes if self.pre_bytes else 1.0


# ---------------------------------------------------------------------------
# Skeleton encoding: message structure -> JSON-able tree + buffer list
# ---------------------------------------------------------------------------


def _encode_node(obj: Any, bufs: list, opts: WireOptions, stats: WireStats):
    if obj is None:
        return {"t": "none"}
    if isinstance(obj, bool):
        return {"t": "bool", "v": obj}
    # explicit tags (not type(obj).__name__): an int/float/str SUBCLASS
    # (IntEnum, ...) must still land on a tag the peer can decode
    if isinstance(obj, int):
        return {"t": "i", "v": int(obj)}
    if isinstance(obj, float):
        return {"t": "f", "v": float(obj)}
    if isinstance(obj, str):
        return {"t": "s", "v": str(obj)}
    if isinstance(obj, bytes):
        import base64

        return {"t": "by", "v": base64.b64encode(obj).decode("ascii")}
    if isinstance(obj, RawArrays):
        # the raw batch frame: per-leaf options forced to raw transport
        # regardless of what the connection negotiated (class docstring)
        return {"t": "raw",
                "v": [_encode_array(a, bufs, _RAW_OPTS, stats)
                      for a in obj]}
    if isinstance(obj, np.ndarray):
        return _encode_array(obj, bufs, opts, stats)
    if isinstance(obj, np.generic):  # numpy scalar (np.float32(3), ...)
        return {"t": "np0", "dtype": obj.dtype.name,
                "v": obj.item() if obj.dtype.kind != "V" else None}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        # namedtuple (optax states): record the class by import path —
        # rebuilt by validated import, never by pickle
        cls = type(obj)
        return {"t": "nt", "mod": cls.__module__,
                "qual": cls.__qualname__,
                "v": [_encode_node(v, bufs, opts, stats) for v in obj]}
    if isinstance(obj, tuple):
        return {"t": "tuple",
                "v": [_encode_node(v, bufs, opts, stats) for v in obj]}
    if isinstance(obj, list):
        return {"t": "list",
                "v": [_encode_node(v, bufs, opts, stats) for v in obj]}
    if isinstance(obj, dict):
        return {"t": "dict",
                "v": [[_encode_node(k, bufs, opts, stats),
                       _encode_node(v, bufs, opts, stats)]
                      for k, v in obj.items()]}
    # last resort for exotic structure (NOT arrays — handled above):
    # a restricted pickle escape, decodable only when the peer allows
    import base64
    import pickle

    return {"t": "pkl",
            "v": base64.b64encode(
                pickle.dumps(obj, protocol=2)).decode("ascii")}


def _array_bytes_view(wire: np.ndarray):
    """Zero-copy byte view of a C-contiguous array, via the
    same-width-uint reinterpretation for dtypes outside the buffer
    protocol (bfloat16)."""
    try:
        return memoryview(wire).cast("B")
    except (ValueError, TypeError):
        return memoryview(
            wire.view(np.dtype(f"u{wire.dtype.itemsize}"))).cast("B")


def _encode_array(arr: np.ndarray, bufs: list, opts: WireOptions,
                  stats: WireStats) -> dict:
    orig_dtype = arr.dtype
    stats.pre_bytes += arr.nbytes
    # out-of-band lane: when this frame holds a lease (encode_frame
    # allocated one off the connection's ShmChannel), large leaves are
    # copied ONCE into the shared segment at their ORIGINAL dtype — no
    # bf16 re-dtype, no zlib — so delivery is bit-exact and the
    # receiver's mapping is the only other touch.  The lease rides
    # WireStats because RawArrays leaves encode under _RAW_OPTS, not
    # the connection's opts, and must still go out-of-band.
    lease = getattr(stats, "_shm_lease", None)
    if (lease is not None and arr.nbytes
            and arr.nbytes >= stats._shm_min):
        wire = arr if arr.flags["C_CONTIGUOUS"] \
            else np.ascontiguousarray(arr)
        off = lease.put(_array_bytes_view(wire))
        if off is not None:
            stats._shm_oob += arr.nbytes
            stats.n_buffers += 1
            return {"t": "nd", "dtype": orig_dtype.name,
                    "shape": list(arr.shape), "rawlen": arr.nbytes,
                    "comp": "none",
                    "shm": [lease.name, off, arr.nbytes,
                            lease.generation]}
        # segment full (scan undercounted a non-eligible duplicate or
        # the cap clipped the alloc): this leaf ships in-band
    wire = arr
    wire_dtype = orig_dtype
    if (opts.dtype == "bf16" and orig_dtype == np.float32
            and BF16 is not None):
        wire = arr.astype(BF16)
        wire_dtype = BF16
    if not wire.flags["C_CONTIGUOUS"]:
        wire = np.ascontiguousarray(wire)
    if wire.nbytes == 0:
        # memoryview cannot cast shapes with zeros; an empty leaf is
        # an empty buffer
        data: Any = b""
    else:
        try:
            data = memoryview(wire).cast("B")
        except (ValueError, TypeError):
            # dtypes outside the buffer protocol (bfloat16):
            # reinterpret as a same-width unsigned-int view — still
            # zero-copy
            data = memoryview(
                wire.view(np.dtype(f"u{wire.dtype.itemsize}"))).cast("B")
    rawlen = wire.nbytes
    comp = "none"
    if opts.compression == "zlib" and rawlen >= _MIN_COMPRESS_BYTES:
        packed = zlib.compress(bytes(data), 1)
        if len(packed) < rawlen:  # keep zlib only when it shrinks
            data, comp = packed, "zlib"
    node = {"t": "nd", "i": len(bufs), "dtype": orig_dtype.name,
            "shape": list(arr.shape), "rawlen": rawlen, "comp": comp}
    if wire_dtype is not orig_dtype:
        node["wire"] = "bfloat16"
    bufs.append(data)
    stats.post_bytes += len(data) if isinstance(data, bytes) \
        else data.nbytes
    stats.n_buffers += 1
    return node


def _decode_node(node: Any, bufs: list, opts: WireOptions) -> Any:
    try:
        t = node["t"]
    except (TypeError, KeyError) as e:
        raise WireDecodeError(f"malformed skeleton node: {node!r}") from e
    if t == "none":
        return None
    if t in ("bool", "i", "f", "s"):
        return node["v"]
    if t == "by":
        import base64

        return base64.b64decode(node["v"])
    if t == "np0":
        return np.dtype(node["dtype"]).type(node["v"])
    if t == "nd":
        return _decode_array(node, bufs, opts)
    if t == "raw":
        # a raw batch frame decodes to a plain tuple of arrays; each
        # element must be an array node (malformed ones raise the same
        # typed error as any corrupt skeleton)
        return tuple(_decode_array(v, bufs, opts) for v in node["v"])
    if t == "shmenv":
        # the lane's piggybacked decref acks: applied to OUR arena
        # before the payload decodes.  Refusals (double decref, stale
        # generation, foreign segment) are typed and per-frame — the
        # connection survives, the client disables its lane.
        ch = getattr(opts, "shm", None)
        if ch is None:
            raise ShmRefusal(
                "frame piggybacks shared-memory acks but this "
                "connection negotiated no shm lane")
        try:
            ch.apply_acks(node.get("acks"))
        except _shm.ShmError as e:
            raise ShmRefusal(f"{type(e).__name__}: {e}") from e
        return _decode_node(node["v"], bufs, opts)
    if t == "tuple":
        return tuple(_decode_node(v, bufs, opts) for v in node["v"])
    if t == "list":
        return [_decode_node(v, bufs, opts) for v in node["v"]]
    if t == "dict":
        return {_decode_node(k, bufs, opts): _decode_node(v, bufs, opts)
                for k, v in node["v"]}
    if t == "nt":
        cls = _resolve_namedtuple(node["mod"], node["qual"])
        vals = [_decode_node(v, bufs, opts) for v in node["v"]]
        return cls(*vals)
    if t == "pkl":
        if not opts.allow_pickle:
            raise WireDecodeError(
                "frame carries a pickled structural leaf but this peer "
                "decodes with allow_pickle=False")
        import base64
        import pickle

        return pickle.loads(base64.b64decode(node["v"]))
    raise WireDecodeError(f"unknown skeleton node type {t!r}")


def _resolve_namedtuple(mod: str, qual: str):
    """Validated import of a namedtuple class — the structural escape
    hatch that replaces pickle for optax states.  Anything that is not
    an importable namedtuple class is refused (no arbitrary callables,
    no ``__reduce__`` execution)."""
    try:
        obj: Any = importlib.import_module(mod)
        for part in qual.split("."):
            obj = getattr(obj, part)
    except Exception as e:
        raise WireDecodeError(
            f"cannot resolve namedtuple {mod}.{qual}: {e}") from e
    if not (isinstance(obj, type) and issubclass(obj, tuple)
            and hasattr(obj, "_fields")):
        raise WireDecodeError(
            f"{mod}.{qual} is not a namedtuple class; refusing to call it")
    return obj


def _decode_shm_array(node: dict, desc: Any,
                      opts: WireOptions | None) -> np.ndarray:
    """Decode one out-of-band leaf: map its segment read-only via the
    connection's lane (the map queues the decref ack) and view the
    descriptor's byte range zero-copy.  Every lane failure is a typed
    :class:`ShmRefusal` naming the underlying refusal class."""
    ch = getattr(opts, "shm", None) if opts is not None else None
    if ch is None:
        raise ShmRefusal(
            "frame carries shared-memory descriptors but this "
            "connection negotiated no shm lane")
    try:
        name, off, length, gen = desc
        name, off, length, gen = str(name), int(off), int(length), int(gen)
        shape = tuple(int(d) for d in node["shape"])
        dtype = np.dtype(node["dtype"])
    except (KeyError, TypeError, ValueError) as e:
        raise ShmRefusal(f"malformed shm descriptor node: {node!r}") from e
    if length > MAX_BUFFER_BYTES or off < 0:
        raise ShmRefusal(
            f"shm descriptor range [{off}, {off + length}) refused")
    try:
        m = ch.map_for_read(name, gen)
    except _shm.ShmError as e:
        raise ShmRefusal(f"{type(e).__name__}: {e}") from e
    if off + length > len(m):
        raise ShmRefusal(
            f"shm descriptor [{off}, {off + length}) exceeds the "
            f"{len(m)}-byte segment {name}")
    if dtype.itemsize == 0 or length % dtype.itemsize:
        raise ShmRefusal(
            f"shm leaf of {length} bytes is not a whole number of "
            f"{dtype} items")
    try:
        # PROT_READ mapping -> the view arrives read-only, matching
        # the in-band frombuffer path; the mmap stays alive via the
        # view's base chain even after the owner unlinks the name
        arr = np.frombuffer(m, dtype=dtype, count=length // dtype.itemsize,
                            offset=off).reshape(shape)
    except ValueError as e:
        raise ShmRefusal(
            f"shm leaf does not reshape to {shape}: {e}") from e
    if monitor.enabled():
        monitor.inc("shm/oob_bytes_total", length, dir="recv")
    return arr


def _decode_array(node: dict, bufs: list,
                  opts: WireOptions | None = None) -> np.ndarray:
    desc = node.get("shm") if isinstance(node, dict) else None
    if desc is not None:
        return _decode_shm_array(node, desc, opts)
    try:
        idx = int(node["i"])
        rawlen = int(node["rawlen"])
        shape = tuple(int(d) for d in node["shape"])
        dtype = np.dtype(node["dtype"])
        comp = node.get("comp", "none")
        wire = node.get("wire")
    except (KeyError, TypeError, ValueError) as e:
        raise WireDecodeError(f"malformed array node: {node!r}") from e
    if not 0 <= idx < len(bufs):
        raise WireDecodeError(
            f"array node references buffer {idx} of {len(bufs)}")
    if rawlen > MAX_BUFFER_BYTES:
        raise WireDecodeError(
            f"array buffer declares {rawlen} bytes "
            f"(> {MAX_BUFFER_BYTES}); refusing oversized frame")
    data = bufs[idx]
    if comp == "zlib":
        # bounded decompress: a zlib bomb cannot expand past rawlen
        d = zlib.decompressobj()
        try:
            data = d.decompress(data, rawlen)
            tail = d.decompress(d.unconsumed_tail, 1)
        except zlib.error as e:
            raise WireDecodeError(f"corrupt zlib buffer {idx}: {e}") from e
        if tail or not d.eof:
            raise WireDecodeError(
                f"zlib buffer {idx} does not decompress to its declared "
                f"{rawlen} bytes")
    elif comp != "none":
        raise WireDecodeError(f"unknown buffer compression {comp!r}")
    if len(data) != rawlen:
        raise WireDecodeError(
            f"buffer {idx} is {len(data)} bytes, header declared {rawlen}")
    wire_dtype = BF16 if wire == "bfloat16" else dtype
    if wire_dtype is None:  # pragma: no cover
        raise WireDecodeError("bf16 frame but ml_dtypes is unavailable")
    try:
        arr = np.frombuffer(data, dtype=wire_dtype).reshape(shape)
    except ValueError as e:
        raise WireDecodeError(
            f"buffer {idx} does not reshape to {shape}: {e}") from e
    if wire == "bfloat16":
        arr = arr.astype(dtype)  # f32 restore: accumulation stays f32
    return arr


# ---------------------------------------------------------------------------
# Frame assembly / parsing
# ---------------------------------------------------------------------------


def _scan_shm_bytes(msg: Any, min_b: int) -> int:
    """Segment size one frame needs: the 64-byte-aligned sum of every
    lane-eligible leaf (``nbytes >= min_b``).  A pre-pass so the frame
    leases exactly one segment, sized once."""
    total = 0
    for a in _iter_arrays(msg):
        if a.nbytes >= min_b:
            total += -(-a.nbytes // 64) * 64 + 64
    return total


def encode_frame(msg: Any, opts: WireOptions
                 ) -> tuple[bytes, list, WireStats]:
    """``msg`` (any pytree of JSON-ables + ndarrays) -> (header+skeleton
    bytes, buffer list, stats).  Buffers are memoryviews into the
    source arrays wherever the layout allows — the zero-copy path."""
    stats = WireStats()
    bufs: list = []
    ch = getattr(opts, "shm", None)
    lease = None
    if ch is not None and ch.send_ok:
        want = _scan_shm_bytes(msg, _shm.min_bytes())
        if want:
            lease = ch.alloc(want)
        if lease is not None:
            stats._shm_lease = lease
            stats._shm_min = _shm.min_bytes()
            stats._shm_oob = 0
    try:
        tree = _encode_node(msg, bufs, opts, stats)
    except BaseException:
        if lease is not None:
            ch.cancel(lease)
        raise
    if lease is not None and not lease.used:
        # every eligible leaf fell back in-band — return the segment
        # now instead of waiting out its lease
        ch.cancel(lease)
    elif lease is not None and monitor.enabled():
        monitor.inc("shm/oob_bytes_total", stats._shm_oob, dir="send")
    if ch is not None:
        # piggyback the decref acks for segments WE mapped since the
        # last outgoing frame — the other half of the lane's refcount
        acks = ch.drain_acks()
        if acks:
            tree = {"t": "shmenv", "acks": acks, "v": tree}
    skeleton = json.dumps(
        tree,
        separators=(",", ":")).encode("utf-8")
    stats.pre_bytes += len(skeleton)
    flags = 0
    if len(skeleton) >= _MIN_COMPRESS_BYTES and opts.compression == "zlib":
        packed = zlib.compress(skeleton, 1)
        if len(packed) < len(skeleton):
            skeleton, flags = packed, _FLAG_SKELETON_ZLIB
    if len(bufs) > MAX_BUFFERS:
        raise WireError(f"{len(bufs)} array leaves exceed the frame "
                        f"limit of {MAX_BUFFERS}")
    header = _HEADER.pack(MAGIC, WIRE_VERSION, flags, len(bufs),
                          len(skeleton))
    stats.post_bytes += len(header) + len(skeleton)
    return header + skeleton, bufs, stats


def send_msg(conn, msg: Any, opts: WireOptions) -> WireStats:
    """Send one framed message: header+skeleton, then each buffer as
    its own length-prefixed chunk (``send_bytes`` accepts the
    memoryview directly — no pickle, no concatenation copy)."""
    head, bufs, stats = encode_frame(msg, opts)
    conn.send_bytes(head)
    for b in bufs:
        conn.send_bytes(b)
    if monitor.enabled():
        monitor.inc("service/wire_bytes_pre", stats.pre_bytes, dir="send")
        monitor.inc("service/wire_bytes_post", stats.post_bytes, dir="send")
        monitor.set_gauge("service/wire_compression_ratio", stats.ratio,
                          dir="send")
    return stats


def parse_header(head: bytes) -> tuple[int, int, bytes]:
    """(flags, n_bufs, skeleton_bytes) from a header+skeleton chunk;
    raises :class:`WireDecodeError` on anything malformed."""
    if len(head) < _HEADER.size:
        raise WireDecodeError(
            f"frame header is {len(head)} bytes, need {_HEADER.size}")
    magic, version, flags, n_bufs, skel_len = _HEADER.unpack_from(head)
    if magic != MAGIC:
        raise WireDecodeError(f"bad frame magic {magic!r}")
    if version != WIRE_VERSION:
        raise WireDecodeError(f"unsupported wire version {version}")
    if n_bufs > MAX_BUFFERS:
        raise WireDecodeError(f"frame declares {n_bufs} buffers "
                              f"(> {MAX_BUFFERS})")
    if skel_len > MAX_SKELETON_BYTES:
        raise WireDecodeError(f"frame declares a {skel_len}-byte skeleton "
                              f"(> {MAX_SKELETON_BYTES})")
    skeleton = head[_HEADER.size:]
    if len(skeleton) != skel_len:
        raise WireDecodeError(
            f"skeleton is {len(skeleton)} bytes, header declared "
            f"{skel_len} (truncated frame)")
    return flags, n_bufs, skeleton


def decode_frame(head: bytes, bufs: list,
                 opts: WireOptions | None = None) -> Any:
    """Rebuild the message from a header+skeleton chunk and its
    buffers.  All failures raise :class:`WireDecodeError`."""
    opts = opts or WireOptions()
    flags, n_bufs, skeleton = parse_header(head)
    if n_bufs != len(bufs):
        raise WireDecodeError(
            f"frame declared {n_bufs} buffers, got {len(bufs)}")
    if flags & _FLAG_SKELETON_ZLIB:
        d = zlib.decompressobj()
        try:
            skeleton = d.decompress(skeleton, MAX_SKELETON_BYTES)
        except zlib.error as e:
            raise WireDecodeError(f"corrupt skeleton zlib: {e}") from e
        if not d.eof:
            raise WireDecodeError("skeleton exceeds the size ceiling")
    try:
        tree = json.loads(skeleton.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireDecodeError(f"corrupt frame skeleton: {e}") from e
    ch = getattr(opts, "shm", None)
    if ch is None:
        return _decode_node(tree, bufs, opts)
    # frame-scope the lane's map cache: a (segment, generation) pair
    # is referenced by exactly ONE frame, so once this decode returns
    # the mapping's only owners are the decoded views — their death
    # fires the decref ack that lets the sender recycle the segment
    ch.begin_frame()
    try:
        return _decode_node(tree, bufs, opts)
    finally:
        ch.end_frame()


def recv_msg(conn, opts: WireOptions | None = None,
             buf_timeout_s: float | None = None,
             first_chunk: bytes | None = None) -> Any:
    """Receive one framed message.

    ``first_chunk`` lets a caller that already pulled the first chunk
    off the connection (the server's negotiation loop) hand it in.
    After a valid header, each declared buffer must arrive within
    ``buf_timeout_s`` — a peer that stops mid-frame produces a typed
    :class:`WireDecodeError`, never a hang.  When the header was
    parseable, the declared buffers are drained even if the skeleton
    later proves corrupt, so the connection stays frame-aligned and
    usable ('the connection survives').
    """
    timeout = DEFAULT_BUF_TIMEOUT_S if buf_timeout_s is None \
        else buf_timeout_s
    # the ceilings must bind at READ time, not after the allocation:
    # recv_bytes(maxlength) makes a chunk whose own length prefix
    # declares more raise OSError before the body is ever buffered
    head = conn.recv_bytes(_HEADER.size + MAX_SKELETON_BYTES) \
        if first_chunk is None else first_chunk
    # an unparseable header raises with frame_drained=False: the peer's
    # buffer chunks (if any) are unidentifiable, so the stream cannot
    # be resynchronized — the caller should close this connection
    flags, n_bufs, _ = parse_header(head)
    bufs: list = []
    pre = post = 0
    for i in range(n_bufs):
        if not conn.poll(timeout):
            raise WireDecodeError(
                f"truncated frame: buffer {i}/{n_bufs} never arrived "
                f"within {timeout}s")
        bufs.append(conn.recv_bytes(MAX_BUFFER_BYTES))
        post += len(bufs[-1])
    try:
        msg = decode_frame(head, bufs, opts)
    except WireDecodeError as e:
        # header was valid and every declared buffer was consumed, so
        # the stream is still frame-aligned — the connection survives
        e.frame_drained = True
        raise
    if monitor.enabled():
        for a in _iter_arrays(msg):
            pre += a.nbytes
        pre += len(head)
        post += len(head)
        monitor.inc("service/wire_bytes_pre", pre, dir="recv")
        monitor.inc("service/wire_bytes_post", post, dir="recv")
    return msg


def account_send(stats: WireStats) -> None:
    """Send-side byte accounting for a frame encoded with
    :func:`encode_frame` but written by a caller-owned transport (the
    selector loop's scatter-gather path) — same series as
    :func:`send_msg`."""
    if monitor.enabled():
        monitor.inc("service/wire_bytes_pre", stats.pre_bytes, dir="send")
        monitor.inc("service/wire_bytes_post", stats.post_bytes,
                    dir="send")
        monitor.set_gauge("service/wire_compression_ratio", stats.ratio,
                          dir="send")


def account_recv(msg: Any, head_len: int, post: int) -> None:
    """Recv-side byte accounting for a frame decoded with
    :func:`decode_frame` from caller-received chunks — same series as
    :func:`recv_msg`."""
    if monitor.enabled():
        pre = head_len
        for a in _iter_arrays(msg):
            pre += a.nbytes
        monitor.inc("service/wire_bytes_pre", pre, dir="recv")
        monitor.inc("service/wire_bytes_post", post + head_len,
                    dir="recv")


def _iter_arrays(obj: Any):
    if isinstance(obj, np.ndarray):
        yield obj
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            yield from _iter_arrays(v)
    elif isinstance(obj, dict):
        for v in obj.values():
            yield from _iter_arrays(v)


# ---------------------------------------------------------------------------
# Negotiation (rides the v1 pickle channel once per connection)
# ---------------------------------------------------------------------------

#: the op a v2-capable client sends as its FIRST request; a v2 server
#: answers ("ok", {"version": 2, ...}) and switches the connection to
#: framed mode, a legacy server answers ("err", "unknown op ...") and
#: the client stays on v1 pickle.
HELLO_OP = "wire_hello"

#: trace-context envelope: a client that was granted ``trace`` in the
#: hello may send ``(TRACE_OP, ctx_dict, real_op, *args)`` — the server
#: unwraps the context and dispatches ``real_op`` under it, so its
#: spans become children of the caller's span.  Never sent without the
#: grant, so a legacy server (which would answer "unknown op") never
#: sees it — the same silent-degradation contract as compression/dtype.
TRACE_OP = "wire_trace_ctx"


def hello_payload(opts: WireOptions, trace: bool | None = None,
                  shm_offer: dict | None = None) -> dict:
    """The client's hello.  ``trace=None`` (every existing caller)
    auto-requests trace propagation when tracing is enabled in this
    process — one switch lights up every client in the fleet.

    ``shm_offer`` (``shm.client_offer()``) asks for the shared-memory
    payload lane: it carries the same-host proof (boot-id + uid + a
    nonce the grant must echo), riding the HMAC-authenticated hello.
    A legacy server ignores the key; a remote server refuses it —
    both silently, the same degradation contract as mux."""
    out = {"version": WIRE_VERSION, "compression": opts.compression,
           "dtype": opts.dtype}
    if trace is None:
        trace = _trace.enabled()
    if trace:
        out["trace"] = True
    if shm_offer:
        out["shm"] = shm_offer
    return out


def accept_hello(payload: Any, allow_mux: bool = False,
                 allow_shm: bool = False) -> tuple[WireOptions, dict, bool]:
    """Server side: validate a hello payload, returning the negotiated
    options, the reply dict, and whether connection multiplexing was
    granted.  Unknown/newer options degrade to the safe defaults
    rather than failing the connection.

    ``allow_shm``: a server loop that closes its connections' lane
    channels on teardown may grant the shared-memory payload lane —
    ``shm.server_grant`` checks the offer's same-host proof (boot-id
    + uid) and the granted channel lands on the returned options'
    ``shm`` field.  Refusal just omits the key from the reply: old
    clients never sent the offer, old servers never echo it, and a
    remote peer falls back to in-band bytes silently.

    ``mux`` (``parallel/rpc.py``): a client may request stream
    multiplexing — many logical request/reply streams framed over one
    socket — by adding ``"mux": True`` to its hello.  Only a server
    whose loop can demultiplex (the selector loop) passes
    ``allow_mux=True``; everyone else omits ``mux`` from the reply and
    the client falls back to one socket per stream, so an old client
    (which never sends the key) and an old server (which never echoes
    it) both keep working byte-compatibly."""
    if not isinstance(payload, dict):
        raise WireProtocolError(f"malformed wire_hello: {payload!r}")
    version = payload.get("version")
    if version != WIRE_VERSION:
        raise WireProtocolError(
            f"peer requested wire version {version!r}; this server "
            f"speaks {WIRE_VERSION} (v1 pickle needs no hello)")
    comp = payload.get("compression", "none")
    dtype = payload.get("dtype", "f32")
    if comp not in ("none", "zlib"):
        comp = "none"
    if dtype not in ("f32", "bf16"):
        dtype = "f32"
    shm_ch = shm_reply = None
    if allow_shm and "shm" in payload:
        shm_ch, shm_reply = _shm.server_grant(payload.get("shm"))
    # the pickle escape stays OFF for frames the server decodes: an
    # authenticated-but-hostile peer must not reach pickle.loads
    opts = WireOptions(compression=comp, dtype=dtype, allow_pickle=False,
                       shm=shm_ch)
    mux = bool(allow_mux and payload.get("mux"))
    # the grant is bilateral: the client asked AND this server has
    # tracing on — a reply without the key tells the client to never
    # send the TRACE_OP envelope on this connection
    reply = hello_payload(opts, trace=bool(payload.get("trace")
                                           and _trace.enabled()))
    if mux:
        reply["mux"] = True
    if shm_reply is not None:
        reply["shm"] = shm_reply
    return opts, reply, mux
