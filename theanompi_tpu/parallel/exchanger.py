"""Parameter/gradient exchange — the heart of the framework.

TPU-native rebuild of the reference's exchanger layer (reference layout
``theanompi/lib/exchanger.py`` + ``lib/exchanger_strategy.py``,
SURVEY.md §2.4–§2.5; the reference mount was empty this round so
citations are to SURVEY.md sections, not file:line).

The reference flattened Theano shared variables into GPU buffers and
dispatched to one of six transport strategies (``ar``, ``asa32``,
``asa16``, ``copper``, ``nccl32``, ``nccl16``) for an MPI- or
NCCL-backed allreduce after each iteration.  On TPU the transport zoo
collapses: XLA emits ICI collectives for ``jax.lax.psum`` inside the
jitted SPMD step, and the compiler — not the framework — schedules and
overlaps them.  What survives of the reference's strategy seam is the
*numeric* choice the strategies encoded:

* fp32 exchange (``ar``/``asa32``/``copper``/``nccl32``) -> ``psum``
  on the native dtype;
* fp16-compressed exchange (``asa16``/``nccl16``) -> cast to bfloat16,
  ``psum``, cast back.  bf16 keeps fp32's exponent range, so the
  reference's fp16 loss-scale knob is unnecessary on TPU (kept as a
  config field for API parity; default 1.0).
* sum vs average (the reference's ``avg`` flag).

This module also carries the async rules' merge arithmetic (EASGD
elastic update, ASGD server update, GOSGD weighted merge — SURVEY.md
§2.3/§2.5) as small pure jitted functions; the rules in
``theanompi_tpu/rules`` own the process topology around them.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from theanompi_tpu import monitor
from theanompi_tpu.parallel.mesh import AXIS_DATA
from theanompi_tpu.parallel.partition import balanced_ranges

PyTree = Any


def bucket_ranges(sizes, n_buckets: int) -> list[tuple[int, int]]:
    """Layer-ordered, byte-balanced bucket plan over flatten-order
    leaves: contiguous ``(lo, hi)`` leaf ranges, a pure function of
    (leaf byte sizes, bucket count) — every rank derives the identical
    plan from its own model tree, exactly like the shard fleet's
    ``partition_ranges`` (same greedy walk, ``parallel/partition.py``).
    Unlike the shard plan, a bucket count beyond the leaf count CLAMPS
    to per-leaf buckets instead of raising: the bucket plan is a
    scheduling hint, not an ownership contract."""
    sizes = list(sizes)
    return balanced_ranges(sizes, min(int(n_buckets), len(sizes)))


def validate_bucket_count(exchange_buckets) -> int:
    """The ONE contract check for the ``exchange_buckets`` knob (the
    exchanger and the zero/fsdp step builders all accept it — one
    validator keeps the three planes' accepted values and error text
    identical)."""
    b = exchange_buckets
    if isinstance(b, bool) or not isinstance(b, int) or b < 1:
        raise ValueError(
            f"exchange_buckets must be an int >= 1, got {b!r}")
    return b


def _leaf_nbytes(leaf) -> int:
    import numpy as np

    size = getattr(leaf, "size", None)
    if size is None:
        size = int(np.prod(getattr(leaf, "shape", ())))
    return int(size) * np.dtype(leaf.dtype).itemsize


def emit_bucket_gauges(plane: str, ranges, leaves, wire_dtype: str) -> None:
    """Trace-time bucket telemetry (same contract as the exchange
    gauges below: recorded once per compile, bytes/step = gauge x
    steps): the live bucket count and each bucket's wire bytes."""
    if not monitor.enabled():
        return
    monitor.set_gauge("bsp/exchange_buckets", len(ranges), plane=plane,
                      dtype=wire_dtype)
    for i, (lo, hi) in enumerate(ranges):
        if wire_dtype == "bf16":
            nbytes = 2 * sum(int(getattr(l, "size", 0))
                             for l in leaves[lo:hi])
        else:
            nbytes = sum(_leaf_nbytes(l) for l in leaves[lo:hi])
        monitor.set_gauge("bsp/exchange_bucket_bytes", nbytes,
                          plane=plane, bucket=str(i), dtype=wire_dtype)

# Reference strategy names -> TPU numeric strategy.
_STRATEGY_ALIASES = {
    "ar": "psum",
    "asa32": "psum",
    "copper": "psum",
    "nccl32": "psum",
    "psum": "psum",
    "asa16": "psum_bf16",
    "nccl16": "psum_bf16",
    "psum_bf16": "psum_bf16",
}


def resolve_strategy(name: str) -> str:
    """Map a reference-era strategy name to its TPU numeric strategy
    ('psum' | 'psum_bf16'); raises on unknown names."""
    try:
        return _STRATEGY_ALIASES[name]
    except KeyError:
        raise ValueError(
            f"unknown exchange strategy {name!r}; "
            f"expected one of {sorted(_STRATEGY_ALIASES)}") from None


@dataclasses.dataclass(frozen=True)
class BSP_Exchanger:
    """BSP exchange semantics, applied *inside* the SPMD training step.

    Name kept for API parity with the reference's ``BSP_Exchanger``
    (SURVEY.md §2.4).  Unlike the reference this is not a stateful
    buffer manager: it is a pure ``tree -> tree`` transform traced into
    the jitted step, so exchange overlaps backprop wherever XLA can
    schedule it.

    Args:
      strategy: one of the reference names (``ar``/``asa32``/``asa16``/
        ``copper``/``nccl32``/``nccl16``) or the native names
        (``psum``/``psum_bf16``).
      avg: True -> average over the data axis (the reference's ``avg``
        sync type); False -> plain sum (``cdd``-style; caller is then
        expected to have pre-scaled its learning rate, cf. the
        reference's ``scale_lr``).
      exchange_what: ``'grads'`` (allreduce gradients each iteration,
        the reference BSP default) or ``'params'`` (average parameters,
        the reference's alternative BSP mode).
      fp16_scale: kept for parity with the reference's fp16 strategies;
        bf16 needs no scaling, default 1.0.
      axis: mesh axis name (or tuple of names) to reduce over — a
        data x seq training step exchanges over both axes.
      exchange_dtype: ``None`` (derive from ``strategy``) | ``'f32'`` |
        ``'bf16'`` — the ICI wire dtype of the exchange.  ``'bf16'``
        quantizes each leaf to bfloat16 before the psum (half the
        gradient bytes on the pod interconnect) and restores float32
        BEFORE the average, so the mean and the optimizer update
        accumulate in f32.  The ``ModelConfig.exchange_dtype`` knob
        lands here; the reference-era ``nccl16``-family strategy names
        remain the parity spelling of the same choice.
      error_feedback: carry the per-shard bf16 quantization error into
        the next step's gradient (1-bit-SGD-style residual, SURVEY.md
        compression lineage): ``exchange_with_residual`` adds the
        stored residual before quantizing and returns the new one.
        The residual rides ``TrainState.exchange_residual`` with a
        leading shard axis (parallel/bsp.py threads it).  Requires the
        bf16 wire dtype and ``exchange_what='grads'``.
      exchange_buckets: partition the flatten-order gradient leaves
        into this many layer-ordered, byte-balanced buckets
        (``bucket_ranges``) and issue ONE collective per bucket
        instead of per-leaf ops the compiler must re-combine.  On the
        training step's grads path the collectives are embedded INTO
        the backward DAG (``backward_exchange``: custom_vjp boundary
        tags fire each bucket's psum the moment its layers' cotangents
        are complete), so XLA's latency-hiding scheduler overlaps
        bucket i's collective with bucket i+1's gradient compute — the
        layer-ordered bucketing of arXiv:1802.06949 expressed in the
        compiler's DAG.  ``1`` (default) keeps today's whole-tree
        post-backward exchange byte-identical.  Numerics are identical
        under any bucket count (pinned): bucketing regroups elementwise
        collectives, it never reorders a per-element sum.
    """

    strategy: str = "psum"
    avg: bool = True
    exchange_what: str = "grads"
    fp16_scale: float = 1.0
    axis: str | tuple[str, ...] = AXIS_DATA
    exchange_dtype: str | None = None
    error_feedback: bool = False
    exchange_buckets: int = 1

    def __post_init__(self):
        validate_bucket_count(self.exchange_buckets)
        if self.strategy not in _STRATEGY_ALIASES:
            raise ValueError(
                f"unknown exchange strategy {self.strategy!r}; "
                f"expected one of {sorted(_STRATEGY_ALIASES)}"
            )
        if self.exchange_what not in ("grads", "params"):
            raise ValueError("exchange_what must be 'grads' or 'params'")
        if self.exchange_dtype not in (None, "f32", "bf16"):
            raise ValueError(
                f"exchange_dtype must be 'f32' or 'bf16', "
                f"got {self.exchange_dtype!r}")
        if self.error_feedback:
            if self.wire_dtype != "bf16":
                raise ValueError(
                    "error_feedback compensates bf16 quantization; it "
                    "needs exchange_dtype='bf16' (or a bf16 strategy)")
            if self.exchange_what != "grads":
                raise ValueError(
                    "error_feedback is a gradient-compression technique; "
                    "exchange_what='params' has no residual semantics")

    @property
    def resolved(self) -> str:
        if self.exchange_dtype == "bf16":
            return "psum_bf16"
        if self.exchange_dtype == "f32":
            return "psum"
        return _STRATEGY_ALIASES[self.strategy]

    @property
    def wire_dtype(self) -> str:
        """'bf16' | 'f32' — what actually moves over ICI."""
        return "bf16" if self.resolved == "psum_bf16" else "f32"

    # -- the exchange itself (must run inside shard_map over self.axis) --

    def exchange(self, tree: PyTree) -> PyTree:
        """Allreduce a pytree over the data axis. Traced into the step."""
        axis = self.axis

        # Telemetry: this body executes at TRACE time (the exchange is
        # compiled into the step), so per-call counting is impossible
        # from here — what IS knowable here, exactly once per compile,
        # is the exchange's shape: bytes moved per call and the wire
        # dtype.  Per-step totals = bytes_per_call x the step counter.
        if monitor.enabled():
            if self.resolved == "psum_bf16":
                # the compressed strategy ships 2 bytes/element
                # regardless of the storage dtype
                wire_dtype = "bfloat16"
                nbytes = 2 * sum(
                    int(getattr(l, "size", 0))
                    for l in jax.tree.leaves(tree))
            else:
                wire_dtype = monitor.tree_dtypes(tree)
                nbytes = monitor.tree_bytes(tree)
            monitor.set_gauge("exchange/bytes_per_call", nbytes,
                              strategy=self.resolved, dtype=wire_dtype,
                              what=self.exchange_what)
            monitor.inc("exchange/traces_total", strategy=self.resolved)

        if self.exchange_buckets > 1:
            # post-backward bucketed exchange (the grad-accum tail and
            # the 'params' averaging mode; the single/multi grads path
            # embeds the buckets into the backward via
            # ``backward_exchange`` instead): one collective per
            # byte-balanced leaf bucket
            leaves, treedef = jax.tree.flatten(tree)
            ranges = bucket_ranges([_leaf_nbytes(l) for l in leaves],
                                   self.exchange_buckets)
            emit_bucket_gauges("bsp", ranges, leaves, self.wire_dtype)
            out = []
            for lo, hi in ranges:
                out.extend(self._reduce_bucket(tuple(leaves[lo:hi])))
            return jax.tree.unflatten(treedef, out)

        if self.resolved == "psum_bf16":
            def reduce_leaf(x):
                orig = x.dtype
                y = (x * self.fp16_scale).astype(jnp.bfloat16)
                y = self._bf16_sum(y, axis)
                return (y / self.fp16_scale).astype(orig)
        else:
            def reduce_leaf(x):
                return jax.lax.psum(x, axis)

        out = jax.tree.map(reduce_leaf, tree)
        if self.avg:
            n = self._axis_size()
            out = jax.tree.map(lambda x: x / n, out)
        return out

    def _axis_size(self):
        axes = ((self.axis,) if isinstance(self.axis, str)
                else tuple(self.axis))
        n = 1
        for a in axes:
            n *= jax.lax.axis_size(a)
        return n

    @staticmethod
    def _bf16_sum(y, axis):
        """Sum bf16-quantized leaves over ``axis`` with a bf16 WIRE and
        f32 ACCUMULATION: all_gather the quantized values (bf16 on the
        interconnect — (N-1)/N x 2 bytes/element, half a bf16 ring
        all-reduce's traffic and a quarter of the f32 one) and reduce
        locally in float32.

        Why not ``psum(bf16)``: the psum accumulates IN bf16, and at N
        shards the partial sums sit N x above the payload — each add
        can then swallow an entire quantization step of the increment
        (at N=8 a 2^-8 correction on a ~1.0 payload vanishes into the
        ~8.0 partial sum's 2^-5 spacing).  Measured on the 8-dev CPU
        mesh, that rounding defeats error feedback almost entirely;
        the local f32 reduce is what makes the residual pin
        (tests/test_exchanger.py long-run gradient-sum) hold."""
        g = jax.lax.all_gather(y, axis)
        return jnp.sum(g.astype(jnp.float32), axis=0)

    # -- bucketed exchange (ISSUE 13) -----------------------------------

    @staticmethod
    def _bucket_flat(cts: tuple):
        """Concatenate a bucket's leaves into ONE vector when their
        dtypes agree (one collective per bucket in the lowered
        program — the reference's bucket flattening); ``None`` for a
        mixed-dtype bucket (the per-leaf fallback keeps numerics
        exact instead of forcing a cast)."""
        if len({jnp.result_type(c) for c in cts}) != 1:
            return None
        if len(cts) == 1:
            return cts[0].reshape(-1)
        return jnp.concatenate([c.reshape(-1) for c in cts])

    @staticmethod
    def _split_like(flat, refs: tuple) -> tuple:
        out, off = [], 0
        for r in refs:
            n = int(r.size)
            out.append(flat[off:off + n].reshape(r.shape))
            off += n
        return tuple(out)

    def _reduce_bucket(self, cts: tuple) -> tuple:
        """Exchange one bucket of gradient leaves: elementwise-identical
        to the per-leaf ``exchange`` (psum and the bf16 quantize/sum
        are elementwise across shards — regrouping leaves cannot move
        a single per-element sum), but issued as ONE collective."""
        axis = self.axis
        flat = self._bucket_flat(cts)
        if flat is None:  # mixed dtypes: per-leaf ops, same boundary
            if self.resolved == "psum_bf16":
                red = tuple(
                    (self._bf16_sum((c * self.fp16_scale)
                                    .astype(jnp.bfloat16), axis)
                     / self.fp16_scale).astype(c.dtype) for c in cts)
            else:
                red = jax.lax.psum(cts, axis)
            if self.avg:
                n = self._axis_size()
                red = tuple(x / n for x in red)
            return tuple(red)
        if self.resolved == "psum_bf16":
            y = (flat * self.fp16_scale).astype(jnp.bfloat16)
            red = (self._bf16_sum(y, axis)
                   / self.fp16_scale).astype(flat.dtype)
        else:
            red = jax.lax.psum(flat, axis)
        if self.avg:
            red = red / self._axis_size()
        return self._split_like(red, cts)

    def _reduce_bucket_ef(self, cts: tuple, res: tuple
                          ) -> tuple[tuple, tuple]:
        """Error-feedback variant of ``_reduce_bucket``: quantize
        ``ct + residual`` to bf16, one all-gather + f32 sum for the
        bucket, return (exchanged, new per-shard residual slice) —
        the per-leaf ``exchange_with_residual`` math on one flat
        bucket vector."""
        axis = self.axis
        flat = self._bucket_flat(cts)
        if flat is None:
            comp = tuple(c.astype(jnp.float32) + r
                         for c, r in zip(cts, res))
            q = tuple(c.astype(jnp.bfloat16) for c in comp)
            new_r = tuple(c - qq.astype(jnp.float32)
                          for c, qq in zip(comp, q))
            out = tuple(self._bf16_sum(qq, axis).astype(c.dtype)
                        for qq, c in zip(q, cts))
            if self.avg:
                n = self._axis_size()
                out = tuple(x / n for x in out)
            return out, new_r
        rflat = self._bucket_flat(res)
        comp = flat.astype(jnp.float32) + rflat
        q = comp.astype(jnp.bfloat16)
        new_r = comp - q.astype(jnp.float32)
        out = self._bf16_sum(q, axis).astype(flat.dtype)
        if self.avg:
            out = out / self._axis_size()
        return (self._split_like(out, cts),
                self._split_like(new_r, res))

    def _grad_tag(self):
        """custom_vjp boundary marker for one bucket: identity forward;
        the backward fires the bucket's collective the moment its
        leaves' cotangents are complete, embedding the exchange into
        the backward DAG for the latency-hiding scheduler to overlap
        with the remaining segments' gradient compute."""

        @jax.custom_vjp
        def tag(leaves):
            return leaves

        def fwd(leaves):
            return leaves, None

        def bwd(_, cts):
            return (self._reduce_bucket(cts),)

        tag.defvjp(fwd, bwd)
        return tag

    def _ef_tag(self):
        """Error-feedback boundary marker.  The residual slice is a
        *differentiated* input whose "cotangent" we define to be the
        NEW residual — the only side channel a backward segment has
        for emitting state (a custom_vjp bwd returns exactly one
        cotangent per input)."""

        @jax.custom_vjp
        def tag(leaves, res):
            return leaves

        def fwd(leaves, res):
            return leaves, res

        def bwd(res, cts):
            out, new_r = self._reduce_bucket_ef(cts, res)
            return out, new_r

        tag.defvjp(fwd, bwd)
        return tag

    def backward_exchange(self, loss_fn, params: PyTree,
                          model_state: PyTree, batch, rng,
                          residual: PyTree | None = None):
        """value_and_grad with the bucketed exchange embedded in the
        backward DAG (the ``exchange_buckets > 1`` grads path).

        The flatten-order leaves are cut into layer-ordered buckets
        (``bucket_ranges``); each bucket's leaves pass through a
        boundary tag whose custom backward issues that bucket's
        collective as soon as all its cotangents exist.  Autodiff
        runs the backward segment for the deepest layers first, so
        the last bucket's psum is already on the interconnect while
        earlier layers' cotangents are still being computed — the
        lowered program carries B collectives interleaved with the
        backward fusions instead of one trailing exchange block
        (pinned structurally in tests/test_exchanger.py).

        Returns ``(loss, (new_model_state, metrics), grads,
        new_residual)`` where ``grads`` is ALREADY exchanged (and
        averaged when ``avg``) and ``new_residual`` is ``None``
        unless ``error_feedback``.
        """
        if self.exchange_what != "grads":
            raise ValueError("backward_exchange embeds the GRADIENT "
                             "exchange; exchange_what='params' has no "
                             "backward to interleave with")
        leaves, treedef = jax.tree.flatten(params)
        ranges = bucket_ranges([_leaf_nbytes(l) for l in leaves],
                               self.exchange_buckets)
        emit_bucket_gauges("bsp", ranges, leaves, self.wire_dtype)
        ef = self.error_feedback
        if ef:
            if residual is None:
                raise ValueError("error_feedback needs the residual "
                                 "tree (TrainState.exchange_residual)")
            rleaves = jax.tree.flatten(residual)[0]

        def tagged_loss(diff_arg, model_state, batch, rng):
            buckets, rbuckets = (diff_arg if ef else (diff_arg, None))
            new_leaves = []
            for b in range(len(ranges)):
                if ef:
                    new_leaves.extend(
                        self._ef_tag()(buckets[b], rbuckets[b]))
                else:
                    new_leaves.extend(self._grad_tag()(buckets[b]))
            return loss_fn(jax.tree.unflatten(treedef, new_leaves),
                           model_state, batch, rng)

        buckets = tuple(tuple(leaves[lo:hi]) for lo, hi in ranges)
        if ef:
            rbuckets = tuple(tuple(rleaves[lo:hi]) for lo, hi in ranges)
            diff_arg = (buckets, rbuckets)
        else:
            diff_arg = buckets
        grad_fn = jax.value_and_grad(tagged_loss, has_aux=True)
        (loss, (new_ms, metrics)), g = grad_fn(diff_arg, model_state,
                                               batch, rng)
        if ef:
            gb, rb = g
            new_residual = jax.tree.unflatten(
                treedef, [r for rt in rb for r in rt])
        else:
            gb, new_residual = g, None
        grads = jax.tree.unflatten(treedef,
                                   [x for bt in gb for x in bt])
        return loss, (new_ms, metrics), grads, new_residual

    def exchange_with_residual(self, tree: PyTree,
                               residual: PyTree) -> tuple[PyTree, PyTree]:
        """bf16 exchange with error feedback: quantize ``tree +
        residual`` to bfloat16, sum the quantized values over the axis
        with ``_bf16_sum`` (bf16 on the wire — 2 bytes/element — f32
        accumulation locally), average in f32, and
        return the NEW per-shard residual — the f32 difference between
        what this shard wanted to send and what the quantizer let
        through.  Over a run the residual re-injects every bit the
        wire dropped, so the cumulative applied gradient tracks the
        cumulative true gradient to within one quantization step
        (pinned by test)."""
        if not self.error_feedback:
            raise ValueError("exchange_with_residual needs "
                             "error_feedback=True")

        if self.exchange_buckets > 1:
            # post-backward bucketed EF exchange (the grad-accum tail;
            # per-bucket residual slices are the same leaves, just
            # grouped): one all-gather per bucket
            leaves, treedef = jax.tree.flatten(tree)
            rleaves = jax.tree.flatten(residual)[0]
            ranges = bucket_ranges([_leaf_nbytes(l) for l in leaves],
                                   self.exchange_buckets)
            emit_bucket_gauges("bsp", ranges, leaves, self.wire_dtype)
            out, new_res = [], []
            for lo, hi in ranges:
                o, r = self._reduce_bucket_ef(
                    tuple(leaves[lo:hi]), tuple(rleaves[lo:hi]))
                out.extend(o)
                new_res.extend(r)
            return (jax.tree.unflatten(treedef, out),
                    jax.tree.unflatten(treedef, new_res))

        # comp appears in both maps; XLA CSEs the duplicate add
        q_tree = jax.tree.map(
            lambda x, r: (x.astype(jnp.float32) + r).astype(jnp.bfloat16),
            tree, residual)
        new_residual = jax.tree.map(
            lambda x, r, q: (x.astype(jnp.float32) + r)
            - q.astype(jnp.float32),
            tree, residual, q_tree)
        axis = self.axis
        out = jax.tree.map(
            lambda q, x: self._bf16_sum(q, axis).astype(x.dtype),
            q_tree, tree)
        if self.avg:
            n = self._axis_size()
            out = jax.tree.map(lambda x: x / n, out)
        return out, new_residual


# ---------------------------------------------------------------------------
# Async-rule merge arithmetic (EASGD / ASGD / GOSGD)
#
# In the reference these were tiny Theano functions compiled on the
# worker/server GPUs and driven by MPI Sendrecv of GPU buffers
# (SURVEY.md §2.5, §3.3).  Here they are pure jitted pytree ops; the
# host-side rule actors in theanompi_tpu/rules move the data.
# ---------------------------------------------------------------------------


@partial(jax.jit, donate_argnums=(0,))
def easgd_worker_update(worker: PyTree, center: PyTree, alpha) -> PyTree:
    """worker <- worker - alpha * (worker - center)  (SURVEY.md §2.3)."""
    return jax.tree.map(lambda w, c: w - alpha * (w - c), worker, center)


@partial(jax.jit, donate_argnums=(0,))
def easgd_center_update(center: PyTree, worker: PyTree, alpha) -> PyTree:
    """center <- center + alpha * (worker - center)  (SURVEY.md §2.3)."""
    return jax.tree.map(lambda c, w: c + alpha * (w - c), center, worker)


@jax.jit
def easgd_both_updates(worker: PyTree, center: PyTree, alpha):
    """One fused elastic exchange: returns (new_worker, new_center).

    The reference did this as one MPI Sendrecv + two GPU kernels; fusing
    both sides into one jitted call halves the host round-trips.
    """
    new_w = jax.tree.map(lambda w, c: w - alpha * (w - c), worker, center)
    new_c = jax.tree.map(lambda c, w: c + alpha * (w - c), center, worker)
    return new_w, new_c


@jax.jit
def easgd_center_update_n(center: PyTree, worker_mean: PyTree,
                          alpha_eff) -> PyTree:
    """Aggregated center move (hierarchical exchange,
    ``parallel/aggregate.py``): ``center + alpha_eff*(mean - center)``
    with ``alpha_eff = n*alpha`` — the closed-form composition of n
    same-version elastic exchanges.  Deliberately NON-donating: the
    caller returns the pre-update ``center`` to the aggregator, which
    computes each worker's own elastic pull against it."""
    return jax.tree.map(lambda c, m: c + alpha_eff * (m - c),
                        center, worker_mean)


@partial(jax.jit, donate_argnums=(0,))
def easgd_apply_delta(current: PyTree, snapshot: PyTree,
                      returned: PyTree) -> PyTree:
    """Overlapped-EASGD correction (rules/async_rules.py overlap mode).

    The exchange thread shipped ``snapshot`` (the params at submit
    time) and got back ``returned = snapshot - alpha*(snapshot -
    center)``; meanwhile the worker trained on.  The elastic force the
    server computed is ``delta = snapshot - returned = alpha*(snapshot
    - center)`` — apply it to the params the worker has NOW:
    ``current - delta``.  This is the classic staleness-1 elastic
    update: same force, applied one exchange period late, bounded by
    the pipe's max-1-outstanding barrier."""
    return jax.tree.map(lambda c, s, r: c - (s - r),
                        current, snapshot, returned)


@partial(jax.jit, donate_argnums=(0,))
def asgd_apply_grads(center: PyTree, grads: PyTree, lr) -> PyTree:
    """Parameter-server SGD step: center <- center - lr * grads."""
    return jax.tree.map(lambda c, g: c - lr * g, center, grads)


@jax.jit
def gosgd_merge(own: PyTree, own_w, recv: PyTree, recv_w):
    """Gossip merge (Blot et al., SURVEY.md §2.3):

    receiver params <- weighted average of (own, received) by their
    scalar weights; receiver weight <- own_w + recv_w.
    """
    total = own_w + recv_w
    merged = jax.tree.map(
        lambda a, b: (own_w * a + recv_w * b) / total, own, recv
    )
    return merged, total


#: optimizer-state fields that hold FIRST-moment information (gradient
#: direction memory) — the slots a gossip merge must scale.  Second
#: moments (adam/rmsprop ``nu``) are deliberately NOT here: shrinking a
#: curvature estimate toward zero while its bias-correction ``count``
#: stays put would make the next preconditioned step
#: mu_hat/sqrt(nu_hat) BLOW UP at exactly the teleported point —
#: the opposite of the stabilization this exists for.
_FIRST_MOMENT_FIELDS = frozenset({"trace", "mu", "mean", "momentum"})


def gosgd_scale_momentum(opt_state: PyTree, frac: float) -> PyTree:
    """Scale the optimizer's first-moment slots by the receiver's
    share of a gossip merge.

    The merge teleports params toward the sender when recv_w >> own_w,
    but the local momentum buffer was accumulated along the OLD
    trajectory — applying it unscaled at the new point is the measured
    divergence mode of gossip over slow links (docs/SCALING.md: loss
    5-9 vs the 2.3 random floor at momentum 0.9, stable at 0).
    Treating momentum like params in the weighted average — with the
    sender's (unshipped) momentum taken as zero — scales it by
    own_w/total: a small merge barely touches it, a dominating push
    resets it.

    Slots are matched by state-field NAME (optax state namedtuples:
    sgd/momentum ``trace``, adam/adamw ``mu``, adabelief-style
    ``mean``); everything else — second moments, counts, injected
    hyperparams — is kept, which is the conservative direction (``keep``
    was the reference's raw behavior).  A cheap path-walk per message,
    no optimizer re-initialization."""
    from jax import tree_util as jtu

    def scale(path, leaf):
        names = {p.name for p in path if isinstance(p, jtu.GetAttrKey)}
        if names & _FIRST_MOMENT_FIELDS:
            return leaf * frac
        return leaf

    return jtu.tree_map_with_path(scale, opt_state)
