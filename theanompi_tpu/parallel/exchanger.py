"""Parameter/gradient exchange — the heart of the framework.

TPU-native rebuild of the reference's exchanger layer (reference layout
``theanompi/lib/exchanger.py`` + ``lib/exchanger_strategy.py``,
SURVEY.md §2.4–§2.5; the reference mount was empty this round so
citations are to SURVEY.md sections, not file:line).

The reference flattened Theano shared variables into GPU buffers and
dispatched to one of six transport strategies (``ar``, ``asa32``,
``asa16``, ``copper``, ``nccl32``, ``nccl16``) for an MPI- or
NCCL-backed allreduce after each iteration.  On TPU the transport zoo
collapses: XLA emits ICI collectives for ``jax.lax.psum`` inside the
jitted SPMD step, and the compiler — not the framework — schedules and
overlaps them.  What survives of the reference's strategy seam is the
*numeric* choice the strategies encoded:

* fp32 exchange (``ar``/``asa32``/``copper``/``nccl32``) -> ``psum``
  on the native dtype;
* fp16-compressed exchange (``asa16``/``nccl16``) -> cast to bfloat16,
  ``psum``, cast back.  bf16 keeps fp32's exponent range, so the
  reference's fp16 loss-scale knob is unnecessary on TPU (kept as a
  config field for API parity; default 1.0).
* sum vs average (the reference's ``avg`` flag).

This module also carries the async rules' merge arithmetic (EASGD
elastic update, ASGD server update, GOSGD weighted merge — SURVEY.md
§2.3/§2.5) as small pure jitted functions; the rules in
``theanompi_tpu/rules`` own the process topology around them.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from theanompi_tpu import monitor
from theanompi_tpu.parallel.mesh import AXIS_DATA

PyTree = Any

# Reference strategy names -> TPU numeric strategy.
_STRATEGY_ALIASES = {
    "ar": "psum",
    "asa32": "psum",
    "copper": "psum",
    "nccl32": "psum",
    "psum": "psum",
    "asa16": "psum_bf16",
    "nccl16": "psum_bf16",
    "psum_bf16": "psum_bf16",
}


def resolve_strategy(name: str) -> str:
    """Map a reference-era strategy name to its TPU numeric strategy
    ('psum' | 'psum_bf16'); raises on unknown names."""
    try:
        return _STRATEGY_ALIASES[name]
    except KeyError:
        raise ValueError(
            f"unknown exchange strategy {name!r}; "
            f"expected one of {sorted(_STRATEGY_ALIASES)}") from None


@dataclasses.dataclass(frozen=True)
class BSP_Exchanger:
    """BSP exchange semantics, applied *inside* the SPMD training step.

    Name kept for API parity with the reference's ``BSP_Exchanger``
    (SURVEY.md §2.4).  Unlike the reference this is not a stateful
    buffer manager: it is a pure ``tree -> tree`` transform traced into
    the jitted step, so exchange overlaps backprop wherever XLA can
    schedule it.

    Args:
      strategy: one of the reference names (``ar``/``asa32``/``asa16``/
        ``copper``/``nccl32``/``nccl16``) or the native names
        (``psum``/``psum_bf16``).
      avg: True -> average over the data axis (the reference's ``avg``
        sync type); False -> plain sum (``cdd``-style; caller is then
        expected to have pre-scaled its learning rate, cf. the
        reference's ``scale_lr``).
      exchange_what: ``'grads'`` (allreduce gradients each iteration,
        the reference BSP default) or ``'params'`` (average parameters,
        the reference's alternative BSP mode).
      fp16_scale: kept for parity with the reference's fp16 strategies;
        bf16 needs no scaling, default 1.0.
      axis: mesh axis name (or tuple of names) to reduce over — a
        data x seq training step exchanges over both axes.
      exchange_dtype: ``None`` (derive from ``strategy``) | ``'f32'`` |
        ``'bf16'`` — the ICI wire dtype of the exchange.  ``'bf16'``
        quantizes each leaf to bfloat16 before the psum (half the
        gradient bytes on the pod interconnect) and restores float32
        BEFORE the average, so the mean and the optimizer update
        accumulate in f32.  The ``ModelConfig.exchange_dtype`` knob
        lands here; the reference-era ``nccl16``-family strategy names
        remain the parity spelling of the same choice.
      error_feedback: carry the per-shard bf16 quantization error into
        the next step's gradient (1-bit-SGD-style residual, SURVEY.md
        compression lineage): ``exchange_with_residual`` adds the
        stored residual before quantizing and returns the new one.
        The residual rides ``TrainState.exchange_residual`` with a
        leading shard axis (parallel/bsp.py threads it).  Requires the
        bf16 wire dtype and ``exchange_what='grads'``.
    """

    strategy: str = "psum"
    avg: bool = True
    exchange_what: str = "grads"
    fp16_scale: float = 1.0
    axis: str | tuple[str, ...] = AXIS_DATA
    exchange_dtype: str | None = None
    error_feedback: bool = False

    def __post_init__(self):
        if self.strategy not in _STRATEGY_ALIASES:
            raise ValueError(
                f"unknown exchange strategy {self.strategy!r}; "
                f"expected one of {sorted(_STRATEGY_ALIASES)}"
            )
        if self.exchange_what not in ("grads", "params"):
            raise ValueError("exchange_what must be 'grads' or 'params'")
        if self.exchange_dtype not in (None, "f32", "bf16"):
            raise ValueError(
                f"exchange_dtype must be 'f32' or 'bf16', "
                f"got {self.exchange_dtype!r}")
        if self.error_feedback:
            if self.wire_dtype != "bf16":
                raise ValueError(
                    "error_feedback compensates bf16 quantization; it "
                    "needs exchange_dtype='bf16' (or a bf16 strategy)")
            if self.exchange_what != "grads":
                raise ValueError(
                    "error_feedback is a gradient-compression technique; "
                    "exchange_what='params' has no residual semantics")

    @property
    def resolved(self) -> str:
        if self.exchange_dtype == "bf16":
            return "psum_bf16"
        if self.exchange_dtype == "f32":
            return "psum"
        return _STRATEGY_ALIASES[self.strategy]

    @property
    def wire_dtype(self) -> str:
        """'bf16' | 'f32' — what actually moves over ICI."""
        return "bf16" if self.resolved == "psum_bf16" else "f32"

    # -- the exchange itself (must run inside shard_map over self.axis) --

    def exchange(self, tree: PyTree) -> PyTree:
        """Allreduce a pytree over the data axis. Traced into the step."""
        axis = self.axis

        # Telemetry: this body executes at TRACE time (the exchange is
        # compiled into the step), so per-call counting is impossible
        # from here — what IS knowable here, exactly once per compile,
        # is the exchange's shape: bytes moved per call and the wire
        # dtype.  Per-step totals = bytes_per_call x the step counter.
        if monitor.enabled():
            if self.resolved == "psum_bf16":
                # the compressed strategy ships 2 bytes/element
                # regardless of the storage dtype
                wire_dtype = "bfloat16"
                nbytes = 2 * sum(
                    int(getattr(l, "size", 0))
                    for l in jax.tree.leaves(tree))
            else:
                wire_dtype = monitor.tree_dtypes(tree)
                nbytes = monitor.tree_bytes(tree)
            monitor.set_gauge("exchange/bytes_per_call", nbytes,
                              strategy=self.resolved, dtype=wire_dtype,
                              what=self.exchange_what)
            monitor.inc("exchange/traces_total", strategy=self.resolved)

        if self.resolved == "psum_bf16":
            def reduce_leaf(x):
                orig = x.dtype
                y = (x * self.fp16_scale).astype(jnp.bfloat16)
                y = self._bf16_sum(y, axis)
                return (y / self.fp16_scale).astype(orig)
        else:
            def reduce_leaf(x):
                return jax.lax.psum(x, axis)

        out = jax.tree.map(reduce_leaf, tree)
        if self.avg:
            n = self._axis_size()
            out = jax.tree.map(lambda x: x / n, out)
        return out

    def _axis_size(self):
        axes = ((self.axis,) if isinstance(self.axis, str)
                else tuple(self.axis))
        n = 1
        for a in axes:
            n *= jax.lax.axis_size(a)
        return n

    @staticmethod
    def _bf16_sum(y, axis):
        """Sum bf16-quantized leaves over ``axis`` with a bf16 WIRE and
        f32 ACCUMULATION: all_gather the quantized values (bf16 on the
        interconnect — (N-1)/N x 2 bytes/element, half a bf16 ring
        all-reduce's traffic and a quarter of the f32 one) and reduce
        locally in float32.

        Why not ``psum(bf16)``: the psum accumulates IN bf16, and at N
        shards the partial sums sit N x above the payload — each add
        can then swallow an entire quantization step of the increment
        (at N=8 a 2^-8 correction on a ~1.0 payload vanishes into the
        ~8.0 partial sum's 2^-5 spacing).  Measured on the 8-dev CPU
        mesh, that rounding defeats error feedback almost entirely;
        the local f32 reduce is what makes the residual pin
        (tests/test_exchanger.py long-run gradient-sum) hold."""
        g = jax.lax.all_gather(y, axis)
        return jnp.sum(g.astype(jnp.float32), axis=0)

    def exchange_with_residual(self, tree: PyTree,
                               residual: PyTree) -> tuple[PyTree, PyTree]:
        """bf16 exchange with error feedback: quantize ``tree +
        residual`` to bfloat16, sum the quantized values over the axis
        with ``_bf16_sum`` (bf16 on the wire — 2 bytes/element — f32
        accumulation locally), average in f32, and
        return the NEW per-shard residual — the f32 difference between
        what this shard wanted to send and what the quantizer let
        through.  Over a run the residual re-injects every bit the
        wire dropped, so the cumulative applied gradient tracks the
        cumulative true gradient to within one quantization step
        (pinned by test)."""
        if not self.error_feedback:
            raise ValueError("exchange_with_residual needs "
                             "error_feedback=True")

        # comp appears in both maps; XLA CSEs the duplicate add
        q_tree = jax.tree.map(
            lambda x, r: (x.astype(jnp.float32) + r).astype(jnp.bfloat16),
            tree, residual)
        new_residual = jax.tree.map(
            lambda x, r, q: (x.astype(jnp.float32) + r)
            - q.astype(jnp.float32),
            tree, residual, q_tree)
        axis = self.axis
        out = jax.tree.map(
            lambda q, x: self._bf16_sum(q, axis).astype(x.dtype),
            q_tree, tree)
        if self.avg:
            n = self._axis_size()
            out = jax.tree.map(lambda x: x / n, out)
        return out, new_residual


# ---------------------------------------------------------------------------
# Async-rule merge arithmetic (EASGD / ASGD / GOSGD)
#
# In the reference these were tiny Theano functions compiled on the
# worker/server GPUs and driven by MPI Sendrecv of GPU buffers
# (SURVEY.md §2.5, §3.3).  Here they are pure jitted pytree ops; the
# host-side rule actors in theanompi_tpu/rules move the data.
# ---------------------------------------------------------------------------


@partial(jax.jit, donate_argnums=(0,))
def easgd_worker_update(worker: PyTree, center: PyTree, alpha) -> PyTree:
    """worker <- worker - alpha * (worker - center)  (SURVEY.md §2.3)."""
    return jax.tree.map(lambda w, c: w - alpha * (w - c), worker, center)


@partial(jax.jit, donate_argnums=(0,))
def easgd_center_update(center: PyTree, worker: PyTree, alpha) -> PyTree:
    """center <- center + alpha * (worker - center)  (SURVEY.md §2.3)."""
    return jax.tree.map(lambda c, w: c + alpha * (w - c), center, worker)


@jax.jit
def easgd_both_updates(worker: PyTree, center: PyTree, alpha):
    """One fused elastic exchange: returns (new_worker, new_center).

    The reference did this as one MPI Sendrecv + two GPU kernels; fusing
    both sides into one jitted call halves the host round-trips.
    """
    new_w = jax.tree.map(lambda w, c: w - alpha * (w - c), worker, center)
    new_c = jax.tree.map(lambda c, w: c + alpha * (w - c), center, worker)
    return new_w, new_c


@partial(jax.jit, donate_argnums=(0,))
def easgd_apply_delta(current: PyTree, snapshot: PyTree,
                      returned: PyTree) -> PyTree:
    """Overlapped-EASGD correction (rules/async_rules.py overlap mode).

    The exchange thread shipped ``snapshot`` (the params at submit
    time) and got back ``returned = snapshot - alpha*(snapshot -
    center)``; meanwhile the worker trained on.  The elastic force the
    server computed is ``delta = snapshot - returned = alpha*(snapshot
    - center)`` — apply it to the params the worker has NOW:
    ``current - delta``.  This is the classic staleness-1 elastic
    update: same force, applied one exchange period late, bounded by
    the pipe's max-1-outstanding barrier."""
    return jax.tree.map(lambda c, s, r: c - (s - r),
                        current, snapshot, returned)


@partial(jax.jit, donate_argnums=(0,))
def asgd_apply_grads(center: PyTree, grads: PyTree, lr) -> PyTree:
    """Parameter-server SGD step: center <- center - lr * grads."""
    return jax.tree.map(lambda c, g: c - lr * g, center, grads)


@jax.jit
def gosgd_merge(own: PyTree, own_w, recv: PyTree, recv_w):
    """Gossip merge (Blot et al., SURVEY.md §2.3):

    receiver params <- weighted average of (own, received) by their
    scalar weights; receiver weight <- own_w + recv_w.
    """
    total = own_w + recv_w
    merged = jax.tree.map(
        lambda a, b: (own_w * a + recv_w * b) / total, own, recv
    )
    return merged, total


#: optimizer-state fields that hold FIRST-moment information (gradient
#: direction memory) — the slots a gossip merge must scale.  Second
#: moments (adam/rmsprop ``nu``) are deliberately NOT here: shrinking a
#: curvature estimate toward zero while its bias-correction ``count``
#: stays put would make the next preconditioned step
#: mu_hat/sqrt(nu_hat) BLOW UP at exactly the teleported point —
#: the opposite of the stabilization this exists for.
_FIRST_MOMENT_FIELDS = frozenset({"trace", "mu", "mean", "momentum"})


def gosgd_scale_momentum(opt_state: PyTree, frac: float) -> PyTree:
    """Scale the optimizer's first-moment slots by the receiver's
    share of a gossip merge.

    The merge teleports params toward the sender when recv_w >> own_w,
    but the local momentum buffer was accumulated along the OLD
    trajectory — applying it unscaled at the new point is the measured
    divergence mode of gossip over slow links (docs/SCALING.md: loss
    5-9 vs the 2.3 random floor at momentum 0.9, stable at 0).
    Treating momentum like params in the weighted average — with the
    sender's (unshipped) momentum taken as zero — scales it by
    own_w/total: a small merge barely touches it, a dominating push
    resets it.

    Slots are matched by state-field NAME (optax state namedtuples:
    sgd/momentum ``trace``, adam/adamw ``mu``, adabelief-style
    ``mean``); everything else — second moments, counts, injected
    hyperparams — is kept, which is the conservative direction (``keep``
    was the reference's raw behavior).  A cheap path-walk per message,
    no optimizer re-initialization."""
    from jax import tree_util as jtu

    def scale(path, leaf):
        names = {p.name for p in path if isinstance(p, jtu.GetAttrKey)}
        if names & _FIRST_MOMENT_FIELDS:
            return leaf * frac
        return leaf

    return jtu.tree_map_with_path(scale, opt_state)
