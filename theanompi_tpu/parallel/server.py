"""Host-side parameter services for the asynchronous rules.

The reference ran EASGD/ASGD servers as dedicated MPI ranks owning a
GPU, serializing worker exchanges through a probe/recv message loop
(SURVEY.md §2.3, §3.3 — mount empty, no file:line), and GOSGD used
point-to-point MPI sends to random peers.

TPU-native redesign: the server is not a device-owning process — it is
a thread-safe store on the controller host.  Worker<->server traffic is
XLA host<->device transfer (the ``[driver]`` north-star: elastic copies
move from GPUDirect/mpi4py to host<->device transfers); in multi-host
deployments the same store sits behind the launcher's host process and
traffic rides DCN.  The merge arithmetic itself
(``easgd_both_updates``, optax server updates, ``gosgd_merge``) runs
jitted on the worker's own device — the host only holds and swaps
buffers.

The lock serializes center access exactly like the reference's server
loop did; the known serialization bottleneck (SURVEY.md §3.3) is
mitigated by keeping the critical section to a device dispatch (the
elastic update is async-dispatched; the lock is released before the
result is fetched).
"""

from __future__ import annotations

import queue
import threading
from typing import Any

import jax
import numpy as np
import optax

from theanompi_tpu.parallel.exchanger import (
    easgd_both_updates,
    easgd_center_update_n,
)
from theanompi_tpu.resilience import faults

PyTree = Any


def _is_host(tree: PyTree) -> bool:
    leaves = jax.tree.leaves(tree)
    return not leaves or isinstance(leaves[0], np.ndarray)


class EASGDServer:
    """Center-parameter store with the elastic-averaging exchange."""

    def __init__(self, params: PyTree, alpha: float = 0.5):
        self.alpha = alpha
        self._center = jax.tree.map(np.asarray, params)  # guarded_by: self._lock
        self._lock = threading.Lock()
        self.n_exchanges = 0  # guarded_by: self._lock

    def exchange(self, worker_params: PyTree) -> PyTree:
        """One elastic exchange; returns the worker's new params.

        worker <- worker - a(worker - center); center <- center + a(worker - center)

        The lock covers fetching the previous center value and
        dispatching the fused update — NOT the update's device
        execution (dispatch is async) nor the caller's use of its new
        params.  The unavoidable serialization is the fetch: exchange
        k+1 must see exchange k's center, so it blocks until k's device
        work finishes — but worker k keeps training in the meantime.
        """
        # fault plane: the 'raise in an exchanger hook' site — a no-op
        # (one is-None check) without an installed plan
        faults.fire("exchange", kind="easgd")
        with self._lock:
            # prior center may be an un-fetched device array committed to
            # another worker's device; materialize on host so this
            # worker's jit doesn't see mixed devices
            center = self._center
            if not _is_host(center):
                center = jax.device_get(center)
            new_w, new_c = easgd_both_updates(worker_params, center,
                                              self.alpha)
            self._center = new_c  # lazily fetched by the next exchange
            self.n_exchanges += 1
        return new_w

    def exchange_n(self, worker_mean: PyTree, n: int) -> PyTree:
        """Aggregated elastic exchange (the hierarchical plane,
        ``parallel/aggregate.py``): ``worker_mean`` is the mean of
        ``n`` co-located workers' params, and the center applies the
        closed-form composition of n independent exchanges against ONE
        center version::

            center += n * alpha * (mean - center)
                   == center + alpha * sum_i (w_i - center)

        Returns the PRE-update center: each worker's own elastic pull
        ``w_i - alpha*(w_i - center)`` uses that same version, so the
        workers compute their returns host-side (each on its own
        thread) and the wire carries ONE tree each way instead of n.  Stability note
        (docs/DESIGN.md "Hierarchical exchange"): the composed center
        move is ``n*alpha`` — operators pick alpha so ``n*alpha <= 1``,
        the EASGD paper's ``beta = N*alpha`` parameterization."""
        faults.fire("exchange", kind="easgd")
        n = int(n)
        if n < 1:
            raise ValueError(f"exchange_n needs n >= 1, got {n}")
        with self._lock:
            center = self._center
            if not _is_host(center):
                center = jax.device_get(center)
            self._center = easgd_center_update_n(center, worker_mean,
                                                 n * self.alpha)
            self.n_exchanges += n
        return center

    def get_center(self) -> PyTree:
        with self._lock:
            return jax.device_get(self._center)


class ASGDServer:
    """Classic async parameter server: workers push grads, server
    applies its optimizer to the center and returns fresh params."""

    def __init__(self, params: PyTree,
                 tx: optax.GradientTransformation):
        self._center = params            # guarded_by: self._lock
        self.tx = tx
        self._opt_state = tx.init(params)  # guarded_by: self._lock
        self._lock = threading.Lock()
        self.n_updates = 0               # guarded_by: self._lock

        @jax.jit
        def _apply(params, opt_state, grads):
            updates, new_opt = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        self._apply = _apply

    def set_lr(self, lr: float) -> None:
        """Apply the per-epoch LR schedule to the SERVER's optimizer —
        the one that actually applies updates (workers' own opt_states
        are unused in ASGD).  Requires inject_hyperparams (which the
        TpuModel optimizer builder always uses)."""
        from theanompi_tpu.utils.helper_funcs import set_learning_rate

        with self._lock:
            self._opt_state = set_learning_rate(self._opt_state, lr)

    def push_pull(self, grads: PyTree) -> PyTree:
        """Apply worker grads to the center; return fresh center params
        (host arrays — the caller places them on its own device).

        Grads are fetched to host first: workers live on different
        devices, and the center is committed to the server's device
        (the reference's server owned its own GPU the same way)."""
        faults.fire("exchange", kind="asgd")
        host_grads = jax.device_get(grads)
        with self._lock:
            self._center, self._opt_state = self._apply(
                self._center, self._opt_state, host_grads)
            self.n_updates += 1
            center = self._center
        return jax.device_get(center)

    def push_pull_n(self, grad_sum: PyTree, n: int) -> PyTree:
        """Aggregated grad push (the hierarchical plane,
        ``parallel/aggregate.py``): ``grad_sum`` is the SUM of ``n``
        co-located workers' gradients, applied as ONE optimizer step —
        the delta-sum of n same-version pushes (exact for any
        gradient-linear update; for stateful optimizers this is the
        standard large-batch composition, docs/DESIGN.md "Hierarchical
        exchange").  ``n`` rides along so the update count — and the
        shard plane's version accounting — reflect the n logical
        pushes.  Returns the fresh center, fanned back to all n
        workers by the aggregator."""
        faults.fire("exchange", kind="asgd")
        n = int(n)
        if n < 1:
            raise ValueError(f"push_pull_n needs n >= 1, got {n}")
        host_grads = jax.device_get(grad_sum)
        with self._lock:
            self._center, self._opt_state = self._apply(
                self._center, self._opt_state, host_grads)
            self.n_updates += n
            center = self._center
        return jax.device_get(center)

    def get_center(self) -> PyTree:
        with self._lock:
            return self._center

    def get_opt_state(self) -> PyTree:
        with self._lock:
            return self._opt_state

    def set_opt_state(self, opt_state: PyTree) -> None:
        """Install a restored optimizer state (ASGD resume — the
        server's momentum/hyperparams ARE the training state)."""
        with self._lock:
            self._opt_state = opt_state


class GossipHub:
    """Rendezvous for GOSGD's point-to-point pushes (the TPU stand-in
    for the reference's random-peer MPI sends).  Each worker has an
    inbox; senders never block."""

    def __init__(self, n_workers: int, maxsize: int = 64):
        self.n_workers = n_workers
        self._inboxes = [queue.Queue(maxsize=maxsize) for _ in range(n_workers)]
        self._active = [True] * n_workers

    def push(self, dst: int, params: PyTree, weight: float) -> bool:
        """Deliver (params, weight) to worker ``dst``; False if refused.

        A refused push costs the sender nothing — it keeps its weight.
        Pushes to deactivated (finished) workers are refused, otherwise
        stragglers would bleed gossip weight into inboxes nobody drains
        (breaking the sum-of-weights≈1 conservation invariant)."""
        faults.fire("exchange", kind="gosgd")
        if not self._active[dst]:
            return False
        payload = (jax.tree.map(np.asarray, params), float(weight))
        try:
            self._inboxes[dst].put_nowait(payload)
            return True
        except queue.Full:
            return False

    def deactivate(self, rank: int) -> None:
        """Mark ``rank`` finished; peers stop pushing to it."""
        self._active[rank] = False

    def drain(self, rank: int) -> list[tuple[PyTree, float]]:
        """All pending deliveries for worker ``rank`` (non-blocking)."""
        out = []
        q = self._inboxes[rank]
        while True:
            try:
                out.append(q.get_nowait())
            except queue.Empty:
                return out
